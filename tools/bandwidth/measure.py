#!/usr/bin/env python
"""Communication micro-benchmark (the reference tools/bandwidth/measure.py
analog): times device-side AllReduce across a size sweep.

Two modes:
- single-process (default): jitted psum over a mesh of all visible devices
  (the GSPMD collective the fused train step uses).  On a multi-chip host
  this measures ICI; on the virtual CPU mesh it validates the harness.
- multi-process (under tools/launch.py): the distributed Collective's
  cross-process AllReduce (gloo on CPU, ICI/DCN on pods).

Usage::

    python tools/bandwidth/measure.py --sizes 1KB,1MB,16MB --iters 20
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python tools/bandwidth/measure.py
    python tools/launch.py -n 4 --platform cpu \
        python tools/bandwidth/measure.py --dist
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")))


def parse_size(s):
    s = s.strip().upper()
    mult = 1
    for suffix, m in (("KB", 1 << 10), ("MB", 1 << 20), ("GB", 1 << 30),
                      ("B", 1)):
        if s.endswith(suffix):
            return int(float(s[:-len(suffix)]) * m)
    return int(s)


def bench_single(sizes, iters):
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    mesh = Mesh(np.asarray(devs), ("dp",))
    rep = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P("dp"))

    @jax.jit
    def allreduce(x):
        # dp-sharded input, replicated output: GSPMD emits AllReduce/
        # AllGather over the mesh — the fused trainer's gradient pattern
        return jax.lax.with_sharding_constraint(x, rep)

    results = []
    for size in sizes:
        n = max(len(devs), size // 4 // len(devs) * len(devs))
        x = jax.device_put(jnp.arange(n, dtype=jnp.float32), shard)
        allreduce(x).block_until_ready()      # compile + warm
        tic = time.time()
        for _ in range(iters):
            out = allreduce(x)
        out.block_until_ready()
        dt = (time.time() - tic) / iters
        results.append({"size_bytes": n * 4, "num_devices": len(devs),
                        "time_ms": round(dt * 1e3, 4),
                        "gbytes_per_s": round(n * 4 / dt / 1e9, 3)})
    return results


def bench_dist(sizes, iters):
    import numpy as np
    from mxnet_tpu import distributed
    distributed.initialize()
    coll = distributed.Collective()
    results = []
    for size in sizes:
        n = max(1, size // 4)
        x = np.ones(n, np.float32)
        coll.allreduce_sum(x)                 # warm
        tic = time.time()
        for _ in range(iters):
            out = coll.allreduce_sum(x)
        np.asarray(out)
        dt = (time.time() - tic) / iters
        results.append({"size_bytes": n * 4,
                        "num_workers": coll.num_workers,
                        "time_ms": round(dt * 1e3, 4),
                        "gbytes_per_s": round(n * 4 / dt / 1e9, 3)})
    return results


def main():
    parser = argparse.ArgumentParser(description="allreduce bandwidth sweep")
    parser.add_argument("--sizes", default="4KB,64KB,1MB,16MB,64MB",
                        help="comma-separated message sizes")
    parser.add_argument("--iters", type=int, default=20)
    parser.add_argument("--dist", action="store_true",
                        help="cross-process mode (run under tools/launch.py)")
    parser.add_argument("--virtual-devices", type=int, default=0,
                        help="provision an N-device virtual CPU mesh before "
                             "JAX init (for harness validation on 1-chip "
                             "hosts; the TPU plugin overrides JAX_PLATFORMS "
                             "so this must be set via jax.config)")
    args = parser.parse_args()
    if args.virtual_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            " --xla_force_host_platform_device_count=%d"
            % args.virtual_devices)
        import jax
        jax.config.update("jax_platforms", "cpu")
    sizes = [parse_size(s) for s in args.sizes.split(",")]
    rows = bench_dist(sizes, args.iters) if args.dist else \
        bench_single(sizes, args.iters)
    for r in rows:
        print(json.dumps(r))


if __name__ == "__main__":
    main()
