#!/usr/bin/env python
"""mxserve daemon: serve trained checkpoints over HTTP
(docs/how_to/serving.md).

::

    python tools/serve.py --model mlp=/ckpts/mlp:3 \\
        --model resnet=/ckpts/resnet-dir \\
        --input-shape mlp:data=784 --input-shape resnet:data=3,32,32 \\
        --port 8100 [--buckets 1,2,4,8,16,32] [--dtype bfloat16] \\
        [--warmup] [--port-file /run/mxserve.port]

Model specs: ``name=prefix:epoch`` loads the ``prefix-symbol.json`` +
``prefix-%04d.params`` pair; ``name=directory`` (a path holding a
``CheckpointManager`` manifest) loads the newest intact epoch with
checksum verification.

Lifecycle: SIGTERM/SIGINT drain (finish accepted requests, then exit 0);
a wedged forward is killed by the StepWatchdog (``MXTPU_STEP_TIMEOUT``,
exit 87) so ``tools/supervise.py`` can relaunch the daemon — warm, when
``MXTPU_COMPILE_CACHE`` is set (compiled bucket programs reload from
disk).  Serving knobs: ``MXTPU_SERVE_*`` (docs/env_vars.md) or the
equivalent flags below.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _parse_shape_specs(specs):
    """``["mlp:data=784", "data=3,32,32"]`` -> {model_or_None: {input:
    shape}} (no model prefix = applies to every model)."""
    out = {}
    for spec in specs or ():
        model = None
        head, _, tail = spec.partition("=")
        if ":" in head:
            model, _, head = head.partition(":")
        shape = tuple(int(x) for x in tail.split(",") if x)
        out.setdefault(model, {})[head] = shape
    return out


def _load_models(pool, specs, shape_specs):
    for spec in specs:
        name, _, target = spec.partition("=")
        if not name or not target:
            raise SystemExit("bad --model spec %r (want name=prefix:epoch "
                             "or name=ckpt-dir)" % spec)
        shapes = shape_specs.get(name, shape_specs.get(None))
        if os.path.isdir(target):
            entry = pool.load_dir(name, target, sample_shapes=shapes)
            src = "%s (epoch %d)" % (target, entry.loaded_epoch)
        else:
            prefix, _, epoch = target.rpartition(":")
            if not prefix or not epoch.isdigit():
                raise SystemExit("bad --model target %r (want "
                                 "prefix:epoch or a checkpoint dir)"
                                 % target)
            pool.load(name, prefix, int(epoch), sample_shapes=shapes)
            src = "%s:%s" % (prefix, epoch)
        sys.stderr.write("mxserve: loaded model %r from %s\n" % (name, src))


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="inference serving daemon (docs/how_to/serving.md)")
    parser.add_argument("--model", action="append", default=[],
                        metavar="NAME=PREFIX:EPOCH|NAME=DIR",
                        help="model to serve (repeatable)")
    parser.add_argument("--input-shape", action="append", default=[],
                        metavar="[MODEL:]INPUT=D1,D2,...",
                        help="per-sample input shape, enables --warmup "
                             "and load-time analysis (repeatable)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8100,
                        help="0 = ephemeral (see --port-file)")
    parser.add_argument("--port-file", default=None,
                        help="write 'host:port' here once listening")
    parser.add_argument("--buckets", default=None,
                        help="override MXTPU_SERVE_BUCKETS")
    parser.add_argument("--max-wait-ms", type=float, default=None,
                        help="override MXTPU_SERVE_MAX_WAIT_MS")
    parser.add_argument("--max-queue", type=int, default=None,
                        help="override MXTPU_SERVE_MAX_QUEUE")
    parser.add_argument("--seq-buckets", default=None,
                        help="sequence-LENGTH buckets for "
                             "/predict_seq, e.g. '8,16,32' (default: "
                             "MXTPU_SERVE_SEQ_BUCKETS)")
    parser.add_argument("--tenant-weights", default=None,
                        help="weighted-fair tenant shares, e.g. "
                             "'gold:4,free:1' (default: "
                             "MXTPU_SERVE_TENANT_WEIGHTS)")
    parser.add_argument("--tenant-quota", type=int, default=None,
                        help="per-tenant queued-request quota; beyond "
                             "it a tenant is shed 429 (default: "
                             "MXTPU_SERVE_TENANT_QUOTA; 0 disables)")
    parser.add_argument("--slo-ms", type=float, default=None,
                        help="override MXTPU_SERVE_SLO_MS")
    parser.add_argument("--dtype", default=None,
                        help="override MXTPU_SERVE_DTYPE (e.g. bfloat16)")
    parser.add_argument("--warmup", action="store_true",
                        help="compile every bucket per model before "
                             "accepting traffic (needs --input-shape)")
    parser.add_argument("--watch", action="store_true",
                        help="tail each checkpoint-DIRECTORY model for "
                             "new epochs and hot-swap verified ones in "
                             "with zero dropped requests (MXTPU_SWAP_* "
                             "knobs; docs/how_to/serving.md "
                             "'Continuous deployment')")
    parser.add_argument("--warmup-only", action="store_true",
                        help="warm every (model, bucket) forward, print "
                             "`mxserve: warmup_s=<s>`, exit 0 WITHOUT "
                             "serving (the fleet bring-up measurement; "
                             "docs/how_to/fleet.md)")
    parser.add_argument("--export-aot", action="store_true",
                        help="BUILD the AOT executable store: compile "
                             "every (model, bucket) forward and "
                             "serialize the executables under "
                             "MXTPU_COMPILE_CACHE/aot (pair with "
                             "--warmup-only; replicas launched with "
                             "the same cache dir then warm by LOADING "
                             "instead of compiling)")
    args = parser.parse_args(argv)
    if not args.model:
        parser.error("at least one --model is required")

    from mxnet_tpu.resilience import StepWatchdog, step_timeout_configured
    from mxnet_tpu.serving import ModelPool, ServingFrontend, parse_buckets

    pool = ModelPool(dtype=args.dtype)
    _load_models(pool, args.model, _parse_shape_specs(args.input_shape))

    watchdog = None
    if step_timeout_configured():
        watchdog = StepWatchdog()

    frontend = ServingFrontend(
        pool, host=args.host, port=args.port, buckets=args.buckets,
        max_wait_ms=args.max_wait_ms, max_queue=args.max_queue,
        slo_ms=args.slo_ms, watchdog=watchdog,
        tenant_weights=args.tenant_weights,
        tenant_quota=args.tenant_quota,
        seq_buckets=args.seq_buckets)

    # handlers + bind BEFORE the (possibly minutes-long) warmup: a
    # SIGTERM during warmup must drain to exit 0, not die rc 143 on the
    # default handler.  The port file is only written after warmup, so
    # no client connects early.
    frontend.install_signal_handlers()
    frontend.start()

    if args.warmup or args.warmup_only or args.export_aot:
        import time as _time

        from mxnet_tpu.base import get_env as _get_env
        from mxnet_tpu.base import ENV_COMPILE_CACHE as _ENV_CC
        from mxnet_tpu.serving.aot import aot_dir_for_cache

        cache_dir = _get_env(_ENV_CC)
        aot_dir = aot_dir_for_cache(cache_dir) if cache_dir else None
        tic = _time.monotonic()
        buckets = parse_buckets(args.buckets)
        for name in pool.names():
            if frontend.draining:     # SIGTERM mid-warmup: stop compiling
                break
            entry = pool.get(name)
            if entry.sample_shapes is None:
                sys.stderr.write("mxserve: cannot warm %r — no "
                                 "--input-shape declared\n" % name)
                continue
            if args.export_aot:
                # the store BUILDER: compile + serialize each bucket's
                # executable (no Predictor warmup — this process never
                # serves)
                if aot_dir is None:
                    raise SystemExit("--export-aot needs "
                                     "MXTPU_COMPILE_CACHE set")
                entry.export_aot(buckets, aot_dir)
                sys.stderr.write("mxserve: exported AOT executables "
                                 "for %r over buckets %s\n"
                                 % (name, list(buckets)))
                continue
            loaded = entry.load_aot(aot_dir, buckets) if aot_dir else 0
            if loaded:
                sys.stderr.write("mxserve: warmed %r from the AOT "
                                 "store (%d/%d buckets)\n"
                                 % (name, loaded, len(buckets)))
            if loaded < len(buckets):
                # no store / partial store / meta mismatch: classic
                # trace-and-compile warmup for what is missing
                entry.warmup([b for b in buckets
                              if b not in entry._aot])
                sys.stderr.write("mxserve: warmed %r over buckets %s\n"
                                 % (name, [b for b in buckets
                                           if b not in entry._aot]))
        # the bring-up number bench.py fleet compares cold vs AOT-warm
        # (process start/imports excluded — this is the compile cost
        # the warm store removes)
        sys.stderr.write("mxserve: warmup_s=%.3f\n"
                         % (_time.monotonic() - tic))
    if args.warmup_only:
        # no serve_forever ran, so there is nothing to drain — the
        # bound (never-advertised) socket dies with the process
        sys.stderr.write("mxserve: warmup-only — exiting 0\n")
        sys.stderr.flush()
        return 0
    if args.watch:
        for name in pool.names():
            entry = pool.get(name)
            if entry.source_dir:
                frontend.watcher(name, start=True)
                sys.stderr.write(
                    "mxserve: watching %s (epoch %s) for new epochs of "
                    "%r\n" % (entry.source_dir, entry.loaded_epoch, name))
            else:
                sys.stderr.write(
                    "mxserve: --watch: model %r was loaded from a "
                    "prefix:epoch pair, not a checkpoint directory — "
                    "not watchable\n" % name)
    sys.stderr.write("mxserve: listening on %s:%d (models: %s)\n"
                     % (frontend.host, frontend.port, pool.names()))
    sys.stderr.flush()
    if args.port_file:
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write("%s:%d" % (frontend.host, frontend.port))
        os.replace(tmp, args.port_file)
    frontend.serve_forever()
    sys.stderr.write("mxserve: drained — exiting 0\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
