"""Per-layer rank selection for the VH decomposition.

Capability port of the reference tools/accnn/rank_selection.py:1: each
convolution's spatial-SVD spectrum defines how much "energy" a rank-K
approximation keeps; dynamic programming distributes ranks across
layers to maximize total kept (log-)energy under a global FLOP budget
``speedup_ratio`` times smaller than the original network.
"""
import json

import numpy as np

import utils


def conv_spectrum(arg_params, name):
    W = np.asarray(arg_params[name + "_weight"].asnumpy())
    N, C, y, x = W.shape
    Wm = W.transpose(1, 2, 0, 3).reshape(C * y, N * x)
    return np.linalg.svd(Wm, compute_uv=False)


def conv_costs(node, in_shape, out_shape):
    """(flops per unit rank of the VH pair, original flops): the
    vertical (K, C, y, 1) conv costs C*y per output position per rank,
    the horizontal (N, K, 1, x) conv costs N*x (the reference's
    calc_complexity priced both factors at x, wrong for rectangular
    kernels)."""
    attrs = utils.node_attrs(node)
    y, x = attrs["kernel"]
    N = attrs["num_filter"]
    C = in_shape[1]
    Y, X = out_shape[2], out_shape[3]
    return (C * y + N * x) * X * Y, x * y * N * C * X * Y


def get_ranksel(sym, arg_params, data_shape, speedup_ratio=2.0,
                min_rank=4, rank_step=4):
    """{conv_name: K} maximizing kept log-energy within the budget."""
    internals = sym.get_internals()
    out_names = internals.list_outputs()
    _, out_shapes, _ = internals.infer_shape_partial(data=data_shape)
    shape_of = dict(zip(out_names, out_shapes))
    nodes = utils.topsort(json.loads(sym.tojson())["nodes"])
    node_of = {n["name"]: n for n in nodes}

    convs = []
    for node in nodes:
        if node["op"] != "Convolution":
            continue
        name = node["name"]
        # input shape = the producing node's output shape
        src = None
        for j in node.get("inputs", []):
            cand = nodes[j[0]]
            if cand["op"] == "null" and cand["name"] == "data":
                src_shape = data_shape
                src = cand
                break
            if cand["op"] != "null":
                src_shape = shape_of.get(cand["name"] + "_output")
                src = cand
                break
        if src is None or src_shape is None:
            continue
        out_shape = shape_of.get(name + "_output")
        if out_shape is None:
            continue
        spec = conv_spectrum(arg_params, name)
        unit, orig = conv_costs(node, src_shape, out_shape)
        convs.append((name, spec, unit, orig))

    total_orig = sum(c[3] for c in convs)
    budget = total_orig / speedup_ratio

    # greedy marginal-gain allocation (the DP of the reference collapsed
    # to its greedy equivalent: energy curves are concave in K)
    ranks = {name: min_rank for name, _, _, _ in convs}

    def cost():
        return sum(unit * ranks[name]
                   for name, _, unit, _ in convs)

    def gain(name, spec, k):
        lo = (spec[:k] ** 2).sum()
        hi = (spec[:k + rank_step] ** 2).sum()
        return np.log(hi + 1e-12) - np.log(lo + 1e-12)

    improved = True
    while improved:
        improved = False
        best = None
        for name, spec, unit, _ in convs:
            k = ranks[name]
            if k + rank_step > len(spec):
                continue
            if cost() + unit * rank_step > budget:
                continue
            g = gain(name, spec, k) / (unit * rank_step)
            if best is None or g > best[0]:
                best = (g, name)
        if best is not None:
            ranks[best[1]] += rank_step
            improved = True
    return ranks, {"orig_flops": total_orig, "new_flops": cost(),
                   "speedup": total_orig / max(cost(), 1)}
