"""Vertical-horizontal (spatial SVD) convolution decomposition.

Capability port of the reference tools/accnn/acc_conv.py:1 (Jaderberg
et al. 2014): a trained k_y x k_x convolution W (N, C, y, x) factors
into a (K, C, y, 1) vertical convolution followed by an (N, K, 1, x)
horizontal one, K chosen by rank selection.  The factors come from the
SVD of W reshaped to (C*y, N*x), split as U*sqrt(D) / sqrt(D)*Q.
"""
import argparse

import numpy as np

import utils

import mxnet_tpu as mx


def vh_factors(W, K):
    """(V, H) low-rank factors of a conv kernel (N, C, y, x)."""
    N, C, y, x = W.shape
    Wm = W.transpose(1, 2, 0, 3).reshape(C * y, N * x)
    U, D, Q = np.linalg.svd(Wm, full_matrices=False)
    sqrt_d = np.sqrt(D[:K])
    V = (U[:, :K] * sqrt_d)          # (C*y, K)
    H = (Q[:K, :].T * sqrt_d)        # (N*x, K)
    V = V.T.reshape(K, C, y, 1)
    H = H.reshape(N, x, 1, K).transpose(0, 3, 2, 1)  # (N, K, 1, x)
    return V.astype(W.dtype), H.astype(W.dtype)


def conv_vh_decomposition(sym, arg_params, layer, K, data_shape):
    """Replace ``layer`` (a Convolution) with its VH pair; returns
    (new_sym, new_arg_params)."""
    W = np.asarray(arg_params[layer + "_weight"].asnumpy())
    b = arg_params.get(layer + "_bias")
    V, H = vh_factors(W, K)

    def sym_handle(data, node):
        attrs = utils.node_attrs(node)
        kernel = tuple(attrs["kernel"])
        pad = tuple(attrs.get("pad", (0, 0)))
        stride = tuple(attrs.get("stride", (1, 1)))
        s1 = mx.sym.Convolution(
            data, kernel=(kernel[0], 1), pad=(pad[0], 0),
            stride=(stride[0], 1), num_filter=K, no_bias=True,
            name=node["name"] + "_v")
        return mx.sym.Convolution(
            s1, kernel=(1, kernel[1]), pad=(0, pad[1]),
            stride=(1, stride[1]), num_filter=W.shape[0],
            no_bias=b is None, name=node["name"] + "_h")

    def arg_handle(arg_shape_dic, new_args):
        new_args[layer + "_v_weight"] = mx.nd.array(V)
        new_args[layer + "_h_weight"] = mx.nd.array(H)
        assert tuple(V.shape) == arg_shape_dic[layer + "_v_weight"]
        assert tuple(H.shape) == arg_shape_dic[layer + "_h_weight"]
        if b is not None:
            new_args[layer + "_h_bias"] = b.copy()

    return utils.replace_layers(sym, arg_params,
                                {layer: (sym_handle, arg_handle)},
                                data_shape)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-m", "--model", required=True,
                    help="checkpoint prefix to speed up")
    ap.add_argument("--load-epoch", type=int, default=1)
    ap.add_argument("--layer", required=True)
    ap.add_argument("--K", type=int, required=True)
    ap.add_argument("--save-model", required=True)
    ap.add_argument("--data-shape", default="1,3,224,224")
    args = ap.parse_args()
    shape = tuple(int(s) for s in args.data_shape.split(","))
    sym, arg_params, aux_params = utils.load_checkpoint(
        args.model, args.load_epoch)
    new_sym, new_args = conv_vh_decomposition(
        sym, arg_params, args.layer, args.K, shape)
    utils.save_checkpoint(args.save_model, 1, new_sym, new_args,
                          aux_params)


if __name__ == "__main__":
    main()
