"""Whole-network low-rank acceleration driver.

Capability port of the reference tools/accnn/accnn.py:1: pick per-layer
ranks for a target speedup (rank_selection), then apply the VH
decomposition to every convolution (acc_conv) — one pass, emitting an
accelerated checkpoint whose outputs approximate the original's.

    python accnn.py -m model_prefix --load-epoch 1 --ratio 2 \
        --save-model model_acc --data-shape 1,3,224,224
"""
import argparse

import acc_conv
import rank_selection
import utils


def accelerate(sym, arg_params, aux_params, data_shape, ratio=2.0,
               min_rank=4):
    ranks, stats = rank_selection.get_ranksel(
        sym, arg_params, data_shape, speedup_ratio=ratio,
        min_rank=min_rank)
    cur_sym, cur_args = sym, arg_params
    for layer, K in ranks.items():
        cur_sym, cur_args = acc_conv.conv_vh_decomposition(
            cur_sym, cur_args, layer, K, data_shape)
    return cur_sym, cur_args, aux_params, ranks, stats


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-m", "--model", required=True)
    ap.add_argument("--load-epoch", type=int, default=1)
    ap.add_argument("--ratio", type=float, default=2.0,
                    help="target conv-FLOP speedup")
    ap.add_argument("--save-model", required=True)
    ap.add_argument("--data-shape", default="1,3,224,224")
    args = ap.parse_args()
    shape = tuple(int(s) for s in args.data_shape.split(","))
    sym, arg_params, aux_params = utils.load_checkpoint(
        args.model, args.load_epoch)
    new_sym, new_args, aux, ranks, stats = accelerate(
        sym, arg_params, aux_params, shape, args.ratio)
    print("ranks:", ranks)
    print("conv flops: %.3g -> %.3g (%.2fx)"
          % (stats["orig_flops"], stats["new_flops"], stats["speedup"]))
    utils.save_checkpoint(args.save_model, 1, new_sym, new_args, aux)


if __name__ == "__main__":
    main()
