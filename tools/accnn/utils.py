"""Graph-surgery utilities for the accnn low-rank toolkit.

Capability port of the reference tools/accnn/utils.py:1 — rebuild a
Symbol from its JSON while handing selected layers to a replacement
callback, preserving every other op and the trained parameters.
"""
import ast
import copy
import json
from collections import deque

import mxnet_tpu as mx


def load_checkpoint(prefix, epoch):
    sym, arg_params, aux_params = mx.model.load_checkpoint(prefix, epoch)
    return sym, arg_params, aux_params


def save_checkpoint(prefix, epoch, sym, arg_params, aux_params):
    mx.model.save_checkpoint(prefix, epoch, sym, arg_params, aux_params)


def topsort(nodes):
    """Topological order of graph-json nodes, inputs re-indexed
    (reference utils.py:topsort)."""
    n = len(nodes)
    deg = [0] * n
    g = [[] for _ in range(n)]
    for i, node in enumerate(nodes):
        for j in node.get("inputs", []):
            deg[i] += 1
            g[j[0]].append(i)
    q = deque(i for i in range(n) if deg[i] == 0)
    res = []
    while q:
        i = q.popleft()
        res.append(nodes[i])
        for j in g[i]:
            deg[j] -= 1
            if deg[j] == 0:
                q.append(j)
    new_ids = {node["name"]: i for i, node in enumerate(res)}
    for node in res:
        for j in node.get("inputs", []):
            j[0] = new_ids[nodes[j[0]]["name"]]
    return res


def node_attrs(node):
    """Python-typed attr dict of a graph-json node."""
    raw = node.get("attrs", node.get("param", {})) or {}
    out = {}
    for k, v in raw.items():
        try:
            out[k] = ast.literal_eval(v)
        except (ValueError, SyntaxError):
            out[k] = v
    return out


def _is_param(node):
    name = node["name"]
    return node["op"] == "null" and (
        name.endswith(("_weight", "_bias", "_gamma", "_beta"))
        or "moving_" in name or "_mu" in name)


def sym_factory(node, data_inputs):
    op = getattr(mx.sym, node["op"])
    if len(data_inputs) == 1:
        return op(data_inputs[0], name=node["name"], **node_attrs(node))
    return op(*data_inputs, name=node["name"], **node_attrs(node))


def replace_layers(sym, arg_params, handlers, data_shape):
    """Rebuild ``sym`` with each layer named in ``handlers`` replaced.

    handlers: {layer_name: (sym_handle, arg_handle)} where
    ``sym_handle(data_sym, node) -> new_sym`` builds the substitute
    subgraph and ``arg_handle(arg_shape_dic, new_arg_params)`` installs
    its weights.  Returns (new_sym, new_arg_params).
    Reference utils.py:replace_conv_layer generalized to several layers
    per pass and to multi-input ops.
    """
    nodes = topsort(json.loads(sym.tojson())["nodes"])
    sym_of = {}
    result = None
    for node in nodes:
        name = node["name"]
        if node["op"] == "null":
            if not _is_param(node):
                sym_of[name] = mx.sym.Variable(name)
            continue
        data_inputs = []
        for j in node.get("inputs", []):
            src = nodes[j[0]]
            if _is_param(src) or src["name"].startswith(name):
                continue
            if src["name"] in sym_of:
                data_inputs.append(sym_of[src["name"]])
        if name in handlers:
            out = handlers[name][0](data_inputs[0], node)
        else:
            out = sym_factory(node, data_inputs)
        sym_of[name] = out
        result = out

    new_args = copy.deepcopy(dict(arg_params))
    # drop the replaced layers' original weights, add the factors
    for name in handlers:
        for suffix in ("_weight", "_bias"):
            new_args.pop(name + suffix, None)
    arg_shapes, _, _ = result.infer_shape(data=data_shape)
    arg_shape_dic = dict(zip(result.list_arguments(), arg_shapes))
    for name in handlers:
        handlers[name][1](arg_shape_dic, new_args)
    return result, new_args
