"""SVD decomposition of a FullyConnected layer.

Capability port of the reference tools/accnn/acc_fc.py:1: a trained FC
weight W (N, M) factors into W2 @ W1 with W1 = S_k V_k (K, M, no bias)
and W2 = U_k (N, K, carries the original bias).
"""
import argparse

import numpy as np

import utils

import mxnet_tpu as mx


def fc_factors(W, K):
    u, s, v = np.linalg.svd(W, full_matrices=False)
    W1 = (s[:K, None] * v[:K, :])       # (K, M)
    W2 = u[:, :K]                       # (N, K)
    return W1.astype(W.dtype), W2.astype(W.dtype)


def fc_decomposition(sym, arg_params, layer, K, data_shape):
    W = np.asarray(arg_params[layer + "_weight"].asnumpy())
    b = arg_params.get(layer + "_bias")
    W1, W2 = fc_factors(W.reshape(W.shape[0], -1), K)

    def sym_handle(data, node):
        s1 = mx.sym.FullyConnected(data, num_hidden=K, no_bias=True,
                                   name=node["name"] + "_red")
        return mx.sym.FullyConnected(s1, num_hidden=W.shape[0],
                                     no_bias=b is None,
                                     name=node["name"] + "_rec")

    def arg_handle(arg_shape_dic, new_args):
        new_args[layer + "_red_weight"] = mx.nd.array(
            W1.reshape(arg_shape_dic[layer + "_red_weight"]))
        new_args[layer + "_rec_weight"] = mx.nd.array(
            W2.reshape(arg_shape_dic[layer + "_rec_weight"]))
        if b is not None:
            new_args[layer + "_rec_bias"] = b.copy()

    return utils.replace_layers(sym, arg_params,
                                {layer: (sym_handle, arg_handle)},
                                data_shape)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-m", "--model", required=True)
    ap.add_argument("--load-epoch", type=int, default=1)
    ap.add_argument("--layer", required=True)
    ap.add_argument("--K", type=int, required=True)
    ap.add_argument("--save-model", required=True)
    ap.add_argument("--data-shape", default="1,3,224,224")
    args = ap.parse_args()
    shape = tuple(int(s) for s in args.data_shape.split(","))
    sym, arg_params, aux_params = utils.load_checkpoint(
        args.model, args.load_epoch)
    new_sym, new_args = fc_decomposition(sym, arg_params, args.layer,
                                         args.K, shape)
    utils.save_checkpoint(args.save_model, 1, new_sym, new_args,
                          aux_params)


if __name__ == "__main__":
    main()
