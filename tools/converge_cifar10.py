"""Convergence-at-accuracy on the real chip -> CONVERGE_r05.json.

The reference's convergence tier trains cifar10 to a fixed accuracy
(tests/python/train/test_dtype.py; example train_cifar10.py recipe:
resnet-20, batch 128, sgd momentum 0.9, wd 1e-4, lr 0.05).  This harness
has no network egress, so the dataset is a deterministic synthetic
CIFAR stand-in: class templates + heavy noise + translation jitter (a
hardened variant of example/image-classification/train_cifar10.py's
synthetic_cifar — weaker signal so resnet-20 needs several epochs,
giving a convergence CURVE; the generator is local, below), packed
into RecordIO so the full production feed path runs: native libjpeg
decode -> uint8 NHWC batches -> on-device normalize folded into the
fused bf16 train step.

Round 5: runs the SAME recipe in bfloat16 AND float32 from identical
seeds and records both val-acc curves — the dtype-parity claim that
protects the bf16-default training path (reference anchor:
example/image-classification/README.md:311-315 trains across dtypes).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "example", "image-classification"))

import numpy as np

import mxnet_tpu as mx


def pack_rec(X, y, prefix, quality=92):
    import cv2
    from mxnet_tpu import recordio
    w = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(len(X)):
        img = (X[i].transpose(1, 2, 0) * 255).astype(np.uint8)
        ok, buf = cv2.imencode(".jpg", img[..., ::-1],
                               [cv2.IMWRITE_JPEG_QUALITY, quality])
        assert ok
        hdr = recordio.IRHeader(0, float(y[i]), i, 0)
        w.write_idx(i, recordio.pack(hdr, buf.tobytes()))
    w.close()


def main():
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-train", type=int, default=20000)
    ap.add_argument("--num-val", type=int, default=2000)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--target-acc", type=float, default=0.90)
    ap.add_argument("--max-epochs", type=int, default=30)
    ap.add_argument("--out", type=str, default="CONVERGE_r05.json")
    ap.add_argument("--dtypes", type=str,
                    default="bfloat16,float32")
    args = ap.parse_args()

    def synthetic_cifar(num, num_classes=10, seed=0):
        """Harder variant of the example's synthetic set: weaker class
        signal + per-image geometric jitter, so resnet-20 needs several
        epochs to reach 90% — a convergence CURVE, not a one-shot fit."""
        templates = np.random.RandomState(42).rand(num_classes, 3, 32, 32)
        rs = np.random.RandomState(seed)
        labels = rs.randint(0, num_classes, size=num).astype("f")
        images = templates[labels.astype(int)] * 90
        images += rs.randn(num, 3, 32, 32) * 40
        # random roll = translation jitter (defeats pure pixel matching)
        for i in range(num):
            images[i] = np.roll(images[i],
                                (rs.randint(-2, 3), rs.randint(-2, 3)),
                                axis=(1, 2))
        return (np.clip(images, 0, 255).astype(np.float32) / 255,
                labels)

    from importlib import import_module
    net_mod = import_module("symbols.resnet")
    sym = net_mod.get_symbol(num_classes=10, num_layers=20,
                             image_shape="3,32,32")

    # cache keyed on the dataset sizes, and only valid when complete
    # v3: hardened dataset recipe (key must change when the recipe does)
    tmp = "/tmp/converge_cifar_v3_%d_%d" % (args.num_train, args.num_val)
    os.makedirs(tmp, exist_ok=True)
    t_pack = time.time()
    done_mark = os.path.join(tmp, "PACKED")
    if not os.path.exists(done_mark):
        Xtr, ytr = synthetic_cifar(args.num_train, seed=0)
        Xv, yv = synthetic_cifar(args.num_val, seed=1)
        pack_rec(Xtr, ytr, os.path.join(tmp, "train"))
        pack_rec(Xv, yv, os.path.join(tmp, "val"))
        open(done_mark, "w").write("ok")
    t_pack = time.time() - t_pack

    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import SPMDTrainer

    mean = jnp.array([125.3, 122.9, 113.9], jnp.float32)
    std = jnp.array([51.6, 50.8, 51.7], jnp.float32)

    def make_iter(split, train):
        return mx.io.ImageRecordIter(
            path_imgrec=os.path.join(tmp, split + ".rec"),
            path_imgidx=os.path.join(tmp, split + ".idx"),
            data_shape=(3, 32, 32), batch_size=args.batch_size,
            shuffle=train, rand_mirror=train, preprocess_threads=4,
            prefetch_buffer=4, dtype="uint8", layout="NHWC", seed=5)

    def run_dtype(dtype):
        """One full convergence run at the given compute dtype, from
        identical data, identical init seed, identical iterator seed."""
        cdt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32

        def data_tf(x):
            x = (x.astype(jnp.float32) - mean) / std
            return jnp.transpose(x, (0, 3, 1, 2)).astype(cdt)

        tr = SPMDTrainer(sym, "sgd",
                         {"learning_rate": args.lr, "momentum": 0.9,
                          "wd": 1e-4,
                          "rescale_grad": 1.0 / args.batch_size},
                         mesh=None, compute_dtype=dtype,
                         input_transforms={"data": data_tf})
        tr.bind([("data", (args.batch_size, 3, 32, 32))],
                [("softmax_label", (args.batch_size,))])
        mx.random.seed(7)
        tr.init_params(mx.initializer.Xavier(rnd_type="gaussian",
                                             factor_type="in",
                                             magnitude=2))
        train_it = make_iter("train", True)
        val_it = make_iter("val", False)
        hist = []
        tic = time.time()
        reached = None
        for epoch in range(args.max_epochs):
            for b in train_it:
                tr.step(b.data[0], b.label[0])
            train_it.reset()
            correct = total = 0
            for b in val_it:
                # the val fetch is the TRUE epoch sync point on this
                # tunneled backend (block_until_ready acks dispatch only)
                outs = tr.forward_only(b.data[0], b.label[0])
                pred = np.asarray(outs[0]).argmax(-1)
                lab = np.asarray(b.label[0].asnumpy())
                k = args.batch_size - b.pad
                correct += (pred[:k] == lab[:k]).sum()
                total += k
            val_it.reset()
            acc = correct / total
            hist.append(round(float(acc), 4))
            print("[%s] epoch %d val-acc %.4f (%.1fs)"
                  % (dtype, epoch, acc, time.time() - tic))
            if acc >= args.target_acc and reached is None:
                reached = epoch + 1
                break
        wall = time.time() - tic
        train_it.close()
        val_it.close()
        tr.close()
        return {
            "compute_dtype": dtype,
            "target_val_acc": args.target_acc,
            "epochs_to_target": reached,
            "final_val_acc": hist[-1] if hist else None,
            "val_acc_per_epoch": hist,
            "wall_clock_s": round(wall, 1),
            "imgs_per_sec_end_to_end": round(
                args.num_train * len(hist) / wall, 1),
        }

    curves = {}
    for dtype in args.dtypes.split(","):
        curves[dtype] = run_dtype(dtype.strip())

    out = {
        "workload": "train_cifar10 recipe (resnet-20, sgd m=0.9 wd=1e-4, "
                    "lr=%g, batch=%d) on synthetic CIFAR stand-in "
                    "(no egress), full RecordIO->native-decode->fused-"
                    "step path on the real chip; identical seeds per "
                    "dtype" % (args.lr, args.batch_size),
        "platform": "%s (%s)" % (jax.default_backend(),
                                 jax.devices()[0].device_kind),
        "num_train": args.num_train,
        "num_val": args.num_val,
        "curves": curves,
    }
    if "bfloat16" in curves and "float32" in curves:
        b, f = curves["bfloat16"], curves["float32"]
        out["bf16_final_minus_f32_final"] = round(
            (b["final_val_acc"] or 0) - (f["final_val_acc"] or 0), 4)
        out["bf16_within_noise_of_f32"] = bool(
            abs(out["bf16_final_minus_f32_final"]) <= 0.02)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
