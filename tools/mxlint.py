#!/usr/bin/env python
"""mxlint — static analyzer for the mxnet_tpu tree.

Level 2 (AST) runs always: traced-host calls in jitted functions,
lock-order cycles, bare excepts, and env-registry discipline over the
given paths (default: the ``mxnet_tpu`` package, ``tools/`` and
``bench.py`` next to this script — zero carve-outs).
Level 3 (whole-repo) also runs always: the shared-mutation race lint
(``repo-shared-mutation`` / ``repo-check-then-act``) and the
wire-contract drift lint (``wire-contract-drift``, driven by the
declared surface registry in ``analysis/contract_lint.py``).
Level 1 (graph) is opt-in via ``--graph``: builds the standard MLP fused
step on a dp mesh (8 virtual CPU devices) and lints its program —
donation coverage, host callbacks, the collective audit, dtype drift.

Exit codes: 0 = clean, 1 = findings, 2 = internal/usage error.

Reports: human lines on stdout; ``--json PATH`` (or the
``MXTPU_ANALYZE_REPORT`` env var) writes the stable machine-readable
report CI/bench diff across commits (see
docs/how_to/static_analysis.md).  Suppress a finding inline with
``# mxlint: disable=<rule>`` on (or above) the offending line.

    tools/mxlint.py                      # lint the tree
    tools/mxlint.py --changed            # only files changed vs HEAD
    tools/mxlint.py --self               # lint the linter too
    tools/mxlint.py --graph --json r.json mxnet_tpu
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import subprocess
import sys
import time
import types

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ANALYSIS_DIR = os.path.join(_REPO, "mxnet_tpu", "analysis")


def _load_ast_level():
    """Load report.py + the lint passes by file path under a synthetic
    package, WITHOUT importing mxnet_tpu — the AST level is stdlib-only
    by design, and this CLI must work (and stay side-effect-free) in
    containers with no jax/accelerator runtime and in launch-configured
    environments where importing the package would auto-join a
    distributed process group."""
    pkg = types.ModuleType("_mxlint_analysis")
    pkg.__path__ = [_ANALYSIS_DIR]
    sys.modules.setdefault("_mxlint_analysis", pkg)

    def load(modname):
        fullname = "_mxlint_analysis." + modname
        if fullname in sys.modules:
            return sys.modules[fullname]
        spec = importlib.util.spec_from_file_location(
            fullname, os.path.join(_ANALYSIS_DIR, modname + ".py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[fullname] = mod
        spec.loader.exec_module(mod)
        return mod

    load("report")
    return load("ast_lint"), load("race_lint"), load("contract_lint")


def _graph_lint_mlp():
    """Build the standard 2-layer MLP fused step on a dp mesh and lint
    it (the same model tier-1 regression tests pin) — proving the
    shipped trainer's program donates its carries, syncs nothing to the
    host, and emits only the expected dp all-reduces.  The ONLY mode
    that imports the package (and jax)."""
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    from mxnet_tpu.analysis import fixtures

    trainer = fixtures.standard_mlp_trainer()
    try:
        return trainer.analyze(*fixtures.standard_mlp_batch())
    finally:
        trainer.close()


def _default_paths():
    """The zero-carve-out lint scope: the package, the tools, and the
    bench harness (PR 16 retired bench.py's last inline-disable; keeping
    it in the default scope is what keeps it retired)."""
    return [os.path.join(_REPO, "mxnet_tpu"),
            os.path.join(_REPO, "tools"),
            os.path.join(_REPO, "bench.py")]


def _changed_paths(ref):
    """Python files changed vs ``ref`` per git (the pre-commit loop's
    sub-second scope).  Returns None when not in a git checkout (caller
    falls back to the full tree)."""
    try:
        out = subprocess.run(
            ["git", "diff", "--name-only", ref, "--", "*.py"],
            cwd=_REPO, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    paths = []
    for line in out.stdout.splitlines():
        full = os.path.join(_REPO, line.strip())
        if line.strip() and os.path.isfile(full):
            paths.append(full)
    return paths


def _grep(path, needles):
    try:
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return False
    return any(n in text for n in needles)


def _mentions_env(paths):
    """Cheap text probe: does any changed file touch the env-registry
    machinery (the only rules that need the package-wide registry)?"""
    return any(_grep(p, ("get_env", "getenv", "environ", "register_env"))
               for p in paths)


def _registry_sources():
    """Package files that can declare env knobs (contain a
    ``register_env`` call) — a text prefilter so --changed mode parses
    a handful of files for the registry instead of the whole package."""
    out = []
    for root, _dirs, files in os.walk(os.path.join(_REPO, "mxnet_tpu")):
        for name in files:
            if name.endswith(".py"):
                full = os.path.join(root, name)
                if _grep(full, ("register_env",)):
                    out.append(full)
    return out


def _touches_surfaces(contract_lint, paths):
    """Does any changed file participate in a declared wire surface
    (producer, consumer, or the fault namespace, which spans the whole
    tree)?"""
    refs = set()
    for surface in contract_lint.repo_registry():
        if surface.kind == "faults":
            # fault armings can live anywhere — any changed file counts
            return bool(paths)
        for relpath, _q in tuple(surface.producers) + tuple(
                surface.consumers):
            refs.add(os.path.normpath(os.path.join(_REPO, relpath)))
    return any(os.path.normpath(os.path.abspath(p)) in refs
               for p in paths)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="mxlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("paths", nargs="*",
                        help="files/directories to lint (default: the "
                             "mxnet_tpu package + tools/ + bench.py)")
    parser.add_argument("--self", dest="lint_self", action="store_true",
                        help="lint the linter (tools/mxlint.py + the "
                             "analysis package) along with the package")
    parser.add_argument("--changed", nargs="?", const="HEAD",
                        default=None, metavar="REF",
                        help="lint only .py files in `git diff "
                             "--name-only REF` (default HEAD); falls "
                             "back to the full tree outside a git "
                             "checkout.  The contract pass stays "
                             "repo-global either way (its registry "
                             "pulls in both sides of every surface)")
    parser.add_argument("--graph", action="store_true",
                        help="also graph-lint the standard MLP fused "
                             "step (compiles a small program)")
    parser.add_argument("--json", dest="json_path", default=None,
                        help="write the machine-readable report here "
                             "(default: $MXTPU_ANALYZE_REPORT if set)")
    parser.add_argument("--select", "--rules", dest="select",
                        default=None,
                        help="comma-separated rule subset to run")
    parser.add_argument("--list-faults", action="store_true",
                        help="print the fault-point registry (every "
                             "statically resolvable faults.maybe_* "
                             "site under the paths) and exit — the "
                             "mechanical source for docs/how_to/"
                             "fault_tolerance.md's list")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="suppress the human report (exit code and "
                             "--json only)")
    args = parser.parse_args(argv)

    t0 = time.monotonic()
    try:
        ast_lint, race_lint, contract_lint = _load_ast_level()
    except Exception as e:  # noqa: BLE001 — report, don't traceback
        sys.stderr.write("mxlint: cannot load the analysis modules: %s\n"
                         % (e,))
        return 2

    paths = list(args.paths)
    if not paths:
        paths = _default_paths()
    changed_mode = False
    if args.changed is not None and not args.paths:
        changed = _changed_paths(args.changed)
        if changed is not None:
            paths = changed
            changed_mode = True
    if args.list_faults:
        points = ast_lint.collect_fault_points(paths)
        for name in sorted(points):
            sites = ", ".join(
                "%s:%d" % (os.path.relpath(f, _REPO), line)
                for f, line, _ in points[name])
            print("%-22s %s" % (name, sites))
        print("mxlint: %d fault point(s)" % len(points))
        return 0
    if args.lint_self:
        paths.append(os.path.abspath(__file__))

    all_rules = tuple(ast_lint.RULES) + tuple(race_lint.RULES) + \
        tuple(contract_lint.RULES)
    select = None
    if args.select:
        select = [r.strip() for r in args.select.split(",") if r.strip()]
        unknown = set(select) - set(all_rules)
        if unknown:
            sys.stderr.write("mxlint: unknown rule(s) %s (known: %s)\n"
                             % (sorted(unknown), ", ".join(all_rules)))
            return 2

    # one parse per file, shared by every pass (and by the env-registry
    # collection below when the package is inside the lint scope)
    cache = {}
    # the registry, collected STATICALLY from the package (register_env
    # call literals) so linting paths outside it — this file, example
    # scripts — still knows every declared knob without importing
    # anything.  In --changed mode the package-wide collection is the
    # dominant cost, so it is skipped unless a changed file actually
    # touches the env machinery (the rules that need the registry can
    # only fire on such a file).
    registry = None
    if not changed_mode:
        registry = ast_lint.collect_registered(
            [os.path.join(_REPO, "mxnet_tpu")], cache=cache)
    elif _mentions_env(paths):
        registry = ast_lint.collect_registered(
            _registry_sources(), cache=cache)

    report = ast_lint.lint_paths(paths, env_registry=registry,
                                 select=select, cache=cache)
    extras = [race_lint.lint_paths(paths, select=select, cache=cache)]
    # the contract pass is repo-global (it pulls in both sides of every
    # declared surface); in --changed mode it can only change verdict
    # when a changed file participates in some surface, so skip it
    # otherwise and keep the pre-commit loop sub-second
    if not changed_mode or _touches_surfaces(contract_lint, paths):
        extras.append(contract_lint.lint_paths(paths, select=select,
                                               cache=cache))
    for extra in extras:
        extra.files_scanned = 0       # same files, already counted
        report.merge(extra)
    if args.graph:
        try:
            report.merge(_graph_lint_mlp())
        except Exception as e:  # noqa: BLE001 — device bring-up varies
            sys.stderr.write("mxlint: graph level failed to run: %s\n"
                             % (e,))
            return 2
    elapsed = time.monotonic() - t0

    # read directly: this CLI must not import the package for get_env
    json_path = args.json_path or \
        os.environ.get("MXTPU_ANALYZE_REPORT")  # mxlint: disable=env-direct-read
    if json_path:
        payload = report.to_dict()
        # timing lives OUTSIDE the diffable findings/summary contract
        payload["elapsed_s"] = round(elapsed, 3)
        with open(json_path, "w") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
    if not args.quiet:
        print(report.format_text())
        print("mxlint: %.2fs" % elapsed)
    return 1 if report.findings else 0


if __name__ == "__main__":
    sys.exit(main())
