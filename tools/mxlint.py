#!/usr/bin/env python
"""mxlint — static analyzer for the mxnet_tpu tree.

Level 2 (AST) runs always: traced-host calls in jitted functions,
lock-order cycles, bare excepts, and env-registry discipline over the
given paths (default: the ``mxnet_tpu`` package next to this script).
Level 1 (graph) is opt-in via ``--graph``: builds the standard MLP fused
step on a dp mesh (8 virtual CPU devices) and lints its program —
donation coverage, host callbacks, the collective audit, dtype drift.

Exit codes: 0 = clean, 1 = findings, 2 = internal/usage error.

Reports: human lines on stdout; ``--json PATH`` (or the
``MXTPU_ANALYZE_REPORT`` env var) writes the stable machine-readable
report CI/bench diff across commits (see
docs/how_to/static_analysis.md).  Suppress a finding inline with
``# mxlint: disable=<rule>`` on (or above) the offending line.

    tools/mxlint.py                      # lint the package
    tools/mxlint.py --self               # lint the linter + the package
    tools/mxlint.py --graph --json r.json mxnet_tpu
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time
import types

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ANALYSIS_DIR = os.path.join(_REPO, "mxnet_tpu", "analysis")


def _load_ast_level():
    """Load report.py + ast_lint.py by file path under a synthetic
    package, WITHOUT importing mxnet_tpu — the AST level is stdlib-only
    by design, and this CLI must work (and stay side-effect-free) in
    containers with no jax/accelerator runtime and in launch-configured
    environments where importing the package would auto-join a
    distributed process group."""
    pkg = types.ModuleType("_mxlint_analysis")
    pkg.__path__ = [_ANALYSIS_DIR]
    sys.modules.setdefault("_mxlint_analysis", pkg)

    def load(modname):
        fullname = "_mxlint_analysis." + modname
        if fullname in sys.modules:
            return sys.modules[fullname]
        spec = importlib.util.spec_from_file_location(
            fullname, os.path.join(_ANALYSIS_DIR, modname + ".py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[fullname] = mod
        spec.loader.exec_module(mod)
        return mod

    load("report")
    return load("ast_lint")


def _graph_lint_mlp():
    """Build the standard 2-layer MLP fused step on a dp mesh and lint
    it (the same model tier-1 regression tests pin) — proving the
    shipped trainer's program donates its carries, syncs nothing to the
    host, and emits only the expected dp all-reduces.  The ONLY mode
    that imports the package (and jax)."""
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    from mxnet_tpu.analysis import fixtures

    trainer = fixtures.standard_mlp_trainer()
    try:
        return trainer.analyze(*fixtures.standard_mlp_batch())
    finally:
        trainer.close()


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="mxlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("paths", nargs="*",
                        help="files/directories to lint (default: the "
                             "mxnet_tpu package)")
    parser.add_argument("--self", dest="lint_self", action="store_true",
                        help="lint the linter (tools/mxlint.py + the "
                             "analysis package) along with the package")
    parser.add_argument("--graph", action="store_true",
                        help="also graph-lint the standard MLP fused "
                             "step (compiles a small program)")
    parser.add_argument("--json", dest="json_path", default=None,
                        help="write the machine-readable report here "
                             "(default: $MXTPU_ANALYZE_REPORT if set)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule subset to run")
    parser.add_argument("--list-faults", action="store_true",
                        help="print the fault-point registry (every "
                             "statically resolvable faults.maybe_* "
                             "site under the paths) and exit — the "
                             "mechanical source for docs/how_to/"
                             "fault_tolerance.md's list")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="suppress the human report (exit code and "
                             "--json only)")
    args = parser.parse_args(argv)

    t0 = time.monotonic()
    try:
        ast_lint = _load_ast_level()
    except Exception as e:  # noqa: BLE001 — report, don't traceback
        sys.stderr.write("mxlint: cannot load the analysis modules: %s\n"
                         % (e,))
        return 2

    paths = list(args.paths)
    if not paths:
        paths = [os.path.join(_REPO, "mxnet_tpu")]
    if args.list_faults:
        points = ast_lint.collect_fault_points(paths)
        for name in sorted(points):
            sites = ", ".join(
                "%s:%d" % (os.path.relpath(f, _REPO), line)
                for f, line, _ in points[name])
            print("%-22s %s" % (name, sites))
        print("mxlint: %d fault point(s)" % len(points))
        return 0
    if args.lint_self:
        paths.append(os.path.abspath(__file__))
    # the registry, collected STATICALLY from the package (register_env
    # call literals) so linting paths outside it — this file, example
    # scripts — still knows every declared knob without importing
    # anything
    registry = ast_lint.collect_registered(
        [os.path.join(_REPO, "mxnet_tpu")])

    select = None
    if args.rules:
        select = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = set(select) - set(ast_lint.RULES)
        if unknown:
            sys.stderr.write("mxlint: unknown rule(s) %s (known: %s)\n"
                             % (sorted(unknown),
                                ", ".join(ast_lint.RULES)))
            return 2

    report = ast_lint.lint_paths(paths, env_registry=registry,
                                 select=select)
    if args.graph:
        try:
            report.merge(_graph_lint_mlp())
        except Exception as e:  # noqa: BLE001 — device bring-up varies
            sys.stderr.write("mxlint: graph level failed to run: %s\n"
                             % (e,))
            return 2
    elapsed = time.monotonic() - t0

    # read directly: this CLI must not import the package for get_env
    json_path = args.json_path or \
        os.environ.get("MXTPU_ANALYZE_REPORT")  # mxlint: disable=env-direct-read
    if json_path:
        payload = report.to_dict()
        # timing lives OUTSIDE the diffable findings/summary contract
        payload["elapsed_s"] = round(elapsed, 3)
        with open(json_path, "w") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
    if not args.quiet:
        print(report.format_text())
        print("mxlint: %.2fs" % elapsed)
    return 1 if report.findings else 0


if __name__ == "__main__":
    sys.exit(main())
