#!/usr/bin/env python
"""mxdata network-tier server: decode batches on THIS host's cores and
stream them to a remote consumer (docs/how_to/performance.md, "Scaling
the input pipeline" — the network tier).

::

    # on each CPU decode host (the .rec/.idx live on THIS host)
    python tools/data_server.py --host 0.0.0.0 --port 9410

    # on the TPU host
    it = mx.io.ImageRecordIter(..., data_service='cpu1:9410,cpu2:9410')
    # or fleet-wide: export MXTPU_DATA_SERVERS=cpu1:9410,cpu2:9410

The server is stateless across connections: every consumer connection
carries its full stream config (dataset paths AS SEEN FROM THIS HOST,
shapes, seed, shard offset/stride, local decode-worker count) in the
handshake, and the server builds a fresh sharded-reader/decode-worker
service for it — so one server process serves any number of jobs, and
a SIGKILLed server respawned by the host's supervisor (systemd,
supervise.py, k8s) needs no state handoff: the consumer's reconnect
handshake re-requests its stream at the last consumed batch.

IMPORT DISCIPLINE: this process NEVER imports jax — a decode host that
spun up an XLA client would burn seconds of startup and hundreds of MB
per server, and on a mixed host would fight the trainer for the chip
(the ``tools/supervise.py`` lesson).  The data_service package's
server half is jax-free by design; it is imported through the
synthetic-package stub below (the ``tools/mxlint.py`` idiom) so
``mxnet_tpu/__init__`` never executes.
"""
import argparse
import importlib.machinery
import os
import signal
import sys
import types

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)


def _bootstrap():
    """Install the package-path stub and import the jax-free leaves."""
    if "mxnet_tpu" not in sys.modules:
        pkg = types.ModuleType("mxnet_tpu")
        pkg.__path__ = [os.path.join(_ROOT, "mxnet_tpu")]
        pkg.__spec__ = importlib.machinery.ModuleSpec(
            "mxnet_tpu", None, is_package=True)
        pkg.__spec__.submodule_search_locations = pkg.__path__
        sys.modules["mxnet_tpu"] = pkg
    from mxnet_tpu.data_service import net
    return net


def _log(msg):
    sys.stderr.write(msg + "\n")
    sys.stderr.flush()


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="data-service network-tier server (jax-free; "
                    "docs/how_to/performance.md)")
    parser.add_argument("--host", default="127.0.0.1",
                        help="bind address (0.0.0.0 for remote "
                             "consumers)")
    parser.add_argument("--port", type=int, default=9410,
                        help="TCP port (0 = ephemeral; see --port-file)")
    parser.add_argument("--port-file", default=None,
                        help="write 'host:port' here once listening "
                             "(benches/tests discover ephemeral ports)")
    args = parser.parse_args(argv)

    net = _bootstrap()
    server = net.BatchServer(host=args.host, port=args.port, log=_log)

    def _on_signal(signum, frame):
        _log("data_server: signal %d — shutting down" % signum)
        server.shutdown()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _on_signal)

    _log("data_server: listening on %s:%d (pid %d)"
         % (server.host, server.port, os.getpid()))
    if args.port_file:
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write("%s:%d" % (server.host, server.port))
        os.replace(tmp, args.port_file)
    return server.serve_forever()


if __name__ == "__main__":
    sys.exit(main())
