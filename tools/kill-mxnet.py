#!/usr/bin/env python
"""Kill stray training processes on this host (reference
tools/kill-mxnet.py's role for the local launcher).  Matches processes
whose command line contains the given pattern (default: the MXTPU worker
env marker or a python command running a mxnet_tpu script).

Usage::

    python tools/kill-mxnet.py              # kill launcher workers
    python tools/kill-mxnet.py train_lm.py  # kill by script name
"""
import os
import signal
import sys


def main():
    pattern = sys.argv[1] if len(sys.argv) > 1 else None
    me = os.getpid()
    killed = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit() or int(pid) == me:
            continue
        try:
            with open("/proc/%s/cmdline" % pid, "rb") as f:
                cmd = f.read().replace(b"\0", b" ").decode(
                    "utf-8", "replace")
            with open("/proc/%s/environ" % pid, "rb") as f:
                env = f.read().decode("utf-8", "replace")
        except OSError:
            continue
        if pattern is not None:
            match = pattern in cmd
        else:
            match = "MXTPU_WORKER_RANK=" in env and "python" in cmd
        if match:
            try:
                os.kill(int(pid), signal.SIGTERM)
                killed.append((int(pid), cmd.strip()[:80]))
            except OSError:
                pass
    for pid, cmd in killed:
        print("killed %d: %s" % (pid, cmd))
    if not killed:
        print("no matching processes")
    return 0


if __name__ == "__main__":
    sys.exit(main())
