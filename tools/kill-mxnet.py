#!/usr/bin/env python
"""Kill stray training processes on this host (reference
tools/kill-mxnet.py's role for the local launcher).

Default (no argument): kills processes carrying the launcher's
MXTPU_WORKER_RANK env marker — i.e. workers spawned by tools/launch.py.
With a pattern: kills PYTHON processes whose command line contains it
(the invoking process and its ancestors are always excluded).

Usage::

    python tools/kill-mxnet.py              # kill launcher workers
    python tools/kill-mxnet.py train_lm.py  # kill python ... train_lm.py
"""
import os
import signal
import sys


def _ancestors():
    """pids of this process and its parent chain."""
    out = set()
    pid = os.getpid()
    while pid > 1:
        out.add(pid)
        try:
            with open("/proc/%d/stat" % pid) as f:
                pid = int(f.read().rsplit(") ", 1)[1].split()[1])
        except (OSError, IndexError, ValueError):
            break
    return out


def main():
    pattern = sys.argv[1] if len(sys.argv) > 1 else None
    skip = _ancestors()
    killed = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit() or int(pid) in skip:
            continue
        try:
            with open("/proc/%s/cmdline" % pid, "rb") as f:
                cmd = f.read().replace(b"\0", b" ").decode(
                    "utf-8", "replace")
            with open("/proc/%s/environ" % pid, "rb") as f:
                env = f.read().decode("utf-8", "replace")
        except OSError:
            continue
        if pattern is not None:
            match = pattern in cmd and "python" in cmd
        else:
            match = "MXTPU_WORKER_RANK=" in env and "python" in cmd
        if match:
            try:
                os.kill(int(pid), signal.SIGTERM)
                killed.append((int(pid), cmd.strip()[:80]))
            except OSError:
                pass
    for pid, cmd in killed:
        print("killed %d: %s" % (pid, cmd))
    if not killed:
        print("no matching processes")
    return 0


if __name__ == "__main__":
    sys.exit(main())
