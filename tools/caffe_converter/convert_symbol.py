"""Caffe prototxt -> Symbol converter (reference
tools/caffe_converter/convert_symbol.py).

Self-contained: parses the protobuf TEXT format directly (the reference
compiles caffe.proto; the text grammar — `key: value` scalars and nested
`block { ... }` messages with repeated keys — needs no schema), then maps
the classic layer zoo onto mx.sym calls.  Covers the layers the Caffe
model zoo's classification nets use: Input/data, Convolution,
Pooling (incl. global), InnerProduct, ReLU, Dropout, LRN, Concat,
Eltwise(SUM/MAX/PROD), BatchNorm(+folded Scale), Flatten,
Softmax/SoftmaxWithLoss.

Weight import (.caffemodel) is out of scope: the binary format needs the
full caffe.proto schema; architecture import plus our reference-format
.params loading covers the practical migration path.

Usage:
    python convert_symbol.py net.prototxt out-symbol.json
or  sym, input_name = proto_to_symbol(open("net.prototxt").read())
"""
from __future__ import annotations

import re
import sys


# ---------------------------------------------------------------------------
# protobuf text-format parser (schema-free)
# ---------------------------------------------------------------------------

_TOKEN = re.compile(r"""
    (?P<comment>\#[^\n]*)
  | (?P<brace>[{}])
  | (?P<name>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<colon>:)
  | (?P<string>"(?:[^"\\]|\\.)*")
  | (?P<number>[-+]?(?:\d+\.\d*|\.\d+|\d+)(?:[eE][-+]?\d+)?)
  | (?P<ws>\s+)
""", re.X)


def _tokens(text):
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if m is None:
            raise ValueError("prototxt parse error at %r" % text[pos:pos + 20])
        pos = m.end()
        kind = m.lastgroup
        if kind in ("ws", "comment"):
            continue
        yield kind, m.group()


class Message(dict):
    """dict with repeated-field semantics: every value is a LIST."""

    def add(self, key, value):
        self.setdefault(key, []).append(value)

    def one(self, key, default=None):
        v = self.get(key)
        return v[0] if v else default


def parse_prototxt(text):
    """Parse protobuf text format into a Message tree."""
    root = Message()
    stack = [root]
    toks = _tokens(text)
    pending = None
    for kind, tok in toks:
        if kind == "name":
            if pending is not None:
                # bare enum after a name without colon? treat prev as flag
                raise ValueError("unexpected name %r after %r"
                                 % (tok, pending))
            pending = tok
        elif kind == "colon":
            if pending is None:
                raise ValueError("stray ':'")
            kind2, tok2 = next(toks)
            if kind2 == "string":
                val = tok2[1:-1].encode().decode("unicode_escape")
            elif kind2 == "number":
                val = float(tok2) if ("." in tok2 or "e" in tok2.lower()) \
                    else int(tok2)
            elif kind2 == "name":   # enum / bool literal
                val = {"true": True, "false": False}.get(tok2, tok2)
            else:
                raise ValueError("bad value token %r" % tok2)
            stack[-1].add(pending, val)
            pending = None
        elif kind == "brace" and tok == "{":
            if pending is None:
                raise ValueError("stray '{'")
            child = Message()
            stack[-1].add(pending, child)
            stack.append(child)
            pending = None
        elif kind == "brace" and tok == "}":
            stack.pop()
            if not stack:
                raise ValueError("unbalanced '}'")
    if len(stack) != 1:
        raise ValueError("unbalanced '{'")
    return root


# ---------------------------------------------------------------------------
# layer mapping
# ---------------------------------------------------------------------------

def _pair(v):
    v = int(v or 0)
    return (v, v)


def _conv_args(p):
    args = {
        "num_filter": int(p.one("num_output")),
        "kernel": _pair(p.one("kernel_size", 1)),
        "stride": _pair(p.one("stride", 1)),
        "pad": _pair(p.one("pad", 0)),
        "no_bias": not p.one("bias_term", True),
    }
    if p.one("kernel_h"):
        args["kernel"] = (int(p.one("kernel_h")), int(p.one("kernel_w")))
    if p.one("stride_h"):
        args["stride"] = (int(p.one("stride_h")), int(p.one("stride_w")))
    if p.one("pad_h") is not None and (p.one("pad_h") or p.one("pad_w")):
        args["pad"] = (int(p.one("pad_h", 0)), int(p.one("pad_w", 0)))
    d = p.one("dilation")
    if d and int(d) > 1:
        args["dilate"] = _pair(d)
    g = p.one("group")
    if g and int(g) > 1:
        args["num_group"] = int(g)
    return args


def proto_to_symbol(text):
    """Returns (output_symbol, input_name).  Caffe blob names become node
    names; in-place layers (top == bottom) chain naturally."""
    import mxnet_tpu as mx

    proto = parse_prototxt(text)
    layers = proto.get("layer") or proto.get("layers") or []

    blobs = {}

    # input declaration: `input:` field or an Input layer
    input_name = proto.one("input")
    if layers and layers[0].one("type") in ("Input", "Data", "DATA"):
        lay0 = layers[0]
        input_name = lay0.one("top", lay0.one("name"))
        layers = layers[1:]
    if input_name is None and layers:
        input_name = layers[0].get("bottom", ["data"])[0]
    input_name = input_name or "data"
    blobs[input_name] = mx.sym.Variable(input_name)
    prev_type = {}    # top blob -> producing layer type (Scale pairing)
    loss_heads = []
    out = None

    for lay in layers:
        ltype = lay.one("type")
        name = lay.one("name")
        bottoms = [blobs[b] for b in lay.get("bottom", []) if b in blobs]
        tops = lay.get("top", [name])
        # phase-gated layers (TEST-only accuracy etc.) and data layers skip
        if ltype in ("Accuracy", "ACCURACY", "Silence"):
            continue
        if not bottoms:
            continue
        x = bottoms[0]
        if ltype in ("Convolution", "CONVOLUTION"):
            out = mx.sym.Convolution(
                x, name=name, **_conv_args(lay.one("convolution_param",
                                                   Message())))
        elif ltype in ("InnerProduct", "INNER_PRODUCT"):
            p = lay.one("inner_product_param", Message())
            out = mx.sym.FullyConnected(
                mx.sym.Flatten(x), name=name,
                num_hidden=int(p.one("num_output")),
                no_bias=not p.one("bias_term", True))
        elif ltype in ("Pooling", "POOLING"):
            p = lay.one("pooling_param", Message())
            pool = {0: "max", "MAX": "max", 1: "avg", "AVE": "avg"}.get(
                p.one("pool", "MAX"), "max")
            if p.one("global_pooling", False):
                out = mx.sym.Pooling(x, name=name, kernel=(1, 1),
                                     global_pool=True, pool_type=pool)
            else:
                kernel = _pair(p.one("kernel_size", 1))
                stride = _pair(p.one("stride", 1))
                pad = _pair(p.one("pad", 0))
                if p.one("kernel_h"):
                    kernel = (int(p.one("kernel_h")), int(p.one("kernel_w")))
                if p.one("stride_h"):
                    stride = (int(p.one("stride_h")), int(p.one("stride_w")))
                if p.one("pad_h") or p.one("pad_w"):
                    pad = (int(p.one("pad_h", 0)), int(p.one("pad_w", 0)))
                out = mx.sym.Pooling(
                    x, name=name, pool_type=pool, kernel=kernel,
                    stride=stride, pad=pad,
                    pooling_convention="full")   # caffe ceil semantics
        elif ltype in ("ReLU", "RELU"):
            out = mx.sym.Activation(x, name=name, act_type="relu")
        elif ltype in ("Sigmoid", "SIGMOID"):
            out = mx.sym.Activation(x, name=name, act_type="sigmoid")
        elif ltype in ("TanH", "TANH"):
            out = mx.sym.Activation(x, name=name, act_type="tanh")
        elif ltype in ("Dropout", "DROPOUT"):
            p = lay.one("dropout_param", Message())
            out = mx.sym.Dropout(x, name=name,
                                 p=float(p.one("dropout_ratio", 0.5)))
        elif ltype in ("LRN", "LRN_V1"):
            p = lay.one("lrn_param", Message())
            out = mx.sym.LRN(x, name=name,
                             nsize=int(p.one("local_size", 5)),
                             alpha=float(p.one("alpha", 1e-4)),
                             beta=float(p.one("beta", 0.75)))
        elif ltype in ("Concat", "CONCAT"):
            out = mx.sym.Concat(*bottoms, name=name)
        elif ltype in ("Eltwise", "ELTWISE"):
            p = lay.one("eltwise_param", Message())
            op = p.one("operation", "SUM")
            coeff = [float(c) for c in p.get("coeff", [])]
            if op in ("SUM", 1):
                if coeff and len(coeff) != len(bottoms):
                    raise ValueError(
                        "Eltwise %r: %d coeffs for %d bottoms"
                        % (name, len(coeff), len(bottoms)))
                terms = [b if not coeff or coeff[i] == 1.0 else b * coeff[i]
                         for i, b in enumerate(bottoms)]
                out = terms[0]
                for b in terms[1:]:
                    out = out + b
            elif op in ("MAX", 2):
                out = bottoms[0]
                for b in bottoms[1:]:
                    out = mx.sym.maximum(out, b)
            else:
                out = bottoms[0]
                for b in bottoms[1:]:
                    out = out * b
        elif ltype in ("BatchNorm", "BATCHNORM"):
            # caffe always pairs BatchNorm with a following Scale layer for
            # the learnable affine; our BatchNorm carries gamma/beta itself
            # (fix_gamma=False), so the Scale folds into this node
            p = lay.one("batch_norm_param", Message())
            out = mx.sym.BatchNorm(
                x, name=name, fix_gamma=False,
                eps=float(p.one("eps", 1e-5)))
        elif ltype in ("Scale", "SCALE"):
            if prev_type.get(lay.get("bottom", [None])[0]) not in (
                    "BatchNorm", "BATCHNORM"):
                raise ValueError(
                    "standalone Scale layer %r is unsupported (only the "
                    "canonical BatchNorm+Scale pair folds into "
                    "BatchNorm gamma/beta)" % (name,))
            out = x   # folded into the preceding BatchNorm's gamma/beta
        elif ltype in ("Flatten", "FLATTEN"):
            out = mx.sym.Flatten(x, name=name)
        elif ltype in ("Softmax", "SOFTMAX", "SoftmaxWithLoss",
                       "SOFTMAX_LOSS"):
            out = mx.sym.SoftmaxOutput(x, name=name or "softmax")
            loss_heads.append(out)
        else:
            raise ValueError("unsupported caffe layer type %r (layer %r)"
                             % (ltype, name))
        for t in tops:
            blobs[t] = out
            prev_type[t] = ltype

    if out is None:
        raise ValueError("prototxt contains no convertible layers")
    if len(loss_heads) > 1:
        # multi-loss nets (GoogLeNet train_val aux heads) keep every head
        out = mx.sym.Group(loss_heads)
    return out, input_name


def main():
    if len(sys.argv) != 3:
        print("usage: convert_symbol.py net.prototxt out-symbol.json")
        return 1
    with open(sys.argv[1]) as f:
        sym, input_name = proto_to_symbol(f.read())
    sym.save(sys.argv[2])
    print("input blob: %s -> wrote %s" % (input_name, sys.argv[2]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
