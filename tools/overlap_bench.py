"""Measured allreduce ablation on the multi-process virtual cluster.

Run under tools/launch.py (CPU collectives over gloo):

    python tools/launch.py -n 8 --platform cpu \
        python tools/overlap_bench.py --steps 8

Three jitted programs over the same ResNet-50-sized parameter volume
(~25.5M params -> 51 MB bf16 gradients):
  t_full    — fused fwd+bwd+psum(grads)+sgd step (the dist trainer path)
  t_nocomm  — identical program with the psum ablated (identity)
  t_comm    — psum of the same gradient pytree alone
Rank 0 prints one JSON line:  exposed = t_full - t_nocomm, compared
against t_comm.  overlap_fraction = 1 - exposed/t_comm (clamped to [0,1]).
On the CPU backend this measures whether XLA+gloo hides collective time
behind compute at all; the TPU projection uses the measured per-layer
backward timeline instead (tools/overlap_model.py).  Optionally writes a
jax.profiler trace of the full step (--trace-dir, rank 0 only).
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np

from mxnet_tpu import distributed

distributed.initialize()

import jax                      # noqa: E402
import jax.numpy as jnp         # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--hidden", type=int, default=2920)  # 3 layers ~25.6M
    ap.add_argument("--trace-dir", type=str, default=None)
    args = ap.parse_args()

    devs = np.array(jax.devices())
    mesh = Mesh(devs, ("dp",))
    nproc = jax.process_count()
    rank = jax.process_index()
    rs = np.random.RandomState(0)
    h = args.hidden
    params = {
        "w1": jnp.asarray(rs.randn(h, h).astype(np.float32) * 0.02),
        "w2": jnp.asarray(rs.randn(h, h).astype(np.float32) * 0.02),
        "w3": jnp.asarray(rs.randn(h, h).astype(np.float32) * 0.02),
    }
    n_params = sum(v.size for v in params.values())
    rep = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P("dp"))
    params = jax.device_put(params, rep)
    from jax import shard_map
    x_host = rs.randn(args.batch, h).astype(np.float32)
    gbatch = args.batch * len(devs)
    x = jax.make_array_from_process_local_data(
        shard, np.tile(x_host, (len(devs) // nproc if nproc > 1 else
                                len(devs), 1)).reshape(-1, h)[: gbatch //
                                                              nproc],
        (gbatch, h)) if nproc > 1 else jax.device_put(
        np.tile(x_host, (len(devs), 1)), shard)

    def loss(p, xb):
        y = jnp.tanh(xb @ p["w1"].astype(jnp.bfloat16).astype(jnp.float32))
        y = jnp.tanh(y @ p["w2"].astype(jnp.bfloat16).astype(jnp.float32))
        y = y @ p["w3"].astype(jnp.bfloat16).astype(jnp.float32)
        return jnp.mean(y * y)

    def grads_of(p, xb):
        return jax.grad(loss)(p, xb)

    def make_step(comm):
        @jax.jit
        def step(p, xb):
            def body(p, xb):
                g = grads_of(p, xb)
                g = {k: v.astype(jnp.bfloat16) for k, v in g.items()}
                if comm:
                    g = {k: jax.lax.psum(v, "dp") for k, v in g.items()}
                return {k: p[k] - 0.01 * g[k].astype(jnp.float32)
                        for k in p}
            return shard_map(
                body, mesh=mesh, in_specs=(P(), P("dp")),
                out_specs=P(), check_vma=False)(p, xb)
        return step

    @jax.jit
    def comm_only(p):
        def body(p):
            return {k: jax.lax.psum(v.astype(jnp.bfloat16), "dp")
                    for k, v in p.items()}
        return shard_map(body, mesh=mesh, in_specs=(P(),),
                         out_specs=P(), check_vma=False)(p)

    def timeit(fn, *a):
        out = None
        for _ in range(args.warmup):
            out = fn(*a)
        if out is not None:
            jax.block_until_ready(out)
        best = float("inf")
        for _ in range(3):
            tic = time.time()
            for _ in range(args.steps):
                out = fn(*a)
            jax.block_until_ready(out)
            best = min(best, (time.time() - tic) / args.steps)
        return best * 1e3

    step_full = make_step(True)
    step_nocomm = make_step(False)
    t_full = timeit(step_full, params, x)
    t_nocomm = timeit(step_nocomm, params, x)
    t_comm = timeit(comm_only, params)
    if args.trace_dir and rank == 0:
        jax.profiler.start_trace(args.trace_dir)
        for _ in range(3):
            out = step_full(params, x)
        jax.block_until_ready(out)
        jax.profiler.stop_trace()
    if rank == 0:
        exposed = max(0.0, t_full - t_nocomm)
        res = {
            "nproc": nproc,
            "n_devices": len(devs),
            "param_count": int(n_params),
            "grad_bytes_bf16": int(n_params * 2),
            "t_full_ms": round(t_full, 2),
            "t_nocomm_ms": round(t_nocomm, 2),
            "t_comm_solo_ms": round(t_comm, 2),
            "t_exposed_ms": round(exposed, 2),
            "overlap_fraction": round(
                max(0.0, min(1.0, 1.0 - exposed / t_comm)), 3)
            if t_comm > 0 else None,
        }
        print("OVERLAP_BENCH " + json.dumps(res))


if __name__ == "__main__":
    main()
