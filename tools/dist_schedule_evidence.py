"""Collective-overlap evidence for the grad_sync='zero' fused dist step.

Compiles the ResNet-50 weight-sharded-DP training step with the REAL
TPU compilation pipeline via AOT topology compilation
(jax.experimental.topologies, v5e:2x4 — no chips needed) and analyzes
the post-scheduling HLO:

- gradient sync is emitted as **bucketed all-reduce-scatter** fusions
  (XLA's combiner groups several layer grads per bucket, bf16);
- parameter gathers are bf16 all-gathers (the FSDP mixed-precision comm
  discipline — the f32 master is cast before gathering);
- the latency-hiding scheduler splits collectives into async
  start/done pairs with independent compute fusions SCHEDULED BETWEEN
  them — counted per pair below.  This is the on-silicon schedule the
  TPU runtime executes, not a dependence-order argument.

Falls back to the 8-device virtual CPU mesh (correctness-only pipeline:
sync collectives, no scheduler) when topology AOT is unavailable.

Writes docs/profiles/dist_step_zero_hlo_r05.txt and prints a JSON
summary line.
"""
import json
import os
import re
import statistics
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
import jax

jax.config.update("jax_platforms", "cpu")


def _build_trainer(mesh, batch, side):
    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.parallel import SPMDTrainer

    sym = models.get_symbol("resnet-50", num_classes=100)
    trainer = SPMDTrainer(
        sym, "sgd", {"learning_rate": 0.1, "momentum": 0.9,
                     "rescale_grad": 1.0 / batch},
        mesh=mesh, compute_dtype="bfloat16", grad_sync="zero")
    trainer.bind([("data", (batch, 3, side, side))],
                 [("softmax_label", (batch,))])
    return trainer


def lower_tpu(batch=64, side=224):
    """AOT-compile for a v5e 2x4 slice: the actual TPU pass pipeline
    (ReduceScatter creation, collective combiner, latency-hiding
    scheduler) with no chips attached."""
    import numpy as np

    import jax.numpy as jnp
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name="v5e:2x4")
    mesh = Mesh(np.array(topo.devices), ("dp",))
    tr = _build_trainer(mesh, batch, side)

    def sds(shape, dtype, spec):
        return jax.ShapeDtypeStruct(shape, dtype,
                                    sharding=NamedSharding(mesh, spec))

    params = {k: sds(tr.arg_shapes[k], np.float32,
                     tr._param_spec(k, tr.arg_shapes[k]))
              for k in tr.param_names}
    aux = {k: sds(tr.aux_shapes[k], np.float32, P())
           for k in tr.aux_names}
    opt_state = {k: (sds(tr.arg_shapes[k], np.float32,
                         tr._param_spec(k, tr.arg_shapes[k])),)
                 for k in tr.param_names}
    data = {"data": sds((batch, 3, side, side), jnp.bfloat16, P("dp")),
            "softmax_label": sds((batch,), jnp.bfloat16, P("dp"))}
    rng = jax.ShapeDtypeStruct((2,), np.uint32)
    scalar = jax.ShapeDtypeStruct((), np.float32)
    counter = sds((), np.int32, P())
    extras = {"guard": (counter, counter, counter)}
    lowered = tr._step_fn.lower(params, aux, opt_state, extras, data, rng,
                                scalar, scalar, 1)
    return lowered.compile().as_text(), "tpu-aot v5e:2x4"


def lower_cpu(batch=8, side=64):
    import numpy as np

    import jax.numpy as jnp

    import mxnet_tpu as mx
    from mxnet_tpu import random as _random
    from mxnet_tpu.parallel import local_mesh

    tr = _build_trainer(local_mesh("dp"), batch, side)
    mx.random.seed(7)
    tr.init_params(mx.initializer.Xavier())
    X = np.random.RandomState(0).rand(batch, 3, side, side).astype("f")
    y = np.random.RandomState(1).randint(0, 100, batch).astype("f")
    data = tr._shard_batch((X, y))
    # the step's guard carry: one stacked i32[3] (total, consec, trips)
    extras = {"guard": tr._scalar_acc(np.zeros(3, np.int32), np.int32)}
    lowered = tr._step_fn.lower(
        tr.params, tr.aux, tr.opt_state, extras, data, _random.peek_key(),
        jnp.asarray(0.1, jnp.float32), jnp.asarray(0.0, jnp.float32), 1)
    return lowered.compile().as_text(), "cpu virtual 8-mesh"


def _op_of(line):
    if "=" not in line:
        return None, None
    name = re.match(r"\s*%?([\w.\-]+)\s*=", line)
    body = line.split("=", 1)[1]
    op = re.search(r"([a-z][a-z0-9\-]*)\(", body)
    return (name.group(1) if name else None), (op.group(1) if op else None)


def analyze(hlo):
    m = re.search(r"ENTRY [^{]+\{(.*)\n\}", hlo, re.S)
    lines = (m.group(1) if m else hlo).splitlines()

    counts = {}
    for ln in lines:
        _, op = _op_of(ln)
        if op:
            counts[op] = counts.get(op, 0) + 1

    # async pairs: compute fusions scheduled between start and done.
    # Collectives (the overlap claim) are counted separately from async
    # host/device DMAs (copy-start/slice-start — a different mechanism).
    COLLECTIVE_STARTS = ("collective-permute-start", "all-gather-start",
                         "all-reduce-start", "reduce-scatter-start")
    pairs = {}
    spans = []
    dma_pairs = 0
    for i, ln in enumerate(lines):
        name, op = _op_of(ln)
        if op and op.endswith("-start"):
            pairs[name] = (i, op)
        elif op and op.endswith("-done"):
            ref = re.search(r"-done\(\s*%?([\w.\-]+)", ln)
            if ref and ref.group(1) in pairs:
                s, sop = pairs.pop(ref.group(1))
                if sop not in COLLECTIVE_STARTS:
                    dma_pairs += 1
                    continue
                between = lines[s + 1:i]
                nfus = sum(1 for b in between
                           if _op_of(b)[1] in ("fusion", "convolution"))
                spans.append({"op": sop, "span": i - s,
                              "compute_between": nfus})

    # bucketed reduce-scatter: kCustom fusions calling all-reduce-scatter
    buckets = []
    for ln in lines:
        if "calls=%all-reduce-scatter" in ln:
            shapes = re.findall(r"(?:bf16|f32)\[[^\]]*\]", ln.split("=")[1]
                                .split("fusion(")[0])
            buckets.append(shapes)
    rs_plain = counts.get("reduce-scatter", 0)

    ag_dtypes = {}
    for ln in lines:
        _, op = _op_of(ln)
        if op in ("all-gather", "all-gather-start"):
            dm = re.search(r"=\s*\(?\s*([a-z0-9]+)\[", ln)
            if dm:
                ag_dtypes[dm.group(1)] = ag_dtypes.get(dm.group(1), 0) + 1

    overlapped = [s for s in spans if s["compute_between"] > 0]
    return {
        "n_async_dma_pairs": dma_pairs,
        "entry_instructions": len(lines),
        "op_counts": {k: v for k, v in sorted(counts.items())
                      if "all-" in k or "collective" in k
                      or "reduce-scatter" in k or k in ("fusion",
                                                        "convolution")},
        "n_async_pairs": len(spans),
        "n_async_pairs_with_compute_between": len(overlapped),
        "compute_ops_inside_collective_windows": sum(
            s["compute_between"] for s in spans),
        "median_compute_between": (statistics.median(
            [s["compute_between"] for s in spans]) if spans else 0),
        "n_bucketed_reduce_scatter_fusions": len(buckets),
        "bucket_tensor_counts": [len(b) for b in buckets],
        "bucket_example_shapes": buckets[0] if buckets else [],
        "n_plain_reduce_scatter": rs_plain,
        "all_gather_dtypes": ag_dtypes,
        "async_spans": spans,
    }


def main():
    try:
        hlo, pipeline = lower_tpu()
    except Exception as e:  # noqa: BLE001 — no topology support
        sys.stderr.write("TPU AOT unavailable (%s); falling back to the "
                         "CPU virtual mesh\n" % e)
        hlo, pipeline = lower_cpu()
    a = analyze(hlo)
    a["pipeline"] = pipeline
    here = os.path.dirname(os.path.abspath(__file__))
    out_path = os.path.join(here, "..", "docs", "profiles",
                            "dist_step_zero_hlo_r05.txt")
    with open(out_path, "w") as f:
        f.write(
            "Collective scheduling in the compiled grad_sync='zero' "
            "ResNet-50 dist step\n"
            "Pipeline: %s (tools/dist_schedule_evidence.py)\n\n"
            "What this shows: the post-scheduling HLO the TPU runtime "
            "executes.  Async\ncollective start/done pairs with compute "
            "fusions scheduled between them ARE\nthe latency-hiding "
            "scheduler overlapping comm with compute; "
            "all-reduce-scatter\nkCustom fusions with several gradient "
            "tensors are XLA's bucketed gradient\nreduce-scatter; bf16 "
            "all-gathers show the mixed-precision gather of the f32\n"
            "master params.\n\nSummary:\n" % pipeline)
        for k in ("entry_instructions", "n_async_pairs",
                  "n_async_pairs_with_compute_between",
                  "compute_ops_inside_collective_windows",
                  "median_compute_between",
                  "n_bucketed_reduce_scatter_fusions",
                  "bucket_tensor_counts", "bucket_example_shapes",
                  "n_plain_reduce_scatter", "all_gather_dtypes",
                  "op_counts"):
            f.write("  %s: %s\n" % (k, a[k]))
        f.write("\nAsync spans (op, schedule distance, compute between):\n")
        for s in a["async_spans"]:
            f.write("  %-28s span %5d  compute_between %4d\n"
                    % (s["op"], s["span"], s["compute_between"]))
    summary = {k: a[k] for k in
               ("pipeline", "n_async_pairs",
                "n_async_pairs_with_compute_between",
                "compute_ops_inside_collective_windows",
                "n_bucketed_reduce_scatter_fusions",
                "n_plain_reduce_scatter")}
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
