"""Gradient-allreduce overlap model driven by MEASURED per-layer backward
times from the real chip.

Round 3's scaling model credited a single assumed 1.6 ms "overlap window".
This replaces the assumption with data: the per-layer device times of a
ResNet-50 fused training step measured on the v5e chip
(docs/profiles/resnet50_fused_step_per_op.txt, produced by
mx.profiler over XLA HLO metadata) define WHEN each layer's gradient
becomes available during the backward pass; each gradient bucket's
allreduce is then laid onto the ICI timeline (bandwidth from the v5e
spec) the way XLA's latency-hiding scheduler does — comm for layer i can
start once grad_i exists, buckets serialize on the link, and only comm
finishing after the last backward op is EXPOSED time.

Outputs one JSON blob (consumed by SCALING_r04.json) with the exposed-ms
and weak-scaling efficiency at N=8 and N=64.
"""
import json
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


# Profile rows that are neither forward-layer nor backward-layer compute:
# device bookkeeping (copies, async markers, layout changes, optimizer/
# parameter updates, unattributed jvp wrappers).  Excluded from BOTH
# overlap windows — conservative, since in reality these interleave
# through the step and widen the windows.
_BOOKKEEPING = re.compile(
    r"^(copy|async|data formatting|opt_state|params|aux|jvp\(\)$)")


def parse_profile(path, n_steps=3):
    """-> per-STEP device microseconds per layer: {layer: us} for
    _backward_* rows and for forward-layer rows.  Uses the Total-us
    column divided by the number of profiled steps, so layers that XLA
    splits into several HLO instances per step are fully counted.

    Row classification (round-5 correction — the round-4 version lumped
    bookkeeping rows into the forward window): ``_backward_*`` and
    ``transpose(jvp...`` rows are backward; bookkeeping rows (copy-done,
    async-done, data formatting, opt_state/param updates, bare jvp())
    are dropped from both windows; everything else (conv/bn/relu/add/
    pool/cast layer rows) is forward compute."""
    bwd, fwd = {}, {}
    for line in open(path):
        m = re.match(r"(.+?)\s+\d+\s+([\d.]+)\s+[\d.]+\s+[\d.]+\s+[\d.]+\s*$",
                     line)
        if not m:
            continue
        name, per_step = m.group(1).strip(), float(m.group(2)) / n_steps
        if name.startswith("_backward_"):
            bwd[name[len("_backward_"):]] = bwd.get(
                name[len("_backward_"):], 0.0) + per_step
        elif "transpose(jvp" in name:
            bwd["_transposes"] = bwd.get("_transposes", 0.0) + per_step
        elif _BOOKKEEPING.match(name):
            pass
        else:
            fwd[name] = fwd.get(name, 0.0) + per_step
    return fwd, bwd


def layer_param_bytes(dtype_bytes=2):
    """Per-named-layer parameter bytes of resnet-50 (bf16 grads)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from mxnet_tpu import models
    sym = models.get_symbol("resnet-50", num_classes=1000)
    arg_shapes, _, aux_shapes = sym.infer_shape(data=(32, 3, 224, 224))
    out = {}
    for name, shp in zip(sym.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        base = re.sub(r"_(weight|bias|gamma|beta)$", "", name)
        n = 1
        for d in shp:
            n *= d
        out[base] = out.get(base, 0) + n * dtype_bytes
    return out


def simulate(profile_path, n_devices, ici_gbps, hops_factor=1.0,
             time_scale=1.0, _cache={}):
    """Bucketed-allreduce timeline simulation.  ``time_scale`` calibrates
    the profiled per-layer times to unprofiled wall-clock: profiling on
    this backend inflates device durations ~5x (profiled step 13.9 ms vs
    2.4-2.9 ms wall, measured 2026-07-30), so the per-layer DISTRIBUTION
    comes from the profile and the absolute scale from the wall clock."""
    if profile_path not in _cache:
        _cache[profile_path] = (parse_profile(profile_path),
                                layer_param_bytes())
    (fwd, bwd), pbytes = _cache[profile_path]
    bwd = {k: v * time_scale for k, v in bwd.items()}
    # backward completion order: output-side layers first.  The profile
    # doesn't carry start timestamps, so order backward rows by reversed
    # forward topological position — approximate topo order = the order
    # forward rows appear in resnet symbol arguments.
    order = [l for l in pbytes if l in bwd]
    # reversed: loss-side first
    order = list(reversed(order))
    t = 0.0
    link_free = 0.0
    exposed_end = 0.0
    ar_factor = 2.0 * (n_devices - 1) / n_devices   # ring allreduce bytes
    total_comm = 0.0
    for layer in order:
        t += bwd[layer] / 1e3          # us -> ms backward compute
        comm_ms = (pbytes.get(layer, 0) * ar_factor * hops_factor
                   / (ici_gbps * 1e9)) * 1e3
        total_comm += comm_ms
        start = max(t, link_free)
        link_free = start + comm_ms
    t_backward_end = t
    # layers with params but no measured bwd row (fused away): add their
    # comm at the end (conservative)
    for layer, b in pbytes.items():
        if layer not in bwd:
            comm_ms = (b * ar_factor * hops_factor / (ici_gbps * 1e9)) * 1e3
            total_comm += comm_ms
            link_free = max(link_free, t_backward_end) + comm_ms
    exposed = max(0.0, link_free - t_backward_end)
    return {
        "n_devices": n_devices,
        "t_backward_measured_ms": round(t_backward_end, 3),
        "t_comm_total_ms": round(total_comm, 3),
        "t_comm_exposed_ms": round(exposed, 3),
        "overlap_fraction": round(1.0 - exposed / total_comm, 3)
        if total_comm else 1.0,
    }


def simulate_zero(profile_path, n_devices, ici_gbps, hops_factor=1.0,
                  time_scale=1.0, _cache={}):
    """Weight-sharded-DP (grad_sync='zero') timeline: parameter
    AllGathers lay onto the link from step start and overlap the forward
    pass (fwd of layer i waits for AG_i); gradient ReduceScatters issue
    as each grad is produced during backward.  Each collective moves
    (N-1)/N of the param bytes — half the ring-allreduce volume per
    phase, and the two phases overlap DIFFERENT compute (fwd vs bwd), so
    the exposable comm per phase is halved vs allreduce-after-backward.
    """
    if profile_path not in _cache:
        _cache[profile_path] = (parse_profile(profile_path),
                                layer_param_bytes())
    (fwd, bwd), pbytes = _cache[profile_path]
    fwd = {k: v * time_scale for k, v in fwd.items()}
    bwd = {k: v * time_scale for k, v in bwd.items()}
    phase_factor = (n_devices - 1) / n_devices    # RS or AG bytes
    ms_of = lambda b: (b * phase_factor * hops_factor
                       / (ici_gbps * 1e9)) * 1e3

    # Non-param ops (relu/pool/add/softmax) execute adjacent to their
    # layers in topo order and widen the overlap window; the profile
    # doesn't attribute them per-position, so spread each phase's
    # non-param time proportionally over the param layers.
    order = [l for l in pbytes]                    # fwd topo order

    def stretch(times):
        counted = sum(times.get(l, 0.0) for l in order)
        total = sum(times.values())
        return (total / counted) if counted else 1.0

    fscale, bscale = stretch(fwd), stretch(bwd)

    # ---- forward: AGs issue back-to-back from t=0 in topo order; layer
    # i's fwd compute waits for its own AG
    t = 0.0
    link = 0.0
    for layer in order:
        link += ms_of(pbytes[layer])
        t = max(t, link) + fwd.get(layer, 0.0) * fscale / 1e3
    t_fwd_compute = sum(fwd.values()) / 1e3
    fwd_exposed = max(0.0, t - t_fwd_compute)

    # ---- backward: loss-side first; RS_i starts once grad_i exists.
    # Layers with no measured backward row are excluded here and billed
    # at the end (tail loop) — once, not twice.
    t = 0.0
    link = 0.0
    for layer in reversed(order):
        if layer not in bwd:
            continue
        t += bwd[layer] * bscale / 1e3
        link = max(t, link) + ms_of(pbytes[layer])
    t_bwd_compute = t
    for layer, b in pbytes.items():               # unprofiled tail
        if layer not in bwd:
            link = max(link, t_bwd_compute) + ms_of(b)
    bwd_exposed = max(0.0, link - t_bwd_compute)

    total_comm = 2 * sum(ms_of(b) for b in pbytes.values())
    exposed = fwd_exposed + bwd_exposed
    return {
        "n_devices": n_devices,
        "t_fwd_measured_ms": round(t_fwd_compute, 3),
        "t_backward_measured_ms": round(t_bwd_compute, 3),
        "t_comm_total_ms": round(total_comm, 3),
        "t_comm_exposed_ms": round(exposed, 3),
        "t_fwd_exposed_ms": round(fwd_exposed, 3),
        "t_bwd_exposed_ms": round(bwd_exposed, 3),
        "overlap_fraction": round(1.0 - exposed / total_comm, 3)
        if total_comm else 1.0,
    }


def main():
    here = os.path.dirname(os.path.abspath(__file__))
    prof = os.path.join(here, "..", "docs", "profiles",
                        "resnet50_fused_step_per_op.txt")
    fwd, bwd = parse_profile(prof)
    t_fwd = sum(fwd.values()) / 1e3
    t_bwd = sum(bwd.values()) / 1e3
    profiled_step_ms = 13.9       # jit_step device span while profiling
    # Round-5 correction: the profiler's absolute scale was right all
    # along — the "2.4-2.9 ms wall step" it was being calibrated against
    # was the broken block_until_ready dispatch-rate number (a 2.4 ms
    # b32 ResNet-50 step would exceed chip peak FLOP/s).  Fetch-synced
    # work-scaling measures 13.9 ms/step (2,299 img/s, 28.6% MFU,
    # BENCH r05), matching the profiled span; scale is therefore ~1.
    wall_step_ms = float(os.environ.get("OVERLAP_WALL_STEP_MS", "13.9"))
    scale = wall_step_ms / profiled_step_ms
    bw = float(os.environ.get("OVERLAP_ICI_GBPS", "90"))  # bidir ring 2x45
    bw_low = float(os.environ.get("OVERLAP_ICI_GBPS_LOW", "45"))  # one-way
    out = {
        "source_profile": "docs/profiles/resnet50_fused_step_per_op.txt",
        "profiled_fwd_ms": round(t_fwd, 3),
        "profiled_bwd_ms": round(t_bwd, 3),
        "profiled_step_ms": profiled_step_ms,
        "wall_step_ms": wall_step_ms,
        "time_scale_calibration": round(scale, 4),
        "ici_allreduce_GBps": bw,
        "ici_allreduce_GBps_conservative": bw_low,
        "n8": simulate(prof, 8, bw, time_scale=scale),
        "n64": simulate(prof, 64, bw, time_scale=scale),
        "n8_conservative": simulate(prof, 8, bw_low, time_scale=scale),
        "n64_conservative": simulate(prof, 64, bw_low, time_scale=scale),
        # grad_sync='zero' (weight-sharded DP): AG under forward, RS
        # under backward — the mode that must clear >=0.85 at the
        # conservative single-axis one-way bandwidth
        "n8_zero": simulate_zero(prof, 8, bw, time_scale=scale),
        "n64_zero": simulate_zero(prof, 64, bw, time_scale=scale),
        "n8_zero_conservative": simulate_zero(prof, 8, bw_low,
                                              time_scale=scale),
        "n64_zero_conservative": simulate_zero(prof, 64, bw_low,
                                               time_scale=scale),
    }
    for key in out:
        if not key.startswith("n"):
            continue
        r = out[key]
        step = wall_step_ms
        r["weak_scaling_efficiency"] = round(
            step / (step + r["t_comm_exposed_ms"]), 3)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
