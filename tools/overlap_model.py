"""Gradient-allreduce overlap model driven by MEASURED per-layer backward
times from the real chip.

Round 3's scaling model credited a single assumed 1.6 ms "overlap window".
This replaces the assumption with data: the per-layer device times of a
ResNet-50 fused training step measured on the v5e chip
(docs/profiles/resnet50_fused_step_per_op.txt, produced by
mx.profiler over XLA HLO metadata) define WHEN each layer's gradient
becomes available during the backward pass; each gradient bucket's
allreduce is then laid onto the ICI timeline (bandwidth from the v5e
spec) the way XLA's latency-hiding scheduler does — comm for layer i can
start once grad_i exists, buckets serialize on the link, and only comm
finishing after the last backward op is EXPOSED time.

Outputs one JSON blob (consumed by SCALING_r04.json) with the exposed-ms
and weak-scaling efficiency at N=8 and N=64.
"""
import json
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def parse_profile(path, n_steps=3):
    """-> per-STEP device microseconds per layer: {layer: us} for
    _backward_* rows and for forward rows.  Uses the Total-us column
    divided by the number of profiled steps, so layers that XLA splits
    into several HLO instances per step are fully counted."""
    bwd, fwd = {}, {}
    for line in open(path):
        m = re.match(r"(\S+)\s+\d+\s+([\d.]+)\s+[\d.]+\s+[\d.]+\s+[\d.]+\s*$",
                     line)
        if not m:
            continue
        name, per_step = m.group(1), float(m.group(2)) / n_steps
        if name.startswith("_backward_"):
            bwd[name[len("_backward_"):]] = bwd.get(
                name[len("_backward_"):], 0.0) + per_step
        else:
            fwd[name] = fwd.get(name, 0.0) + per_step
    return fwd, bwd


def layer_param_bytes(dtype_bytes=2):
    """Per-named-layer parameter bytes of resnet-50 (bf16 grads)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from mxnet_tpu import models
    sym = models.get_symbol("resnet-50", num_classes=1000)
    arg_shapes, _, aux_shapes = sym.infer_shape(data=(32, 3, 224, 224))
    out = {}
    for name, shp in zip(sym.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        base = re.sub(r"_(weight|bias|gamma|beta)$", "", name)
        n = 1
        for d in shp:
            n *= d
        out[base] = out.get(base, 0) + n * dtype_bytes
    return out


def simulate(profile_path, n_devices, ici_gbps, hops_factor=1.0,
             time_scale=1.0, _cache={}):
    """Bucketed-allreduce timeline simulation.  ``time_scale`` calibrates
    the profiled per-layer times to unprofiled wall-clock: profiling on
    this backend inflates device durations ~5x (profiled step 13.9 ms vs
    2.4-2.9 ms wall, measured 2026-07-30), so the per-layer DISTRIBUTION
    comes from the profile and the absolute scale from the wall clock."""
    if profile_path not in _cache:
        _cache[profile_path] = (parse_profile(profile_path),
                                layer_param_bytes())
    (fwd, bwd), pbytes = _cache[profile_path]
    bwd = {k: v * time_scale for k, v in bwd.items()}
    # backward completion order: output-side layers first.  The profile
    # doesn't carry start timestamps, so order backward rows by reversed
    # forward topological position — approximate topo order = the order
    # forward rows appear in resnet symbol arguments.
    order = [l for l in pbytes if l in bwd]
    # reversed: loss-side first
    order = list(reversed(order))
    t = 0.0
    link_free = 0.0
    exposed_end = 0.0
    ar_factor = 2.0 * (n_devices - 1) / n_devices   # ring allreduce bytes
    total_comm = 0.0
    for layer in order:
        t += bwd[layer] / 1e3          # us -> ms backward compute
        comm_ms = (pbytes.get(layer, 0) * ar_factor * hops_factor
                   / (ici_gbps * 1e9)) * 1e3
        total_comm += comm_ms
        start = max(t, link_free)
        link_free = start + comm_ms
    t_backward_end = t
    # layers with params but no measured bwd row (fused away): add their
    # comm at the end (conservative)
    for layer, b in pbytes.items():
        if layer not in bwd:
            comm_ms = (b * ar_factor * hops_factor / (ici_gbps * 1e9)) * 1e3
            total_comm += comm_ms
            link_free = max(link_free, t_backward_end) + comm_ms
    exposed = max(0.0, link_free - t_backward_end)
    return {
        "n_devices": n_devices,
        "t_backward_measured_ms": round(t_backward_end, 3),
        "t_comm_total_ms": round(total_comm, 3),
        "t_comm_exposed_ms": round(exposed, 3),
        "overlap_fraction": round(1.0 - exposed / total_comm, 3)
        if total_comm else 1.0,
    }


def main():
    here = os.path.dirname(os.path.abspath(__file__))
    prof = os.path.join(here, "..", "docs", "profiles",
                        "resnet50_fused_step_per_op.txt")
    fwd, bwd = parse_profile(prof)
    t_fwd = sum(fwd.values()) / 1e3
    t_bwd = sum(bwd.values()) / 1e3
    profiled_step_ms = 13.9       # jit_step device span while profiling
    wall_step_ms = float(os.environ.get("OVERLAP_WALL_STEP_MS", "2.9"))
    scale = wall_step_ms / profiled_step_ms
    bw = float(os.environ.get("OVERLAP_ICI_GBPS", "90"))  # bidir ring 2x45
    out = {
        "source_profile": "docs/profiles/resnet50_fused_step_per_op.txt",
        "profiled_fwd_ms": round(t_fwd, 3),
        "profiled_bwd_ms": round(t_bwd, 3),
        "profiled_step_ms": profiled_step_ms,
        "wall_step_ms": wall_step_ms,
        "time_scale_calibration": round(scale, 4),
        "ici_allreduce_GBps": bw,
        "n8": simulate(prof, 8, bw, time_scale=scale),
        "n64": simulate(prof, 64, bw, time_scale=scale),
    }
    for key in ("n8", "n64"):
        r = out[key]
        step = wall_step_ms
        r["weak_scaling_efficiency"] = round(
            step / (step + r["t_comm_exposed_ms"]), 3)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
