#!/usr/bin/env python
"""mxfleet daemon: a multi-replica serving fleet behind one routing
front end (docs/how_to/fleet.md).

::

    # build the AOT warm store (pre-compile every model x bucket)
    python tools/fleet.py warmup --model mlp=/ckpts/mlp:3 \\
        --input-shape mlp:data=784 --warm-store /run/fleet-warm

    # serve: N replica daemons + the router on the public port
    python tools/fleet.py serve --model mlp=/ckpts/mlp:3 \\
        --input-shape mlp:data=784 --replicas 2 --port 8200 \\
        --warm-store /run/fleet-warm [--manifest fleet.json] \\
        [--device-sets cpu|tpu:0,1;2,3] [--buckets 1,2,4,8] \\
        [--run-dir DIR] [--port-file F] [--max-restarts N] \\
        [--workers N] [--autoscale]

``--workers N`` (default ``MXTPU_FLEET_WORKERS``) SHARDS the front
end: N router worker processes accept on the SAME public port via
SO_REUSEPORT, each routing off the shared fleet-view snapshot ONE
controller-side prober publishes (fleet/view.py) — the single-router
dispatch ceiling multiplies by N.  ``--autoscale`` closes the loop on
the aggregated est_wait_ms signal (fleet/autoscale.py): scale-up via
warm AOT bring-up, scale-down via fence -> drain -> stop.  The
``router-worker`` subcommand is the worker binary (spawned by
``serve``, not run by hand).

Model/shape specs are the ``tools/serve.py`` formats; ``--manifest``
loads the same fields from JSON (flags override).  ``serve`` builds a
missing warm store first, spawns the replicas (each a real
``tools/serve.py`` process pinned to its device subset, supervised by
the exit-code discipline — 85/87 relaunch with resume, other deaths
respawn within a budget), runs one router health pass, writes
``--port-file`` and serves.  SIGTERM fences new work on the public
port, drains the router's in-flight forwards, then forwards the drain
to every replica (each exits 0) and exits 0.

IMPORT DISCIPLINE: this process NEVER imports jax — a router that
spun up an XLA client would steal the device its replicas need (the
``tools/supervise.py`` lesson).  The fleet package is jax-free by
design; it is imported through the synthetic-package stub below (the
``tools/mxlint.py`` idiom) so ``mxnet_tpu/__init__`` never executes.
"""
import argparse
import importlib.machinery
import json
import os
import sys
import types

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)


def _bootstrap():
    """Install the package-path stub and import the jax-free leaves."""
    if "mxnet_tpu" not in sys.modules:
        pkg = types.ModuleType("mxnet_tpu")
        pkg.__path__ = [os.path.join(_ROOT, "mxnet_tpu")]
        pkg.__spec__ = importlib.machinery.ModuleSpec(
            "mxnet_tpu", None, is_package=True)
        pkg.__spec__.submodule_search_locations = pkg.__path__
        sys.modules["mxnet_tpu"] = pkg
    from mxnet_tpu import fleet
    return fleet


def _build_manifest(fleet, args):
    if args.manifest:
        man = fleet.FleetManifest.from_file(args.manifest)
        if args.model:          # flags override/extend the file
            over = fleet.FleetManifest.from_flags(
                args.model, args.input_shape, replicas=man.replicas)
            man.models.update(over.models)
        if args.replicas is not None:
            man.replicas = int(args.replicas)
        if args.buckets is not None:
            man.buckets = args.buckets
        if args.device_sets is not None:
            man.device_sets = args.device_sets
        return man
    if not args.model:
        raise SystemExit("need --model (or --manifest)")
    return fleet.FleetManifest.from_flags(
        args.model, args.input_shape, replicas=args.replicas,
        buckets=args.buckets, device_sets=args.device_sets)


def _add_manifest_flags(p):
    p.add_argument("--manifest", default=None,
                   help="fleet manifest JSON (flags override)")
    p.add_argument("--model", action="append", default=[],
                   metavar="NAME=PREFIX:EPOCH|NAME=DIR",
                   help="model to serve (repeatable; serve.py format)")
    p.add_argument("--input-shape", action="append", default=[],
                   metavar="[MODEL:]INPUT=D1,D2,...",
                   help="per-sample input shape (repeatable)")
    p.add_argument("--replicas", type=int, default=None,
                   help="replica daemon count (default "
                        "MXTPU_FLEET_REPLICAS)")
    p.add_argument("--buckets", default=None,
                   help="override MXTPU_SERVE_BUCKETS for every replica")
    p.add_argument("--device-sets", default=None,
                   help="device placement: 'cpu' or 'tpu:0,1;2,3' "
                        "(replica i -> chip set i)")
    p.add_argument("--warm-store", default=None,
                   help="AOT warm store directory (MXTPU_COMPILE_CACHE "
                        "for every replica; `serve` builds it when "
                        "missing)")


def _log(msg):
    sys.stderr.write(msg + "\n")
    sys.stderr.flush()


def _cmd_warmup(fleet, args):
    man = _build_manifest(fleet, args)
    if not args.warm_store:
        raise SystemExit("warmup needs --warm-store DIR")
    doc = fleet.build_warm_store(man, args.warm_store, log=_log,
                                 force=args.force)
    print(json.dumps(doc, sort_keys=True))
    return 0


def _cmd_serve(fleet, args):
    man = _build_manifest(fleet, args)
    if args.warm_store and \
            fleet.warm_store_manifest(args.warm_store) is None:
        fleet.build_warm_store(man, args.warm_store, log=_log)
    if args.run_dir:
        run_dir = args.run_dir
    elif args.warm_store:
        run_dir = os.path.join(args.warm_store,
                               "fleet-run-%d" % os.getpid())
    else:
        import tempfile
        run_dir = tempfile.mkdtemp(prefix="mxfleet_run_")
    workers_n = args.workers
    if workers_n is None:
        workers_n = man.router_workers
    if workers_n is None:
        from mxnet_tpu.base import get_env as _get_env
        workers_n = int(_get_env(fleet.ENV_FLEET_WORKERS))
    man.router_workers = int(workers_n)
    env_by_rid = {}
    for spec in getattr(args, "replica_env", []):
        try:
            rid, assign = spec.split(":", 1)
            name, value = assign.split("=", 1)
        except ValueError:
            raise SystemExit("--replica-env wants RID:NAME=VALUE, "
                             "got %r" % spec)
        env_by_rid.setdefault(int(rid), {})[name] = value
    controller = fleet.ReplicaController(
        man, run_dir, warm_store=args.warm_store,
        max_restarts=args.max_restarts, extra_env_by_rid=env_by_rid,
        log=_log)
    # sharded mode: this router never serves HTTP — it is the
    # controller-side PROBER (health loop, fence state, capacity
    # floor) behind the view publisher; port 0 keeps the public port
    # free for the reuseport worker shard
    router = fleet.FleetRouter(controller, man, host=args.host,
                               port=args.port if workers_n <= 1 else 0,
                               slo_ms=args.slo_ms)
    # a SIGTERM during the (possibly long) replica bring-up must drain
    # the already-spawned replicas to rc 0 and exit 0 — the full router
    # drain path only takes over once bring-up completed (its server
    # does not exist yet, and the controller drain makes wait_ready
    # bail instead of sitting out --ready-timeout)
    import signal as _signal
    import threading as _threading
    early_drain = _threading.Event()

    def _on_early_signal(signum, frame):
        early_drain.set()
        _threading.Thread(target=router.drain_and_stop,
                          name="mxfleet-early-drain",
                          daemon=True).start()
    for _sig in (_signal.SIGTERM, _signal.SIGINT):
        _signal.signal(_sig, _on_early_signal)
    controller.start()
    try:
        controller.wait_ready(timeout=args.ready_timeout)
    except Exception as e:  # noqa: BLE001 — bring-up failed: clean up
        if early_drain.is_set():
            _log("fleet: drained during bring-up — exiting 0")
            return 0
        _log("fleet: bring-up failed: %s" % e)
        controller.kill()
        return 1
    if early_drain.is_set():
        _log("fleet: drained during bring-up — exiting 0")
        return 0
    if workers_n > 1:
        return _serve_sharded(fleet, args, man, run_dir, controller,
                              router, int(workers_n))
    router.install_signal_handlers()
    router.start()          # binds + one synchronous probe pass
    if args.watch:
        # rolling hot swap: tail every checkpoint-DIRECTORY model and
        # roll verified new epochs one replica at a time
        # (docs/how_to/fleet.md "Rolling deployment"; jax-free like
        # the rest of this process)
        watched = {name: spec["target"]
                   for name, spec in man.models.items()
                   if os.path.isdir(spec["target"])}
        if watched:
            fleet.RollingSwap(router, watched, log=_log).start()
            _log("fleet: watching %s for new epochs"
                 % sorted(watched.values()))
        else:
            _log("fleet: --watch: no checkpoint-directory models in "
                 "the manifest — nothing to watch")
    if args.autoscale:
        fleet.Autoscaler(controller, router, log=_log).start()
        _log("fleet: autoscaler on (replica bounds via "
             "MXTPU_FLEET_MIN/MAX_REPLICAS)")
    _log("fleet: %d replica(s) ready; router on %s:%d (models: %s)"
         % (man.replicas, router.host, router.port, man.names()))
    if args.port_file:
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write("%s:%d" % (router.host, router.port))
        os.replace(tmp, args.port_file)
    router.serve_forever()
    if router.draining and router.replica_rcs is None:
        # the drain thread may still be collecting replica exits
        import time as _time
        deadline = _time.monotonic() + 120
        while router.replica_rcs is None and \
                _time.monotonic() < deadline:
            _time.sleep(0.1)
    rcs = router.replica_rcs or {}
    _log("fleet: drained — replica exit codes %s"
         % {k: rcs[k] for k in sorted(rcs)})
    return 0 if all(rc == 0 for rc in rcs.values()) else 1


def _serve_sharded(fleet, args, man, run_dir, controller, prober,
                   workers_n):
    """The sharded front end: publish the fleet view off ``prober``
    (which never serves HTTP), reserve the public port, spawn
    ``workers_n`` reuseport router workers, optionally close the
    autoscale loop, then park until SIGTERM and drain everything in
    dependency order (workers first — they stop ANSWERING; replicas
    last — they stop COMPUTING)."""
    import signal as _signal
    import threading as _threading
    from mxnet_tpu.fleet.view import VIEW_BASENAME
    view_path = os.path.join(run_dir, VIEW_BASENAME)
    manifest_path = os.path.join(run_dir, "manifest.json")
    man.save(manifest_path)
    if args.watch:
        watched = {name: spec["target"]
                   for name, spec in man.models.items()
                   if os.path.isdir(spec["target"])}
        if watched:
            fleet.RollingSwap(prober, watched, log=_log).start()
            _log("fleet: watching %s for new epochs"
                 % sorted(watched.values()))
    publisher = fleet.FleetViewPublisher(prober, view_path,
                                         log=_log).start()
    autoscaler = None
    if args.autoscale:
        autoscaler = fleet.Autoscaler(controller, prober,
                                      publisher=publisher,
                                      log=_log).start()
        _log("fleet: autoscaler on (replica bounds via "
             "MXTPU_FLEET_MIN/MAX_REPLICAS)")
    sock, port = fleet.reserve_port(args.host, args.port)
    wset = fleet.RouterWorkerSet(
        manifest_path, view_path, args.host, port, workers_n, run_dir,
        slo_ms=args.slo_ms, log=_log)
    stop = _threading.Event()

    def _on_signal(signum, frame):
        stop.set()
    for _sig in (_signal.SIGTERM, _signal.SIGINT):
        _signal.signal(_sig, _on_signal)
    failed = False
    try:
        wset.start()
        wset.wait_ready(timeout=60.0)
        _log("fleet: %d replica(s) ready; %d router worker(s) on "
             "%s:%d (models: %s)" % (man.replicas, workers_n,
                                     args.host, port, man.names()))
        if args.port_file:
            tmp = args.port_file + ".tmp"
            with open(tmp, "w") as f:
                f.write("%s:%d" % (args.host, port))
            os.replace(tmp, args.port_file)
        stop.wait()
    except Exception as e:  # noqa: BLE001 — bring-up failed: clean up
        _log("fleet: sharded bring-up failed: %s" % e)
        failed = True
    if autoscaler is not None:
        autoscaler.stop()
    wrcs = wset.drain()
    publisher.stop()
    rrcs = controller.drain()
    sock.close()
    _log("fleet: drained — worker exit codes %s, replica exit codes %s"
         % ({k: wrcs[k] for k in sorted(wrcs)},
            {k: rrcs[k] for k in sorted(rrcs)}))
    ok = all(rc == 0 for rc in wrcs.values()) and \
        all(rc == 0 for rc in rrcs.values())
    return 0 if (ok and not failed) else 1


def _cmd_router_worker(fleet, args):
    """One reuseport router worker (spawned by ``serve --workers N``):
    route off the shared view snapshot, never probe, dump counters for
    the sibling /stats merge, drain on SIGTERM."""
    man = fleet.FleetManifest.from_file(args.manifest_file)
    reader = fleet.FleetViewReader(args.view)
    router = fleet.FleetRouter(
        reader, man, host=args.host, port=args.port,
        spill_queue=args.spill_queue, slo_ms=args.slo_ms,
        request_timeout=args.request_timeout, reuse_port=True,
        worker_id=args.worker_id, run_dir=args.run_dir)
    router.install_signal_handlers()
    _log("fleet: router worker %d on %s:%d (pid %d)"
         % (args.worker_id, args.host, args.port, os.getpid()))
    router.serve_forever()
    return 0


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="multi-replica serving fleet "
                    "(docs/how_to/fleet.md)")
    sub = parser.add_subparsers(dest="cmd")

    p_warm = sub.add_parser("warmup", help="build the AOT warm store")
    _add_manifest_flags(p_warm)
    p_warm.add_argument("--force", action="store_true",
                        help="rebuild even if the store marker exists")

    p_serve = sub.add_parser("serve", help="run the fleet")
    _add_manifest_flags(p_serve)
    p_serve.add_argument("--host", default="127.0.0.1")
    p_serve.add_argument("--port", type=int, default=8200,
                         help="the router's public port (0 = ephemeral; "
                              "see --port-file)")
    p_serve.add_argument("--port-file", default=None,
                         help="write 'host:port' here once the fleet "
                              "is ready")
    p_serve.add_argument("--run-dir", default=None,
                         help="replica port files + logs (default: "
                              "under --warm-store or cwd)")
    p_serve.add_argument("--replica-env", action="append", default=[],
                         metavar="RID:NAME=VALUE",
                         help="extra env for ONE replica (repeatable) "
                              "— e.g. 0:MXTPU_FAULTS=slow_replica:100 "
                              "arms a fault on replica 0 only (chaos "
                              "drills, bench.py tail)")
    p_serve.add_argument("--max-restarts", type=int, default=3,
                         help="per-replica consecutive-relaunch budget")
    p_serve.add_argument("--slo-ms", type=float, default=0.0,
                         help="spill when the home replica's estimated "
                              "wait exceeds this (0 = depth-only)")
    p_serve.add_argument("--ready-timeout", type=float, default=600.0,
                         help="seconds to wait for every replica's "
                              "bring-up")
    p_serve.add_argument("--watch", action="store_true",
                         help="tail each checkpoint-directory model "
                              "and roll verified new epochs across "
                              "the replicas one at a time "
                              "(MXTPU_SWAP_* knobs; docs/how_to/"
                              "fleet.md 'Rolling deployment')")
    p_serve.add_argument("--workers", type=int, default=None,
                         help="router worker processes sharing the "
                              "public port via SO_REUSEPORT (default "
                              "manifest router_workers, then "
                              "MXTPU_FLEET_WORKERS; 1 = in-line "
                              "single-process router)")
    p_serve.add_argument("--autoscale", action="store_true",
                         help="close the autoscale loop on the "
                              "aggregated est_wait_ms signal "
                              "(MXTPU_FLEET_SCALE_* / MIN/MAX_REPLICAS "
                              "knobs; scale-down is fence -> drain -> "
                              "stop)")

    p_rw = sub.add_parser("router-worker",
                          help="one reuseport router worker (spawned "
                               "by `serve --workers N`, not run by "
                               "hand)")
    p_rw.add_argument("--manifest-file", required=True,
                      help="the manifest JSON `serve` saved under the "
                           "run dir")
    p_rw.add_argument("--view", required=True,
                      help="the shared fleet-view snapshot path")
    p_rw.add_argument("--host", default="127.0.0.1")
    p_rw.add_argument("--port", type=int, required=True,
                      help="the reserved public port (every worker "
                           "binds it with SO_REUSEPORT)")
    p_rw.add_argument("--worker-id", type=int, required=True)
    p_rw.add_argument("--run-dir", required=True,
                      help="where this worker dumps its counters for "
                           "the sibling /stats merge")
    p_rw.add_argument("--slo-ms", type=float, default=0.0)
    p_rw.add_argument("--request-timeout", type=float, default=60.0)
    p_rw.add_argument("--spill-queue", type=int, default=None)

    args = parser.parse_args(argv)
    if not args.cmd:
        parser.error("need a subcommand: serve, warmup or "
                     "router-worker")
    fleet = _bootstrap()
    from mxnet_tpu.base import MXNetError
    try:
        if args.cmd == "warmup":
            return _cmd_warmup(fleet, args)
        if args.cmd == "router-worker":
            return _cmd_router_worker(fleet, args)
        return _cmd_serve(fleet, args)
    except MXNetError as e:
        _log("fleet: error: %s" % e)
        return 1


if __name__ == "__main__":
    sys.exit(main())
