#!/usr/bin/env python
"""Supervised launcher: relaunch-and-resume for preemptible training.

The runtime side of the survival story lives in ``mxnet_tpu.resilience``:
graceful preemption saves a mid-epoch checkpoint and exits with code 85,
the hung-step watchdog dumps stacks and aborts with code 87.  This is the
matching driver — the reference had nothing like it (a dead ps-lite
worker was an operator page); cloud schedulers restart the *container*,
but something inside still has to turn "restarted" into "resumed".

::

    python tools/supervise.py [--max-restarts N] [--backoff S]
        [--retry-any] -- python train.py ...

Policy (exit-code-aware):

- 0: training finished — exit 0.
- 85 (preempt: a checkpoint was just saved) or 87 (watchdog: the run
  hung and aborted): relaunch the command with ``MXTPU_RESUME=1`` in its
  environment, which ``fit(checkpoint=...)`` reads as ``resume=True`` —
  until the restart budget is spent.
- anything else (real crash, OOM-kill, assertion): propagate the exit
  code immediately, unless ``--retry-any`` opts those into the same
  relaunch budget (for flaky infra where any death is worth one retry).

A SIGTERM/SIGINT delivered to the SUPERVISOR is forwarded to the child
(giving its preemption handler the chance to checkpoint), the child's
exit is awaited, and the supervisor exits with the child's code — when
the whole allocation is being preempted there is nobody left to relaunch
for.

The exit codes are duplicated here rather than imported: the supervisor
must stay import-light (importing mxnet_tpu spins up a JAX client, which
on single-chip hosts would steal the device from the child it is about
to spawn).  ``tests/test_chaos.py`` asserts they match
``mxnet_tpu.resilience``.
"""
import argparse
import os
import signal
import subprocess
import sys
import threading
import time

# keep in sync with mxnet_tpu/resilience.py (asserted by test_chaos.py)
PREEMPT_EXIT_CODE = 85
WATCHDOG_EXIT_CODE = 87

RESUME_ENV = "MXTPU_RESUME"


def relaunch_decision(rc, restarts, max_restarts, retry_any=False):
    """The exit-code policy, shared by the blocking :func:`supervise`
    loop and the role-oriented :class:`Supervisor`: returns
    ``(verdict, why)`` with verdict one of ``"done"`` (rc 0),
    ``"relaunch"`` (preempt/watchdog — or any death under
    ``retry_any`` — with budget left) or ``"propagate"``."""
    if rc == 0:
        return "done", "completed"
    resumable = rc in (PREEMPT_EXIT_CODE, WATCHDOG_EXIT_CODE)
    why = {PREEMPT_EXIT_CODE: "graceful preemption",
           WATCHDOG_EXIT_CODE: "watchdog abort (hung step)"}.get(
               rc, "exit code %d" % rc)
    if not resumable and not retry_any:
        return "propagate", why + " (not a preempt/watchdog code)"
    if restarts >= max_restarts:
        return "propagate", why + " (restart budget %d spent)" \
            % max_restarts
    return "relaunch", why


class Supervisor(object):
    """The :func:`supervise` policy as a NON-BLOCKING object: one
    instance per role, each with its own monitor thread, so a composed
    launcher (``tools/region.py``) can run a heterogeneous process tree
    — data servers, an elastic trainer, a serving fleet — under one
    exit-code discipline without dedicating its control flow to any
    single child.

    ``command`` is a list, or a callable ``(restarts) -> list`` so a
    respawn can change flags (the elastic resize path respawns the
    trainer at a different ``--devices``).  ``env`` likewise: a dict or
    ``(restarts) -> dict`` — the region drill re-derives it per spawn
    so one role's armed ``MXTPU_FAULTS`` never leaks into (or survives
    on) a respawned sibling, and fired faults fire once.  Respawns get
    ``MXTPU_RESUME=1`` exactly like :func:`supervise` relaunches.
    ``on_exit(role, rc, relaunching)`` is invoked on every child death
    (the region's named-event counter).  A deliberate :meth:`kill`
    (chaos SIGKILL) is just a death: the policy decides — region roles
    run with ``retry_any=True`` so the storm's kills respawn.
    """

    def __init__(self, role, command, env=None, max_restarts=3,
                 backoff=0.5, retry_any=False, log=None, on_exit=None,
                 stdout=None, stderr=None):
        self.role = role
        self._command = command
        self._env = env
        self.max_restarts = max_restarts
        self.backoff = backoff
        self.retry_any = retry_any
        self.restarts = 0
        self.last_rc = None
        self.state = "new"       # new/running/backoff/done/failed/stopped
        self._log = log or (lambda m: sys.stderr.write(m + "\n"))
        self._on_exit = on_exit
        self._stdout, self._stderr = stdout, stderr
        self._proc = None
        self._stopping = threading.Event()
        self._thread = None
        self._lock = threading.Lock()

    # -- observation -------------------------------------------------------
    @property
    def pid(self):
        proc = self._proc
        return proc.pid if proc is not None and proc.poll() is None \
            else None

    def snapshot(self):
        return {"role": self.role, "state": self.state, "pid": self.pid,
                "restarts": self.restarts, "last_rc": self.last_rc}

    def running(self):
        return self._thread is not None and self._thread.is_alive()

    # -- lifecycle ---------------------------------------------------------
    def _spawn(self):
        command = self._command(self.restarts) \
            if callable(self._command) else list(self._command)
        env = self._env(self.restarts) if callable(self._env) \
            else dict(os.environ if self._env is None else self._env)
        if self.restarts > 0:
            env[RESUME_ENV] = "1"
        self._proc = subprocess.Popen(command, env=env,
                                      stdout=self._stdout,
                                      stderr=self._stderr)
        self.state = "running"
        return self._proc

    def start(self):
        """Spawn the child and the monitor thread; returns self."""
        with self._lock:
            if self.running():
                return self
            self._stopping.clear()
            self._spawn()
        self._thread = threading.Thread(
            target=self._monitor, name="supervise-%s" % self.role,
            daemon=True)
        self._thread.start()
        return self

    def _monitor(self):
        while True:
            rc = self._proc.wait()
            self.last_rc = rc
            if self._stopping.is_set():
                self.state = "stopped"
                if self._on_exit is not None:
                    self._on_exit(self.role, rc, False)
                return
            verdict, why = relaunch_decision(
                rc, self.restarts, self.max_restarts,
                retry_any=self.retry_any)
            if self._on_exit is not None:
                self._on_exit(self.role, rc, verdict == "relaunch")
            if verdict == "done":
                self.state = "done"
                return
            if verdict == "propagate":
                self.state = "failed"
                self._log("supervise[%s]: %s — giving up (rc %d)"
                          % (self.role, why, rc))
                return
            with self._lock:
                self.restarts += 1
            self._log("supervise[%s]: %s — relaunch %d/%d with %s=1"
                      % (self.role, why, self.restarts,
                         self.max_restarts, RESUME_ENV))
            self.state = "backoff"
            if self._stopping.wait(self.backoff):
                self.state = "stopped"
                return
            with self._lock:
                if self._stopping.is_set():
                    self.state = "stopped"
                    return
                self._spawn()

    def kill(self, sig=signal.SIGKILL):
        """Send ``sig`` to the CURRENT child (a chaos event, not a
        drain: the monitor thread sees the death and applies the
        policy).  Returns the signalled pid, or None if between
        children."""
        with self._lock:
            proc = self._proc
            if proc is None or proc.poll() is not None:
                return None
            pid = proc.pid
            try:
                proc.send_signal(sig)
            except OSError:
                return None
            return pid

    def drain(self, timeout=30.0, sig=signal.SIGTERM):
        """Stop supervising, forward ``sig``, await the exit.  Returns
        the final rc (None if the child had to be SIGKILLed)."""
        self._stopping.set()
        with self._lock:
            proc = self._proc
        if proc is not None and proc.poll() is None:
            try:
                proc.send_signal(sig)
            except OSError:
                pass
            try:
                proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
                self.last_rc = None
                self.state = "stopped"
                if self._thread is not None:
                    self._thread.join(timeout=5.0)
                return None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if self.state not in ("done", "failed"):
            self.state = "stopped"
        return self.last_rc


def supervise(command, max_restarts=3, backoff=1.0, retry_any=False,
              env=None, log=None):
    """Run ``command`` under the relaunch policy; returns the final exit
    code.  ``env`` overrides the child environment base (default:
    ``os.environ``); ``log`` is a ``print``-like callable."""
    log = log or (lambda msg: sys.stderr.write(msg + "\n"))
    base_env = dict(os.environ if env is None else env)
    restarts = 0
    forwarded = {"sig": None}
    child = {"proc": None}

    def _forward(signum, frame):
        # the supervisor itself is being preempted: hand the signal to
        # the child so its PreemptionHandler checkpoints, then stop
        # relaunching
        forwarded["sig"] = signum
        proc = child["proc"]
        if proc is not None and proc.poll() is None:
            try:
                proc.send_signal(signum)
            except OSError:  # pragma: no cover — child just died
                pass

    old = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            old[sig] = signal.signal(sig, _forward)
        except ValueError:  # pragma: no cover — not on the main thread
            pass
    try:
        while True:
            run_env = dict(base_env)
            if restarts > 0:
                run_env[RESUME_ENV] = "1"
            proc = subprocess.Popen(command, env=run_env)
            child["proc"] = proc
            rc = proc.wait()
            child["proc"] = None
            if forwarded["sig"] is not None:
                log("supervise: forwarded signal %d; child exited %d — "
                    "not relaunching" % (forwarded["sig"], rc))
                return rc
            verdict, why = relaunch_decision(rc, restarts, max_restarts,
                                             retry_any=retry_any)
            if verdict == "done":
                if restarts:
                    log("supervise: run completed after %d relaunch(es)"
                        % restarts)
                return 0
            if verdict == "propagate":
                if rc in (PREEMPT_EXIT_CODE, WATCHDOG_EXIT_CODE) or \
                        retry_any:
                    log("supervise: restart budget (%d) exhausted; last "
                        "exit code %d" % (max_restarts, rc))
                else:
                    log("supervise: child exited %d (not a "
                        "preempt/watchdog code) — propagating" % rc)
                return rc
            restarts += 1
            if rc not in (PREEMPT_EXIT_CODE, WATCHDOG_EXIT_CODE):
                why += " (--retry-any)"
            log("supervise: %s — relaunch %d/%d with %s=1 in %.1fs"
                % (why, restarts, max_restarts, RESUME_ENV, backoff))
            if backoff > 0:
                time.sleep(backoff)
    finally:
        for sig, handler in old.items():
            try:
                signal.signal(sig, handler)
            except ValueError:  # pragma: no cover
                pass


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="relaunch-and-resume supervisor for preemptible "
                    "training (see docs/how_to/fault_tolerance.md)")
    parser.add_argument("--max-restarts", type=int, default=3,
                        help="relaunch budget for resumable exits "
                             "(default 3)")
    parser.add_argument("--backoff", type=float, default=1.0,
                        help="seconds between relaunches (default 1.0)")
    parser.add_argument("--retry-any", action="store_true",
                        help="spend the restart budget on ANY nonzero "
                             "exit, not just preempt/watchdog codes")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="the training command (prefix with -- to "
                             "separate)")
    args = parser.parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no command given (usage: supervise.py [opts] -- "
                     "python train.py ...)")
    return supervise(command, max_restarts=args.max_restarts,
                     backoff=args.backoff, retry_any=args.retry_any)


if __name__ == "__main__":
    sys.exit(main())
