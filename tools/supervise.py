#!/usr/bin/env python
"""Supervised launcher: relaunch-and-resume for preemptible training.

The runtime side of the survival story lives in ``mxnet_tpu.resilience``:
graceful preemption saves a mid-epoch checkpoint and exits with code 85,
the hung-step watchdog dumps stacks and aborts with code 87.  This is the
matching driver — the reference had nothing like it (a dead ps-lite
worker was an operator page); cloud schedulers restart the *container*,
but something inside still has to turn "restarted" into "resumed".

::

    python tools/supervise.py [--max-restarts N] [--backoff S]
        [--retry-any] -- python train.py ...

Policy (exit-code-aware):

- 0: training finished — exit 0.
- 85 (preempt: a checkpoint was just saved) or 87 (watchdog: the run
  hung and aborted): relaunch the command with ``MXTPU_RESUME=1`` in its
  environment, which ``fit(checkpoint=...)`` reads as ``resume=True`` —
  until the restart budget is spent.
- anything else (real crash, OOM-kill, assertion): propagate the exit
  code immediately, unless ``--retry-any`` opts those into the same
  relaunch budget (for flaky infra where any death is worth one retry).

A SIGTERM/SIGINT delivered to the SUPERVISOR is forwarded to the child
(giving its preemption handler the chance to checkpoint), the child's
exit is awaited, and the supervisor exits with the child's code — when
the whole allocation is being preempted there is nobody left to relaunch
for.

The exit codes are duplicated here rather than imported: the supervisor
must stay import-light (importing mxnet_tpu spins up a JAX client, which
on single-chip hosts would steal the device from the child it is about
to spawn).  ``tests/test_chaos.py`` asserts they match
``mxnet_tpu.resilience``.
"""
import argparse
import os
import signal
import subprocess
import sys
import time

# keep in sync with mxnet_tpu/resilience.py (asserted by test_chaos.py)
PREEMPT_EXIT_CODE = 85
WATCHDOG_EXIT_CODE = 87

RESUME_ENV = "MXTPU_RESUME"


def supervise(command, max_restarts=3, backoff=1.0, retry_any=False,
              env=None, log=None):
    """Run ``command`` under the relaunch policy; returns the final exit
    code.  ``env`` overrides the child environment base (default:
    ``os.environ``); ``log`` is a ``print``-like callable."""
    log = log or (lambda msg: sys.stderr.write(msg + "\n"))
    base_env = dict(os.environ if env is None else env)
    restarts = 0
    forwarded = {"sig": None}
    child = {"proc": None}

    def _forward(signum, frame):
        # the supervisor itself is being preempted: hand the signal to
        # the child so its PreemptionHandler checkpoints, then stop
        # relaunching
        forwarded["sig"] = signum
        proc = child["proc"]
        if proc is not None and proc.poll() is None:
            try:
                proc.send_signal(signum)
            except OSError:  # pragma: no cover — child just died
                pass

    old = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            old[sig] = signal.signal(sig, _forward)
        except ValueError:  # pragma: no cover — not on the main thread
            pass
    try:
        while True:
            run_env = dict(base_env)
            if restarts > 0:
                run_env[RESUME_ENV] = "1"
            proc = subprocess.Popen(command, env=run_env)
            child["proc"] = proc
            rc = proc.wait()
            child["proc"] = None
            if forwarded["sig"] is not None:
                log("supervise: forwarded signal %d; child exited %d — "
                    "not relaunching" % (forwarded["sig"], rc))
                return rc
            if rc == 0:
                if restarts:
                    log("supervise: run completed after %d relaunch(es)"
                        % restarts)
                return 0
            resumable = rc in (PREEMPT_EXIT_CODE, WATCHDOG_EXIT_CODE)
            if not resumable and not retry_any:
                log("supervise: child exited %d (not a preempt/watchdog "
                    "code) — propagating" % rc)
                return rc
            if restarts >= max_restarts:
                log("supervise: restart budget (%d) exhausted; last exit "
                    "code %d" % (max_restarts, rc))
                return rc
            restarts += 1
            why = {PREEMPT_EXIT_CODE: "graceful preemption",
                   WATCHDOG_EXIT_CODE: "watchdog abort (hung step)"}.get(
                       rc, "exit code %d (--retry-any)" % rc)
            log("supervise: %s — relaunch %d/%d with %s=1 in %.1fs"
                % (why, restarts, max_restarts, RESUME_ENV, backoff))
            if backoff > 0:
                time.sleep(backoff)
    finally:
        for sig, handler in old.items():
            try:
                signal.signal(sig, handler)
            except ValueError:  # pragma: no cover
                pass


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="relaunch-and-resume supervisor for preemptible "
                    "training (see docs/how_to/fault_tolerance.md)")
    parser.add_argument("--max-restarts", type=int, default=3,
                        help="relaunch budget for resumable exits "
                             "(default 3)")
    parser.add_argument("--backoff", type=float, default=1.0,
                        help="seconds between relaunches (default 1.0)")
    parser.add_argument("--retry-any", action="store_true",
                        help="spend the restart budget on ANY nonzero "
                             "exit, not just preempt/watchdog codes")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="the training command (prefix with -- to "
                             "separate)")
    args = parser.parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no command given (usage: supervise.py [opts] -- "
                     "python train.py ...)")
    return supervise(command, max_restarts=args.max_restarts,
                     backoff=args.backoff, retry_any=args.retry_any)


if __name__ == "__main__":
    sys.exit(main())
