#!/usr/bin/env python
"""Weak-scaling harness for the fused data-parallel train step — the
measurement BASELINE.json's north star asks for ("KVStore allreduce
scaling 8 -> 64 chips, >=85% efficiency").

Holds per-device batch fixed, grows the dp mesh, reports images/sec and
weak-scaling efficiency vs the smallest run.  On a real pod the mesh axes
ride ICI; pass --virtual-devices N to validate the harness on a 1-chip
host (numbers then reflect host-CPU contention, not ICI).

Usage::

    python tools/scaling_bench.py                      # real devices
    python tools/scaling_bench.py --virtual-devices 8  # harness check
    python tools/scaling_bench.py --network resnet-50 --per-device-batch 32
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..")))


def run_one(n_dev, network, per_batch, steps, warmup, image_shape,
            num_classes):
    import numpy as np
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.parallel import SPMDTrainer, build_mesh

    devices = jax.devices()[:n_dev]
    mesh = build_mesh({"dp": n_dev}, devices) if n_dev > 1 else None
    batch = per_batch * n_dev
    sym = models.get_symbol(network, num_classes=num_classes)
    trainer = SPMDTrainer(
        sym, "sgd", {"learning_rate": 0.1, "momentum": 0.9,
                     "rescale_grad": 1.0 / batch},
        mesh=mesh, compute_dtype="bfloat16")
    trainer.bind([("data", (batch,) + image_shape)],
                 [("softmax_label", (batch,))])
    trainer.init_params(mx.initializer.Xavier(rnd_type="gaussian",
                                              factor_type="in", magnitude=2))
    rs = np.random.RandomState(0)
    staged = []
    for _ in range(4):
        d = mx.nd.array(rs.rand(batch, *image_shape).astype("f")) \
            .astype("bfloat16")
        l = mx.nd.array(rs.randint(0, num_classes, batch).astype("f"))
        d.wait_to_read()
        staged.append((d, l))
    for i in range(warmup):
        trainer.step(*staged[i % len(staged)])
    jax.block_until_ready(trainer.params)
    tic = time.time()
    for i in range(steps):
        trainer.step(*staged[i % len(staged)])
    jax.block_until_ready(trainer.params)
    dt = time.time() - tic
    return batch * steps / dt


def main():
    parser = argparse.ArgumentParser(description="weak-scaling sweep")
    parser.add_argument("--network", default="resnet-50")
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--image-shape", default="3,224,224")
    parser.add_argument("--per-device-batch", type=int, default=32)
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--warmup", type=int, default=3)
    parser.add_argument("--device-counts", default="",
                        help="comma list; default: 1,2,4,... up to all")
    parser.add_argument("--virtual-devices", type=int, default=0)
    args = parser.parse_args()

    if args.virtual_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=%d"
            % args.virtual_devices)
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    total = len(jax.devices())
    if args.device_counts:
        counts = [int(c) for c in args.device_counts.split(",")]
    else:
        counts, c = [], 1
        while c <= total:
            counts.append(c)
            c *= 2
    image_shape = tuple(int(x) for x in args.image_shape.split(","))

    counts = sorted(set(counts))  # efficiency baselines on the smallest
    base_per_dev = None
    for n in counts:
        ips = run_one(n, args.network, args.per_device_batch, args.steps,
                      args.warmup, image_shape, args.num_classes)
        per_dev = ips / n
        if base_per_dev is None:
            base_per_dev = per_dev
        print(json.dumps({
            "devices": n,
            "images_per_sec": round(ips, 2),
            "images_per_sec_per_device": round(per_dev, 2),
            "weak_scaling_efficiency": round(per_dev / base_per_dev, 3),
        }))


if __name__ == "__main__":
    main()
