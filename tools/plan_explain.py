#!/usr/bin/env python
"""Explain / gate a sharding plan (mxplan's operator CLI).

::

    python tools/plan_explain.py PLAN.json               # a saved plan file
    python tools/plan_explain.py CKPT_DIR [--epoch N]    # a checkpoint's plan
    python tools/plan_explain.py TARGET --check --devices 8 [--hbm BYTES]
    python tools/plan_explain.py TARGET --json report.json

``TARGET`` is either a plan JSON file (``ShardingPlan.save``) or a
CheckpointManager directory whose manifest entries carry a ``plan``
(written by ``SPMDTrainer.save_checkpoint``).  The default action
prints ``ShardingPlan.explain()`` — mesh, strategy, per-param actions,
gather groups and every decision with the byte model behind it.

``--check`` is the PRE-RESUME GATE: exit 0 when the plan still fits the
given device inventory, nonzero when it does not — unsatisfiable mesh
axes, a batch the new dp axis cannot shard, or a
blown HBM budget are hard problems; a plain world-size change prints as
a NOTE and passes (gather-on-save checkpoints re-shard elastically
through ``set_params``; docs/how_to/planner.md).  ``tools/ckpt_fsck.py
--devices N`` runs the same check inside the full directory audit.

``--devices N`` names the inventory explicitly (required for
``--check`` on a jax-free host); without it the CLI asks jax — the
ONLY path that touches an accelerator runtime.

Deliberately jax-free by default: ``mxnet_tpu.parallel.planner`` is
imported through synthetic package stubs (the mxlint/ckpt_fsck idiom)
so ``mxnet_tpu/__init__`` and ``parallel/__init__`` never execute and
no XLA client is created — auditing a plan must work on the login host,
not just the pod.
"""
import argparse
import importlib.machinery
import json
import os
import sys
import types

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_planner():
    """Import ``mxnet_tpu.parallel.planner`` without executing either
    package ``__init__`` (both would spin up jax)."""
    for name, path in (("mxnet_tpu", os.path.join(_REPO, "mxnet_tpu")),
                       ("mxnet_tpu.parallel",
                        os.path.join(_REPO, "mxnet_tpu", "parallel"))):
        if name in sys.modules:
            continue
        pkg = types.ModuleType(name)
        pkg.__path__ = [path]
        pkg.__spec__ = importlib.machinery.ModuleSpec(
            name, None, is_package=True)
        pkg.__spec__.submodule_search_locations = pkg.__path__
        sys.modules[name] = pkg
    from mxnet_tpu.parallel import planner
    return planner


def _load_plan_doc(target, epoch=None, prefix="checkpoint"):
    """(doc, origin) from a plan file or a checkpoint directory's
    manifest.  Raises ValueError with a message on anything unreadable."""
    if os.path.isdir(target):
        manifest = os.path.join(target, "manifest.json")
        try:
            with open(manifest) as f:
                man = json.load(f)
        except (OSError, ValueError) as e:
            raise ValueError("cannot read %s: %s" % (manifest, e))
        if man.get("prefix") and man["prefix"] != prefix:
            raise ValueError(
                "manifest in %r belongs to prefix %r (asked for %r) — "
                "pass --prefix %s" % (target, man["prefix"], prefix,
                                      man["prefix"]))
        entries = [e for e in man.get("checkpoints", [])
                   if e.get("plan") is not None]
        if not entries:
            raise ValueError(
                "no manifest entry in %r carries a sharding plan (the "
                "run predates mxplan, or saved without save_checkpoint)"
                % target)
        if epoch is not None:
            entries = [e for e in entries
                       if int(e["epoch"]) == int(epoch)]
            if not entries:
                raise ValueError("epoch %d has no plan in %r"
                                 % (epoch, target))
        entry = max(entries, key=lambda e: int(e["epoch"]))
        return entry["plan"], "%s (epoch %d)" % (target,
                                                 int(entry["epoch"]))
    try:
        with open(target) as f:
            return json.load(f), target
    except (OSError, ValueError) as e:
        raise ValueError("cannot read plan file %r: %s" % (target, e))


def _inventory(args):
    """Device count for --check: --devices wins; otherwise ask jax (the
    only accelerator-touching path)."""
    if args.devices is not None:
        return int(args.devices)
    try:
        import jax
        return len(jax.devices())
    except Exception as e:  # noqa: BLE001 — no runtime on this host
        raise ValueError(
            "no --devices given and jax is unavailable here (%s) — pass "
            "--devices N" % e)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Explain a sharding plan, or gate it against the "
                    "current device inventory (--check).")
    parser.add_argument("target",
                        help="plan JSON file or checkpoint directory")
    parser.add_argument("--epoch", type=int, default=None,
                        help="checkpoint epoch (directory targets; "
                             "default: newest with a plan)")
    parser.add_argument("--prefix", default="checkpoint",
                        help="checkpoint prefix for directory targets")
    parser.add_argument("--check", action="store_true",
                        help="gate: exit 0 iff the plan fits the device "
                             "inventory (world changes are notes, not "
                             "failures)")
    parser.add_argument("--devices", type=int, default=None,
                        help="device count to check against (default: "
                             "ask jax — requires a runtime)")
    parser.add_argument("--hbm", type=int, default=None,
                        help="per-device HBM budget in bytes for "
                             "--check (default: the plan's recorded "
                             "budget, if any)")
    parser.add_argument("--json", metavar="PATH", default=None,
                        help="also write a machine-readable report")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="suppress the stdout explanation")
    args = parser.parse_args(argv)

    planner = _load_planner()
    try:
        doc, origin = _load_plan_doc(args.target, epoch=args.epoch,
                                     prefix=args.prefix)
    except ValueError as e:
        sys.stderr.write("plan_explain: %s\n" % e)
        return 2

    report = {"origin": origin, "checked": bool(args.check)}
    try:
        sp = planner.ShardingPlan.from_doc(doc)
    except Exception as e:  # noqa: BLE001 — version/shape problems
        report["problems"] = [str(e)]
        if args.json:
            with open(args.json, "w") as f:
                json.dump(report, f, indent=2, sort_keys=True)
        sys.stderr.write("plan_explain: %s\n" % e)
        return 1
    report["digest"] = sp.digest()
    report["world"] = sp.world
    report["grad_sync"] = sp.grad_sync

    rc = 0
    if not args.quiet:
        print("plan: %s" % origin)
        print(sp.explain())
    if args.check:
        try:
            ndev = _inventory(args)
        except ValueError as e:
            sys.stderr.write("plan_explain: %s\n" % e)
            return 2
        problems, notes = sp.check_inventory(ndev, hbm_bytes=args.hbm)
        report.update({"devices": ndev, "problems": problems,
                       "notes": notes, "fits": not problems})
        for n in notes:
            print("plan_explain: NOTE: %s" % n)
        for p in problems:
            sys.stderr.write("plan_explain: PROBLEM: %s\n" % p)
        print("plan_explain: %s on %d device(s)"
              % ("FITS" if not problems else "DOES NOT FIT", ndev))
        rc = 0 if not problems else 1
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
