#!/usr/bin/env python
"""Launch a distributed job (the reference tools/launch.py analog).

The reference forks scheduler/server/worker roles with ``DMLC_*`` envs via
the dmlc-core tracker (reference ``tools/launch.py:46-70``,
``dmlc_tracker/local.py``).  The TPU-native cluster has one symmetric role:
N JAX processes that join a global device topology through
``jax.distributed.initialize`` (see ``mxnet_tpu/distributed.py``); this
launcher spawns them with the ``MXTPU_*`` envs the workers read.

Local mode (default) runs all N workers on this host — the exact analog of
the reference's ``--launcher local`` used by its nightly dist tests.  For
real multi-host pods, use the cluster scheduler (GKE/slurm) to start one
process per host with the same envs; there is no ssh fan-out here by
design (pods are provisioned, not ssh'd into).

Usage::

    python tools/launch.py -n 4 python train.py --kv-store dist_sync
    python tools/launch.py -n 2 --platform cpu python tests/dist/dist_sync_kvstore.py
"""
import argparse
import os
import signal
import socket
import subprocess
import sys
import threading


def _free_port():
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _pump(stream, prefix, out):
    for line in iter(stream.readline, b""):
        out.write(("%s %s" % (prefix, line.decode("utf-8", "replace"))))
        out.flush()
    stream.close()


def launch(num_workers, command, platform=None, port=None, env=None,
           quiet=False):
    """Spawn ``num_workers`` local worker processes running ``command``.

    Returns the list of exit codes (in rank order).  The first failing
    worker triggers termination of the rest, like the reference tracker's
    local mode killing the job on a dead role.
    """
    port = port or _free_port()
    base = dict(os.environ if env is None else env)
    base["MXTPU_COORDINATOR"] = "127.0.0.1:%d" % port
    base["MXTPU_NUM_WORKERS"] = str(num_workers)
    if platform:
        base["MXTPU_PLATFORM"] = platform
    procs, pumps = [], []
    for r in range(num_workers):
        wenv = dict(base)
        wenv["MXTPU_WORKER_RANK"] = str(r)
        p = subprocess.Popen(command, env=wenv,
                             stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT)
        procs.append(p)
        if not quiet:
            t = threading.Thread(target=_pump,
                                 args=(p.stdout, "[worker %d]" % r,
                                       sys.stdout),
                                 daemon=True)
            t.start()
            pumps.append(t)
    codes = [None] * num_workers
    try:
        for r, p in enumerate(procs):
            codes[r] = p.wait()
            if codes[r] != 0:  # fail fast: tear the job down
                for q in procs:
                    if q.poll() is None:
                        q.terminate()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for t in pumps:
            t.join(timeout=5)
    return [c if c is not None else -signal.SIGKILL for c in codes]


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Launch a distributed mxnet_tpu job")
    parser.add_argument("-n", "--num-workers", required=True, type=int,
                        help="number of worker processes to launch")
    parser.add_argument("--launcher", default="local", choices=["local"],
                        help="only 'local' spawns here; multi-host pods are "
                             "started by the cluster scheduler (see module "
                             "docstring)")
    parser.add_argument("--platform", default=None,
                        help="force a JAX platform in workers (e.g. 'cpu' "
                             "for the virtual cluster used in tests)")
    parser.add_argument("--port", type=int, default=None,
                        help="coordinator port (default: pick a free one)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="command to run in each worker")
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("no command given")
    command = args.command[1:] if args.command[0] == "--" else args.command
    codes = launch(args.num_workers, command, platform=args.platform,
                   port=args.port)
    bad = [(r, c) for r, c in enumerate(codes) if c != 0]
    if bad:
        sys.stderr.write("workers failed: %s\n" % bad)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
