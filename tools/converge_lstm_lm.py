"""Bucketing LSTM-LM perplexity-vs-epoch on the real chip.

The language-model convergence companion to converge_cifar10.py
(together -> CONVERGE_r05.json): trains the lstm_bucketing example's
workload (BucketingModule over per-bucket unrolled LSTM graphs, the
reference example/rnn/lstm_bucketing.py recipe) on the synthetic
Markov corpus and records train/val perplexity per epoch — evidence
that the bucketed RNN path CONVERGES, not merely runs.

    python tools/converge_lstm_lm.py --num-epochs 6 --out lstm_part.json
"""
import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "example", "rnn"))

import numpy as np

import mxnet_tpu as mx


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=6)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-hidden", type=int, default=200)
    ap.add_argument("--num-embed", type=int, default=200)
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--num-sent", type=int, default=3000)
    ap.add_argument("--out", default="CONVERGE_LSTM_r05.json")
    args = ap.parse_args()

    from lstm_bucketing import synthetic_corpus

    buckets = [10, 20, 30, 40, 60]
    vocab_size = 200
    train_sent = synthetic_corpus(args.num_sent, vocab_size, seed=0)
    # enough val sentences that every bucket fills at least one batch
    val_sent = synthetic_corpus(max(args.num_sent // 5,
                                    10 * args.batch_size),
                                vocab_size, seed=1)

    train_it = mx.rnn.BucketSentenceIter(train_sent, args.batch_size,
                                         buckets=buckets,
                                         invalid_label=0)
    val_it = mx.rnn.BucketSentenceIter(val_sent, args.batch_size,
                                       buckets=buckets, invalid_label=0)

    stack = mx.rnn.SequentialRNNCell()
    for i in range(args.num_layers):
        stack.add(mx.rnn.LSTMCell(num_hidden=args.num_hidden,
                                  prefix="lstm_l%d_" % i))

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=vocab_size,
                                 output_dim=args.num_embed, name="embed")
        stack.reset()
        outputs, _ = stack.unroll(seq_len, inputs=embed,
                                  merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, args.num_hidden))
        pred = mx.sym.FullyConnected(pred, num_hidden=vocab_size,
                                     name="pred")
        label = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(pred, label=label, name="softmax",
                                    use_ignore=True, ignore_label=0)
        return pred, ("data",), ("softmax_label",)

    model = mx.mod.BucketingModule(
        sym_gen, default_bucket_key=train_it.default_bucket_key)
    model.bind(data_shapes=train_it.provide_data,
               label_shapes=train_it.provide_label)
    mx.random.seed(3)
    model.init_params(mx.initializer.Xavier())
    model.init_optimizer(
        kvstore="local", optimizer="sgd",
        optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                          "wd": 1e-5, "clip_gradient": 5.0,
                          "rescale_grad": 1.0 / args.batch_size})

    metric = mx.metric.Perplexity(ignore_label=0)
    hist = []
    tic = time.time()
    for epoch in range(args.num_epochs):
        metric.reset()
        train_it.reset()
        for batch in train_it:
            model.forward_backward(batch)
            model.update()
            model.update_metric(metric, batch.label)
        train_ppl = metric.get()[1]
        metric.reset()
        val_it.reset()
        for batch in val_it:
            model.forward(batch, is_train=False)
            model.update_metric(metric, batch.label)
        val_ppl = metric.get()[1]
        hist.append({"epoch": epoch, "train_ppl": round(train_ppl, 2),
                     "val_ppl": round(val_ppl, 2)})
        print("epoch %d train-ppl %.2f val-ppl %.2f (%.1fs)"
              % (epoch, train_ppl, val_ppl, time.time() - tic))

    import jax
    out = {
        "workload": "lstm_bucketing recipe (%d-layer LSTM h=%d e=%d, "
                    "buckets=%s, batch=%d, sgd m=0.9 clip=5) on the "
                    "synthetic Markov corpus (vocab %d; uniform ppl = "
                    "%d, corpus structure supports ~4 likely successors"
                    ")" % (args.num_layers, args.num_hidden,
                           args.num_embed, buckets, args.batch_size,
                           vocab_size, vocab_size),
        "platform": "%s (%s)" % (jax.default_backend(),
                                 jax.devices()[0].device_kind),
        "ppl_per_epoch": hist,
        "final_val_ppl": hist[-1]["val_ppl"] if hist else None,
        "uniform_baseline_ppl": vocab_size,
        "wall_clock_s": round(time.time() - tic, 1),
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
