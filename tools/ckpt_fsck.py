#!/usr/bin/env python
"""Offline checkpoint-directory audit (the fsck for CheckpointManager).

::

    python tools/ckpt_fsck.py CKPT_DIR [--prefix checkpoint]
        [--json report.json] [-q]

Walks ``manifest.json`` and re-verifies every recorded file — existence,
size and checksum (sha256/crc32/crc32c, whichever the manifest recorded)
— plus the replication shards: a shard partition counts as intact when
its primary file OR any peer replica verifies.  Exits 0 when every
listed checkpoint is fully intact, 1 otherwise (and 2 on usage errors),
and always emits a JSON report::

    {"directory": ..., "prefix": ..., "ok": true,
     "checkpoints": [{"epoch": 7, "ok": true, "problems": [],
                      "unverified": [], "degraded": []}, ...],
     "problems": [...]}                  # directory-level problems

A rotted/lost REPLICA behind an intact primary is reported under
``degraded`` without failing the audit (nothing is needed to restore);
a dead primary leaning on its last replica fails it (one fault from
data loss).

Entries written before the integrity layer (no ``files`` records) are
checked for existence only and reported under ``unverified``.

Sharded-native (format-2) entries are audited as a SHARD SET: every
blob index 0..world-1 must carry a record and verify — an incomplete
set or any damaged blob fails the epoch (one torn shard means the whole
epoch must not promote); scan-rebuilt entries whose blobs carry no
digests land under ``unverified`` (restorable, never promotable).

PRE-RESUME PLAN GATE: ``--devices N`` [``--hbm BYTES``] additionally
checks each entry's recorded sharding plan (``parallel/planner.py``,
persisted by ``SPMDTrainer.save_checkpoint``) against that inventory —
a world-size change is reported under ``plan_notes`` (elastic resume
re-shards through ``set_params``), while an unsatisfiable mesh, an
indivisible batch or a blown HBM budget FAILS the audit, so a
resume on the wrong inventory is caught by fsck, not by an OOM or a
partitioner crash mid-restore.  Same math as ``tools/plan_explain.py
--check``.

PROMOTE MODES (the train-to-serve hot-swap gate, docs/how_to/serving.md
"Continuous deployment")::

    python tools/ckpt_fsck.py CKPT_DIR --promote-gate        # one shot
    python tools/ckpt_fsck.py CKPT_DIR --watch [--poll 1.0]  # tail

Both run ``mxnet_tpu.resilience.verify_promotion`` — the SAME routine
``serving.deploy.CheckpointWatcher`` gates every hot swap on and
``fleet.deploy.RollingSwap`` gates every rollout on, so fsck and the
deploy path can never drift on what "healthy enough to promote" means.
``--promote-gate`` verifies the newest (or ``--epoch N``) checkpoint
and exits 0 iff a watcher would promote it; ``--watch`` polls the
manifest and prints a PROMOTABLE/REJECTED verdict line for every new
publish (``--watch-count N`` exits after N verdicts — CI/test use).

Deliberately IMPORT-LIGHT (stdlib only — no jax, no package import):
auditing a checkpoint directory must work on a machine with no
accelerator runtime, and importing ``mxnet_tpu`` would spin up a JAX
client.  The classic audit's checksum implementations are therefore
duplicated from ``mxnet_tpu/resilience.py`` (``tests/test_resilience.
py`` asserts the two stay in lockstep); the promote modes import ONLY
``mxnet_tpu.resilience`` through a synthetic-package stub (the
mxlint/fleet idiom) — ``mxnet_tpu/__init__`` never executes, so no
accelerator client is ever created.
"""
import argparse
import importlib.machinery
import json
import os
import sys
import time
import types

# -- checksums (duplicated from mxnet_tpu/resilience.py; lockstep-tested) --

_CRC32C_POLY = 0x82F63B78
_CRC32C_TABLE = None


def _crc32c_table():
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ _CRC32C_POLY if c & 1 else c >> 1
            table.append(c)
        _CRC32C_TABLE = table
    return _CRC32C_TABLE


def checksum_file(path, algo, chunk=1 << 20):
    """(size, hexdigest) of ``path`` under ``algo`` (sha256/crc32/
    crc32c/off); digest is None under ``off``."""
    size = 0
    if algo == "sha256":
        import hashlib
        h = hashlib.sha256()
    elif algo == "crc32":
        import zlib
        crc = 0
    elif algo == "crc32c":
        crc = 0xFFFFFFFF
        table = _crc32c_table()
    elif algo == "off":
        return os.path.getsize(path), None
    else:
        raise ValueError("unknown checksum algo %r" % (algo,))
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            size += len(block)
            if algo == "sha256":
                h.update(block)
            elif algo == "crc32":
                import zlib
                crc = zlib.crc32(block, crc)
            else:
                for b in block:
                    crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    if algo == "sha256":
        return size, h.hexdigest()
    crc ^= 0xFFFFFFFF if algo == "crc32c" else 0
    return size, "%08x" % (crc & 0xFFFFFFFF)


# -- the audit --------------------------------------------------------------

def _check_file(directory, name, rec, algo, problems):
    """Verify one recorded file; append human-readable problems."""
    path = os.path.join(directory, name)
    if not os.path.exists(path):
        problems.append("%s: missing" % name)
        return False
    try:
        if not algo or algo == "off" or not rec.get("digest"):
            size = os.path.getsize(path)
            if size != rec["size"]:
                problems.append("%s: size %d != recorded %d"
                                % (name, size, rec["size"]))
                return False
            return True
        size, digest = checksum_file(path, algo)
    except (OSError, ValueError) as e:
        problems.append("%s: unreadable (%s)" % (name, e))
        return False
    if size != rec["size"] or digest != rec["digest"]:
        problems.append(
            "%s: %s mismatch (got %s/%d bytes, recorded %s/%d bytes)"
            % (name, algo, digest, size, rec["digest"], rec["size"]))
        return False
    return True


def _check_entry(directory, entry):
    """One manifest entry -> {"epoch", "ok", "problems", "unverified"}."""
    epoch = int(entry["epoch"])
    algo = entry.get("checksum")
    files = entry.get("files") or {}
    problems, unverified, degraded = [], [], []
    for name in (entry.get("params"), entry.get("states")):
        if not name:
            continue
        if name in files:
            continue  # verified below with its record
        if not os.path.exists(os.path.join(directory, name)):
            problems.append("%s: missing (no checksum record)" % name)
        else:
            unverified.append(name)
    shard_set = entry.get("shard_set") or {}
    if shard_set:
        # sharded-native (format-2) entry: the whole epoch lives in the
        # shard blobs — every index 0..world-1 must carry a record
        # (digests verified below via ``files``), and a record-less
        # blob (scan-rebuilt manifest) is existence-checked only
        world = int(shard_set.get("world", 0))
        recs = {}
        for rec in shard_set.get("files", []):
            recs[int(rec.get("shard", -1))] = rec
        missing = [k for k in range(world) if k not in recs]
        if world < 1 or missing:
            problems.append(
                "shard set incomplete: world=%d, missing shard "
                "record(s) %s" % (world, missing or "all"))
        for k in sorted(recs):
            name = recs[k]["file"]
            if name in files:
                continue  # verified below with its record
            if not os.path.exists(os.path.join(directory, name)):
                problems.append("%s: missing (no checksum record)"
                                % name)
            else:
                unverified.append(name)
    primary_ok = True
    for name in sorted(files):
        if not _check_file(directory, name, files[name], algo, problems):
            primary_ok = False
    shards = entry.get("shards") or {}
    for part in shards.get("parts", []):
        copies_ok = []
        copy_problems = []
        for fname in [part["file"]] + list(part.get("replicas", [])):
            ok = _check_file(directory, fname, part, algo, copy_problems)
            copies_ok.append(ok)
        if not any(copies_ok):
            problems.append(
                "shard %d: no intact copy (%s)"
                % (part["shard"], "; ".join(copy_problems)))
        elif not copies_ok[0]:
            # a dead primary leaning on its last replica is restorable
            # TODAY but one fault from data loss — fail the audit so an
            # operator fixes it before the next fault
            problems.extend(
                "shard %d (primary dead): %s" % (part["shard"], p)
                for p in copy_problems)
        elif not all(copies_ok):
            # intact primary, rotted/lost replica: redundancy is
            # degraded but nothing is needed to restore — surface it
            # without failing the audit
            degraded.extend(
                "shard %d (degraded): %s" % (part["shard"], p)
                for p in copy_problems)
    ok = not problems
    return {"epoch": epoch, "ok": ok, "problems": problems,
            "unverified": unverified, "degraded": degraded,
            "primary_ok": primary_ok}


def audit(directory, prefix="checkpoint", devices=None, hbm=None):
    """Audit one checkpoint directory -> the JSON-serializable report.

    With ``devices`` given, every manifest entry carrying a sharding
    plan is additionally gated against that inventory
    (``parallel.planner.check_inventory`` — the pre-resume
    world-size/plan-mismatch check): hard misfits (unsatisfiable mesh
    axes, an indivisible batch, a blown HBM budget) FAIL the
    audit; a plain world change is reported per entry under
    ``plan_notes`` without failing (elastic resume handles it)."""
    report = {"directory": os.path.abspath(directory), "prefix": prefix,
              "ok": True, "problems": [], "checkpoints": []}
    planner = _load_planner() if devices is not None else None
    manifest_path = os.path.join(directory, "manifest.json")
    if not os.path.isdir(directory):
        report["ok"] = False
        report["problems"].append("not a directory")
        return report
    if not os.path.exists(manifest_path):
        has_params = any(
            n.startswith(prefix + "-") and
            (n.endswith(".params") or ".params.s" in n)
            for n in os.listdir(directory))
        if has_params:
            report["ok"] = False
            report["problems"].append(
                "manifest.json missing but %s-*.params present — "
                "recover with CheckpointManager's directory scan"
                % prefix)
        else:
            report["problems"].append("empty (no manifest, no params)")
        return report
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        report["ok"] = False
        report["problems"].append("manifest.json unreadable: %s" % e)
        return report
    for entry in manifest.get("checkpoints", []):
        res = _check_entry(directory, entry)
        if planner is not None:
            plan_doc = entry.get("plan")
            if plan_doc is None:
                res["plan_notes"] = ["no sharding plan recorded — "
                                     "inventory fit cannot be checked"]
            else:
                probs, notes = planner.check_inventory(
                    plan_doc, devices, hbm_bytes=hbm)
                if notes:
                    res["plan_notes"] = notes
                if probs:
                    res["problems"].extend(
                        "plan: %s" % p for p in probs)
                    res["ok"] = False
        report["checkpoints"].append(res)
        if not res["ok"]:
            report["ok"] = False
    return report


# -- promote modes (the ONE verifier, shared with serving/deploy.py) -------

def _stub_package(name, path):
    """Install a synthetic package so submodules import WITHOUT the real
    ``__init__`` executing (which would spin up an accelerator client)."""
    if name in sys.modules:
        return
    pkg = types.ModuleType(name)
    pkg.__path__ = [path]
    pkg.__spec__ = importlib.machinery.ModuleSpec(name, None,
                                                  is_package=True)
    pkg.__spec__.submodule_search_locations = pkg.__path__
    sys.modules[name] = pkg


def _pkg_root():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(root, "mxnet_tpu")


def _verify_promotion():
    """Import ``resilience.verify_promotion`` through a synthetic
    package stub — ``mxnet_tpu/__init__`` never executes, so this stays
    runnable where no accelerator runtime exists (the data_service
    worker / tools/fleet.py idiom)."""
    _stub_package("mxnet_tpu", _pkg_root())
    from mxnet_tpu.resilience import verify_promotion
    return verify_promotion


def _load_planner():
    """Import ``parallel.planner`` the same jax-free way (both package
    ``__init__``s stubbed) for the ``--devices`` plan gate."""
    _stub_package("mxnet_tpu", _pkg_root())
    _stub_package("mxnet_tpu.parallel", os.path.join(_pkg_root(),
                                                     "parallel"))
    from mxnet_tpu.parallel import planner
    return planner


def _promote_gate(args):
    """One-shot gate: exit 0 iff a CheckpointWatcher would promote the
    newest (or the given) epoch right now."""
    verify = _verify_promotion()
    epoch, problems = verify(args.directory, epoch=args.epoch,
                             prefix=args.prefix)
    doc = {"directory": os.path.abspath(args.directory),
           "epoch": epoch, "promotable": not problems,
           "problems": problems}
    if not args.quiet:
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        for p in problems:
            sys.stderr.write("ckpt_fsck: %s\n" % p)
    return 0 if not problems else 1


def _watch(args):
    """Tail the manifest and print one verdict line per new publish —
    the operator's view of exactly what the serving watcher will do."""
    verify = _verify_promotion()
    seen = None                  # (epoch, promotable) last reported
    reported = 0
    rc = 0
    while args.watch_count is None or reported < args.watch_count:
        epoch, problems = verify(args.directory, prefix=args.prefix)
        state = (epoch, not problems)
        if epoch is not None and state != seen:
            seen = state
            reported += 1
            if problems:
                rc = 1
                print("ckpt_fsck: epoch %d REJECTED: %s"
                      % (epoch, "; ".join(problems)), flush=True)
            else:
                print("ckpt_fsck: epoch %d PROMOTABLE" % epoch,
                      flush=True)
        if args.watch_count is not None and reported >= args.watch_count:
            break
        time.sleep(args.poll)
    return rc


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Verify a CheckpointManager directory offline: "
                    "manifest-recorded sizes + checksums, shard-replica "
                    "recoverability.  Exit 0 = every checkpoint intact.")
    parser.add_argument("directory", help="checkpoint directory")
    parser.add_argument("--prefix", default="checkpoint",
                        help="checkpoint prefix (default: checkpoint)")
    parser.add_argument("--json", metavar="PATH",
                        help="also write the report to this file")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="suppress the stdout report")
    parser.add_argument("--promote-gate", action="store_true",
                        help="verify ONE epoch with the promote-path "
                             "verifier (resilience.verify_promotion — "
                             "the same routine the serving hot-swap "
                             "gates on); exit 0 iff promotable")
    parser.add_argument("--epoch", type=int, default=None,
                        help="epoch for --promote-gate (default: the "
                             "manifest's newest)")
    parser.add_argument("--watch", action="store_true",
                        help="tail the manifest and print a PROMOTABLE/"
                             "REJECTED verdict per new publish")
    parser.add_argument("--poll", type=float, default=1.0,
                        help="--watch poll interval in seconds")
    parser.add_argument("--watch-count", type=int, default=None,
                        help="exit after reporting this many verdicts "
                             "(tests/CI; default: run until killed)")
    parser.add_argument("--devices", type=int, default=None,
                        help="also gate each entry's recorded sharding "
                             "plan against this device inventory (the "
                             "pre-resume world-size/plan check; see "
                             "tools/plan_explain.py --check)")
    parser.add_argument("--hbm", type=int, default=None,
                        help="per-device HBM budget in bytes for the "
                             "--devices plan gate (default: each "
                             "plan's recorded budget)")
    args = parser.parse_args(argv)
    if args.promote_gate:
        return _promote_gate(args)
    if args.watch:
        return _watch(args)
    report = audit(args.directory, prefix=args.prefix,
                   devices=args.devices, hbm=args.hbm)
    payload = json.dumps(report, indent=2, sort_keys=True)
    if args.json:
        with open(args.json, "w") as f:
            f.write(payload)
    if not args.quiet:
        print(payload)
    if not report["ok"] and args.quiet:
        for p in report["problems"]:
            sys.stderr.write("ckpt_fsck: %s\n" % p)
        for e in report["checkpoints"]:
            for p in e["problems"]:
                sys.stderr.write("ckpt_fsck: epoch %d: %s\n"
                                 % (e["epoch"], p))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
