#!/usr/bin/env python
"""im2rec: pack an image dataset into RecordIO (reference tools/im2rec.py).

Two modes, CLI-compatible with the reference:

* --list: walk an image root, assign integer labels per subdirectory, and
  write ``prefix.lst`` ("index\\tlabel\\trelpath" lines, optional
  train/val/test split via --train-ratio/--test-ratio).
* pack (default): read ``prefix.lst``, encode each image (optional
  --resize shorter-side resize, --quality, --center-crop) and write
  ``prefix.rec`` + ``prefix.idx`` with pack_img, using --num-thread worker
  threads feeding a single writer.
"""
from __future__ import annotations

import argparse
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def list_image(root, recursive, exts):
    """Yield (index, relpath, label) walking root."""
    i = 0
    if recursive:
        cat = {}
        for path, dirs, files in sorted(os.walk(root, followlinks=True)):
            dirs.sort()
            files.sort()
            for fname in files:
                fpath = os.path.join(path, fname)
                suffix = os.path.splitext(fname)[1].lower()
                if os.path.isfile(fpath) and (suffix in exts):
                    if path not in cat:
                        cat[path] = len(cat)
                    yield (i, os.path.relpath(fpath, root), cat[path])
                    i += 1
    else:
        for fname in sorted(os.listdir(root)):
            fpath = os.path.join(root, fname)
            suffix = os.path.splitext(fname)[1].lower()
            if os.path.isfile(fpath) and (suffix in exts):
                yield (i, os.path.relpath(fpath, root), 0)
                i += 1


def write_list(path_out, image_list):
    with open(path_out, "w") as fout:
        for i, item in enumerate(image_list):
            line = "%d\t" % item[0]
            for j in item[2:]:
                line += "%f\t" % j
            line += "%s\n" % item[1]
            fout.write(line)


def make_list(args):
    image_list = list(list_image(args.root, args.recursive, args.exts))
    if args.shuffle:
        random.seed(100)
        random.shuffle(image_list)
    n = len(image_list)
    sep = int(n * args.train_ratio)
    sep_test = int(n * args.test_ratio)
    if args.train_ratio == 1.0:
        write_list(args.prefix + ".lst", image_list)
    else:
        if args.test_ratio:
            write_list(args.prefix + "_test.lst", image_list[:sep_test])
        if args.train_ratio + args.test_ratio < 1.0:
            write_list(args.prefix + "_val.lst", image_list[sep_test + sep:])
        write_list(args.prefix + "_train.lst",
                   image_list[sep_test:sep_test + sep])


def read_list(path_in):
    with open(path_in) as fin:
        for line in fin:
            line = [i.strip() for i in line.strip().split("\t")]
            if len(line) < 3:
                continue
            yield (int(line[0]), line[-1]) + tuple(map(float, line[1:-1]))


def image_encode(args, item, path):
    """Read + transform + encode one image; returns packed record bytes."""
    from mxnet_tpu import recordio

    header = recordio.IRHeader(
        0, item[2] if len(item) == 3 else np.array(item[2:], "f"),
        item[0], 0)
    if args.pass_through:  # raw bytes: no decoder needed
        with open(path, "rb") as fin:
            return recordio.pack(header, fin.read())
    import cv2
    img = cv2.imread(path, args.color)
    if img is None:
        raise IOError("cannot read %s" % path)
    if args.center_crop and img.shape[0] != img.shape[1]:
        margin = (max(img.shape[:2]) - min(img.shape[:2])) // 2
        if img.shape[0] > img.shape[1]:
            img = img[margin:margin + img.shape[1], :]
        else:
            img = img[:, margin:margin + img.shape[0]]
    if args.resize:
        h, w = img.shape[:2]
        if h > w:
            new_w, new_h = args.resize, int(h * args.resize / w)
        else:
            new_w, new_h = int(w * args.resize / h), args.resize
        img = cv2.resize(img, (new_w, new_h))
    return recordio.pack_img(header, img, quality=args.quality,
                             img_fmt=args.encoding)


def make_record_native(args):
    """Pack via the C++ packer (native/im2rec.cc — the reference
    tools/im2rec.cc analog): libjpeg decode -> shorter-edge resize ->
    libjpeg encode on a worker pool, list-ordered records.  Returns
    False when the native library is unavailable or the requested
    options aren't covered (the Python path then serves)."""
    from mxnet_tpu import native as _native
    lib = _native.get_lib()
    if lib is None or not getattr(lib, "_has_im2rec", False):
        return False
    if args.center_crop or args.encoding != ".jpg" or args.color != 1:
        return False   # cv2-only options
    # the native packer covers single-label JPEG lists; multi-label rows
    # (label arrays) and non-JPEG sources keep the Python path, which
    # transcodes/encodes them correctly
    with open(args.prefix + ".lst") as f:
        for line in f:
            fields = line.rstrip("\n").split("\t")
            if len(fields) < 3:
                continue
            if len(fields) > 3:
                return False   # multi-label
            if not args.pass_through and \
                    not fields[-1].lower().endswith((".jpg", ".jpeg")):
                return False   # non-JPEG needs cv2 transcoding
    import ctypes
    packed = ctypes.c_uint64(0)
    skipped = ctypes.c_uint64(0)
    tic = time.time()
    rc = lib.MXTPUIm2Rec(
        (args.prefix + ".lst").encode(), args.root.encode(),
        (args.prefix + ".rec").encode(), (args.prefix + ".idx").encode(),
        0 if args.pass_through else args.resize, args.quality,
        max(1, args.num_thread), 1 if args.pass_through else 0,
        ctypes.byref(packed), ctypes.byref(skipped))
    if rc != 0:
        raise RuntimeError("native im2rec failed rc=%d" % rc)
    print("packed %d records into %s.rec (%d skipped) [native, %.1fs]"
          % (packed.value, args.prefix, skipped.value, time.time() - tic))
    return True


def make_record(args):
    """Pack prefix.lst -> prefix.rec/.idx with a decode worker pool ordered
    through the host dependency engine."""
    import threading

    from mxnet_tpu import engine as eng
    from mxnet_tpu import recordio

    items = list(read_list(args.prefix + ".lst"))
    record = recordio.MXIndexedRecordIO(
        args.prefix + ".idx", args.prefix + ".rec", "w")
    engine = eng.Engine(num_workers=max(1, args.num_thread))
    results = {}
    write_var = engine.new_variable()
    count = [0]
    skipped = [0]
    tic = time.time()
    # Bound decoded-but-unwritten records held in memory.
    inflight = threading.Semaphore(4 * max(1, args.num_thread))

    def encode_one(i, item):
        path = os.path.join(args.root, item[1])
        try:
            results[i] = image_encode(args, item, path)
        except Exception as e:  # skip unreadable images, as the reference does
            print("skipping %s: %s" % (path, e))
            results[i] = None

    def write_one(i, item):
        buf = results.pop(i)
        inflight.release()
        if buf is None:
            skipped[0] += 1
            return
        record.write_idx(item[0], buf)
        count[0] += 1
        if count[0] % 1000 == 0:
            print("time: %.3f count: %d" % (time.time() - tic, count[0]))

    for i, item in enumerate(items):
        inflight.acquire()
        enc_var = engine.new_variable()
        engine.push(lambda i=i, item=item: encode_one(i, item),
                    mutable_vars=(enc_var,), name="imdecode")
        # Writes serialize on write_var in push order -> .rec order == .lst
        # order even though decodes run in parallel.
        engine.push(lambda i=i, item=item: write_one(i, item),
                    const_vars=(enc_var,), mutable_vars=(write_var,),
                    name="record_write")
        # Dependency-ordered: reclaimed after its consumers complete.
        engine.delete_variable(enc_var)
    engine.wait_for_all()
    engine.shutdown()
    record.close()
    print("packed %d records into %s.rec (%d skipped)"
          % (count[0], args.prefix, skipped[0]))


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("prefix", help="prefix of .lst/.rec/.idx files")
    p.add_argument("root", help="image root folder")
    p.add_argument("--list", action="store_true",
                   help="create image list instead of packing")
    p.add_argument("--exts", nargs="+",
                   default=[".jpeg", ".jpg", ".png"])
    p.add_argument("--recursive", action="store_true")
    p.add_argument("--shuffle",
                   type=lambda s: s.strip().lower() in
                   ("1", "true", "yes", "on"),
                   default=True)
    p.add_argument("--train-ratio", type=float, default=1.0)
    p.add_argument("--test-ratio", type=float, default=0.0)
    p.add_argument("--resize", type=int, default=0)
    p.add_argument("--center-crop", action="store_true")
    p.add_argument("--quality", type=int, default=95)
    p.add_argument("--encoding", choices=[".jpg", ".png"], default=".jpg")
    p.add_argument("--pass-through", action="store_true",
                   help="skip transcoding, pack raw bytes")
    p.add_argument("--color", type=int, default=1, choices=[-1, 0, 1])
    p.add_argument("--num-thread", type=int, default=1)
    p.add_argument("--native", type=lambda s: s.strip().lower() in
                   ("1", "true", "yes", "on"), default=True,
                   help="use the C++ packer when available (falls back "
                        "to the Python pool otherwise)")
    return p.parse_args()


if __name__ == "__main__":
    args = parse_args()
    if args.list:
        make_list(args)
    elif not (args.native and make_record_native(args)):
        make_record(args)
