#!/usr/bin/env python
"""Parse a training log into a per-epoch table (reference
tools/parse_log.py): epoch, train/validation metric values, speed, time
cost.  Reads the log format emitted by Module.fit + Speedometer.

Usage::

    python tools/parse_log.py train.log
    python tools/parse_log.py train.log --format csv
"""
import argparse
import re
import sys

EPOCH_METRIC = re.compile(
    r"Epoch\[(\d+)\]\s+(Train|Validation)-([\w-]+)=([\d.eE+-]+)")
EPOCH_TIME = re.compile(r"Epoch\[(\d+)\]\s+Time cost=([\d.eE+-]+)")
SPEED = re.compile(
    r"Epoch\[(\d+)\]\s+Batch\s*\[\d+\]\s+Speed:\s*([\d.eE+-]+)")


def parse(lines):
    rows = {}

    def row(e):
        return rows.setdefault(int(e), {"epoch": int(e), "speeds": []})

    for line in lines:
        m = EPOCH_METRIC.search(line)
        if m:
            e, kind, name, val = m.groups()
            row(e)["%s-%s" % (kind.lower(), name)] = float(val)
            continue
        m = EPOCH_TIME.search(line)
        if m:
            row(m.group(1))["time"] = float(m.group(2))
            continue
        m = SPEED.search(line)
        if m:
            row(m.group(1))["speeds"].append(float(m.group(2)))
    out = []
    for e in sorted(rows):
        r = rows[e]
        speeds = r.pop("speeds")
        if speeds:
            r["speed"] = sum(speeds) / len(speeds)
        out.append(r)
    return out


def main():
    parser = argparse.ArgumentParser(description="parse a training log")
    parser.add_argument("logfile")
    parser.add_argument("--format", choices=["table", "csv"],
                        default="table")
    args = parser.parse_args()
    with open(args.logfile) as f:
        rows = parse(f)
    if not rows:
        sys.stderr.write("no epochs found\n")
        return 1
    cols = ["epoch"]
    for r in rows:
        for k in r:
            if k not in cols:
                cols.append(k)
    if args.format == "csv":
        print(",".join(cols))
        for r in rows:
            print(",".join(str(r.get(c, "")) for c in cols))
    else:
        widths = [max(len(c), 12) for c in cols]
        print("  ".join(c.ljust(w) for c, w in zip(cols, widths)))
        for r in rows:
            print("  ".join(
                (("%.6g" % r[c]) if isinstance(r.get(c), float)
                 else str(r.get(c, "-"))).ljust(w)
                for c, w in zip(cols, widths)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
