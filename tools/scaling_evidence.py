#!/usr/bin/env python
"""Produce the scaling-evidence artifact (SCALING_r{N}.json).

Records, on a single host (see docs/scaling_model.md for how these carry
the 8→64-chip claim):
  - virtual-mesh weak scaling (tools/scaling_bench.py, 1..8 virtual devs)
  - multi-process launcher weak scaling (tools/launch.py +
    tools/dist_step_bench.py, 1..8 workers)
  - collective-bandwidth sweep (tools/bandwidth/measure.py, single- and
    multi-process)
  - the analytic ICI communication model with measured inputs

Usage: python tools/scaling_evidence.py [-o SCALING_r03.json]
"""
import argparse
import json
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
PY = sys.executable


def _run(cmd, timeout=900, env_extra=None):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update(env_extra or {})
    res = subprocess.run(cmd, capture_output=True, text=True,
                         timeout=timeout, env=env, cwd=REPO)
    return res


def _json_lines(text):
    out = []
    for line in text.splitlines():
        line = line.strip()
        # launcher prefixes worker output with "[worker N] "
        if "] " in line and line.startswith("[worker"):
            line = line.split("] ", 1)[1]
        if line.startswith("{"):
            try:
                out.append(json.loads(line))
            except ValueError:
                pass
    return out


def virtual_mesh_weak_scaling(network="lenet", per_batch=64,
                              image_shape="1,28,28", classes=10):
    res = _run([PY, os.path.join("tools", "scaling_bench.py"),
                "--network", network, "--num-classes", str(classes),
                "--image-shape", image_shape,
                "--per-device-batch", str(per_batch),
                "--steps", "20", "--warmup", "5",
                "--virtual-devices", "8"])
    rows = _json_lines(res.stdout)
    # on one physical core, total throughput at N vs N=1 = sharding overhead
    if rows:
        base = rows[0]["images_per_sec"]
        for r in rows:
            r["total_vs_1dev"] = round(r["images_per_sec"] / base, 3)
    return {"note": "8 virtual CPU devices on ONE physical core: "
                    "total_vs_1dev ~= 1.0 means GSPMD partitioning adds no "
                    "host-side overhead (per-device falls 1/N by "
                    "construction; ICI efficiency is carried by the "
                    "analytic model, docs/scaling_model.md)",
            "network": network, "rows": rows,
            "stderr_tail": res.stderr[-400:] if res.returncode else ""}


def multiproc_weak_scaling(counts=(1, 2, 4, 8)):
    rows = []
    for n in counts:
        res = _run([PY, os.path.join("tools", "launch.py"), "-n", str(n),
                    "--platform", "cpu", PY,
                    os.path.join("tools", "dist_step_bench.py"),
                    "--steps", "20", "--warmup", "5"])
        got = _json_lines(res.stdout)
        if got:
            rows.append(got[0])
        else:
            rows.append({"workers": n, "error": res.stdout[-300:]})
    base = None
    for r in rows:
        if "step_ms" in r:
            if base is None:
                base = r["step_ms"]
            r["step_time_vs_1proc"] = round(r["step_ms"] / base, 3)
    return {"note": "real multi-process runtime (launcher + gloo "
                    "collectives — the code path that rides ICI/DCN on "
                    "pods) on ONE core: step time grows ~N by construction; "
                    "records the 8-process cluster executing the fused "
                    "dist step correctly",
            "rows": rows}


def collective_bandwidth():
    single = _run([PY, os.path.join("tools", "bandwidth", "measure.py"),
                   "--sizes", "64KB,1MB,16MB,64MB", "--iters", "10",
                   "--virtual-devices", "8"])
    dist = _run([PY, os.path.join("tools", "launch.py"), "-n", "4",
                 "--platform", "cpu", PY,
                 os.path.join("tools", "bandwidth", "measure.py"),
                 "--dist", "--sizes", "64KB,1MB,16MB", "--iters", "10"])
    return {"gspmd_virtual_mesh": _json_lines(single.stdout),
            "cross_process_gloo": _json_lines(dist.stdout)}


def measured_overlap_model():
    """tools/overlap_model.py at the four (wall-clock x ICI-credit)
    corners: allreduce laid onto the MEASURED per-layer backward timeline
    from the committed on-chip ResNet-50 profile (round 3's assumed
    1.6 ms window replaced; see docs/scaling_model.md for what is
    measured vs structural vs calibrated)."""
    corners = {}
    for wall in ("2.4", "2.9"):
        for bw in ("45", "90"):
            res = _run([PY, os.path.join("tools", "overlap_model.py")],
                       env_extra={"OVERLAP_WALL_STEP_MS": wall,
                                  "OVERLAP_ICI_GBPS": bw})
            try:
                # overlap_model prints ONE pretty-printed JSON object
                corners["wall%s_bw%s" % (wall, bw)] = json.loads(res.stdout)
            except ValueError:
                corners["wall%s_bw%s" % (wall, bw)] = {
                    "error": (res.stderr or res.stdout)[-400:]}
    return corners


def allreduce_ablation(nproc=8):
    """tools/overlap_bench.py on the real multi-process cluster:
    step-with-psum vs psum-ablated vs psum-solo over ResNet-50-sized
    bf16 gradients.  On the CPU backend this is the honest no-overlap
    lower bound (gloo collectives are not hidden there); the TPU
    projection carries the measured-timeline model above."""
    res = _run([PY, os.path.join("tools", "launch.py"), "-n", str(nproc),
                "--platform", "cpu", PY,
                os.path.join("tools", "overlap_bench.py"),
                "--steps", "6", "--warmup", "2"], timeout=1800)
    for line in res.stdout.splitlines():
        if "OVERLAP_BENCH" in line:
            return json.loads(line.split("OVERLAP_BENCH ", 1)[1])
    return {"error": (res.stderr or res.stdout)[-400:]}


def analytic_model(measured_step_ms=2.4):
    params_m = 25.56e6
    v_bf16 = params_m * 2
    ici_axis_bw = 2 * 45e9  # one torus axis, bidirectional
    out = {"inputs": {
        "resnet50_params": params_m,
        "grad_bytes_bf16": v_bf16,
        "measured_step_ms_b32_bf16": measured_step_ms,
        "v5e_ici_link_oneway_GBps": 45,
        "credited_allreduce_bw_GBps": ici_axis_bw / 1e9,
        "backward_overlap_window_ms": round(measured_step_ms * 2 / 3, 2),
    }}
    for n in (8, 64):
        t_comm = 2 * (n - 1) / n * v_bf16 / ici_axis_bw * 1e3
        overlap = measured_step_ms * 2 / 3
        exposed = max(0.0, t_comm - overlap)
        out["n%d" % n] = {
            "t_comm_ms_bf16": round(t_comm, 3),
            "t_exposed_ms_with_overlap": round(exposed, 3),
            "weak_scaling_efficiency_overlapped": round(
                measured_step_ms / (measured_step_ms + exposed), 3),
            "weak_scaling_efficiency_no_overlap": round(
                measured_step_ms / (measured_step_ms + t_comm), 3),
        }
    out["conclusion"] = (
        "legacy round-3 closed-form model kept for comparison; the "
        "round-4 projection lives in measured_overlap_model (per-layer "
        "backward timeline from the on-chip profile) with "
        "allreduce_ablation as the CPU no-overlap lower bound — see "
        "docs/scaling_model.md")
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-o", "--output", default="SCALING_r04.json")
    ap.add_argument("--skip-virtual", action="store_true")
    args = ap.parse_args()
    art = {"doc": "see docs/scaling_model.md",
           "measured_overlap_model": measured_overlap_model(),
           "allreduce_ablation_cpu8": allreduce_ablation(),
           "legacy_analytic_model": analytic_model()}
    if not args.skip_virtual:
        art["virtual_mesh_weak_scaling"] = virtual_mesh_weak_scaling()
    art["multiproc_weak_scaling"] = multiproc_weak_scaling()
    art["collective_bandwidth"] = collective_bandwidth()
    with open(os.path.join(REPO, args.output), "w") as f:
        json.dump(art, f, indent=1)
    print("wrote", args.output)


if __name__ == "__main__":
    main()
