#!/usr/bin/env python
"""Produce the scaling-evidence artifact (SCALING_r{N}.json).

Records, on a single host (see docs/scaling_model.md for how these carry
the 8→64-chip claim):
  - virtual-mesh weak scaling (tools/scaling_bench.py, 1..8 virtual devs)
  - multi-process launcher weak scaling (tools/launch.py +
    tools/dist_step_bench.py, 1..8 workers)
  - collective-bandwidth sweep (tools/bandwidth/measure.py, single- and
    multi-process)
  - the analytic ICI communication model with measured inputs

Usage: python tools/scaling_evidence.py [-o SCALING_r03.json]
"""
import argparse
import json
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
PY = sys.executable


def _run(cmd, timeout=900, env_extra=None):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update(env_extra or {})
    res = subprocess.run(cmd, capture_output=True, text=True,
                         timeout=timeout, env=env, cwd=REPO)
    return res


def _json_lines(text):
    out = []
    for line in text.splitlines():
        line = line.strip()
        # launcher prefixes worker output with "[worker N] "
        if "] " in line and line.startswith("[worker"):
            line = line.split("] ", 1)[1]
        if line.startswith("{"):
            try:
                out.append(json.loads(line))
            except ValueError:
                pass
    return out


def virtual_mesh_weak_scaling(network="lenet", per_batch=64,
                              image_shape="1,28,28", classes=10):
    res = _run([PY, os.path.join("tools", "scaling_bench.py"),
                "--network", network, "--num-classes", str(classes),
                "--image-shape", image_shape,
                "--per-device-batch", str(per_batch),
                "--steps", "20", "--warmup", "5",
                "--virtual-devices", "8"])
    rows = _json_lines(res.stdout)
    # on one physical core, total throughput at N vs N=1 = sharding overhead
    if rows:
        base = rows[0]["images_per_sec"]
        for r in rows:
            r["total_vs_1dev"] = round(r["images_per_sec"] / base, 3)
    return {"note": "8 virtual CPU devices on ONE physical core: "
                    "total_vs_1dev ~= 1.0 means GSPMD partitioning adds no "
                    "host-side overhead (per-device falls 1/N by "
                    "construction; ICI efficiency is carried by the "
                    "analytic model, docs/scaling_model.md)",
            "network": network, "rows": rows,
            "stderr_tail": res.stderr[-400:] if res.returncode else ""}


def multiproc_weak_scaling(counts=(1, 2, 4, 8)):
    rows = []
    for n in counts:
        res = _run([PY, os.path.join("tools", "launch.py"), "-n", str(n),
                    "--platform", "cpu", PY,
                    os.path.join("tools", "dist_step_bench.py"),
                    "--steps", "20", "--warmup", "5"])
        got = _json_lines(res.stdout)
        if got:
            rows.append(got[0])
        else:
            rows.append({"workers": n, "error": res.stdout[-300:]})
    base = None
    for r in rows:
        if "step_ms" in r:
            if base is None:
                base = r["step_ms"]
            r["step_time_vs_1proc"] = round(r["step_ms"] / base, 3)
    return {"note": "real multi-process runtime (launcher + gloo "
                    "collectives — the code path that rides ICI/DCN on "
                    "pods) on ONE core: step time grows ~N by construction; "
                    "records the 8-process cluster executing the fused "
                    "dist step correctly",
            "rows": rows}


def collective_bandwidth():
    single = _run([PY, os.path.join("tools", "bandwidth", "measure.py"),
                   "--sizes", "64KB,1MB,16MB,64MB", "--iters", "10",
                   "--virtual-devices", "8"])
    dist = _run([PY, os.path.join("tools", "launch.py"), "-n", "4",
                 "--platform", "cpu", PY,
                 os.path.join("tools", "bandwidth", "measure.py"),
                 "--dist", "--sizes", "64KB,1MB,16MB", "--iters", "10"])
    return {"gspmd_virtual_mesh": _json_lines(single.stdout),
            "cross_process_gloo": _json_lines(dist.stdout)}


def measured_overlap_model():
    """tools/overlap_model.py at the (wall-clock x ICI-credit) corners,
    allreduce AND grad_sync='zero' timelines laid onto the MEASURED
    per-layer fwd/bwd windows from the committed on-chip ResNet-50
    profile.  Round-5 corners: 13.9 ms is the true fetch-synced step
    (matches the profiled span — the round-2..4 "2.4-2.9 ms wall" was
    the broken dispatch-rate sync); 4.0 ms is a peak-MFU STRESS step
    (what b32 would take at 100% MFU — comm windows shrink 3.5x), kept
    so the claim is not carried by a low-MFU denominator alone."""
    corners = {}
    for wall, tag in (("13.9", "measured"), ("4.0", "stress_peak_mfu")):
        for bw in ("45", "90"):
            res = _run([PY, os.path.join("tools", "overlap_model.py")],
                       env_extra={"OVERLAP_WALL_STEP_MS": wall,
                                  "OVERLAP_ICI_GBPS": bw})
            try:
                # overlap_model prints ONE pretty-printed JSON object
                corners["%s_bw%s" % (tag, bw)] = json.loads(res.stdout)
            except ValueError:
                corners["%s_bw%s" % (tag, bw)] = {
                    "error": (res.stderr or res.stdout)[-400:]}
    return corners


def allreduce_ablation(nproc=8):
    """tools/overlap_bench.py on the real multi-process cluster:
    step-with-psum vs psum-ablated vs psum-solo over ResNet-50-sized
    bf16 gradients.  On the CPU backend this is the honest no-overlap
    lower bound (gloo collectives are not hidden there); the TPU
    projection carries the measured-timeline model above."""
    res = _run([PY, os.path.join("tools", "launch.py"), "-n", str(nproc),
                "--platform", "cpu", PY,
                os.path.join("tools", "overlap_bench.py"),
                "--steps", "6", "--warmup", "2"], timeout=1800)
    for line in res.stdout.splitlines():
        if "OVERLAP_BENCH" in line:
            return json.loads(line.split("OVERLAP_BENCH ", 1)[1])
    return {"error": (res.stderr or res.stdout)[-400:]}


def analytic_model(measured_step_ms=13.9):
    params_m = 25.56e6
    v_bf16 = params_m * 2
    ici_axis_bw = 2 * 45e9  # one torus axis, bidirectional
    out = {"inputs": {
        "resnet50_params": params_m,
        "grad_bytes_bf16": v_bf16,
        "measured_step_ms_b32_bf16": measured_step_ms,
        "v5e_ici_link_oneway_GBps": 45,
        "credited_allreduce_bw_GBps": ici_axis_bw / 1e9,
        "backward_overlap_window_ms": round(measured_step_ms * 2 / 3, 2),
    }}
    for n in (8, 64):
        t_comm = 2 * (n - 1) / n * v_bf16 / ici_axis_bw * 1e3
        overlap = measured_step_ms * 2 / 3
        exposed = max(0.0, t_comm - overlap)
        out["n%d" % n] = {
            "t_comm_ms_bf16": round(t_comm, 3),
            "t_exposed_ms_with_overlap": round(exposed, 3),
            "weak_scaling_efficiency_overlapped": round(
                measured_step_ms / (measured_step_ms + exposed), 3),
            "weak_scaling_efficiency_no_overlap": round(
                measured_step_ms / (measured_step_ms + t_comm), 3),
        }
    out["conclusion"] = (
        "legacy round-3 closed-form model kept for comparison; the "
        "round-4 projection lives in measured_overlap_model (per-layer "
        "backward timeline from the on-chip profile) with "
        "allreduce_ablation as the CPU no-overlap lower bound — see "
        "docs/scaling_model.md")
    return out


def schedule_evidence():
    """tools/dist_schedule_evidence.py summary: the real-TPU-pipeline
    (AOT v5e:2x4) compiled zero step with async collectives overlapping
    compute and bucketed all-reduce-scatter gradient fusions."""
    res = _run([PY, os.path.join("tools", "dist_schedule_evidence.py")],
               timeout=1200)
    got = _json_lines(res.stdout)
    if got:
        out = got[-1]
        out["artifact"] = "docs/profiles/dist_step_zero_hlo_r05.txt"
        return out
    return {"error": (res.stderr or res.stdout)[-400:]}


def _headline(art):
    """The numbers this artifact actually claims, stated first so the
    harness-bound rows below cannot be misread as framework properties
    (round-4 verdict: re-headline)."""
    h = {
        "claim_1_modeled_n64_efficiency": {},
        "claim_2_partitioning_overhead": None,
        "claim_3_schedule_overlap": None,
        "host_artifact_rows": [
            "virtual_mesh_weak_scaling.rows[*].images_per_sec_per_device "
            "(falls ~1/N on a 1-core host BY CONSTRUCTION; the invariant "
            "is total_vs_1dev ~= 1.0)",
            "multiproc_weak_scaling.rows[*].step_time_vs_1proc (grows ~N "
            "on one core BY CONSTRUCTION; records the 8-process cluster "
            "executing the fused dist step CORRECTLY)",
        ],
    }
    corners = art.get("measured_overlap_model", {})
    for corner_key, step_label in (("measured_bw90", "13.9ms measured"),
                                   ("stress_peak_mfu_bw90",
                                    "4.0ms peak-MFU stress")):
        corner = corners.get(corner_key)
        if not isinstance(corner, dict):
            continue
        for key, label in (("n64_zero_conservative",
                            "zero @45GBps one-way"),
                           ("n64_conservative",
                            "allreduce @45GBps one-way"),
                           ("n64_zero", "zero @90GBps bidir"),
                           ("n64", "allreduce @90GBps bidir")):
            row = corner.get(key)
            if row:
                h["claim_1_modeled_n64_efficiency"][
                    "%s, %s" % (step_label, label)] = \
                    row.get("weak_scaling_efficiency")
    vm = art.get("virtual_mesh_weak_scaling", {}).get("rows") or []
    if vm:
        h["claim_2_partitioning_overhead"] = (
            "total throughput flat across 1..8 virtual devices: "
            "total_vs_1dev = %s"
            % [r.get("total_vs_1dev") for r in vm])
    se = art.get("schedule_evidence", {})
    if "n_async_pairs_with_compute_between" in se:
        h["claim_3_schedule_overlap"] = (
            "%d/%d async collective pairs in the TPU-pipeline-compiled "
            "zero step have compute scheduled inside their windows "
            "(%d fused ops total); gradient sync emitted as %d bucketed "
            "all-reduce-scatter fusions"
            % (se["n_async_pairs_with_compute_between"],
               se["n_async_pairs"],
               se["compute_ops_inside_collective_windows"],
               se["n_bucketed_reduce_scatter_fusions"]))
    return h


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-o", "--output", default="SCALING_r05.json")
    ap.add_argument("--skip-virtual", action="store_true")
    args = ap.parse_args()
    art = {"doc": "see docs/scaling_model.md",
           "measured_overlap_model": measured_overlap_model(),
           "schedule_evidence": schedule_evidence(),
           "allreduce_ablation_cpu8": allreduce_ablation(),
           "legacy_analytic_model": analytic_model()}
    if not args.skip_virtual:
        art["virtual_mesh_weak_scaling"] = virtual_mesh_weak_scaling()
    art["multiproc_weak_scaling"] = multiproc_weak_scaling()
    art["collective_bandwidth"] = collective_bandwidth()
    art = {"headline": _headline(art), **art}
    with open(os.path.join(REPO, args.output), "w") as f:
        json.dump(art, f, indent=1)
    print("wrote", args.output)


if __name__ == "__main__":
    main()
