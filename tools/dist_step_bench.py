"""Distributed fused-step timing worker (run under tools/launch.py).

Each worker trains the same conv net through Module.fit's fused SPMD path
(kvstore='tpu' — grads psum across the process mesh each step) on its
rank's shard; rank 0 prints one JSON line with the measured steady-state
step time.  The weak-scaling orchestrator (tools/scaling_evidence.py) runs
this at n=1,2,4,8 workers and records the curve.

Launch:  python tools/launch.py -n 4 --platform cpu \
             python tools/dist_step_bench.py --steps 30
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np

from mxnet_tpu import distributed

distributed.initialize()

import mxnet_tpu as mx  # noqa: E402


def build_net(num_classes=10):
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, num_filter=16, kernel=(3, 3),
                             pad=(1, 1), name="c1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=64, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--per-worker-batch", type=int, default=64)
    ap.add_argument("--image-side", type=int, default=16)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=8)
    args = ap.parse_args()

    kv = mx.kv.create("tpu")
    rank, nworker = kv.rank, kv.num_workers
    rs = np.random.RandomState(rank)
    bs = args.per_worker_batch
    shape = (3, args.image_side, args.image_side)
    X = rs.rand(bs * 4, *shape).astype("f")
    y = rs.randint(0, 10, bs * 4).astype("f")
    it = mx.io.NDArrayIter(X, y, batch_size=bs)

    mod = mx.mod.Module(build_net())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(kvstore=kv, optimizer="sgd",
                       optimizer_params={"learning_rate": 0.01,
                                         "momentum": 0.9})
    assert mod._fused is not None, "fused SPMD path did not engage"

    batches = list(it)

    def run(n):
        for i in range(n):
            b = batches[i % len(batches)]
            mod.forward_backward(b)
            mod.update()
        mod.get_params()  # sync point

    run(args.warmup)
    distributed.barrier("bench_start")
    tic = time.time()
    run(args.steps)
    dt = time.time() - tic
    distributed.barrier("bench_end")
    if rank == 0:
        print(json.dumps({
            "workers": nworker,
            "per_worker_batch": bs,
            "step_ms": round(dt / args.steps * 1e3, 3),
            "images_per_sec_total": round(bs * nworker * args.steps / dt, 1),
        }))
    print("dist_step_bench rank %d/%d: OK" % (rank, nworker))


if __name__ == "__main__":
    main()
