#!/usr/bin/env python
"""Benchmark: training throughput on one TPU chip.

Prints ONE JSON line.  Primary metric: ResNet-50 batch-32 training fed by
the RecordIO input pipeline end-to-end (decode + augment + H2D + fused
train step) — the number a user actually gets.  Baseline: the reference's
published ResNet-50 batch-32 training throughput, 109 images/sec on 1x K80
(BASELINE.md row 1, reference example/image-classification/README.md:154).

Secondary metrics in the same JSON object:
  - compute_img_s: steady-state fused-step throughput on pre-staged
    device batches (input pipeline excluded), the r01/r02 headline.
  - pipeline_decode_img_s: iterator-only decode+augment throughput —
    comparable to the reference's "RecordIO pipeline ~3,000 img/s" row
    (BASELINE.md; reference docs imagenet_full.md:37).
  - inception_bn_img_s / resnet152_img_s: train throughput for the other
    BASELINE.md model rows (152 and 57 img/s on K80).
  - lstm_tok_s: 2-layer LSTM LM tokens/sec (BASELINE config #3 workload;
    the reference publishes no tokens/s number, so no vs_baseline).

Feed path design (TPU-first): the native libjpeg pipeline emits raw uint8
NHWC batches (4x fewer host-link bytes than f32; measured ~10x cheaper to
move across this host's tunneled device link than bf16), and
normalize/transpose/cast run on-device inside the fused step where XLA
folds them into the first convolution.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def _env_int(name, default):
    return int(os.environ.get(name, str(default)))


def _make_trainer(sym_name, batch, input_transforms=None, shapes=None):
    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.parallel import SPMDTrainer

    sym = models.get_symbol(sym_name, num_classes=1000)
    trainer = SPMDTrainer(
        sym, "sgd",
        {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4,
         "rescale_grad": 1.0 / batch},
        mesh=None, compute_dtype="bfloat16",
        input_transforms=input_transforms)
    trainer.bind(shapes or [("data", (batch, 3, 224, 224))],
                 [("softmax_label", (batch,))])
    trainer.init_params(mx.initializer.Xavier(rnd_type="gaussian",
                                              factor_type="in", magnitude=2))
    return trainer


def _staged_batches(batch, n_staged, dtype="bfloat16", shape=(3, 224, 224)):
    import mxnet_tpu as mx
    rs = np.random.RandomState(0)
    staged = []
    for _ in range(n_staged):
        d = mx.nd.array(rs.rand(batch, *shape).astype("f")).astype(dtype)
        l = mx.nd.array(rs.randint(0, 1000, size=batch).astype("f"))
        d.wait_to_read()
        l.wait_to_read()
        staged.append((d, l))
    return staged


def _best_of(fn, trials):
    best = 0.0
    for _ in range(max(1, trials)):
        best = max(best, fn())
    return best


def _compute_bench(trainer, batch, steps, warmup, trials,
                   staged=None):
    """Steady-state fused-step throughput on pre-staged device batches."""
    import jax
    staged = staged or _staged_batches(batch, 8)
    for i in range(warmup):
        trainer.step(*staged[i % len(staged)])
    jax.block_until_ready(trainer.params)

    def trial():
        tic = time.time()
        for i in range(steps):
            trainer.step(*staged[i % len(staged)])
        jax.block_until_ready(trainer.params)
        return batch * steps / (time.time() - tic)

    return _best_of(trial, trials)


def _make_dataset(n_img, side=256):
    """Synthetic RecordIO dataset with natural-image-like JPEG statistics
    (smooth gradients + low-frequency texture; ~13 KB/img at q90, in line
    with 256x256 photographic JPEGs — NOT white noise, which carries ~4x
    the entropy and decodes several times slower than any real photo)."""
    import tempfile

    import cv2

    from mxnet_tpu import recordio

    tmp = tempfile.mkdtemp(prefix="bench_rec_")
    prefix = os.path.join(tmp, "bench")
    rs = np.random.RandomState(0)
    xs = np.linspace(0, 1, side)
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    tex_bank = [
        cv2.GaussianBlur(rs.randn(side, side, 3).astype(np.float32) * 40,
                         (7, 7), 0) for _ in range(16)]
    for i in range(n_img):
        base = (np.outer(xs, np.roll(xs, (i * 37) % side))[..., None]
                * np.array([255, 180, 120])).astype(np.float32)
        img = np.clip(base + tex_bank[i % 16], 0, 255).astype(np.uint8)
        header = recordio.IRHeader(0, float(i % 1000), i, 0)
        rec.write_idx(i, recordio.pack_img(header, img, quality=90))
    rec.close()
    return prefix


def _fed_bench(batch, steps, warmup, trials):
    """End-to-end: RecordIO pipeline -> uint8 NHWC batches -> on-device
    normalize/transpose/cast fused into the train step."""
    import jax
    import jax.numpy as jnp

    import mxnet_tpu as mx

    mean = jnp.array([123.68, 116.28, 103.53], jnp.float32)
    std = jnp.array([58.395, 57.12, 57.375], jnp.float32)

    def data_tf(x):
        x = (x.astype(jnp.float32) - mean) / std
        return jnp.transpose(x, (0, 3, 1, 2)).astype(jnp.bfloat16)

    trainer = _make_trainer("resnet-50", batch,
                            input_transforms={"data": data_tf})

    prefix = _make_dataset(max(batch * 8, 1024))
    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", path_imgidx=prefix + ".idx",
        data_shape=(3, 224, 224), batch_size=batch, shuffle=True,
        rand_crop=True, rand_mirror=True,
        preprocess_threads=_env_int("BENCH_DECODE_THREADS", 8),
        prefetch_buffer=6, dtype="uint8", layout="NHWC", seed=0)

    def batches():
        while True:
            it.reset()
            for b in it:
                yield b

    gen = batches()
    for _ in range(warmup + 8):
        b = next(gen)
        trainer.step(b.data[0], b.label[0])
    jax.block_until_ready(trainer.params)

    def trial():
        tic = time.time()
        for _ in range(steps):
            b = next(gen)
            trainer.step(b.data[0], b.label[0])
        jax.block_until_ready(trainer.params)
        return batch * steps / (time.time() - tic)

    fed = _best_of(trial, trials)

    # iterator-only decode+augment rate (reference pipeline row analog)
    def it_trial():
        n = 0
        tic = time.time()
        for _ in range(steps):
            next(gen)
            n += batch
        return n / (time.time() - tic)

    decode_rate = _best_of(it_trial, trials)
    it.close()
    del trainer  # release HBM (params/momentum/exe) before the next bench
    return fed, decode_rate


def _lstm_bench(batch, seq_len, steps, warmup, trials):
    """2-layer LSTM LM (lstm_bucketing workload, one bucket) tokens/sec."""
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu.models import lstm_lm
    from mxnet_tpu.parallel import SPMDTrainer

    vocab = 10000
    sym, data_names, label_names = lstm_lm.lstm_lm_sym(
        seq_len, vocab, num_embed=200, num_hidden=200, num_layers=2)
    trainer = SPMDTrainer(
        sym, "sgd",
        {"learning_rate": 0.1, "momentum": 0.9, "wd": 0.0,
         "rescale_grad": 1.0 / batch},
        mesh=None, compute_dtype="bfloat16")
    shapes = {"data": (batch, seq_len), "softmax_label": (batch, seq_len)}
    trainer.bind([(n, shapes[n]) for n in data_names],
                 [(n, shapes[n]) for n in label_names])
    trainer.init_params(mx.initializer.Xavier())

    rs = np.random.RandomState(0)
    staged = []
    for _ in range(8):
        d = mx.nd.array(rs.randint(0, vocab, (batch, seq_len)).astype("f"))
        l = mx.nd.array(rs.randint(0, vocab, (batch, seq_len)).astype("f"))
        d.wait_to_read()
        l.wait_to_read()
        staged.append((d, l))
    for i in range(warmup):
        trainer.step(*staged[i % 8])
    jax.block_until_ready(trainer.params)

    def trial():
        tic = time.time()
        for i in range(steps):
            trainer.step(*staged[i % 8])
        jax.block_until_ready(trainer.params)
        return batch * seq_len * steps / (time.time() - tic)

    return _best_of(trial, trials)


def main():
    batch = _env_int("BENCH_BATCH", 32)
    steps = _env_int("BENCH_STEPS", 50)
    warmup = _env_int("BENCH_WARMUP", 10)
    trials = _env_int("BENCH_TRIALS", 3)

    result = {}

    # -- primary: pipeline-fed ResNet-50 ---------------------------------
    fed = decode_rate = None
    if os.environ.get("BENCH_PIPELINE", "1") != "0":
        try:
            fed, decode_rate = _fed_bench(batch, steps, warmup, trials)
        except Exception as e:  # noqa: BLE001 — bench must still report
            sys.stderr.write("fed bench failed: %s\n" % e)

    # -- compute-only ResNet-50 ------------------------------------------
    compute = None
    try:
        tr2 = _make_trainer("resnet-50", batch)
        compute = _compute_bench(tr2, batch, steps, warmup, trials)
        del tr2
    except Exception as e:  # noqa: BLE001
        sys.stderr.write("compute bench failed: %s\n" % e)

    baseline = 109.0  # reference: ResNet-50 batch 32 on 1x K80
    if fed is not None:
        result.update({
            "metric": "resnet50_train_throughput_fed_batch%d" % batch,
            "value": round(fed, 2),
            "unit": "images/sec",
            "vs_baseline": round(fed / baseline, 3),
        })
        if decode_rate is not None:
            # reference RecordIO pipeline row: ~3,000 img/s decode+augment
            result["pipeline_decode_img_s"] = round(decode_rate, 2)
            result["pipeline_decode_vs_baseline"] = round(
                decode_rate / 3000.0, 3)
    if compute is not None:
        if fed is None:
            result.update({
                "metric": "resnet50_train_throughput_batch%d" % batch,
                "value": round(compute, 2),
                "unit": "images/sec",
                "vs_baseline": round(compute / baseline, 3),
            })
        else:
            result["compute_img_s"] = round(compute, 2)
            result["compute_vs_baseline"] = round(compute / baseline, 3)
            result["pipeline_frac_of_compute"] = round(fed / compute, 3)

    # -- model sweep (BASELINE.md rows) -----------------------------------
    if os.environ.get("BENCH_SWEEP", "1") != "0":
        sweep_steps = _env_int("BENCH_SWEEP_STEPS", 30)
        for name, key, base in (("inception-bn", "inception_bn", 152.0),
                                ("resnet-152", "resnet152", 57.0)):
            try:
                tr = _make_trainer(name, batch)
                r = _compute_bench(tr, batch, sweep_steps, warmup,
                                   max(1, trials - 1))
                result["%s_img_s" % key] = round(r, 2)
                result["%s_vs_baseline" % key] = round(r / base, 3)
                del tr
            except Exception as e:  # noqa: BLE001
                sys.stderr.write("%s bench failed: %s\n" % (name, e))
        try:
            toks = _lstm_bench(batch, 32, sweep_steps, warmup,
                               max(1, trials - 1))
            result["lstm_tok_s"] = round(toks, 2)
        except Exception as e:  # noqa: BLE001
            sys.stderr.write("lstm bench failed: %s\n" % e)

    print(json.dumps(result))


if __name__ == "__main__":
    main()
