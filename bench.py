#!/usr/bin/env python
"""Benchmark: ResNet-50 training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: the reference's published ResNet-50 batch-32 training throughput,
109 images/sec on 1x K80 (BASELINE.md row 1,
reference example/image-classification/README.md:154).

The whole train step (fwd+bwd+SGD update, bf16 compute / f32 master
weights) is one fused XLA program via parallel.SPMDTrainer.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def main():
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.parallel import SPMDTrainer

    batch = int(os.environ.get("BENCH_BATCH", "32"))
    steps = int(os.environ.get("BENCH_STEPS", "50"))
    warmup = int(os.environ.get("BENCH_WARMUP", "8"))

    sym = models.get_symbol("resnet-50", num_classes=1000)
    trainer = SPMDTrainer(
        sym, "sgd",
        {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4,
         "rescale_grad": 1.0 / batch},
        mesh=None, compute_dtype="bfloat16")
    trainer.bind([("data", (batch, 3, 224, 224))],
                 [("softmax_label", (batch,))])
    trainer.init_params(mx.initializer.Xavier(rnd_type="gaussian",
                                              factor_type="in", magnitude=2))

    # Pre-stage distinct batches on-device (a prefetching input pipeline
    # keeps the device fed in production; the reference's published numbers
    # likewise run with the RecordIO prefetcher ahead of the GPU).  We
    # measure steady-state training-step throughput.
    rs = np.random.RandomState(0)
    n_staged = 8
    staged = []
    for i in range(n_staged):
        d = mx.nd.array(rs.rand(batch, 3, 224, 224).astype("f")) \
            .astype("bfloat16")
        l = mx.nd.array(rs.randint(0, 1000, size=batch).astype("f"))
        d.wait_to_read()
        l.wait_to_read()
        staged.append((d, l))

    for i in range(warmup):
        trainer.step(*staged[i % n_staged])
    jax.block_until_ready(trainer.params)

    # several timed trials, best one: the steady-state number (host/tunnel
    # scheduling jitter only ever subtracts throughput)
    trials = int(os.environ.get("BENCH_TRIALS", "3"))
    img_per_sec = 0.0
    for _ in range(max(1, trials)):
        tic = time.time()
        for i in range(steps):
            trainer.step(*staged[i % n_staged])
        jax.block_until_ready(trainer.params)
        img_per_sec = max(img_per_sec, batch * steps / (time.time() - tic))
    baseline = 109.0  # reference: ResNet-50 batch 32 on 1x K80

    # End-to-end mode: the RecordIO pipeline (decode+augment on engine
    # threads) feeding the same trainer — the reference's published numbers
    # run with its C++ RecordIO prefetcher ahead of the device
    # (BASELINE config #2; pipeline baseline ~3,000 img/s/host,
    # docs imagenet_full.md:37).  Reported alongside compute-only.
    pipe_img_per_sec = None
    if os.environ.get("BENCH_PIPELINE", "1") != "0":
        try:
            pipe_img_per_sec = _pipeline_bench(trainer, batch, steps,
                                               warmup)
        except Exception as e:  # noqa: BLE001 — bench must still report
            sys.stderr.write("pipeline bench skipped: %s\n" % e)

    result = {
        "metric": "resnet50_train_throughput_batch%d" % batch,
        "value": round(img_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_per_sec / baseline, 3),
    }
    if pipe_img_per_sec is not None:
        result["pipeline_img_s"] = round(pipe_img_per_sec, 2)
        result["pipeline_frac_of_compute"] = round(
            pipe_img_per_sec / img_per_sec, 3)
    print(json.dumps(result))


def _pipeline_bench(trainer, batch, steps, warmup):
    """Train-step throughput with the threaded ImageRecordIter feeding
    (decode + augment + batch assembly on host engine workers)."""
    import tempfile

    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import recordio

    n_img = max(batch * 4, 256)
    tmp = tempfile.mkdtemp(prefix="bench_rec_")
    prefix = os.path.join(tmp, "bench")
    rs = np.random.RandomState(0)
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(n_img):
        img = rs.randint(0, 255, (256, 256, 3)).astype(np.uint8)
        header = recordio.IRHeader(0, float(rs.randint(0, 1000)), i, 0)
        rec.write_idx(i, recordio.pack_img(header, img, quality=90))
    rec.close()

    # dtype=bfloat16: cast on host so H2D moves half the bytes
    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", path_imgidx=prefix + ".idx",
        data_shape=(3, 224, 224), batch_size=batch, shuffle=True,
        rand_crop=True, rand_mirror=True, preprocess_threads=8,
        prefetch_buffer=8, dtype="bfloat16")

    def batches():
        while True:
            it.reset()
            for b in it:
                yield b

    gen = batches()
    for _ in range(warmup):
        b = next(gen)
        trainer.step(b.data[0], b.label[0])
    jax.block_until_ready(trainer.params)

    # same best-of-N treatment as the compute-only number, so the
    # reported fraction compares like with like
    best = 0.0
    for _ in range(max(1, int(os.environ.get("BENCH_TRIALS", "3")))):
        tic = time.time()
        for _ in range(steps):
            b = next(gen)
            trainer.step(b.data[0], b.label[0])
        jax.block_until_ready(trainer.params)
        best = max(best, batch * steps / (time.time() - tic))
    return best


if __name__ == "__main__":
    main()
