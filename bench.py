#!/usr/bin/env python
"""Benchmark: training throughput on one TPU chip.

Prints ONE JSON line.  Primary metric: ResNet-50 batch-32 training fed by
the RecordIO input pipeline end-to-end (decode + augment + H2D + fused
train step) — the number a user actually gets.  Baseline: the reference's
published ResNet-50 batch-32 training throughput, 109 images/sec on 1x K80
(BASELINE.md row 1, reference example/image-classification/README.md:154).

Secondary metrics in the same JSON object:
  - compute_img_s: steady-state fused-step throughput on pre-staged
    device batches (input pipeline excluded), the r01/r02 headline.
  - pipeline_decode_img_s: iterator-only decode+augment throughput —
    comparable to the reference's "RecordIO pipeline ~3,000 img/s" row
    (BASELINE.md; reference docs imagenet_full.md:37).
  - inception_bn_img_s / resnet152_img_s: train throughput for the other
    BASELINE.md model rows (152 and 57 img/s on K80).
  - lstm_tok_s: 2-layer LSTM LM tokens/sec (BASELINE config #3 workload;
    the reference publishes no tokens/s number, so no vs_baseline).

Feed path design (TPU-first): the native libjpeg pipeline emits raw uint8
NHWC batches (4x fewer host-link bytes than f32; measured ~10x cheaper to
move across this host's tunneled device link than bf16), and
normalize/transpose/cast run on-device inside the fused step where XLA
folds them into the first convolution.

Measurement caveat (recorded in the JSON as pipeline_note): this harness
reaches its single TPU chip through a tunneled remote-device link with
~100 ms per-operation round-trip latency under concurrent traffic.
Interleaving per-batch host->device uploads with train-step launches is
therefore latency-bound HERE in a way it is not on a directly-attached
TPU host: the same pipeline sustains >3,000 img/s of decode (single
core), and the same train step sustains ~2,300 img/s when batches are
staged — the fed number reflects the link, not the framework.  Each
metric runs in its own subprocess (see _collect).

Roofline accounting (round-5 correction): on this tunneled backend
``jax.block_until_ready`` returns on dispatch acknowledgement, NOT on
device completion — a dependent 64-matmul chain "timed" at 185 PFLOP/s
(940x the chip's peak) under that sync, which is how earlier rounds
recorded a ResNet-152 rate above 100% MFU.  The only true completion
barrier here is a device->host fetch of a value that data-depends on the
result.  Every on-chip metric therefore times S1 and S2 steps each ended
by a scalar fetch of the updated parameters and takes the slope
(work-scaling), which also cancels the ~60 ms fixed tunnel round-trip.
Calibration under this method: sustained large-matmul bf16 rate is
~172 TFLOP/s = 87% of the v5e's 197 TFLOP/s nominal peak (sane).  Each
model metric carries {flops_per_img, tflops, mfu} from analytic model
FLOPs (contrib/flops.py, 1 MAC = 2 FLOPs, training = 3x forward;
cross-checked against XLA cost_analysis: 69.1 vs 67.2 GFLOP/img for the
ResNet-152 train step) against the chip's nominal peak, and the run
fails loudly if any MFU exceeds 1.0.
"""
import contextlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def _env_int(name, default):
    return int(os.environ.get(name, str(default)))


#: sentinel for _scoped_env: "don't touch the value on entry" (the body
#: sets its own values; only the exit-time restore is wanted)
_KEEP = object()


@contextlib.contextmanager
def _scoped_env(name, value=_KEEP):
    """Scoped RAW save/restore of one environment variable.

    Deliberately raw (not get_env): the restore must distinguish "the
    operator never set it" (pop) from an explicit value, and get_env
    cannot — it substitutes the registered default, so a round-trip
    through it would leave later modes measuring under the default
    instead of the operator's (absent) setting.  ``value`` is applied
    on entry (``None`` unsets for the scope; the ``_KEEP`` default
    leaves the current value alone — for bodies that steer the
    variable themselves and only need the exit-time restore)."""
    prev = os.environ.get(name)
    if value is None:
        os.environ.pop(name, None)
    elif value is not _KEEP:
        os.environ[name] = value
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = prev


def _make_trainer(sym_name, batch, input_transforms=None, shapes=None):
    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.parallel import SPMDTrainer

    sym = models.get_symbol(sym_name, num_classes=1000)
    trainer = SPMDTrainer(
        sym, "sgd",
        {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4,
         "rescale_grad": 1.0 / batch},
        mesh=None, compute_dtype="bfloat16",
        input_transforms=input_transforms)
    trainer.bind(shapes or [("data", (batch, 3, 224, 224))],
                 [("softmax_label", (batch,))])
    trainer.init_params(mx.initializer.Xavier(rnd_type="gaussian",
                                              factor_type="in", magnitude=2))
    return trainer


def _staged_batches(batch, n_staged, dtype="bfloat16", shape=(3, 224, 224)):
    import mxnet_tpu as mx
    rs = np.random.RandomState(0)
    staged = []
    for _ in range(n_staged):
        d = mx.nd.array(rs.rand(batch, *shape).astype("f")).astype(dtype)
        l = mx.nd.array(rs.randint(0, 1000, size=batch).astype("f"))
        d.wait_to_read()
        l.wait_to_read()
        staged.append((d, l))
    return staged


def _best_of(fn, trials):
    best = 0.0
    for _ in range(max(1, trials)):
        best = max(best, fn())
    return best


#: nominal dense bf16 peak by device_kind, TFLOP/s.  Values are the
#: published per-chip numbers; 'cpu' has no meaningful MXU peak.
PEAK_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,
    "TPU v5": 459.0,
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,
    "TPU v6e": 918.0,
}


def _device_peak():
    import jax
    d = jax.devices()[0]
    return d.device_kind, PEAK_TFLOPS.get(d.device_kind)


def _fetch_sync(trainer):
    """TRUE completion barrier: fetch a scalar that data-depends on the
    freshest parameters.  jax.block_until_ready returns on dispatch ack
    on this tunneled backend (see module docstring), so only a
    device->host read of post-update state proves the steps ran."""
    import jax.numpy as jnp
    name = min(trainer.params, key=lambda k: trainer.params[k].size)
    return float(jnp.sum(trainer.params[name].astype(jnp.float32)))


def _slope_rate(run_steps, sync, s1, s2, trials):
    """Work-scaling rate for an arbitrary step driver: time s1 and s2
    steps, each ended by ``sync`` (a dependent-scalar fetch); the slope
    cancels the fixed tunnel RTT (~60 ms/fetch) that would otherwise be
    billed to the device.  Raises instead of returning a bogus 0 when no
    trial yields a positive slope (clock anomaly): the metric then comes
    back missing from the artifact, not silently zero."""
    def timed(nsteps):
        tic = time.perf_counter()
        run_steps(nsteps)
        sync()
        return time.perf_counter() - tic

    best = 0.0
    for _ in range(max(1, trials)):
        t1 = timed(s1)
        t2 = timed(s2)
        if t2 > t1:
            best = max(best, (s2 - s1) / (t2 - t1))
    if best <= 0.0:
        raise RuntimeError(
            "work-scaling slope non-positive across %d trials "
            "(s1=%d, s2=%d) — timing anomaly, refusing to report" %
            (trials, s1, s2))
    return best


def _steps_per_sec(trainer, staged, s1, s2, trials):
    return _slope_rate(
        lambda n: [trainer.step(*staged[i % len(staged)])
                   for i in range(n)],
        lambda: _fetch_sync(trainer), s1, s2, trials)


def _roofline(per_item_rate, flops_per_item):
    """{tflops, mfu, ...} block for one model metric."""
    kind, peak = _device_peak()
    tflops = per_item_rate * flops_per_item / 1e12
    out = {"flops_per_item": int(flops_per_item),
           "tflops": round(tflops, 2)}
    if peak:
        out["mfu"] = round(tflops / peak, 4)
    return out


def _compute_bench(trainer, batch, steps, warmup, trials,
                   staged=None):
    """Steady-state fused-step throughput on pre-staged device batches,
    measured by fetch-synced work-scaling (never block_until_ready)."""
    staged = staged or _staged_batches(batch, 8)
    for i in range(warmup):
        trainer.step(*staged[i % len(staged)])
    _fetch_sync(trainer)
    s1 = max(4, steps // 4)
    return batch * _steps_per_sec(trainer, staged, s1, s1 + steps, trials)


def _make_dataset(n_img, side=256, classes=1000):
    """Synthetic RecordIO dataset with natural-image-like JPEG statistics
    (smooth gradients + low-frequency texture; ~13 KB/img at q90, in line
    with 256x256 photographic JPEGs — NOT white noise, which carries ~4x
    the entropy and decodes several times slower than any real photo).
    ``classes`` bounds the labels: a consumer training a small head must
    ask for a matching range — out-of-range labels under SoftmaxOutput
    one-hot to a ZERO row, so every such example pushes all logits down
    and the fed loop diverges (the fed-cpu guard abort on this host)."""
    import tempfile

    import cv2

    from mxnet_tpu import recordio

    tmp = tempfile.mkdtemp(prefix="bench_rec_")
    prefix = os.path.join(tmp, "bench")
    rs = np.random.RandomState(0)
    xs = np.linspace(0, 1, side)
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    tex_bank = [
        cv2.GaussianBlur(rs.randn(side, side, 3).astype(np.float32) * 40,
                         (7, 7), 0) for _ in range(16)]
    for i in range(n_img):
        base = (np.outer(xs, np.roll(xs, (i * 37) % side))[..., None]
                * np.array([255, 180, 120])).astype(np.float32)
        img = np.clip(base + tex_bank[i % 16], 0, 255).astype(np.uint8)
        header = recordio.IRHeader(0, float(i % classes), i, 0)
        rec.write_idx(i, recordio.pack_img(header, img, quality=90))
    rec.close()
    return prefix


def _fed_bench(batch, steps, warmup, trials):
    """End-to-end: RecordIO pipeline -> uint8 NHWC batches -> device-side
    normalize/transpose/cast in the pipeline's upload stage (overlapped
    across in-flight batches) -> the plain bf16 fused train step."""
    import jax
    import jax.numpy as jnp

    import mxnet_tpu as mx

    mean = jnp.array([123.68, 116.28, 103.53], jnp.float32)
    std = jnp.array([58.395, 57.12, 57.375], jnp.float32)
    pre = jax.jit(lambda x: jnp.transpose(
        (x.astype(jnp.float32) - mean) / std, (0, 3, 1, 2))
        .astype(jnp.bfloat16))

    variant = os.environ.get("BENCH_FED_VARIANT", "instep")
    if variant == "instep":
        def data_tf(x):
            x = (x.astype(jnp.float32) - mean) / std
            return jnp.transpose(x, (0, 3, 1, 2)).astype(jnp.bfloat16)
        trainer = _make_trainer("resnet-50", batch,
                                input_transforms={"data": data_tf})
        pre = None
    else:
        trainer = _make_trainer("resnet-50", batch)

    prefix = _make_dataset(max(batch * 8, 1024))
    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", path_imgidx=prefix + ".idx",
        data_shape=(3, 224, 224), batch_size=batch, shuffle=True,
        rand_crop=True, rand_mirror=True,
        preprocess_threads=_env_int("BENCH_DECODE_THREADS", 8),
        prefetch_buffer=6, dtype="uint8", layout="NHWC",
        device_transform=pre, seed=0)

    def batches():
        while True:
            it.reset()
            for b in it:
                yield b

    gen = batches()

    def run_steps(n):
        for _ in range(n):
            b = next(gen)
            trainer.step(b.data[0], b.label[0])

    run_steps(warmup + 8)
    _fetch_sync(trainer)
    s1 = max(4, steps // 4)
    fed = batch * _slope_rate(run_steps, lambda: _fetch_sync(trainer),
                              s1, s1 + steps, trials)
    it.close()
    trainer.close()  # release HBM (params/momentum/exe) before the next bench
    return fed


def _decode_bench(batch=128, n_img=1024, trials=3):
    """Pure host-side decode+augment throughput with ZERO device
    involvement: the iterator runs in host_batches mode (numpy output, the
    exact product the reference's C++ parser hands out) on the CPU
    platform, in this metric's own subprocess.  Reports total img/s per
    thread count (1/2/4/8) plus the 1-thread per-core number — on a
    single-core host the scaling rows are flat by construction and the
    per-core number IS the capability claim.

    Reference anchor: "~3,000 images/sec decode+augment" for the whole
    2017 multi-core host (docs/tutorials/computer_vision/imagenet_full.md:37,
    C++ parser src/io/iter_image_recordio_2.cc:27-80)."""
    import mxnet_tpu as mx

    prefix = _make_dataset(n_img)
    scaling = {}
    for threads in (1, 2, 4, 8):
        it = mx.io.ImageRecordIter(
            path_imgrec=prefix + ".rec", path_imgidx=prefix + ".idx",
            data_shape=(3, 224, 224), batch_size=batch, shuffle=True,
            rand_crop=True, rand_mirror=True, preprocess_threads=threads,
            prefetch_buffer=4, dtype="uint8", layout="NHWC", seed=0,
            host_batches=True, data_service=False)  # this metric IS the
        # in-process pipe — an ambient MXTPU_DATA_WORKERS must not
        # silently remeasure the service under the pipe's key
        for b in it:   # warm epoch (thread pools, buffers, page cache)
            pass

        def it_trial():
            it.reset()
            n = 0
            tic = time.time()
            for b in it:
                n += b.data[0].shape[0]
            return n / (time.time() - tic)

        scaling[threads] = round(_best_of(it_trial, trials), 2)
        it.close()
    out = {
        "decode": max(scaling.values()),
        "decode_per_core": scaling[1],
        "decode_scaling": scaling,
        "decode_scaling_x": round(max(scaling.values()) / scaling[1], 3),
        "ncores": os.cpu_count(),
    }
    if (os.cpu_count() or 1) == 1:
        # honesty note: with one core the 1/2/4/8 rows are flat BY
        # CONSTRUCTION — the gate skips scaling-shape comparisons on
        # such hosts so a 1-core CI box can neither mask nor fake a
        # real scaling regression (see gate())
        out["decode_scaling_note"] = "flat_by_construction_1core"
    return out


def _data_service_bench(batch=128, n_img=1024, trials=2):
    """The multi-process shared-memory data service
    (mxnet_tpu/data_service/, docs/how_to/performance.md "Scaling the
    input pipeline") against the in-process pipe, pure host work:

      - data_service_transport_overhead: service at workers=1 vs the raw
        in-process native pipe at preprocess_threads=1 — the cost of the
        process hop + ring (decode lands directly in shared memory, the
        collector hands zero-copy views, so this should be < 10% and is
        typically NEGATIVE: the consumer stops stealing decode cycles).
      - data_service_scaling: img/s per worker-process count; with >1
        core this must scale near-linearly where the in-process pipe is
        flat (decode_scaling).  data_service_scaling_x is the ratio at
        min(4, ncores) workers vs 1; linear would equal that worker
        count (data_service_linear_frac = x / workers >= 0.7 is the
        acceptance bar).  On a 1-core host every row is flat by
        construction and the note tells the gate to skip the shape.
      - per-stage counters from the service's stats() surface
        (producer/consumer stall %, mean ring occupancy).
    """
    import mxnet_tpu as mx

    prefix = _make_dataset(n_img)
    ncores = os.cpu_count() or 1
    kw = dict(path_imgrec=prefix + ".rec", path_imgidx=prefix + ".idx",
              data_shape=(3, 224, 224), batch_size=batch, shuffle=True,
              rand_crop=True, rand_mirror=True, prefetch_buffer=4,
              dtype="uint8", layout="NHWC", seed=0, host_batches=True)

    def measure(it):
        """(best img/s, stats-delta of the best trial) after one warm
        epoch."""
        for b in it:
            pass
        best, best_stats = 0.0, None
        for _ in range(max(1, trials)):
            before = it.stats()
            it.reset()
            n = 0
            tic = time.time()
            for b in it:
                n += b.data[0].shape[0]
            dt = time.time() - tic
            rate = n / dt
            if rate > best:
                best = rate
                after = it.stats()
                if after is not None:
                    best_stats = {
                        "elapsed_s": dt,
                        "workers": after["num_workers"],
                        "producer_stall_s":
                            after["producer_stall_s"]
                            - (before or after)["producer_stall_s"],
                        "consumer_stall_s":
                            after["consumer_stall_s"]
                            - (before or after)["consumer_stall_s"],
                        "ring_occupancy": after["ring_occupancy"],
                    }
        it.close()
        return best, best_stats

    # data_service=False pins the baseline to the in-process pipe even
    # when an ambient MXTPU_DATA_WORKERS would route it (a service-vs-
    # service "overhead" of ~0 would be a lie)
    inproc, _ = measure(mx.io.ImageRecordIter(
        preprocess_threads=1, data_service=False, **kw))

    scaling, stats_at = {}, {}
    for w in (1, 2, 4, 8):
        svc, st = measure(mx.io.ImageRecordIter(
            preprocess_threads=w, data_service=True, **kw))
        scaling[w] = round(svc, 2)
        if st is not None:
            stats_at[w] = st

    # the recordio readahead satellite: the same w=1 service with the
    # posix_fadvise window off — the before/after of
    # MXTPU_DATA_READAHEAD (page-cache-warm hosts show ~0; cold/remote
    # storage is where the window pays); workers inherit the env
    with _scoped_env("MXTPU_DATA_READAHEAD", "0"):
        ra_off, _ = measure(mx.io.ImageRecordIter(
            preprocess_threads=1, data_service=True, **kw))

    # largest MEASURED worker count within min(4, ncores) — ncores==3
    # must pick row 2, not KeyError on a row that was never measured
    w_target = max((w for w in scaling if w <= min(4, ncores)),
                   default=1) if ncores > 1 else 1
    sx = round(scaling[w_target] / scaling[1], 3) if scaling[1] else 0.0
    out = {
        "data_service_img_s": max(scaling.values()),
        "data_service_scaling": scaling,
        "data_service_scaling_x": sx,
        "data_service_scaling_workers": w_target,
        "data_service_linear_frac": round(sx / max(1, w_target), 3),
        "data_service_inproc_img_s": round(inproc, 2),
        "data_service_transport_overhead": round(
            1.0 - scaling[1] / inproc, 3) if inproc else None,
        "data_service_readahead_img_s": scaling[1],
        "data_service_readahead_off_img_s": round(ra_off, 2),
        "data_service_readahead_x": round(scaling[1] / ra_off, 3)
        if ra_off else None,
        "data_service_ncores": ncores,
    }
    st = stats_at.get(w_target)
    if st is not None and st["elapsed_s"] > 0:
        out["data_service_producer_stall_pct"] = round(
            100.0 * st["producer_stall_s"]
            / (st["workers"] * st["elapsed_s"]), 1)
        out["data_service_consumer_stall_pct"] = round(
            100.0 * st["consumer_stall_s"] / st["elapsed_s"], 1)
        out["data_service_ring_occupancy"] = st["ring_occupancy"]
    if ncores == 1:
        out["data_service_scaling_note"] = "flat_by_construction_1core"
    return out


def _spawn_data_servers(count, port_dir):
    """``count`` loopback ``tools/data_server.py`` processes (jax-free —
    each holds ONE python interpreter + its decode workers, the real
    remote-host footprint).  Returns (procs, 'host:port,host:port').

    Deliberately standalone from tests/conftest.spawn_data_server: this
    runs inside bench metric subprocesses, which must not import
    pytest/jax-side conftest machinery.  On ANY bring-up failure the
    already-spawned servers are killed before raising — the caller's
    finally block only sees fully-built fleets."""
    import subprocess
    here = os.path.dirname(os.path.abspath(__file__))
    procs, addrs = [], []
    try:
        for n in range(count):
            pf = os.path.join(port_dir, "ds-port-%d" % n)
            if os.path.exists(pf):
                os.remove(pf)
            procs.append(subprocess.Popen(
                [sys.executable,
                 os.path.join(here, "tools", "data_server.py"),
                 "--port", "0", "--port-file", pf],
                stderr=subprocess.DEVNULL))
            deadline = time.monotonic() + 30
            while not os.path.exists(pf):
                if procs[-1].poll() is not None:
                    raise RuntimeError(
                        "data server %d died at startup (rc=%s)"
                        % (n, procs[-1].returncode))
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        "data server %d did not come up" % n)
                time.sleep(0.05)
            with open(pf) as f:
                addrs.append(f.read().strip())
    except BaseException:
        for p in procs:
            p.kill()
        raise
    return procs, ",".join(addrs)


def _data_net_bench(batch=128, n_img=1024, trials=2):
    """The NETWORK tier of the data service (mxnet_tpu/data_service/net.py
    + tools/data_server.py; docs/how_to/performance.md) against the
    in-process service, loopback sockets, pure host work:

      - data_net_transport_overhead: ONE loopback server (1 decode
        worker) vs the in-process service at workers=1 — the cost of
        the TCP hop + frame crc on top of PR 7's process hop
        (acceptance: <= 15%).
      - data_net_scaling: img/s per SERVER-process count (1/2/4, one
        decode worker each); server processes are what a real
        deployment adds per CPU host, so this is the disaggregation
        curve the tier exists for.  data_net_scaling_x is the ratio at
        the largest measured count the host's cores can actually run
        concurrently (consumer + S servers + S workers); hosts with
        < 4 cores emit data_net_scaling_note and the gate skips the
        SHAPE key (absolute throughput still gates).
    """
    import shutil
    import tempfile

    import mxnet_tpu as mx

    prefix = _make_dataset(n_img)
    ncores = os.cpu_count() or 1
    kw = dict(path_imgrec=prefix + ".rec", path_imgidx=prefix + ".idx",
              data_shape=(3, 224, 224), batch_size=batch, shuffle=True,
              rand_crop=True, rand_mirror=True, prefetch_buffer=4,
              dtype="uint8", layout="NHWC", seed=0, host_batches=True)

    def measure(it):
        for b in it:
            pass
        best = 0.0
        for _ in range(max(1, trials)):
            it.reset()
            n = 0
            tic = time.time()
            for b in it:
                n += b.data[0].shape[0]
            best = max(best, n / (time.time() - tic))
        it.close()
        return best

    inproc = measure(mx.io.ImageRecordIter(
        preprocess_threads=1, data_service=True, **kw))

    port_dir = tempfile.mkdtemp(prefix="bench_data_net_")
    scaling = {}
    try:
        for nserv in (1, 2, 4):
            procs, addrs = _spawn_data_servers(nserv, port_dir)
            try:
                scaling[nserv] = round(measure(mx.io.ImageRecordIter(
                    preprocess_threads=1, data_service=addrs, **kw)), 2)
            finally:
                for p in procs:
                    p.terminate()
                for p in procs:
                    try:
                        p.wait(timeout=10)
                    except Exception:  # noqa: BLE001 — bounded teardown
                        p.kill()
    finally:
        shutil.rmtree(port_dir, ignore_errors=True)

    # largest measured server count whose decode workers + the consumer
    # fit the host's cores (the server streamer threads are I/O-bound)
    s_target = max((s for s in scaling
                    if s <= min(4, max(1, ncores - 1))), default=1)
    sx = round(scaling[s_target] / scaling[1], 3) if scaling[1] else 0.0
    overhead = round(1.0 - scaling[1] / inproc, 3) if inproc else None
    out = {
        "data_net_img_s": max(scaling.values()),
        "data_net_scaling": scaling,
        "data_net_scaling_x": sx,
        "data_net_scaling_servers": s_target,
        "data_net_inproc_img_s": round(inproc, 2),
        "data_net_transport_overhead": overhead,
        "data_net_transport_ok": overhead is not None and overhead <= 0.15,
        "data_net_ncores": ncores,
    }
    if ncores < 4:
        # consumer + S servers + S decode workers structurally cannot
        # run concurrently on this host: the scaling SHAPE is
        # meaningless here (the SCALING_SHAPE_KEYS honesty contract);
        # absolute throughput and transport overhead still gate
        out["data_net_scaling_note"] = \
            "flat_by_construction_%dcore" % ncores
    return out


def _fed_cpu_bench(batch=64, steps=40, warmup=8, trials=3):
    """Overlap proof on the CPU backend (no tunneled link): pipeline ->
    device_put -> fused step.  Computes decode-only rate D, staged
    step-only rate S, and the fed rate F.  The feed machinery hides its
    latency when F reaches the host's ceiling: min(D, S) when decode and
    compute can run on different cores, else the single-core serial bound
    1/(1/D + 1/S) — one core cannot decode and matmul at once, so on a
    1-core host the demonstrable property is that the pipeline adds no
    extra serialization on top of the CPU-bound work."""
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu.parallel import SPMDTrainer

    # labels bounded to THIS net's 10-class head (see _make_dataset)
    prefix = _make_dataset(512, side=96, classes=10)
    shape = (3, 64, 64)

    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, num_filter=16, kernel=(3, 3),
                             pad=(1, 1), name="c1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = mx.sym.Convolution(net, num_filter=32, kernel=(3, 3),
                             pad=(1, 1), name="c2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    def make_it(host):
        # mean/std normalization: raw 0-255 pixels into an SGD step at
        # lr 0.01 diverge to non-finite weights within the warmup on
        # this host (the step guard then aborts the bench) — normalized
        # inputs keep the measured work identical and the loop stable
        return mx.io.ImageRecordIter(
            path_imgrec=prefix + ".rec", path_imgidx=prefix + ".idx",
            data_shape=shape, batch_size=batch, shuffle=True,
            rand_crop=True, rand_mirror=True, preprocess_threads=2,
            mean_r=127.0, mean_g=127.0, mean_b=127.0,
            std_r=60.0, std_g=60.0, std_b=60.0,
            prefetch_buffer=4, dtype="float32", seed=0, host_batches=host)

    trainer = SPMDTrainer(
        net, "sgd", {"learning_rate": 0.01, "momentum": 0.9,
                     "rescale_grad": 1.0 / batch},
        mesh=None, compute_dtype="float32")
    trainer.bind([("data", (batch,) + shape)],
                 [("softmax_label", (batch,))])
    trainer.init_params(mx.initializer.Xavier())

    # D: decode-only
    it = make_it(host=True)
    for b in it:
        pass

    def d_trial():
        it.reset()
        n = 0
        tic = time.time()
        for b in it:
            n += b.data[0].shape[0]
        return n / (time.time() - tic)

    D = _best_of(d_trial, trials)
    it.close()

    # S: step-only on staged device batches
    rs = np.random.RandomState(0)
    staged = []
    for _ in range(4):
        d = mx.nd.array(rs.rand(batch, *shape).astype("f"))
        l = mx.nd.array(rs.randint(0, 10, (batch,)).astype("f"))
        d.wait_to_read()
        staged.append((d, l))
    for i in range(warmup):
        trainer.step(*staged[i % 4])
    jax.block_until_ready(trainer.params)

    def s_trial():
        tic = time.time()
        for i in range(steps):
            trainer.step(*staged[i % 4])
        jax.block_until_ready(trainer.params)
        return batch * steps / (time.time() - tic)

    S = _best_of(s_trial, trials)

    # F: fed end-to-end
    it = make_it(host=False)

    def batches():
        while True:
            it.reset()
            for b in it:
                yield b

    gen = batches()
    for _ in range(warmup):
        b = next(gen)
        trainer.step(b.data[0], b.label[0])
    jax.block_until_ready(trainer.params)

    def f_trial():
        tic = time.time()
        for _ in range(steps):
            b = next(gen)
            trainer.step(b.data[0], b.label[0])
        jax.block_until_ready(trainer.params)
        return batch * steps / (time.time() - tic)

    F = _best_of(f_trial, trials)
    it.close()

    ncores = os.cpu_count() or 1
    ceiling = min(D, S) if ncores > 1 else 1.0 / (1.0 / D + 1.0 / S)
    return {
        "fed_cpu": round(F, 2),
        "fed_cpu_decode": round(D, 2),
        "fed_cpu_step": round(S, 2),
        "fed_cpu_ceiling": round(ceiling, 2),
        "fed_cpu_overlap": round(F / ceiling, 3),
    }


def _pipeline_bench(batch=64, steps=40, warmup=6, trials=3):
    """Async input-pipeline overlap proof on the CPU backend: fused-step
    steps/sec against a DELIBERATELY SLOW host iterator (a per-batch
    sleep calibrated to ~1.5x the staged step time), with prefetch depth
    0 (synchronous staging on the consuming thread) vs depth 2
    (DevicePrefetchIter staging on a background thread).  The serial
    bound is 1/(delay+step); full overlap reaches 1/max(delay, step) —
    with delay = 1.5x step that is a ~1.67x ceiling, so the reported
    speedup demonstrates real overlap, not noise."""
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu.dataflow import DevicePrefetchIter
    from mxnet_tpu.parallel import SPMDTrainer

    dim, classes = 256, 10
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=1024, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=1024, name="fc2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc3")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    trainer = SPMDTrainer(
        net, "sgd", {"learning_rate": 0.1, "momentum": 0.9,
                     "rescale_grad": 1.0 / batch},
        mesh=None)
    trainer.bind([("data", (batch, dim))], [("softmax_label", (batch,))])
    trainer.init_params(mx.initializer.Xavier())

    rs = np.random.RandomState(0)
    X = rs.randn(batch * 16, dim).astype("f")
    y = rs.randint(0, classes, batch * 16).astype("f")

    # calibrate: staged step-only time (batches pre-placed, warm program)
    staged = [trainer.stage_batch(X[i:i + batch], y[i:i + batch])
              for i in range(0, batch * 4, batch)]
    from mxnet_tpu.io import StagedBatch
    staged = [StagedBatch(s, data=[], label=[]) for s in staged]
    for i in range(warmup):
        trainer.step(staged[i % len(staged)])
    jax.block_until_ready(trainer.params)
    tic = time.perf_counter()
    for i in range(steps):
        trainer.step(staged[i % len(staged)])
    jax.block_until_ready(trainer.params)
    step_s = (time.perf_counter() - tic) / steps
    delay = max(1.5 * step_s, 0.002)

    class SlowIter(mx.io.NDArrayIter):
        """Host iterator with a fixed per-batch stall (sleep releases the
        GIL, like real decode/storage waits do)."""

        def next(self):
            time.sleep(delay)
            return super().next()

        __next__ = next

    def run(depth):
        src = SlowIter(X, y, batch_size=batch)
        it = DevicePrefetchIter(src, stage=trainer, depth=depth)
        gen = iter(self_repeat(it))
        for _ in range(warmup):
            trainer.step(next(gen))
        jax.block_until_ready(trainer.params)

        def trial():
            tic = time.perf_counter()
            for _ in range(steps):
                trainer.step(next(gen))
            jax.block_until_ready(trainer.params)
            return steps / (time.perf_counter() - tic)

        best = _best_of(trial, trials)
        it.close()
        return best

    def self_repeat(it):
        while True:
            it.reset()
            for b in it:
                yield b

    d0 = run(0)
    d2 = run(2)
    trainer.close()
    return {
        "pipeline_steps_s_depth0": round(d0, 2),
        "pipeline_steps_s_depth2": round(d2, 2),
        "pipeline_speedup": round(d2 / d0, 3),
        "pipeline_step_ms": round(step_s * 1e3, 3),
        "pipeline_iter_delay_ms": round(delay * 1e3, 3),
    }


def _compile_probe():
    """Bring-up time: trainer construction + bind + first step, the part
    MXTPU_COMPILE_CACHE amortizes.  Run twice in fresh subprocesses with
    the same cache dir: run 1 = cold (compiles + populates), run 2 = warm
    (loads compiled programs from disk)."""
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu.parallel import SPMDTrainer

    batch, side = 32, 32
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, num_filter=32, kernel=(3, 3),
                             pad=(1, 1), name="c1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = mx.sym.Convolution(net, num_filter=64, kernel=(3, 3),
                             pad=(1, 1), name="c2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    rs = np.random.RandomState(0)
    X = rs.rand(batch, 3, side, side).astype("f")
    y = rs.randint(0, 10, batch).astype("f")

    tic = time.perf_counter()
    trainer = SPMDTrainer(
        net, "sgd", {"learning_rate": 0.1, "rescale_grad": 1.0 / batch},
        mesh=None)
    trainer.bind([("data", (batch, 3, side, side))],
                 [("softmax_label", (batch,))])
    trainer.init_params(mx.initializer.Xavier())
    trainer.step(X, y)
    jax.block_until_ready(trainer.params)
    bringup = time.perf_counter() - tic
    trainer.close()
    return {"compile_bringup_s": round(bringup, 3)}


def _resume_bench(steps=60, batch=64):
    """resume_overhead: the wall-clock price of surviving a preemption —
    mid-run checkpoint save + fresh-trainer restore + refit of the
    remaining steps to parity — against an uninterrupted run of the same
    total step budget (CPU backend: this measures the framework's
    save/restore/recompile machinery, not the chip).  The refit finishes
    BIT-identical to the baseline (asserted), so "refit-to-parity" is
    exactly the second half's steps; the overhead is save + restore +
    the relaunch recompile (the part MXTPU_COMPILE_CACHE amortizes)."""
    import shutil
    import tempfile

    import mxnet_tpu as mx
    from mxnet_tpu.parallel import SPMDTrainer
    from mxnet_tpu.resilience import CheckpointManager

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=128, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    rs = np.random.RandomState(0)
    X = rs.rand(batch, 64).astype("f")
    y = rs.randint(0, 10, batch).astype("f")

    def make():
        t = SPMDTrainer(net, "sgd",
                        {"learning_rate": 0.1, "momentum": 0.9,
                         "rescale_grad": 1.0 / batch}, mesh=None)
        t.bind([("data", (batch, 64))], [("softmax_label", (batch,))])
        mx.random.seed(11)
        t.init_params(mx.initializer.Xavier())
        return t

    def run(t, n):
        for _ in range(n):
            t.step(X, y)
        t.flush_step_guard()

    # uninterrupted baseline (includes its one compile, like any run)
    base = make()
    tic = time.perf_counter()
    run(base, steps)
    baseline_s = time.perf_counter() - tic
    base_params, _ = base.get_params()
    base.close()

    half = steps // 2
    tmp = tempfile.mkdtemp(prefix="bench_resume_")
    try:
        man = CheckpointManager(tmp)
        a = make()
        run(a, half)
        tic = time.perf_counter()
        a.save_checkpoint(man, half)
        save_s = time.perf_counter() - tic
        a.close()

        # the relaunch: a FRESH trainer (new process in real life —
        # restore + recompile both count)
        b = make()
        tic = time.perf_counter()
        b.restore(man)
        restore_s = time.perf_counter() - tic
        tic = time.perf_counter()
        run(b, steps - half)
        refit_s = time.perf_counter() - tic
        res_params, _ = b.get_params()
        b.close()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    parity = all(
        np.array_equal(base_params[k].asnumpy(), res_params[k].asnumpy())
        for k in base_params)
    total = save_s + restore_s + refit_s
    out = {
        "resume_save_s": round(save_s, 4),
        "resume_restore_s": round(restore_s, 4),
        "resume_refit_s": round(refit_s, 4),
        "resume_baseline_s": round(baseline_s, 4),
        # the preempted run re-trains NO steps (bit-identical resume), so
        # its extra cost over the uninterrupted run is save + restore +
        # the second compile hiding inside refit's first step
        "resume_overhead_s": round(total + baseline_s * half / steps
                                   - baseline_s, 4),
        "resume_parity": parity,
    }
    if not parity:
        out["resume_parity_note"] = ("restored run diverged from the "
                                     "uninterrupted baseline — resume is "
                                     "broken, numbers above are invalid")
    return out


def _checkpoint_bench(saves=5, steps_between=3, batch=64, hidden=1024):
    """The price of a checkpoint, measured where it hurts: the STEP-LOOP
    STALL per save — how long ``save_checkpoint`` blocks the training
    loop — for the blocking path (serialize + atomic write + fsync +
    checksum + manifest, all inline) vs the async path (host snapshot
    only; the CheckpointWriter does the rest off-thread).  Also measures
    the integrity tax: a verified restore vs the file read alone, and a
    full ``tools/ckpt_fsck.py`` audit of the directory.  The async run's
    restored params are asserted byte-identical to the blocking run's
    (``ckpt_parity``) — a fast save that loses bits is not a feature.
    CPU/host work only."""
    import shutil
    import subprocess as _sp
    import tempfile

    import mxnet_tpu as mx
    from mxnet_tpu.parallel import SPMDTrainer
    from mxnet_tpu.resilience import CheckpointManager, checksum_file

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=hidden, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    rs = np.random.RandomState(0)
    X = rs.rand(batch, 1024).astype("f")
    y = rs.randint(0, 10, batch).astype("f")

    def run(blocking):
        tmp = tempfile.mkdtemp(prefix="bench_ckpt_")
        man = CheckpointManager(tmp, keep_last=saves + 1)
        t = SPMDTrainer(net, "sgd",
                        {"learning_rate": 0.1, "momentum": 0.9,
                         "rescale_grad": 1.0 / batch}, mesh=None)
        t.bind([("data", (batch, 1024))], [("softmax_label", (batch,))])
        mx.random.seed(11)
        t.init_params(mx.initializer.Xavier())
        stalls = []
        for i in range(1, saves + 1):
            for _ in range(steps_between):
                t.step(X, y)
            t.flush_step_guard()
            # production checkpoints are minutes apart — by the next save
            # the writer is long idle.  This bench's saves are a few fast
            # CPU steps apart, so drain OUTSIDE the timed window; without
            # this the measured "stall" is mostly the previous write's
            # back-pressure, a regime no sane checkpoint cadence hits.
            man.wait()
            tic = time.perf_counter()
            t.save_checkpoint(man, i, blocking=blocking)
            stalls.append(time.perf_counter() - tic)
        man.wait()
        t.close()
        stalls.sort()
        return stalls[len(stalls) // 2], man, tmp

    out = {}
    try:
        block_stall, man_b, dir_b = run(blocking=True)
        async_stall, man_a, dir_a = run(blocking=False)
        out["ckpt_stall_blocking_s"] = round(block_stall, 5)
        out["ckpt_stall_async_s"] = round(async_stall, 5)
        out["ckpt_stall_ratio"] = round(block_stall / max(async_stall,
                                                          1e-9), 1)
        # identical training streams => the two directories' newest
        # checkpoints must restore byte-identically
        _, pa, _, sa, _ = man_b.restore()
        _, pb, _, sb, _ = man_a.restore()
        out["ckpt_parity"] = bool(
            sa == sb and set(pa) == set(pb) and all(
                np.array_equal(pa[k].asnumpy(), pb[k].asnumpy())
                for k in pa))
        # integrity tax: verified restore vs raw params read, plus the
        # offline fsck audit of the whole directory
        params_path = man_b.params_path(man_b.latest())
        tic = time.perf_counter()
        man_b.restore()
        out["ckpt_restore_verified_s"] = round(time.perf_counter() - tic,
                                               5)
        tic = time.perf_counter()
        checksum_file(params_path, "sha256")
        out["ckpt_verify_s"] = round(time.perf_counter() - tic, 5)
        here = os.path.dirname(os.path.abspath(__file__))
        tic = time.perf_counter()
        res = _sp.run([sys.executable,
                       os.path.join(here, "tools", "ckpt_fsck.py"),
                       dir_b, "-q"], capture_output=True, text=True,
                      timeout=120)
        out["ckpt_fsck_s"] = round(time.perf_counter() - tic, 3)
        out["ckpt_fsck_rc"] = res.returncode
    finally:
        for d in (locals().get("dir_b"), locals().get("dir_a")):
            if d:
                shutil.rmtree(d, ignore_errors=True)
    return out


def _ckpt_sharded_bench(saves=3, steps_between=2, batch=32, hidden=1024):
    """``bench.py ckpt`` — sharded-native vs gathered checkpoints on a
    real zero3 trainer (docs/how_to/fault_tolerance.md "Sharded-native
    checkpoints").  The gathered path pulls every shard into one full
    host copy before the write; the sharded path
    (``save_checkpoint_sharded`` / ``MXTPU_CKPT_SHARDED=1``) writes one
    verified blob per dp shard with peak host residency of a single
    blob.  Gate keys: ``ckpt_save_ms`` (sharded save wall time, lower
    is better) and ``ckpt_peak_host_frac`` (peak single-blob bytes /
    total blob bytes — the whole point of the feature; it rises back
    toward 1.0 if a host-side gather sneaks into the save path).
    ``ckpt_sharded_parity`` asserts the sharded directory restores
    bit-identically to the gathered one — a smaller host copy that
    loses bits is not a feature.  8-virtual-device CPU mesh."""
    import shutil
    import tempfile

    import jax

    import mxnet_tpu as mx
    from mxnet_tpu.parallel import SPMDTrainer, local_mesh
    from mxnet_tpu.resilience import CheckpointManager

    world = len(jax.devices())
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=hidden, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=8, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    rs = np.random.RandomState(0)
    X = rs.randn(batch, 512).astype("f")
    y = rs.randint(0, 8, batch).astype("f")

    t = SPMDTrainer(net, "sgd",
                    {"learning_rate": 0.05, "momentum": 0.9,
                     "rescale_grad": 1.0 / batch},
                    mesh=local_mesh("dp"), grad_sync="zero3")
    t.bind([("data", (batch, 512))], [("softmax_label", (batch,))])
    mx.random.seed(7)
    t.init_params(mx.initializer.Xavier())

    dir_g = tempfile.mkdtemp(prefix="bench_ckpt_gathered_")
    dir_s = tempfile.mkdtemp(prefix="bench_ckpt_sharded_")
    out = {"ckpt_world": world}
    try:
        man_g = CheckpointManager(dir_g, keep_last=None)
        man_s = CheckpointManager(dir_s, keep_last=None)
        gathered, sharded = [], []
        for i in range(1, saves + 1):
            for _ in range(steps_between):
                t.step(X, y)
            t.flush_step_guard()
            # identical trainer state goes to BOTH directories each
            # epoch, so the parity check below compares like with like
            tic = time.perf_counter()
            t.save_checkpoint(man_g, i, blocking=True)
            gathered.append(time.perf_counter() - tic)
            tic = time.perf_counter()
            t.save_checkpoint_sharded(man_s, i)
            sharded.append(time.perf_counter() - tic)
        gathered.sort()
        sharded.sort()
        out["ckpt_gathered_save_ms"] = round(
            gathered[len(gathered) // 2] * 1e3, 2)
        out["ckpt_save_ms"] = round(sharded[len(sharded) // 2] * 1e3, 2)
        stats = man_s.last_save_stats or {}
        if stats.get("total_blob_bytes"):
            out["ckpt_peak_host_bytes"] = stats["peak_blob_bytes"]
            out["ckpt_total_blob_bytes"] = stats["total_blob_bytes"]
            out["ckpt_peak_host_frac"] = round(
                stats["peak_blob_bytes"] / stats["total_blob_bytes"], 4)
        # verified assembly from per-shard blobs, timed where a resuming
        # trainer pays it
        tic = time.perf_counter()
        _, ps, _, ss, _ = man_s.restore()
        out["ckpt_restore_ms"] = round((time.perf_counter() - tic) * 1e3,
                                       2)
        _, pg, _, sg, _ = man_g.restore()
        # content equality, not pickle-byte equality: the two save paths
        # serialize the same state in different dict orders
        import pickle
        oa, ob = pickle.loads(ss), pickle.loads(sg)
        opt_ok = (oa["num_update"] == ob["num_update"] and
                  set(oa["states"]) == set(ob["states"]) and all(
                      len(oa["states"][k]) == len(ob["states"][k]) and
                      all(np.array_equal(x, z) for x, z in
                          zip(oa["states"][k], ob["states"][k]))
                      for k in oa["states"]))
        out["ckpt_sharded_parity"] = bool(
            opt_ok and set(ps) == set(pg) and all(
                np.array_equal(ps[k].asnumpy(), pg[k].asnumpy())
                for k in ps))
        t.close()
    finally:
        shutil.rmtree(dir_g, ignore_errors=True)
        shutil.rmtree(dir_s, ignore_errors=True)
    return out


def _roofline_bench(preset=None, trials=None):
    """``bench.py roofline`` — per-op proof for the fused kernels
    (mxnet_tpu/kernels/, docs/how_to/kernels.md).

    For each kernel the mode times (a) the FUSED implementation (the
    routed tier as one jitted program — fused-lax on the CPU tier,
    Pallas on TPU) and (b) the UNFUSED composition at dispatch
    granularity: every primitive its own compiled call, the execution
    model the pre-fusion graphs (and the reference's per-op engine) pay.
    Each fused time is also compared against an analytic bytes/FLOPs
    roofline (kernels/roofline.py) using the machine's MEASURED matmul
    rate and copy bandwidth (calibrated here, not nominal), so the
    artifact shows how close each kernel runs to the hardware and which
    side binds it.

    Self-gating: every kernel must beat its unfused composition
    (``roofline_<op>_win``); the ``roofline_<op>_speedup`` keys are in
    GATE_KEYS so later rounds cannot silently regress them.
    """
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.kernels import bn_act as BA
    from mxnet_tpu.kernels import flash_attention as FA
    from mxnet_tpu.kernels import lstm_cell as LC
    from mxnet_tpu.kernels import roofline as RL
    from mxnet_tpu.ops import nn as NN

    preset = preset or os.environ.get("BENCH_ROOFLINE_PRESET", "full")
    trials = trials or _env_int("BENCH_TRIALS", 3)
    small = preset == "small"
    reps = 3 if small else 10

    def timeit(fn, *args):
        """Best-of-trials seconds for one call of fn (block-synced; the
        roofline mode runs on the CPU tier where block_until_ready is a
        true completion barrier — see the module docstring for why the
        tunneled TPU tier needs fetch-synced slopes instead)."""
        jax.block_until_ready(fn(*args))           # warm/compile
        best = float("inf")
        for _ in range(max(1, trials)):
            tic = time.perf_counter()
            for _ in range(reps):
                out = fn(*args)
            jax.block_until_ready(out)
            best = min(best, (time.perf_counter() - tic) / reps)
        return best

    # -- machine calibration: achieved matmul rate + copy bandwidth ----
    n = 256 if small else 1024
    a = jnp.ones((n, n), jnp.float32)
    mm = jax.jit(lambda x: x @ x)
    t_mm = timeit(mm, a)
    peak_flops = 2.0 * n * n * n / t_mm
    buf = jnp.ones((1 << 20,) if small else (1 << 24,), jnp.float32)
    scale_pass = jax.jit(lambda x: x * 1.0000001)   # one read + one write
    t_cp = timeit(scale_pass, buf)
    mem_bw = 2.0 * buf.size * 4 / t_cp

    rs = np.random.RandomState(0)
    out = {
        "roofline_peak_gflops": round(peak_flops / 1e9, 1),
        "roofline_mem_gbs": round(mem_bw / 1e9, 2),
        "roofline_preset": preset,
    }

    def record(name, fused_s, unfused_s, work):
        bound_s = RL.roofline_seconds(work["flops"], work["fused_bytes"],
                                      peak_flops, mem_bw)
        out["roofline_%s_fused_us" % name] = round(fused_s * 1e6, 2)
        out["roofline_%s_unfused_us" % name] = round(unfused_s * 1e6, 2)
        out["roofline_%s_speedup" % name] = round(unfused_s / fused_s, 3)
        out["roofline_%s_bound_us" % name] = round(bound_s * 1e6, 2)
        out["roofline_%s_bound" % name] = RL.bound_side(
            work["flops"], work["fused_bytes"], peak_flops, mem_bw)
        out["roofline_%s_of_roofline" % name] = round(
            bound_s / fused_s, 3) if fused_s else None
        out["roofline_%s_win" % name] = bool(unfused_s >= fused_s)

    # -- bn_act: the inception-bn inner loop shape --------------------
    N, C, HW = (8, 32, 28 * 28) if small else (32, 64, 56 * 56)
    x = jnp.asarray(rs.rand(N, C, HW).astype("f").reshape(N, C, HW))
    gam = jnp.asarray(rs.rand(C).astype("f") + 0.5)
    bet = jnp.asarray(rs.rand(C).astype("f"))
    mmean = jnp.zeros(C)
    mvar = jnp.ones(C)

    fused_bn = jax.jit(lambda x, g, b, m, v: BA.fused_bn_act_lax(
        x, g, b, m, v, act_type="relu", fix_gamma=False, is_train=True))
    bn_stage = jax.jit(lambda x, g, b, m, v: NN.batch_norm(
        x, g, b, m, v, fix_gamma=False, is_train=True))
    act_stage = jax.jit(lambda x: NN.activation(x, act_type="relu"))

    def unfused_bn(x, g, b, m, v):
        o, nm, nv = bn_stage(x, g, b, m, v)
        return act_stage(o), nm, nv

    record("bn_act",
           timeit(fused_bn, x, gam, bet, mmean, mvar),
           timeit(unfused_bn, x, gam, bet, mmean, mvar),
           RL.workload("bn_act", n=N, c=C, hw=HW))

    # -- lstm_cell: the lstm_tok_s bench's cell shape -----------------
    B, H = (16, 64) if small else (32, 200)
    gates = jnp.asarray(rs.randn(B, 4 * H).astype("f"))
    cprev = jnp.asarray(rs.randn(B, H).astype("f"))

    fused_cell = jax.jit(LC.lstm_cell_lax)
    sig = jax.jit(jax.nn.sigmoid)
    tnh = jax.jit(jnp.tanh)
    mul = jax.jit(jnp.multiply)
    add = jax.jit(jnp.add)
    split4 = jax.jit(lambda g: tuple(jnp.split(g, 4, axis=-1)))

    def unfused_cell(g, c):
        i, f, gg, o = split4(g)
        c2 = add(mul(sig(f), c), mul(sig(i), tnh(gg)))
        return mul(sig(o), tnh(c2)), c2

    record("lstm_cell",
           timeit(fused_cell, gates, cprev),
           timeit(unfused_cell, gates, cprev),
           RL.workload("lstm_cell", b=B, h=H))

    # -- flash_attention ----------------------------------------------
    Bq, T, Hh, D = (2, 128, 2, 64) if small else (4, 512, 8, 64)
    q = jnp.asarray(rs.randn(Bq, T, Hh, D).astype("f"))
    k = jnp.asarray(rs.randn(Bq, T, Hh, D).astype("f"))
    v = jnp.asarray(rs.randn(Bq, T, Hh, D).astype("f"))

    fused_fa = jax.jit(lambda q, k, v: FA.flash_attention_lax(
        q, k, v, causal=True))
    scores_stage = jax.jit(
        lambda q, k: jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D))
    mask_soft = jax.jit(lambda s: jax.nn.softmax(
        jnp.where(jnp.tril(jnp.ones((T, T), bool)), s, -jnp.inf),
        axis=-1))
    out_stage = jax.jit(lambda p, v: jnp.einsum("bhqk,bkhd->bqhd", p, v))

    def unfused_fa(q, k, v):
        return out_stage(mask_soft(scores_stage(q, k)), v)

    record("flash_attention",
           timeit(fused_fa, q, k, v),
           timeit(unfused_fa, q, k, v),
           RL.workload("flash_attention", b=Bq, t=T, heads=Hh, d=D))

    # -- eltwise_chain: relu -> scale -> add -> tanh run --------------
    Ne, Ce, HWe = (4, 16, 28 * 28) if small else (16, 32, 56 * 56)
    xe = jnp.asarray(rs.rand(Ne, Ce, HWe).astype("f"))
    ye = jnp.asarray(rs.rand(Ne, Ce, HWe).astype("f"))
    relu_j = jax.jit(jax.nn.relu)
    scale_j = jax.jit(lambda v: v * 0.125)
    add_j = jax.jit(jnp.add)
    tanh_j = jax.jit(jnp.tanh)

    def unfused_chain(x, y):
        return tanh_j(add_j(scale_j(relu_j(x)), y))

    fused_chain = jax.jit(
        lambda x, y: jnp.tanh(jax.nn.relu(x) * 0.125 + y))
    record("eltwise_chain",
           timeit(fused_chain, xe, ye),
           timeit(unfused_chain, xe, ye),
           RL.workload("eltwise_chain", n=Ne, c=Ce, hw=HWe, depth=4))

    # -- concat_fuse: sibling 1x1 tower heads as ONE GEMM -------------
    Nc, Cc, Hc = (2, 64, 14) if small else (8, 192, 28)
    widths = (16, 16, 24) if small else (64, 64, 96)
    xc = jnp.asarray(rs.randn(Nc, Cc, Hc, Hc).astype("f"))
    wsc = [jnp.asarray(rs.randn(w, Cc, 1, 1).astype("f") * 0.1)
           for w in widths]
    conv1 = jax.jit(lambda x, w: jax.nn.relu(
        jax.lax.conv_general_dilated(x, w, (1, 1), "VALID")))

    def unfused_cc(x, w1, w2, w3):
        return conv1(x, w1), conv1(x, w2), conv1(x, w3)

    o1, o2 = widths[0], widths[0] + widths[1]

    @jax.jit
    def fused_cc(x, w1, w2, w3):
        m = jax.nn.relu(jax.lax.conv_general_dilated(
            x, jnp.concatenate([w1, w2, w3], axis=0), (1, 1), "VALID"))
        return m[:, :o1], m[:, o1:o2], m[:, o2:]

    record("concat_fuse",
           timeit(fused_cc, xc, *wsc),
           timeit(unfused_cc, xc, *wsc),
           RL.workload("concat_fuse", n=Nc, c=Cc, hw=Hc * Hc,
                       widths=list(widths)))

    # -- pool_act: act->max-pool reordered to pool-first --------------
    Np, Cp, Hp = (4, 16, 56) if small else (16, 64, 112)
    xp = jnp.asarray(rs.randn(Np, Cp, Hp, Hp).astype("f"))
    pool_j = jax.jit(lambda v: NN.pooling(
        v, kernel=(3, 3), stride=(2, 2), pad=(1, 1), pool_type="max"))

    def unfused_pa(x):
        return pool_j(relu_j(x))

    fused_pa = jax.jit(lambda v: NN.activation(NN.pooling(
        v, kernel=(3, 3), stride=(2, 2), pad=(1, 1), pool_type="max"),
        act_type="relu"))
    record("pool_act",
           timeit(fused_pa, xp),
           timeit(unfused_pa, xp),
           RL.workload("pool_act", n=Np, c=Cp, hw=Hp * Hp, stride=2))

    out["roofline_all_win"] = all(
        out["roofline_%s_win" % op]
        for op in ("bn_act", "lstm_cell", "flash_attention",
                   "eltwise_chain", "concat_fuse", "pool_act"))

    # -- whole-model proof: inception-bn forward, new passes on vs off
    out.update(_roofline_inception(small, trials))
    return out


#: the pre-mxfuse kernel set — the "new passes off" baseline the
#: inception stanza (and the headline inception-gap claim) compares
#: against; bn_act/bn_fold stay ON both sides
_PRE_MXFUSE_KERNELS = "bn_act,bn_fold,lstm_cell,flash_attention,augment"


def _small_inception():
    """A trimmed inception-bn (stem + one A tower + one B tower) for
    the small roofline preset — the same patterns every pass matches
    (merge trio, grouped 3x3 siblings, act→pool stem, avg-pool
    branch) at test-tier compile cost."""
    import mxnet_tpu as mx
    from mxnet_tpu.models.inception_bn import (ConvFactory,
                                               InceptionFactoryA,
                                               InceptionFactoryB)
    data = mx.sym.Variable("data")
    c1 = ConvFactory(data, 16, (3, 3), pad=(1, 1), name="conv1")
    p1 = mx.sym.Pooling(c1, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                        pool_type="max", name="pool1")
    a = InceptionFactoryA(p1, 16, 16, 24, 16, 24, "avg", 16, "3a")
    b = InceptionFactoryB(a, 16, 24, 16, 24, "3c")
    flat = mx.sym.Flatten(mx.sym.Pooling(
        b, global_pool=True, kernel=(1, 1), pool_type="avg"))
    fc = mx.sym.FullyConnected(flat, num_hidden=10, name="fc1")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def _roofline_inception(small, trials):
    """The mxfuse headline measurement (ISSUE 15 / ROADMAP item 5):
    inception-bn FORWARD throughput through the real executor with the
    plan-optimizer passes ON (default env) vs OFF (the pre-mxfuse
    kernel set — bn_act/bn_fold still on, so the delta is the NEW
    passes only), plus the infer_trace satellite: eval-trace build
    time with dead-node elimination on vs off (the pruned plan skips
    tracing every conv a fold replaced).

    Both executors are bound first and the timing windows INTERLEAVE
    on/off (best-of): sequential measurement on this host drifts by
    more than the effect under test (page cache, frequency ramp), and
    interleaving cancels it.  The small preset measures a trimmed
    inception (same patterns, test-tier compile cost)."""
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu.executor import _build_eval
    from mxnet_tpu.kernels import KNOWN_KERNELS
    from mxnet_tpu.models import inception_bn

    shape = (2, 3, 32, 32) if small else (8, 3, 96, 96)
    steps = 2 if small else 5
    windows = 2 if small else 7
    sym = _small_inception() if small \
        else inception_bn.get_symbol(num_classes=100)

    def bind(env):
        os.environ["MXTPU_FUSED_KERNELS"] = env
        ex = sym.simple_bind(mx.cpu(), grad_req="null", data=shape)
        rs_i = np.random.RandomState(0)
        for name in sorted(ex.arg_dict):
            if name in ("data", "softmax_label"):
                continue
            arr = ex.arg_dict[name]
            arr[:] = (rs_i.rand(*arr.shape).astype("f") - 0.5) * 0.2
        for name in ex.aux_dict:
            ex.aux_dict[name][:] = 1.0 if name.endswith("var") else 0.0
        ex.arg_dict["data"][:] = rs_i.rand(*shape).astype("f")
        return ex

    def window(ex):
        tic = time.perf_counter()
        for _ in range(steps):
            outs = ex.forward()
        outs[0].asnumpy()                          # completion barrier
        return (time.perf_counter() - tic) / steps

    out = {}
    # bind()/trace_once() steer MXTPU_FUSED_KERNELS themselves; the
    # scope restores the operator's value (or its absence) on exit
    with _scoped_env("MXTPU_FUSED_KERNELS"):
        ex_on = bind("1")
        ex_off = bind(_PRE_MXFUSE_KERNELS)
        ex_on.forward()[0].asnumpy()               # compile + warm
        ex_off.forward()[0].asnumpy()
        best_on = best_off = float("inf")
        for _ in range(max(1, windows)):
            best_on = min(best_on, window(ex_on))
            best_off = min(best_off, window(ex_off))
        ex_on.close()
        ex_off.close()
        on_rate, off_rate = shape[0] / best_on, shape[0] / best_off
        out["roofline_inception_fwd_on_img_s"] = round(on_rate, 2)
        out["roofline_inception_fwd_off_img_s"] = round(off_rate, 2)
        out["roofline_inception_fwd_x"] = round(on_rate / off_rate, 3)
        out["roofline_inception_fwd_win"] = bool(on_rate >= off_rate)

        # infer_trace: eval-trace build time (plan interpretation +
        # jaxpr trace) with the pruned plan vs the full fused plan
        args = {n: np.zeros(s, np.float32) for n, s in zip(
            sym.list_arguments(),
            sym.infer_shape(data=shape)[0])}
        auxs = {n: np.zeros(s, np.float32) for n, s in zip(
            sym.list_auxiliary_states(),
            sym.infer_shape(data=shape)[2])}
        rng = jax.random.PRNGKey(0)

        def trace_once(env):
            os.environ["MXTPU_FUSED_KERNELS"] = env
            tic = time.perf_counter()
            eval_fn = _build_eval(sym)
            jax.make_jaxpr(
                lambda a, x, r: eval_fn(a, x, r, False))(args, auxs,
                                                         rng)
            return time.perf_counter() - tic

        no_prune = ",".join(k for k in KNOWN_KERNELS
                            if k != "infer_trace")
        # same discipline as the forward stanza: warm BOTH paths once
        # untimed (the first trace pays jax tracing-machinery warmup
        # for this program size), then INTERLEAVE best-of windows —
        # sequential on-then-off measurement drifts by more than the
        # ~10-20% effect on a ~0.2s quantity (the r06 dry run measured
        # the on path first-and-cold and "lost" for exactly that
        # reason)
        trace_once("1")
        trace_once(no_prune)
        on_s = off_s = float("inf")
        for _ in range(3 if small else 5):
            off_s = min(off_s, trace_once(no_prune))
            on_s = min(on_s, trace_once("1"))
        out["roofline_infer_trace_on_s"] = round(on_s, 3)
        out["roofline_infer_trace_off_s"] = round(off_s, 3)
        out["roofline_infer_trace_x"] = round(off_s / on_s, 3) \
            if on_s else None
        out["roofline_infer_trace_win"] = bool(off_s >= on_s)
    return out


def _lstm_bench(batch, seq_len, steps, warmup, trials):
    """2-layer LSTM LM (lstm_bucketing workload, one bucket) tokens/sec."""
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu.models import lstm_lm
    from mxnet_tpu.parallel import SPMDTrainer

    vocab = 10000
    sym, data_names, label_names = lstm_lm.lstm_lm_sym(
        seq_len, vocab, num_embed=200, num_hidden=200, num_layers=2)
    trainer = SPMDTrainer(
        sym, "sgd",
        {"learning_rate": 0.1, "momentum": 0.9, "wd": 0.0,
         "rescale_grad": 1.0 / batch},
        mesh=None, compute_dtype="bfloat16")
    shapes = {"data": (batch, seq_len), "softmax_label": (batch, seq_len)}
    trainer.bind([(n, shapes[n]) for n in data_names],
                 [(n, shapes[n]) for n in label_names])
    trainer.init_params(mx.initializer.Xavier())

    rs = np.random.RandomState(0)
    staged = []
    for _ in range(8):
        d = mx.nd.array(rs.randint(0, vocab, (batch, seq_len)).astype("f"))
        l = mx.nd.array(rs.randint(0, vocab, (batch, seq_len)).astype("f"))
        d.wait_to_read()
        l.wait_to_read()
        staged.append((d, l))
    for i in range(warmup):
        trainer.step(*staged[i % 8])
    _fetch_sync(trainer)
    s1 = max(4, steps // 4)
    return batch * seq_len * _steps_per_sec(trainer, staged, s1,
                                            s1 + steps, trials)


def _save_serving_models(tmp, deep=False):
    """Write the two bench serving checkpoints: the standard MLP
    (models/mlp.py shape) and a resnet-shaped small-image net (cifar
    branch of models/resnet.py) -> {name: (prefix, epoch, sample_shape)}.
    ``deep=True`` swaps resnet-20 for resnet-56 (the fleet mode: a
    graph deep enough that bring-up is compile-dominated and a forward
    heavy enough that replica compute, not HTTP plumbing, is the
    scaling bottleneck)."""
    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.model import save_checkpoint

    rs = np.random.RandomState(7)
    out = {}
    for name, sym, sample in (
            ("mlp", models.get_symbol("mlp", num_classes=10), (784,)),
            ("resnet", models.get_symbol("resnet", num_classes=10,
                                         num_layers=56 if deep else 20,
                                         image_shape=(3, 32, 32)),
             (3, 32, 32))):
        shapes = {"data": (1,) + sample}
        arg_shapes, _, aux_shapes = sym.infer_shape(**shapes)
        args = {n: mx.nd.array(rs.uniform(-0.1, 0.1, s).astype("f"))
                for n, s in zip(sym.list_arguments(), arg_shapes)
                if n not in ("data", "softmax_label")}
        auxs = {}
        for n, s in zip(sym.list_auxiliary_states(), aux_shapes):
            # BN moving stats: mean 0, var 1 — a forward through random
            # weights stays finite
            auxs[n] = mx.nd.array(
                (np.ones(s) if n.endswith("var")
                 else np.zeros(s)).astype("f"))
        prefix = os.path.join(tmp, name)
        save_checkpoint(prefix, 1, sym, args, auxs, blocking=True)
        out[name] = (prefix, 1, sample)
    return out


def _serve_load(port, model, sample, concurrency, seconds, warmup_s=0.5,
                npy=False):
    """Closed-loop load: ``concurrency`` threads, each its own keep-alive
    client, firing back-to-back requests for ``seconds`` after a warmup
    window.  ``npy=True`` sends x-npy bodies (C-speed serialization —
    the fleet rows use it so the CLIENT's JSON encode cost cannot mask
    replica scaling).  Returns (qps, p50_ms, p99_ms, shed, errors)."""
    import threading

    from mxnet_tpu.serving import ServeClient

    rs = np.random.RandomState(0)
    stop = threading.Event()
    lats, shed, errors = [], [0], [0]
    lock = threading.Lock()

    def worker(i):
        cli = ServeClient("127.0.0.1", port)
        x = rs.rand(*sample).astype("f") + i  # distinct payloads
        mine = []
        try:
            while not stop.is_set():
                tic = time.perf_counter()
                try:
                    status, _ = cli.predict(model, x, npy=npy)
                except Exception:  # noqa: BLE001 — connection-level loss
                    status = -1
                dt = (time.perf_counter() - tic) * 1e3
                if status == 200:
                    mine.append((tic, dt))
                elif status == 429:
                    with lock:
                        shed[0] += 1
                else:
                    with lock:
                        errors[0] += 1
        finally:
            cli.close()
        with lock:
            lats.extend(mine)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(warmup_s + seconds)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    cut = t0 + warmup_s
    window = sorted(d for (tic, d) in lats if tic >= cut)
    if not window:
        return 0.0, None, None, shed[0], errors[0]
    # the ONE nearest-rank percentile rule — same math /stats reports
    from mxnet_tpu.serving.frontend import _percentile
    return (round(len(window) / seconds, 2),
            round(_percentile(window, 50), 3),
            round(_percentile(window, 99), 3), shed[0], errors[0])


def _serve_open_loop(port, model, sample, rate_qps, seconds, workers=32):
    """Open-loop load: a paced worker pool fires at a fixed AGGREGATE
    arrival rate on a schedule independent of completions (a worker
    that falls behind its slots fires immediately — the standard
    bounded-worker approximation of open-loop arrivals, without the
    thread-per-request storm that would just fill the kernel's accept
    backlog instead of the daemon's bounded queue).  Returns (ok, shed,
    errors, p99_ms_of_successes)."""
    import threading

    from mxnet_tpu.serving import ServeClient

    rs = np.random.RandomState(1)
    x = rs.rand(*sample).astype("f")
    results = []
    lock = threading.Lock()
    interval = workers / float(rate_qps)
    t0 = time.perf_counter() + 0.05
    end = t0 + seconds

    def worker(i):
        cli = ServeClient("127.0.0.1", port, timeout=30)
        nxt = t0 + i * (1.0 / rate_qps)
        try:
            while nxt < end:
                pause = nxt - time.perf_counter()
                if pause > 0:
                    time.sleep(pause)
                tic = time.perf_counter()
                try:
                    status, _ = cli.predict(model, x)
                except Exception:  # noqa: BLE001 — refused/dropped conn
                    status = -1
                with lock:
                    results.append(
                        (status, (time.perf_counter() - tic) * 1e3))
                nxt += interval
        finally:
            cli.close()

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=90)
    ok = sum(1 for s, _ in results if s == 200)
    shed = sum(1 for s, _ in results if s in (429, 503))
    errors = len(results) - ok - shed
    from mxnet_tpu.serving.frontend import _percentile
    p99 = _percentile(sorted(d for s, d in results if s == 200), 99)
    return ok, shed, errors, round(p99, 3) if p99 is not None else None


def _serve_bench(seconds=2.5):
    """The ``bench.py serve`` mode: spin up the real daemon
    (tools/serve.py) on the CPU backend, drive closed-loop load at
    1/8/32 concurrency for the standard MLP and a resnet-shaped model,
    verify serving output is bit-identical to the unbatched Predictor
    forward, then overdrive it open-loop and record the shed rate.

    Headline: ``serve_batch_speedup`` = QPS at concurrency 32 / QPS at
    concurrency 1 for the MLP — continuous batching must buy >= 2x on
    the CPU tier (acceptance criterion)."""
    import shutil
    import signal as _signal
    import subprocess
    import tempfile

    from mxnet_tpu.serving import ServeClient

    tmp = tempfile.mkdtemp(prefix="bench_serve_")
    out = {}
    proc = None
    try:
        specs = _save_serving_models(tmp)
        here = os.path.dirname(os.path.abspath(__file__))
        port_file = os.path.join(tmp, "port")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        cmd = [sys.executable, os.path.join(here, "tools", "serve.py"),
               "--port", "0", "--port-file", port_file,
               "--buckets", "1,2,4,8,16,32", "--max-wait-ms", "2",
               "--max-queue", "64", "--warmup"]
        for name, (prefix, epoch, sample) in specs.items():
            cmd += ["--model", "%s=%s:%d" % (name, prefix, epoch),
                    "--input-shape",
                    "%s:data=%s" % (name, ",".join(map(str, sample)))]
        proc = subprocess.Popen(cmd, env=env, stdout=subprocess.DEVNULL,
                                stderr=subprocess.PIPE, text=True)
        deadline = time.monotonic() + 300
        while not os.path.exists(port_file):
            if proc.poll() is not None:
                raise RuntimeError("serve daemon died: %s"
                                   % proc.stderr.read()[-2000:])
            if time.monotonic() > deadline:
                raise RuntimeError("serve daemon never wrote its port")
            time.sleep(0.1)
        port = int(open(port_file).read().split(":")[1])
        ServeClient("127.0.0.1", port).wait_ready(60)

        # bit-parity: one quiet request == the unbatched (bucket-1)
        # Predictor forward, bitwise
        out["serve_parity"] = _serve_parity(port, specs)

        for name, (_, _, sample) in specs.items():
            for conc in (1, 8, 32):
                qps, p50, p99, shed, errs = _serve_load(
                    port, name, sample, conc, seconds)
                key = "serve_%s_c%d" % (name, conc)
                out[key + "_qps"] = qps
                out[key + "_p50_ms"] = p50
                out[key + "_p99_ms"] = p99
                if shed:
                    out[key + "_shed"] = shed
                if errs:
                    out[key + "_errors"] = errs
        if out.get("serve_mlp_c1_qps"):
            out["serve_batch_speedup"] = round(
                out["serve_mlp_c32_qps"] / out["serve_mlp_c1_qps"], 2)

        # open-loop: paced arrivals at a fixed rate just under the MLP's
        # measured capacity — the sustained-QPS-within-SLO row
        rate = min(400.0, max(50.0,
                              0.8 * (out.get("serve_mlp_c8_qps") or 50.0)))
        ok, shed, errors, p99 = _serve_open_loop(
            port, "mlp", specs["mlp"][2], rate, 1.5)
        out["serve_openloop_rate_qps"] = round(rate, 1)
        out["serve_openloop_ok"] = ok
        out["serve_openloop_shed"] = shed
        out["serve_openloop_errors"] = errors
        if p99 is not None:
            out["serve_openloop_p99_ms"] = p99

        # overload: closed-loop concurrency far past the queue bound —
        # admission control must shed (429) the excess rather than
        # queue it without bound, while the admitted work completes
        _, _, p99o, shed_o, errs_o = _serve_load(
            port, "resnet", specs["resnet"][2], 96, seconds)
        out["serve_overload_shed"] = shed_o
        out["serve_overload_errors"] = errs_o
        if p99o is not None:
            out["serve_overload_p99_ms"] = p99o
        status, stats = ServeClient("127.0.0.1", port).stats()
        if status == 200:
            out["serve_batch_fill"] = stats["batches"].get("fill_ratio")
            out["serve_sheds_counted"] = (
                stats["counters"]["shed_queue"]
                + stats["counters"]["shed_slo"])

        proc.send_signal(_signal.SIGTERM)
        out["serve_drain_rc"] = proc.wait(timeout=60)
        proc = None
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def _serve_parity(port, specs):
    """True iff a request served through the daemon (bucket 1, quiet
    daemon) is BIT-identical to the local unbatched Predictor forward
    for every model."""
    from mxnet_tpu import predict
    from mxnet_tpu.model import load_checkpoint
    from mxnet_tpu.serving import ServeClient

    rs = np.random.RandomState(3)
    cli = ServeClient("127.0.0.1", port)
    try:
        for name, (prefix, epoch, sample) in specs.items():
            x = rs.rand(*sample).astype("f")
            status, payload = cli.predict(name, x)
            if status != 200:
                return False
            got = np.asarray(payload["outputs"][0], dtype=np.float32)
            sym, args, auxs = load_checkpoint(prefix, epoch)
            pred = predict.Predictor(
                sym, {**{"arg:%s" % k: v for k, v in args.items()},
                      **{"aux:%s" % k: v for k, v in auxs.items()}},
                {"data": (1,) + tuple(sample)})
            ref = pred.forward(data=x[None]).get_output(0)[0]
            if not np.array_equal(got, ref):
                return False
    finally:
        cli.close()
    return True


def _hotswap_bench(seconds=2.0):
    """The ``bench.py hotswap`` mode (docs/how_to/serving.md,
    "Continuous deployment"): a LIVE ``tools/serve.py --watch`` daemon
    under closed-loop load while this process streams new verified
    epochs into its checkpoint directory — the train-to-serve seam,
    measured, not assumed.

    - ``hotswap_swap_ms`` — mean dispatch-boundary critical section per
      swap (wait for the in-flight batch + install + probe), as the
      daemon itself measures it.  LOWER is better: the gate treats it
      through ``LOWER_IS_BETTER_KEYS``.
    - ``hotswap_drop_free`` — 1.0 iff ZERO requests were dropped or
      errored across every swap (the zero-dropped-requests contract;
      429 sheds are admission control, not drops, and are counted
      separately).
    - ``hotswap_promote_ms`` — publish-to-served latency (includes the
      MXTPU_SWAP_POLL_S poll; recorded alongside, not gated).
    - ``hotswap_qps_dip_frac`` — completion rate in the worst 250ms
      window around a swap vs the steady-state median (1.0 = no dip).
    """
    import shutil
    import signal as _signal
    import subprocess
    import tempfile
    import threading

    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.resilience import CheckpointManager
    from mxnet_tpu.serving import ServeClient

    tmp = tempfile.mkdtemp(prefix="bench_hotswap_")
    out = {}
    proc = None
    try:
        sym = models.get_symbol("mlp", num_classes=10)
        arg_shapes, _, _ = sym.infer_shape(data=(1, 784))

        def params(seed):
            rs = np.random.RandomState(seed)
            return {n: mx.nd.array(rs.uniform(-0.1, 0.1, s).astype("f"))
                    for n, s in zip(sym.list_arguments(), arg_shapes)
                    if n not in ("data", "softmax_label")}

        ckpt_dir = os.path.join(tmp, "ckpts")
        man = CheckpointManager(ckpt_dir)
        man.save(1, symbol=sym, arg_params=params(1), aux_params={},
                 blocking=True)

        here = os.path.dirname(os.path.abspath(__file__))
        port_file = os.path.join(tmp, "port")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   MXTPU_SWAP_POLL_S="0.1")
        proc = subprocess.Popen(
            [sys.executable, os.path.join(here, "tools", "serve.py"),
             "--model", "mlp=%s" % ckpt_dir,
             "--input-shape", "mlp:data=784",
             "--port", "0", "--port-file", port_file,
             "--buckets", "1,2,4,8", "--max-wait-ms", "2",
             "--warmup", "--watch"],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
            text=True)
        deadline = time.monotonic() + 300
        while not os.path.exists(port_file):
            if proc.poll() is not None:
                raise RuntimeError("hotswap daemon died: %s"
                                   % proc.stderr.read()[-2000:])
            if time.monotonic() > deadline:
                raise RuntimeError("hotswap daemon never wrote its port")
            time.sleep(0.1)
        port = int(open(port_file).read().split(":")[1])
        ServeClient("127.0.0.1", port).wait_ready(60)

        # -- closed-loop load for the whole run ---------------------------
        rs = np.random.RandomState(0)
        stop = threading.Event()
        lock = threading.Lock()
        events = []                 # (t_done, status) per request
        drops = [0]                 # connection-level losses

        def worker(i):
            cli = ServeClient("127.0.0.1", port, timeout=30)
            x = rs.rand(784).astype("f") + i
            try:
                while not stop.is_set():
                    try:
                        status, _ = cli.predict("mlp", x, npy=True)
                    except Exception:  # noqa: BLE001 — dropped response
                        with lock:
                            drops[0] += 1
                        continue
                    with lock:
                        events.append((time.monotonic(), status))
            finally:
                cli.close()

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        time.sleep(max(1.0, seconds / 2.0))   # steady-state baseline

        # -- stream new epochs under load ---------------------------------
        stat_cli = ServeClient("127.0.0.1", port)
        swap_ms, promote_ms, swap_at = [], [], []
        for epoch in (2, 3):
            man.save(epoch, symbol=sym, arg_params=params(epoch),
                     aux_params={}, blocking=True)
            t_pub = time.monotonic()
            lim = time.monotonic() + 60
            while time.monotonic() < lim:
                status, stats = stat_cli.stats()
                if status == 200 and \
                        (stats.get("epochs") or {}).get("mlp") == epoch:
                    break
                time.sleep(0.02)
            else:
                raise RuntimeError("epoch %d never went live" % epoch)
            t_live = time.monotonic()
            swap_at.append(t_live)
            promote_ms.append((t_live - t_pub) * 1e3)
            dep = (stats.get("deploy") or {}).get("mlp") or {}
            if dep.get("last_swap_ms") is not None:
                swap_ms.append(float(dep["last_swap_ms"]))
            time.sleep(max(0.5, seconds / 4.0))
        time.sleep(max(0.5, seconds / 4.0))
        stop.set()
        for t in threads:
            t.join(timeout=30)

        status, stats = stat_cli.stats()
        dep = (stats.get("deploy") or {}).get("mlp") or {}
        stat_cli.close()

        # -- the ledger ---------------------------------------------------
        with lock:
            done = list(events)
        errors = sum(1 for _, s in done if s not in (200, 429))
        sheds = sum(1 for _, s in done if s == 429)
        ok = [t for t, s in done if s == 200]
        out["hotswap_swaps"] = int(dep.get("promoted") or len(swap_at))
        out["hotswap_requests"] = len(done)
        out["hotswap_errors"] = errors
        out["hotswap_dropped_conns"] = drops[0]
        if sheds:
            out["hotswap_sheds"] = sheds
        out["hotswap_drop_free"] = \
            1.0 if errors == 0 and drops[0] == 0 else 0.0
        if swap_ms:
            out["hotswap_swap_ms"] = round(sum(swap_ms) / len(swap_ms), 3)
        out["hotswap_promote_ms"] = round(
            sum(promote_ms) / len(promote_ms), 1)
        # QPS dip: completions per 250ms bucket, worst swap-adjacent
        # bucket vs the steady-state median
        if ok:
            t0 = min(ok)
            buckets = {}
            for t in ok:
                buckets[int((t - t0) / 0.25)] = \
                    buckets.get(int((t - t0) / 0.25), 0) + 1
            hot = set()
            for ts in swap_at:
                base_i = int((ts - t0) / 0.25)
                hot.update((base_i - 1, base_i, base_i + 1))
            steady = sorted(v for k, v in buckets.items()
                            if k not in hot and k != max(buckets))
            inside = [buckets.get(i, 0) for i in sorted(hot)
                      if 0 <= i <= max(buckets)]
            if steady and inside:
                med = steady[len(steady) // 2]
                if med > 0:
                    out["hotswap_qps_dip_frac"] = round(
                        min(inside) / float(med), 3)
        proc.send_signal(_signal.SIGTERM)
        out["hotswap_drain_rc"] = proc.wait(timeout=60)
        proc = None
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def _region_bench(timeout=420):
    """The composed region drill as a metric (docs/how_to/region.md):
    one ``tools/region.py smoke`` run — data plane -> supervised elastic
    trainer -> rolling fleet -> closed-loop clients, with a rot-injected
    publish — measured at the region's own seams:

    - ``region_drop_free`` — 1.0 iff ZERO client requests were dropped
      or errored across the drill (the storm-grade contract).
    - ``region_goodput_chaos_frac`` — fraction of client requests that
      succeeded on the FIRST client attempt.  With exactly-once
      routing the router absorbs dead replicas by keyed resend, so a
      client-side retry (a 502 that leaked through) counts against
      goodput AND should be zero — the storm report carries
      ``client_retries`` as its own top-level number.
    - ``region_freshness_ms`` — end-to-end publish->served freshness:
      wall-clock from the trainer's manifest publish to the watcher's
      committed swap, fleet-wide worst case (lower is better).
    """
    import shutil
    import subprocess
    import tempfile

    region = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "region.py")
    tmp = tempfile.mkdtemp(prefix="bench_region_")
    out = {}
    try:
        report_path = os.path.join(tmp, "report.json")
        res = subprocess.run(
            [sys.executable, region, "smoke", "--run-dir",
             os.path.join(tmp, "run"), "--report", report_path],
            capture_output=True, text=True, timeout=timeout)
        if res.returncode != 0:
            raise RuntimeError("region smoke drill failed (rc %d):\n%s"
                               % (res.returncode, res.stderr[-2000:]))
        with open(report_path) as f:
            doc = json.load(f)
        stats = doc["stats"]
        clients = stats["clients"]
        requests = clients["requests"]
        dropped = clients["dropped"]
        out["region_requests"] = requests
        out["region_dropped"] = dropped
        out["region_retried"] = clients["retried"]
        out["region_drop_free"] = \
            1.0 if dropped == 0 and doc["ok"] else 0.0
        if requests:
            out["region_goodput_chaos_frac"] = round(
                (requests - clients["retried"] - dropped)
                / float(requests), 4)
        if stats.get("freshness_ms") is not None:
            out["region_freshness_ms"] = round(
                float(stats["freshness_ms"]), 3)
        out["region_served_epoch"] = doc["spec"]["epochs"]
        out["region_publish_rejected"] = \
            stats["events"].get("publish_rejected", 0)
        out["region_elapsed_s"] = doc["elapsed_s"]
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def _fleet_manifest(specs, buckets, replicas=1):
    """The bench models as a real :class:`FleetManifest` (the same
    object the CLI builds — no parallel spec format to drift)."""
    from mxnet_tpu.fleet import FleetManifest
    return FleetManifest(
        {name: {"target": "%s:%d" % (prefix, epoch),
                "shapes": {"data": list(sample)}}
         for name, (prefix, epoch, sample) in specs.items()},
        replicas=replicas, buckets=buckets, device_sets="cpu")


def _fleet_warm_run(specs, buckets, cache_dir, timeout=600):
    """One ``tools/serve.py --warmup-only`` bring-up over every bench
    model with ``MXTPU_COMPILE_CACHE=cache_dir``; returns the parsed
    ``warmup_s`` (trace+compile — or, against a built AOT store,
    executable-load — time only; process imports excluded, so the
    number is exactly what the warm store removes)."""
    import subprocess

    from mxnet_tpu.fleet.warm import WARMUP_RE

    argv = _fleet_manifest(specs, buckets).serve_argv(
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "tools", "serve.py"),
        port=0, warmup_only=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXTPU_COMPILE_CACHE=cache_dir)
    res = subprocess.run(argv, env=env, capture_output=True, text=True,
                         timeout=timeout)
    if res.returncode != 0:
        raise RuntimeError("warmup-only run failed (rc %d):\n%s"
                           % (res.returncode, res.stderr[-2000:]))
    m = WARMUP_RE.search(res.stderr)
    if not m:
        raise RuntimeError("warmup-only run printed no warmup_s:\n%s"
                           % res.stderr[-2000:])
    return float(m.group(1))


def _fleet_up(specs, buckets, store, run_dir, replicas, extra_env=None,
              timeout=600, workers=None, autoscale=False,
              replica_env=None):
    """Boot a fleet (router + ``replicas`` daemons) on an ephemeral
    port; returns ``(proc, port)`` once the port file appears.
    ``workers`` > 1 shards the front end into reuseport router workers;
    ``autoscale`` closes the replica-count loop (both: the overdrive
    mode); ``replica_env`` is a list of ``RID:NAME=VALUE`` overrides
    for single replicas (the tail mode arms ONE gray replica with it)."""
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))
    port_file = os.path.join(run_dir, "router.port")
    cmd = [sys.executable, os.path.join(here, "tools", "fleet.py"),
           "serve", "--replicas", str(replicas), "--device-sets", "cpu",
           "--buckets", buckets, "--warm-store", store,
           "--run-dir", run_dir, "--port", "0",
           "--port-file", port_file]
    if workers is not None:
        cmd += ["--workers", str(workers)]
    if autoscale:
        cmd += ["--autoscale"]
    for spec in (replica_env or ()):
        cmd += ["--replica-env", spec]
    for name, (prefix, epoch, sample) in specs.items():
        cmd += ["--model", "%s=%s:%d" % (name, prefix, epoch),
                "--input-shape",
                "%s:data=%s" % (name, ",".join(map(str, sample)))]
    env = dict(os.environ)
    env.update(extra_env or {})
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.DEVNULL,
                            stderr=subprocess.PIPE, text=True)
    deadline = time.monotonic() + timeout
    while not os.path.exists(port_file):
        if proc.poll() is not None:
            raise RuntimeError("fleet died during bring-up: %s"
                               % proc.stderr.read()[-2000:])
        if time.monotonic() > deadline:
            proc.kill()
            raise RuntimeError("fleet never wrote its port file")
        time.sleep(0.1)
    return proc, int(open(port_file).read().split(":")[1])


def _fleet_bench(seconds=2.5):
    """The ``bench.py fleet`` mode (docs/how_to/fleet.md): the three
    fleet claims, measured, not assumed.

    - ``fleet_warm_start_x`` = cold-compile bring-up / AOT-warm
      bring-up: the cold run traces and XLA-compiles every (model,
      bucket) forward against an EMPTY cache; the warm run is a fresh
      process warming from the built AOT executable store
      (deserialized compiled programs — no trace, no compile; exactly
      a respawned replica's warmup).  Bar: >= 3x (``fleet_warm_ok``).
    - ``fleet_qps_x`` = 2-replica fleet QPS / 1-replica fleet QPS on
      the compute-heavy resnet model (npy bodies so client
      serialization cannot mask it; a low spill bar so the second
      replica actually takes overflow — the spill policy IS what is
      being scaled; best-of-2 over 4s windows for gate-grade
      stability).  Bar: >= 1.6x on a host with enough cores to run
      clients + router + two replicas concurrently; smaller hosts emit
      ``fleet_scaling_note`` (the mxdata 1-core honesty rule: the gate
      skips the SHAPE key via SCALING_SHAPE_KEYS, absolute keys still
      gate).
    - ``fleet_route_overhead_ms`` = router p50 - direct-to-replica p50
      at concurrency 1 on the resnet-shaped model (compute-heavy enough
      that the hop is measurable against a stable base).  Bar:
      overhead < 15% of the direct p50 (``fleet_route_ok``).  The GATE
      key is the monotone ratio ``fleet_route_eff`` = direct/router p50
      (higher is better, like every gate key; it collapses when the
      router hop bloats).
    """
    import shutil
    import signal as _signal
    import tempfile

    from mxnet_tpu.serving import ServeClient

    buckets = "1,2,4,8"
    tmp = tempfile.mkdtemp(prefix="bench_fleet_")
    out = {}
    proc = None
    try:
        specs = _save_serving_models(tmp, deep=True)
        store = os.path.join(tmp, "warm_store")
        cold_dir = os.path.join(tmp, "cold_cache")
        os.makedirs(store)
        os.makedirs(cold_dir)

        # --- AOT warm store: cold vs warm bring-up -----------------------
        from mxnet_tpu.fleet import build_warm_store
        built = build_warm_store(_fleet_manifest(specs, buckets), store)
        out["fleet_warm_build_s"] = built["warmup_s"]
        # cold replica: empty cache, no store — trace + XLA compile all
        cold_s = _fleet_warm_run(specs, buckets, cold_dir)
        # warm replica: fresh process against the built store —
        # deserialize the compiled executables
        warm_s = _fleet_warm_run(specs, buckets, store)
        out["fleet_warm_cold_s"] = round(cold_s, 3)
        out["fleet_warm_warm_s"] = round(warm_s, 3)
        out["fleet_warm_start_x"] = round(cold_s / max(warm_s, 1e-6), 2)
        out["fleet_warm_ok"] = bool(out["fleet_warm_start_x"] >= 3.0)

        fleet_env = {
            # spill early so the second replica takes real overflow
            "MXTPU_FLEET_SPILL_QUEUE": "4",
            "MXTPU_FLEET_HEARTBEAT_S": "0.25",
            "MXTPU_SERVE_MAX_WAIT_MS": "2",
        }

        # --- 1-replica fleet: baseline QPS + route overhead --------------
        # the scaling rows drive the resnet-shaped model: its forward
        # is compute-heavy enough that replica COMPUTE, not the python
        # HTTP plumbing (client encode, router hop), is what saturates
        # — the scaling number then measures replicas, not the proxy.
        # (The converse is real and measured: the router is ONE python
        # process, so sub-ms dispatch-bound models cap at its ~1.2k/s
        # proxy ceiling regardless of replica count — scale-out buys
        # throughput for compute-bound work, the docs say so.)
        # Best-of-2 over 4s windows: single short windows put ±15%
        # scheduler noise on a gate key with a 10% tolerance.
        def _scaling_row(port):
            return max(_serve_load(port, "resnet", specs["resnet"][2],
                                   32, 4.0, npy=True)
                       for _ in range(2))

        run1 = os.path.join(tmp, "run1")
        proc, port = _fleet_up(specs, buckets, store, run1, 1,
                               extra_env=fleet_env)
        qps1, _, _, _, _ = _scaling_row(port)
        out["fleet_qps_1"] = qps1
        _, router_p50, _, _, _ = _serve_load(
            port, "resnet", specs["resnet"][2], 1, seconds, npy=True)
        status, stats = ServeClient("127.0.0.1", port).stats()
        direct_port = None
        if status == 200:
            for rep in stats.get("replicas", {}).values():
                direct_port = rep.get("port")
        if direct_port:
            _, direct_p50, _, _, _ = _serve_load(
                direct_port, "resnet", specs["resnet"][2], 1, seconds,
                npy=True)
            if router_p50 and direct_p50:
                out["fleet_route_p50_ms"] = router_p50
                out["fleet_direct_p50_ms"] = direct_p50
                out["fleet_route_overhead_ms"] = round(
                    router_p50 - direct_p50, 3)
                out["fleet_route_eff"] = round(direct_p50 / router_p50,
                                               3)
                out["fleet_route_ok"] = bool(
                    router_p50 - direct_p50 < 0.15 * direct_p50)
        proc.send_signal(_signal.SIGTERM)
        out["fleet_drain_rc_1"] = proc.wait(timeout=90)
        proc = None

        # --- 2-replica fleet: the scale-out claim ------------------------
        run2 = os.path.join(tmp, "run2")
        proc, port = _fleet_up(specs, buckets, store, run2, 2,
                               extra_env=fleet_env)
        qps2, _, p99_2, shed2, err2 = _scaling_row(port)
        out["fleet_qps_2"] = qps2
        if p99_2 is not None:
            out["fleet_qps_2_p99_ms"] = p99_2
        if shed2:
            out["fleet_qps_2_shed"] = shed2
        if err2:
            out["fleet_qps_2_errors"] = err2
        status, stats = ServeClient("127.0.0.1", port).stats()
        if status == 200:
            out["fleet_spilled"] = stats["router"]["counters"].get(
                "spilled", 0)
            out["fleet_routed"] = stats["router"]["counters"].get(
                "routed", 0)
        if qps1:
            out["fleet_qps_x"] = round(qps2 / qps1, 2)
        ncores = os.cpu_count() or 1
        out["fleet_ncores"] = ncores
        if ncores < 4:
            # clients + router + 2 replicas are 4 concurrent python
            # processes: with fewer cores the scaling row is flat by
            # construction — the gate skips the SHAPE key, a capable
            # host still gates it (tests/test_bench_harness.py)
            out["fleet_scaling_note"] = \
                "flat_by_construction_%dcore" % ncores
        elif "fleet_qps_x" in out:
            out["fleet_qps_ok"] = bool(out["fleet_qps_x"] >= 1.6)
        proc.send_signal(_signal.SIGTERM)
        out["fleet_drain_rc"] = proc.wait(timeout=90)
        proc = None
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def _tail_bench(requests=60):
    """The ``bench.py tail`` mode (docs/how_to/fleet.md): hedged tail
    latency against a GRAY replica, measured, not assumed.

    Two 3-replica fleets, replica 0 armed with the ``slow_replica``
    fault (every request it serves stalls ~250 ms — a sick host whose
    probes stay fast).  A single sequential client routes to the
    least-loaded replica with the lowest-rid tie-break, so on an idle
    fleet EVERY request primary-routes to the gray replica — the worst
    case hedging exists for:

    - ``tail_unhedged_p99_ms`` — hedging off: the client eats the
      stall (the fail-once baseline this PR retires).
    - ``tail_p99_ms`` — hedging on (``MXTPU_FLEET_HEDGE_PCT=95``,
      floor 25 ms): the backup to the next-least-loaded replica
      answers first; the stalled primary is the race's counted loser
      (``hedge_wasted``).  GATE key, lower is better.
    - ``tail_drop_free`` — 1.0 iff ZERO non-200s across both windows
      and both fleets drained to rc 0: hedging must never trade
      correctness for latency.
    """
    import shutil
    import signal as _signal
    import tempfile

    from mxnet_tpu.serving import ServeClient

    buckets = "1,2,4,8"
    tmp = tempfile.mkdtemp(prefix="bench_tail_")
    out = {}
    try:
        specs = _save_serving_models(tmp)
        specs = {"mlp": specs["mlp"]}       # cheap model: the stall,
        store = os.path.join(tmp, "warm_store")  # not compute, is the tail
        os.makedirs(store)
        from mxnet_tpu.fleet import build_warm_store
        build_warm_store(_fleet_manifest(specs, buckets), store)
        rs = np.random.RandomState(11)
        x = rs.rand(*specs["mlp"][2]).astype("f")

        def window(run_dir, hedge):
            env = {
                "MXTPU_FLEET_HEARTBEAT_S": "0.25",
                "MXTPU_SERVE_MAX_WAIT_MS": "1",
                "MXTPU_FLEET_HEDGE_PCT": "95" if hedge else "0",
                "MXTPU_FLEET_HEDGE_MIN_MS": "25",
            }
            # arm far more stalls than the window sends: replica 0
            # stays gray for the WHOLE window, never exhausts mid-run
            fproc, port = _fleet_up(
                specs, buckets, store, run_dir, 3, extra_env=env,
                replica_env=["0:MXTPU_FAULTS=slow_replica:%d"
                             % (requests * 10)])
            try:
                lats, errors = [], 0
                cli = ServeClient("127.0.0.1", port, timeout=30)
                try:
                    # unmeasured warmup: first-touch costs (backup
                    # replica's batcher spin-up, conn setup, hedge
                    # thread machinery) would otherwise BE the p99 of
                    # a sequential window
                    for _ in range(3):
                        cli.predict("mlp", x, npy=True)
                    for _ in range(requests):
                        tic = time.perf_counter()
                        try:
                            status, _ = cli.predict("mlp", x, npy=True)
                        except Exception:  # noqa: BLE001 — dropped
                            status = -1
                        dt = (time.perf_counter() - tic) * 1e3
                        if status == 200:
                            lats.append(dt)
                        else:
                            errors += 1
                    status, stats = cli.stats()
                    counters = (stats["router"]["counters"]
                                if status == 200 else {})
                finally:
                    cli.close()
                fproc.send_signal(_signal.SIGTERM)
                rc = fproc.wait(timeout=90)
            finally:
                if fproc.poll() is None:
                    fproc.kill()
                    fproc.wait(timeout=30)
            return lats, errors, counters, rc

        from mxnet_tpu.serving.frontend import _percentile
        cold, errs_u, _, rc_u = window(os.path.join(tmp, "run_u"),
                                       hedge=False)
        hedged, errs_h, counters, rc_h = window(
            os.path.join(tmp, "run_h"), hedge=True)
        if cold:
            out["tail_unhedged_p99_ms"] = round(
                _percentile(sorted(cold), 99), 3)
        if hedged:
            out["tail_p99_ms"] = round(
                _percentile(sorted(hedged), 99), 3)
        if cold and hedged:
            out["tail_hedge_won"] = bool(
                out["tail_p99_ms"] < out["tail_unhedged_p99_ms"])
        out["tail_hedges"] = counters.get("hedges", 0)
        out["tail_hedge_wasted"] = counters.get("hedge_wasted", 0)
        out["tail_errors"] = errs_u + errs_h
        out["tail_drop_free"] = 1.0 if (
            errs_u == 0 and errs_h == 0 and rc_u == 0 and rc_h == 0
            and len(cold) == requests and len(hedged) == requests
        ) else 0.0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def _tenant_load(port, model, sample, tenants, seconds, warmup_s=0.5):
    """Closed-loop load with per-TENANT client pools: ``tenants`` is a
    list of ``(name, nthreads, pause_s)`` rows (``pause_s`` > 0 makes a
    pool well-behaved — it yields between requests instead of hammering
    back-to-back).  Returns {tenant: {"p50", "p99", "ok", "shed",
    "errors"}} from the CLIENT side — the flood's damage, if any, shows
    up in the quiet tenants' p99, not in a server-side counter."""
    import threading

    from mxnet_tpu.serving import ServeClient
    from mxnet_tpu.serving.frontend import _percentile

    rs = np.random.RandomState(3)
    stop = threading.Event()
    lock = threading.Lock()
    acc = {name: {"lat": [], "shed": 0, "errors": 0}
           for name, _, _ in tenants}

    def worker(tenant, pause_s, i):
        cli = ServeClient("127.0.0.1", port)
        x = rs.rand(*sample).astype("f") + i
        mine, shed, errors = [], 0, 0
        try:
            while not stop.is_set():
                tic = time.perf_counter()
                try:
                    status, _ = cli.predict(model, x, npy=True,
                                            tenant=tenant)
                except Exception:  # noqa: BLE001 — connection loss
                    status = -1
                dt = (time.perf_counter() - tic) * 1e3
                if status == 200:
                    mine.append((tic, dt))
                elif status == 429:
                    shed += 1
                else:
                    errors += 1
                if pause_s:
                    time.sleep(pause_s)
        finally:
            cli.close()
        with lock:
            acc[tenant]["lat"].extend(mine)
            acc[tenant]["shed"] += shed
            acc[tenant]["errors"] += errors

    threads = []
    for name, nthreads, pause_s in tenants:
        threads += [threading.Thread(target=worker,
                                     args=(name, pause_s, i))
                    for i in range(nthreads)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(warmup_s + seconds)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    cut = t0 + warmup_s
    out = {}
    for name, row in acc.items():
        window = sorted(d for (tic, d) in row["lat"] if tic >= cut)
        out[name] = {
            "ok": len(window),
            "p50": round(_percentile(window, 50), 3) if window else None,
            "p99": round(_percentile(window, 99), 3) if window else None,
            "shed": row["shed"], "errors": row["errors"]}
    return out


def _view_healthy_count(view_path):
    """Healthy-replica count straight from the published fleet-view
    snapshot (the same doc every router worker routes off) — None when
    the file is missing/torn (the reader's last-good rule; the bench
    just polls again)."""
    try:
        with open(view_path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    reps = doc.get("replicas") or {}
    return sum(1 for rep in reps.values() if rep.get("healthy"))


def _overdrive_bench(seconds=2.5):
    """The ``bench.py overdrive`` mode (docs/how_to/fleet.md "Sharding
    the front end"): the sharded front end's three claims, measured on
    the dispatch-bound tiny MLP — the opposite regime from ``fleet``'s
    compute-heavy resnet, and exactly the one where a single router
    process IS the fleet's QPS ceiling.

    - ``overdrive_qps`` / ``overdrive_qps_x`` = closed-loop QPS through
      4 SO_REUSEPORT router workers, and its ratio over the measured
      1-worker ceiling, with ONE identical replica behind both — the
      delta is pure front-end dispatch, nothing else changes.  Bar:
      >= 4x on a host with cores for clients + 4 workers + replica;
      smaller hosts emit ``overdrive_note`` and only the SHAPE key is
      gate-exempt (the SCALING_SHAPE_KEYS honesty rule — the absolute
      ``overdrive_qps`` still gates round over round).
    - ``overdrive_tenant_p99_ms`` (LOWER is better) = the worst
      WELL-BEHAVED tenant's client-side p99 while one tenant floods
      back-to-back at ~10x its queued-request quota through the same
      sharded front end.  The flood gets quota-shed
      (``overdrive_tenant_flood_shed`` > 0 proves the quota engaged);
      the quiet tenants must hold inside ``overdrive_tenant_slo_ms``.
    - ``overdrive_drop_free`` = 1.0 iff client-visible errors were ZERO
      across one autoscale-up (watermark breach -> warm AOT
      ``add_replica``) and one fenced scale-down (fence -> publish ->
      drain -> stop) under continuous traffic — capacity moved both
      ways and no request was dropped in either direction.
    """
    import shutil
    import signal as _signal
    import tempfile

    buckets = "1,2,4,8"
    tmp = tempfile.mkdtemp(prefix="bench_overdrive_")
    out = {}
    proc = None
    try:
        specs = _save_serving_models(tmp)
        specs = {"mlp": specs["mlp"]}
        sample = specs["mlp"][2]
        store = os.path.join(tmp, "warm_store")
        os.makedirs(store)
        from mxnet_tpu.fleet import build_warm_store
        build_warm_store(_fleet_manifest(specs, buckets), store)

        base_env = {
            "MXTPU_FLEET_HEARTBEAT_S": "0.25",
            "MXTPU_FLEET_VIEW_REFRESH_S": "0.2",
            "MXTPU_SERVE_MAX_WAIT_MS": "2",
        }

        def _qps_row(port):
            # best-of-2: scheduler noise on a shared box is larger
            # than the gate tolerance on a single short window
            return max(_serve_load(port, "mlp", sample, 8, seconds,
                                   npy=True)[0] for _ in range(2))

        # --- 1-worker ceiling vs 4 reuseport workers ---------------------
        run1 = os.path.join(tmp, "run1w")
        proc, port = _fleet_up(specs, buckets, store, run1, 1,
                               extra_env=base_env, workers=1)
        out["overdrive_qps_1w"] = _qps_row(port)
        proc.send_signal(_signal.SIGTERM)
        out["overdrive_drain_rc_1w"] = proc.wait(timeout=90)
        proc = None

        run4 = os.path.join(tmp, "run4w")
        proc, port = _fleet_up(specs, buckets, store, run4, 1,
                               extra_env=base_env, workers=4)
        out["overdrive_workers"] = 4
        out["overdrive_qps"] = _qps_row(port)
        proc.send_signal(_signal.SIGTERM)
        out["overdrive_drain_rc_4w"] = proc.wait(timeout=90)
        proc = None
        if out["overdrive_qps_1w"]:
            out["overdrive_qps_x"] = round(
                out["overdrive_qps"] / out["overdrive_qps_1w"], 2)
        ncores = os.cpu_count() or 1
        out["overdrive_ncores"] = ncores
        if ncores < 6:
            # clients + 4 workers + replica + publisher want >= 6
            # cores; with fewer, the kernel balances connections across
            # workers that all share one core — flat by construction,
            # the gate skips the SHAPE key only
            out["overdrive_note"] = \
                "flat_by_construction_%dcore" % ncores
        elif "overdrive_qps_x" in out:
            out["overdrive_qps_ok"] = bool(out["overdrive_qps_x"] >= 4.0)

        # --- tenant flood through the sharded front end ------------------
        # quota 2 queued; the flood pool runs 8 back-to-back threads
        # (~10x the share a 2-slot quota represents under 3 pools),
        # each quiet pool is 1 paced thread
        runt = os.path.join(tmp, "runt")
        tenant_env = dict(base_env, MXTPU_SERVE_TENANT_QUOTA="2")
        proc, port = _fleet_up(specs, buckets, store, runt, 1,
                               extra_env=tenant_env, workers=4)
        rows = _tenant_load(port, "mlp", sample,
                            [("flood", 8, 0.0),
                             ("quiet-a", 1, 0.005),
                             ("quiet-b", 1, 0.005)], 2.0 + seconds)
        proc.send_signal(_signal.SIGTERM)
        out["overdrive_drain_rc_tenant"] = proc.wait(timeout=90)
        proc = None
        quiet_p99 = [rows[t]["p99"] for t in ("quiet-a", "quiet-b")
                     if rows[t]["p99"] is not None]
        if quiet_p99:
            out["overdrive_tenant_p99_ms"] = max(quiet_p99)
        if rows["flood"]["p99"] is not None:
            out["overdrive_tenant_flood_p99_ms"] = rows["flood"]["p99"]
        out["overdrive_tenant_flood_shed"] = rows["flood"]["shed"]
        out["overdrive_tenant_errors"] = sum(
            r["errors"] for r in rows.values())
        out["overdrive_tenant_slo_ms"] = 500.0
        out["overdrive_tenant_ok"] = bool(
            quiet_p99 and max(quiet_p99) <= 500.0
            and rows["flood"]["shed"] > 0
            and out["overdrive_tenant_errors"] == 0)

        # --- the autoscale round trip, drop-free -------------------------
        # watermarks scaled to the MLP's ms-scale waits; cooldown short
        # so the drill finishes inside the mode budget
        runa = os.path.join(tmp, "runa")
        scale_env = dict(base_env,
                         MXTPU_FLEET_SCALE_HIGH_MS="1.0",
                         MXTPU_FLEET_SCALE_LOW_MS="0.25",
                         MXTPU_FLEET_SCALE_COOLDOWN_S="2",
                         MXTPU_FLEET_MIN_REPLICAS="1",
                         MXTPU_FLEET_MAX_REPLICAS="2")
        proc, port = _fleet_up(specs, buckets, store, runa, 1,
                               extra_env=scale_env, workers=2,
                               autoscale=True)
        view_path = os.path.join(runa, "fleet-view.json")
        import threading

        from mxnet_tpu.serving import ServeClient

        stop_flood = threading.Event()
        stop_all = threading.Event()
        errors = [0]
        sheds = [0]
        requests = [0]
        lock = threading.Lock()
        rs = np.random.RandomState(11)

        def drill_worker(i, flood):
            cli = ServeClient("127.0.0.1", port)
            x = rs.rand(*sample).astype("f") + i
            mine_err = mine_shed = mine_n = 0
            gate = stop_flood if flood else stop_all
            try:
                while not gate.is_set():
                    try:
                        status, _ = cli.predict("mlp", x, npy=True)
                    except Exception:  # noqa: BLE001 — conn loss
                        status = -1
                    mine_n += 1
                    if status == 429:
                        mine_shed += 1
                    elif status != 200:
                        mine_err += 1
                    if not flood:
                        time.sleep(0.05)  # the trickle keeps the
                        # signal under the LOW watermark
            finally:
                cli.close()
            with lock:
                errors[0] += mine_err
                sheds[0] += mine_shed
                requests[0] += mine_n

        threads = [threading.Thread(target=drill_worker,
                                    args=(i, True)) for i in range(8)]
        threads.append(threading.Thread(target=drill_worker,
                                        args=(99, False)))
        for t in threads:
            t.start()

        def _wait_healthy(n, deadline_s, what):
            deadline = time.monotonic() + deadline_s
            tic = time.monotonic()
            while _view_healthy_count(view_path) != n:
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        "overdrive autoscale drill: %s never happened "
                        "(healthy=%s)" % (what,
                                          _view_healthy_count(view_path)))
                time.sleep(0.1)
            return time.monotonic() - tic

        out["overdrive_scale_up_s"] = round(
            _wait_healthy(2, 120, "scale-up to 2 replicas"), 2)
        stop_flood.set()    # trickle only -> signal under LOW
        out["overdrive_scale_down_s"] = round(
            _wait_healthy(1, 120, "fenced scale-down to 1 replica"), 2)
        time.sleep(2.0)     # traffic across the post-fence drain too
        stop_all.set()
        for t in threads:
            t.join(timeout=30)
        out["overdrive_drill_requests"] = requests[0]
        out["overdrive_drill_errors"] = errors[0]
        if sheds[0]:
            out["overdrive_drill_shed"] = sheds[0]
        out["overdrive_drop_free"] = \
            1.0 if errors[0] == 0 and requests[0] > 0 else 0.0
        proc.send_signal(_signal.SIGTERM)
        out["overdrive_drain_rc"] = proc.wait(timeout=90)
        proc = None
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def _train_flops(sym_name):
    """Analytic training FLOPs per image (3x forward; contrib/flops.py)."""
    from mxnet_tpu import models
    from mxnet_tpu.contrib.flops import model_flops
    sym = models.get_symbol(sym_name, num_classes=1000)
    return 3 * model_flops(sym, data=(1, 3, 224, 224))


def _analyze_bench():
    """Static-analysis metrics (docs/how_to/static_analysis.md):
    per-step collective count + bytes from the mxlint graph audit for
    the standard MLP (dp 'allreduce' — expect all-reduce only) and the
    same model under grad_sync='zero' (expect all-gather +
    reduce-scatter by design), plus mxlint wall time over the full
    default scope (package + tools + bench, ALL levels including the
    whole-repo race/contract passes) against its < 5 s budget —
    ``lint_wall_ms`` is gate-guarded LOWER-is-better so a quadratic
    blow-up in a new repo-wide pass cannot land silently.  All host/CPU
    work."""
    import subprocess as _sp
    import time as _time

    out = {}
    here = os.path.dirname(os.path.abspath(__file__))
    t0 = _time.monotonic()
    res = _sp.run([sys.executable, os.path.join(here, "tools",
                                                "mxlint.py"), "-q"],
                  capture_output=True, text=True, timeout=120)
    out["mxlint_wall_s"] = round(_time.monotonic() - t0, 2)
    out["lint_wall_ms"] = round(out["mxlint_wall_s"] * 1000.0, 1)
    out["mxlint_rc"] = res.returncode
    out["mxlint_budget_ok"] = bool(
        res.returncode == 0 and out["mxlint_wall_s"] < 5.0)

    from mxnet_tpu.analysis import fixtures

    X, y = fixtures.standard_mlp_batch()
    findings = 0
    for key, grad_sync in (("analyze_mlp", "allreduce"),
                           ("analyze_zero", "zero")):
        trainer = fixtures.standard_mlp_trainer(grad_sync=grad_sync)
        try:
            rep = trainer.analyze(X, y)
            findings += len(rep.findings)
            out[key + "_collectives"] = rep.stats.get("collectives", {})
        finally:
            trainer.close()
    out["analyze_findings"] = findings
    return out


def _zero3_bench(preset=None):
    """Fully-sharded training sweep (docs/how_to/sharded_training.md):
    allreduce vs zero vs zero3 on the standard MLP and a deliberately
    WIDE model (params dominate activations — the regime zero3 exists
    for), on the 8-virtual-device CPU mesh.

    Self-proof keys: ``zero3_param_bytes_frac`` must show ~1/world
    per-device parameter residency (plus the indivisible-param
    residue), ``zero3_vs_zero_frac`` prices the on-demand gathers
    against zero's monolithic gather block (acceptance: within 10%),
    and ``zero3_schedule_ok`` runs trainer.analyze() so the artifact
    records the PROVEN collective schedule, not an assumption.  Gate
    keys: ``zero3_steps_s`` (throughput), ``zero3_param_shard_x``
    (residency leverage — drops to ~1 if sharding silently breaks),
    ``zero3_wide_mem_x`` (compiled peak-memory leverage on the wide
    model from ``compiled.memory_analysis()``).
    """
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu.analysis import fixtures
    from mxnet_tpu.parallel import SPMDTrainer, local_mesh

    small = preset == "small"
    steps = 10 if small else 30
    warmup = 3 if small else 8
    world = len(jax.devices())
    out = {"zero3_world": world}

    def _wide_sym(nh=2048, nc=8):
        data = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(data, num_hidden=nh, name="fc1")
        net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.FullyConnected(net, num_hidden=nc, name="fc2")
        return mx.sym.SoftmaxOutput(net, name="softmax")

    def _measure(make_trainer, X, y):
        res = {}
        for sync in ("allreduce", "zero", "zero3"):
            trainer = make_trainer(sync)
            full = sum(int(np.prod(v.shape)) *
                       np.dtype(v.dtype).itemsize
                       for v in trainer.params.values())
            resident = sum(v.addressable_shards[0].data.nbytes
                           for v in trainer.params.values())
            opt_res = sum(x.addressable_shards[0].data.nbytes
                          for s in trainer.opt_state.values() for x in s)
            args = trainer._example_args(X, y)
            compiled = trainer._step_fn.lower(*args).compile()
            try:
                ma = compiled.memory_analysis()
                peak = int(getattr(ma, "argument_size_in_bytes", 0) +
                           getattr(ma, "temp_size_in_bytes", 0))
            except Exception:  # noqa: BLE001 — backend without the API
                peak = None
            for _ in range(warmup):
                trainer.step(X, y)
            small_p = min(trainer.params,
                          key=lambda k: trainer.params[k].size)

            def sync_dev():
                np.asarray(
                    trainer.params[small_p].addressable_shards[0].data)

            sync_dev()
            tic = time.perf_counter()
            for _ in range(steps):
                trainer.step(X, y)
            sync_dev()
            elapsed = time.perf_counter() - tic
            entry = {"steps_s": round(steps / elapsed, 2),
                     "param_bytes": full,
                     "param_resident_bytes": resident,
                     "param_bytes_frac": round(resident / full, 4),
                     "opt_resident_bytes": opt_res}
            if peak:
                entry["peak_bytes"] = peak
            if sync == "zero3":
                entry["tier"] = trainer.zero3_tier
                rep = trainer.analyze(X, y)
                coll = rep.stats.get("collectives", {})
                entry["collectives"] = coll
                entry["schedule_ok"] = bool(
                    rep.ok and coll.get("reduce-scatter", {}).get("count")
                    and coll.get("all-gather", {}).get("count"))
            trainer.close()
            res[sync] = entry
        return res

    # standard MLP — the fixture every analyze/lint consumer pins
    X, y = fixtures.standard_mlp_batch()
    std = _measure(
        lambda sync: fixtures.standard_mlp_trainer(grad_sync=sync), X, y)
    out["zero3_steps_s"] = std["zero3"]["steps_s"]
    out["zero3_zero_steps_s"] = std["zero"]["steps_s"]
    out["zero3_allreduce_steps_s"] = std["allreduce"]["steps_s"]
    out["zero3_vs_zero_frac"] = round(
        std["zero3"]["steps_s"] / std["zero"]["steps_s"], 3)
    out["zero3_param_bytes_frac"] = std["zero3"]["param_bytes_frac"]
    out["zero3_param_shard_x"] = round(
        1.0 / max(std["zero3"]["param_bytes_frac"], 1e-9), 2)
    out["zero3_frac_ok"] = bool(
        std["zero3"]["param_bytes_frac"] <= 1.0 / world + 0.05)
    out["zero3_tier"] = std["zero3"].get("tier")
    out["zero3_collectives"] = std["zero3"].get("collectives")
    out["zero3_schedule_ok"] = std["zero3"].get("schedule_ok")

    # deliberately wide model: params >> activations, batch small
    nh = 512 if small else 2048
    din = 128 if small else 512
    rs = np.random.RandomState(0)
    Xw = rs.randn(32, din).astype("f")
    yw = rs.randint(0, 8, 32).astype("f")
    sym = _wide_sym(nh=nh)

    def _wide_trainer(sync):
        t = SPMDTrainer(sym, "sgd",
                        {"learning_rate": 0.05, "momentum": 0.9,
                         "rescale_grad": 1.0 / 32},
                        mesh=local_mesh("dp"), grad_sync=sync)
        t.bind([("data", (32, din))], [("softmax_label", (32,))])
        mx.random.seed(7)
        t.init_params(mx.initializer.Xavier())
        return t

    wide = _measure(_wide_trainer, Xw, yw)
    out["zero3_wide_steps_s"] = wide["zero3"]["steps_s"]
    out["zero3_wide_param_bytes_frac"] = \
        wide["zero3"]["param_bytes_frac"]
    if wide["zero3"].get("peak_bytes") and \
            wide["allreduce"].get("peak_bytes"):
        out["zero3_wide_peak_mb"] = round(
            wide["zero3"]["peak_bytes"] / 1e6, 2)
        out["zero3_allreduce_wide_peak_mb"] = round(
            wide["allreduce"]["peak_bytes"] / 1e6, 2)
        out["zero3_wide_mem_x"] = round(
            wide["allreduce"]["peak_bytes"] /
            wide["zero3"]["peak_bytes"], 2)
    else:
        # a backend without compiled.memory_analysis() cannot measure
        # the key at all — mark it structurally unmeasurable so the
        # self-gate SKIPS the comparison instead of reporting a
        # vanished metric (same contract as the 1-core scaling notes)
        out["zero3_mem_note"] = "unavailable_memory_analysis"
    return out


def _plan_bench(preset=None):
    """mxplan self-proof (docs/how_to/planner.md): planner decision
    time and the planned-vs-manual gather grouping on the zero3 bench
    model, on the 8-virtual-device CPU mesh.

    Gate keys (both LOWER is better): ``plan_decide_ms`` — one full
    prescriptive ``planner.plan()`` pass over the wide model (strategy
    ladder + per-param rules + gather groups; planning must stay a
    bind-time rounding error, never a bring-up tax) — and
    ``plan_step_ms`` — the zero3 step under the planned (=auto)
    grouping.  ``plan_vs_manual_frac`` prices the planned grouping
    against the retired manual default (MXTPU_ZERO3_GATHER_GROUP=1,
    per-layer gathers): < 1.0 means the planner's bucket-merged groups
    beat per-layer dispatch on this host.  Self-proof keys:
    ``plan_roundtrip_ok`` (serialize -> parse -> identical digest, the
    manifest-persistence contract) and ``plan_budget_ladder_ok`` (a
    shrinking HBM budget walks allreduce -> zero -> zero3).
    """
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.parallel import ShardingPlan, SPMDTrainer, local_mesh
    from mxnet_tpu.parallel import planner

    small = preset == "small"
    steps = 10 if small else 30
    warmup = 3 if small else 8
    world = len(jax.devices())
    out = {"plan_world": world}

    nh = 512 if small else 2048
    din = 128 if small else 512

    def _wide_sym():
        data = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(data, num_hidden=nh, name="fc1")
        net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.FullyConnected(net, num_hidden=8, name="fc2")
        return mx.sym.SoftmaxOutput(net, name="softmax")

    data_shapes = [("data", (32, din))]
    label_shapes = [("softmax_label", (32,))]
    # the small preset's whole model fits one default bucket, which
    # would collapse the zero3 byte model into zero's — scale the
    # bucket so the ladder has three distinct rungs on both presets
    bucket = (1 << 16) if small else None

    # 1) decision time: a full prescriptive pass, budget engaged so the
    # strategy ladder actually walks (best-of to shed scheduler noise)
    probe = planner.plan(_wide_sym(), data_shapes, label_shapes,
                         world=world, optimizer="sgd",
                         optimizer_params={"momentum": 0.9},
                         gather_bucket=bucket)
    model = probe.doc["bytes"]["per_device"]
    budget = int((model["zero"] + model["zero3"]) / 2)  # forces zero3
    best = None
    for _ in range(3 if small else 5):
        tic = time.perf_counter()
        chosen = planner.plan(_wide_sym(), data_shapes, label_shapes,
                              world=world, hbm_budget=budget,
                              optimizer="sgd",
                              optimizer_params={"momentum": 0.9},
                              gather_bucket=bucket)
        dt = time.perf_counter() - tic
        best = dt if best is None else min(best, dt)
    out["plan_decide_ms"] = round(best * 1000, 3)
    out["plan_grad_sync"] = chosen.grad_sync
    out["plan_groups"] = len(chosen.gather_groups)

    # self-proof: the budget ladder picks each strategy in turn, and a
    # serialized plan parses back bit-identical (the manifest contract)
    ladder = []
    for b in (model["allreduce"] + 1, model["zero"] + 1,
              model["zero3"] + 1):
        ladder.append(planner.plan(
            _wide_sym(), data_shapes, label_shapes, world=world,
            hbm_budget=int(b), optimizer="sgd",
            optimizer_params={"momentum": 0.9},
            gather_bucket=bucket).grad_sync)
    out["plan_budget_ladder"] = ladder
    out["plan_budget_ladder_ok"] = ladder == ["allreduce", "zero",
                                              "zero3"]
    try:
        planner.plan(_wide_sym(), data_shapes, label_shapes, world=world,
                     hbm_budget=1, optimizer="sgd")
        out["plan_overflow_raises"] = False
    except MXNetError:
        out["plan_overflow_raises"] = True
    rt = ShardingPlan.from_doc(json.loads(chosen.to_json()))
    out["plan_roundtrip_ok"] = bool(rt.digest() == chosen.digest())

    # 2) planned (=auto) vs the retired manual default (=1, per-layer)
    # on a DEEP stack — the regime where the groupings actually differ:
    # per-layer gathers dispatch one collective per fc, the planner's
    # bucket merge fuses consecutive small layers into few collectives
    depth = 4 if small else 10
    dnh = 128 if small else 512

    def _deep_sym():
        net = mx.sym.Variable("data")
        for i in range(depth):
            net = mx.sym.FullyConnected(net, num_hidden=dnh,
                                        name="fc%d" % i)
            net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.FullyConnected(net, num_hidden=8, name="fc_out")
        return mx.sym.SoftmaxOutput(net, name="softmax")

    deep_data = [("data", (32, dnh))]
    rs = np.random.RandomState(0)
    Xw = rs.randn(32, dnh).astype("f")
    yw = rs.randint(0, 8, 32).astype("f")

    def _measure(group_env):
        from mxnet_tpu.parallel.zero3 import ENV_ZERO3_GATHER_GROUP
        # steering the OPERATOR'S variable around one measurement, not
        # reading config — _scoped_env round-trips "unset" faithfully
        with _scoped_env(ENV_ZERO3_GATHER_GROUP, group_env):
            t = SPMDTrainer(_deep_sym(), "sgd",
                            {"learning_rate": 0.001, "momentum": 0.9,
                             "rescale_grad": 1.0 / 32},
                            mesh=local_mesh("dp"), grad_sync="zero3")
            t.bind(deep_data, label_shapes)
            mx.random.seed(7)
            t.init_params(mx.initializer.Xavier())
            ngroups = len(t._zero3_groups)
            for _ in range(warmup):
                t.step(Xw, yw)
            small_p = min(t.params, key=lambda k: t.params[k].size)

            def sync_dev():
                np.asarray(t.params[small_p].addressable_shards[0].data)

            sync_dev()
            tic = time.perf_counter()
            for _ in range(steps):
                t.step(Xw, yw)
            sync_dev()
            elapsed = time.perf_counter() - tic
            t.close()
            return (elapsed / steps) * 1000, ngroups

    # best-of-2, interleaved: host scheduler drift on a shared box is
    # larger than the grouping delta, so each variant keeps its best run
    auto_ms, auto_groups = _measure("auto")
    manual_ms, manual_groups = _measure("1")
    if not small:
        auto_ms = min(auto_ms, _measure("auto")[0])
        manual_ms = min(manual_ms, _measure("1")[0])
    out["plan_step_ms"] = round(auto_ms, 3)
    out["plan_manual_step_ms"] = round(manual_ms, 3)
    out["plan_vs_manual_frac"] = round(auto_ms / manual_ms, 3)
    out["plan_auto_groups"] = auto_groups
    out["plan_manual_groups"] = manual_groups
    return out


def _run_mode(mode):
    """One metric, current process.  Prints a partial-JSON line."""
    batch = _env_int("BENCH_BATCH", 32)
    steps = _env_int("BENCH_STEPS", 30)
    warmup = _env_int("BENCH_WARMUP", 10)
    trials = _env_int("BENCH_TRIALS", 2)
    sweep_steps = _env_int("BENCH_SWEEP_STEPS", 25)
    out = {}
    if mode == "_hang-grandchild":
        # harness self-test fixture (tests/test_bench_harness.py): hang
        # with a grandchild holding the inherited stdout pipe — the
        # BENCH_r05 failure shape.  Never in a real artifact.
        import subprocess as _sp
        _sp.Popen([sys.executable, "-c", "import time; time.sleep(600)"])
        time.sleep(600)
        return
    if mode in ("data_service", "data-service"):
        mode = "data-service"
    if mode in ("data_net", "data-net"):
        mode = "data-net"
    if mode in ("decode", "fed-cpu", "pipeline", "compile-probe",
                "resume", "checkpoint", "ckpt", "analyze", "serve",
                "fleet", "tail", "overdrive", "hotswap", "data-service",
                "data-net", "roofline", "zero3", "plan"):
        # host-side metrics: force the CPU backend BEFORE any jax client
        # exists — the axon plugin otherwise wins over JAX_PLATFORMS and
        # every nd.array would cross the tunneled device link
        if mode in ("analyze", "zero3", "plan", "ckpt"):
            # these lint/shard the dp=8 fused step on a virtual mesh
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count=8"
                ).strip()
        import jax
        jax.config.update("jax_platforms", "cpu")
    if mode == "analyze":
        out.update(_analyze_bench())
    elif mode == "zero3":
        out.update(_zero3_bench())
    elif mode == "plan":
        out.update(_plan_bench())
    elif mode == "roofline":
        out.update(_roofline_bench())
    elif mode == "serve":
        out.update(_serve_bench())
    elif mode == "fleet":
        out.update(_fleet_bench())
    elif mode == "tail":
        out.update(_tail_bench())
    elif mode == "overdrive":
        out.update(_overdrive_bench())
    elif mode == "region":
        out.update(_region_bench())
    elif mode == "hotswap":
        out.update(_hotswap_bench())
    elif mode == "decode":
        out.update(_decode_bench())
    elif mode == "data-service":
        out.update(_data_service_bench())
    elif mode == "data-net":
        out.update(_data_net_bench())
    elif mode == "fed-cpu":
        out.update(_fed_cpu_bench())
    elif mode == "pipeline":
        out.update(_pipeline_bench())
    elif mode == "compile-probe":
        out.update(_compile_probe())
    elif mode == "resume":
        out.update(_resume_bench())
    elif mode == "checkpoint":
        out.update(_checkpoint_bench())
    elif mode == "ckpt":
        out.update(_ckpt_sharded_bench())
    elif mode == "fed":
        out["fed"] = round(_fed_bench(batch, steps, warmup, trials), 2)
        out["fed_roofline"] = _roofline(out["fed"],
                                        _train_flops("resnet-50"))
        out["device_kind"] = _device_peak()[0]
    elif mode == "compute":
        tr = _make_trainer("resnet-50", batch)
        out["compute"] = round(
            _compute_bench(tr, batch, steps, warmup, trials), 2)
        out["compute_roofline"] = _roofline(out["compute"],
                                            _train_flops("resnet-50"))
        out["device_kind"] = _device_peak()[0]
    elif mode == "compute-large":
        # MFU headroom row: the baseline config is batch 32 (the
        # reference's table row); larger per-chip batches raise
        # arithmetic intensity and show the utilization ceiling
        big = _env_int("BENCH_LARGE_BATCH", 256)
        tr = _make_trainer("resnet-50", big)
        out["compute-large"] = round(
            _compute_bench(tr, big, max(8, steps // 3), 4, 1,
                           staged=_staged_batches(big, 2)), 2)
        out["compute-large_roofline"] = _roofline(
            out["compute-large"], _train_flops("resnet-50"))
        out["compute_large_batch"] = big
    elif mode in ("inception-bn", "resnet-152"):
        tr = _make_trainer(mode, batch)
        out[mode] = round(
            _compute_bench(tr, batch, sweep_steps, warmup, 1), 2)
        out[mode + "_roofline"] = _roofline(out[mode], _train_flops(mode))
    elif mode == "lstm":
        out["lstm"] = round(
            _lstm_bench(batch, 32, sweep_steps, warmup, 1), 2)
        from mxnet_tpu.contrib.flops import model_flops
        from mxnet_tpu.models import lstm_lm
        sym, _, _ = lstm_lm.lstm_lm_sym(32, 10000, num_embed=200,
                                        num_hidden=200, num_layers=2)
        # per-token training flops at the bench seq_len
        out["lstm_roofline"] = _roofline(
            out["lstm"], 3 * model_flops(sym, data=(1, 32)) / 32.0)
    else:
        # an unknown mode must fail loudly (-> a "failed" status record
        # in the artifact), not ship an empty part that looks like a
        # metric quietly measuring nothing
        sys.stderr.write("unknown BENCH_MODE %r\n" % mode)
        sys.exit(2)
    print("BENCH_PART " + json.dumps(out))


#: modes the positional CLI form (`python bench.py <mode>`) accepts —
#: the same names BENCH_MODE understands (aliases included)
KNOWN_MODES = frozenset((
    "decode", "data-service", "data_service", "data-net", "data_net",
    "fed-cpu", "pipeline", "compile-probe", "resume", "checkpoint",
    "ckpt", "analyze", "serve", "fleet", "tail", "overdrive", "hotswap",
    "region",
    "roofline", "zero3",
    "plan", "fed", "compute",
    "compute-large", "inception-bn", "resnet-152", "lstm",
))


def _collect(mode, timeout=480, extra_env=None):
    """Run one metric in a FRESH subprocess, with HARD timeout isolation.

    Each metric gets its own process because the tunneled device runtime
    degrades measurably when several large compiled programs share one
    client session (empirically: the same compute-only loop runs ~12x
    slower after another trainer has lived in the process — per-step
    overhead grows from ~2.5 ms to ~30 ms).  Fresh sessions give every
    metric the steady-state it would have in a real training job.
    ``extra_env`` overlays the child environment (the compile-cache
    probes point both runs at one cache directory this way); a value
    of ``None`` REMOVES the variable from the child (the resume mode
    strips an operator's global ``MXTPU_COMPILE_CACHE``, see main()).

    Isolation (the BENCH_r05 regression, ROADMAP item 5): a metric that
    hits its budget must cost THAT metric, never the run.  The child is
    its own session/process group and an overrun SIGKILLs the whole
    group — ``subprocess.run``'s own timeout path kills only the direct
    child and then blocks in ``communicate()`` for as long as any
    grandchild (XLA compile workers, decode pools) holds the inherited
    stdout pipe open, which is how one 480s model kill turned into rc=1
    for the whole r05 run.  The final pipe scavenge is bounded too, so
    even an unkillable (D-state) descendant cannot wedge the harness.
    """
    import signal as _signal
    import subprocess
    env = dict(os.environ)
    env["BENCH_MODE"] = mode
    for k, v in (extra_env or {}).items():
        if v is None:
            env.pop(k, None)
        else:
            env[k] = v
    proc = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True, env=env, start_new_session=True)
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, _signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        try:
            proc.communicate(timeout=15)
        except (subprocess.TimeoutExpired, ValueError, OSError):
            pass
        sys.stderr.write("bench mode %s timed out after %ds — partial "
                         "artifact continues\n" % (mode, timeout))
        return {mode: {"status": "timeout", "timeout_s": timeout}}
    for line in stdout.splitlines():
        if line.startswith("BENCH_PART "):
            return json.loads(line[len("BENCH_PART "):])
    sys.stderr.write("bench mode %s failed (rc=%s):\n%s\n"
                     % (mode, proc.returncode, (stderr or stdout)[-800:]))
    return {mode: {"status": "failed", "rc": proc.returncode}}


# ---------------------------------------------------------------------------
# regression gate (ROADMAP item 5): compare a fresh artifact against the
# most recent BENCH_*.json and fail on >10% drops in the named keys
# ---------------------------------------------------------------------------

#: higher-is-better keys the gate guards (except the members of
#: LOWER_IS_BETTER_KEYS below).  Entries ending in ``*`` are prefixes
#: (every matching key is compared).
GATE_KEYS = ("value", "compute_img_s", "compute_large_img_s",
             "inception_bn_img_s", "resnet152_img_s", "lstm_tok_s",
             "pipeline_decode_img_s", "fed_cpu", "pipeline_speedup",
             "ckpt_stall_ratio", "ckpt_save_ms", "ckpt_peak_host_frac",
             "serve_*_qps", "serve_batch_speedup",
             "data_service_img_s", "data_service_scaling_x",
             "data_net_img_s", "data_net_scaling_x",
             "pipeline_decode_scaling_x", "roofline_*_speedup",
             "roofline_inception_fwd_x", "roofline_infer_trace_x",
             "inception_gap_frac",
             "zero3_steps_s", "zero3_param_shard_x", "zero3_wide_mem_x",
             "fleet_qps_x", "fleet_warm_start_x", "fleet_route_eff",
             "tail_p99_ms", "tail_drop_free",
             "overdrive_qps", "overdrive_qps_x",
             "overdrive_tenant_p99_ms", "overdrive_drop_free",
             "hotswap_drop_free", "hotswap_swap_ms",
             "region_drop_free", "region_goodput_chaos_frac",
             "region_freshness_ms",
             "plan_decide_ms", "plan_step_ms", "lint_wall_ms")

#: GATE_KEYS members where LOWER is better (latencies): the gate flags
#: a RISE past tolerance instead of a drop — gating a latency with the
#: higher-is-better rule would fail every improvement and bless every
#: regression
LOWER_IS_BETTER_KEYS = frozenset(("hotswap_swap_ms", "plan_decide_ms",
                                  "tail_p99_ms",
                                  "plan_step_ms", "region_freshness_ms",
                                  "overdrive_tenant_p99_ms",
                                  "ckpt_save_ms", "ckpt_peak_host_frac",
                                  "lint_wall_ms"))

#: structurally-unmeasurable keys: each maps to a NOTE key whose
#: presence (``flat_by_construction*`` on 1-core hosts — the decode
#: threads/worker processes have nowhere to scale TO — or
#: ``unavailable*`` when the backend lacks the measurement API) makes
#: the gate SKIP that one comparison; a host that CAN measure still
#: gates, so the note can neither mask nor fake a regression.  The
#: absolute-throughput keys above always gate.
SCALING_SHAPE_KEYS = {
    "pipeline_decode_scaling_x": "decode_scaling_note",
    "data_service_scaling_x": "data_service_scaling_note",
    "data_net_scaling_x": "data_net_scaling_note",
    "zero3_wide_mem_x": "zero3_mem_note",
    # clients + router + 2 replicas need >= 4 cores to scale; smaller
    # hosts note it and only the SHAPE key is exempted
    "fleet_qps_x": "fleet_scaling_note",
    # clients + 4 reuseport workers + replica need >= 6 cores; the
    # absolute overdrive_qps always gates
    "overdrive_qps_x": "overdrive_note",
}

#: keys whose absolute value is a property of the ACCELERATOR tier the
#: round ran on (the fed/compute/model-sweep throughputs).  The gate
#: compares them only when baseline and new artifact ran the SAME
#: device tier (``device_kind``): a CPU round "regressing" a TPU
#: round's img/s is a hardware swap, not a code regression — and
#: blessing it would be just as wrong as blocking it.  Skipped keys
#: are listed LOUDLY in the report (``skipped_device_tier_change``);
#: same-tier rounds always gate, so the rule can neither mask nor fake
#: a regression within a tier.  Ratio/host-side keys always gate.
DEVICE_TIER_KEYS = frozenset((
    "value", "compute_img_s", "compute_large_img_s",
    "inception_bn_img_s", "resnet152_img_s", "lstm_tok_s"))


def _gate_payload(path):
    """An artifact file -> the result dict.  Accepts both the raw
    ``bench.py`` stdout object and the driver's ``{n, cmd, rc, parsed,
    tail}`` wrapper; returns None when the file holds no usable run
    (e.g. the r05 rc=1 wrapper with ``parsed: null``)."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "parsed" in doc and "cmd" in doc:
        doc = doc["parsed"]
    if not isinstance(doc, dict) or not doc:
        return None
    return doc


def _latest_artifact(directory, exclude=None):
    """Newest usable ``BENCH_*.json`` by round number (``BENCH_r05`` >
    ``BENCH_r04``), skipping files with no payload AND ``exclude``."""
    import re
    best = None
    exclude = os.path.abspath(exclude) if exclude else None
    for name in os.listdir(directory):
        m = re.match(r"BENCH_r?(\d+)\.json$", name)
        if not m:
            continue
        path = os.path.join(directory, name)
        if exclude and os.path.abspath(path) == exclude:
            continue
        try:
            payload = _gate_payload(path)
        except (OSError, ValueError):
            continue
        if payload is None:
            continue
        if best is None or int(m.group(1)) > best[0]:
            best = (int(m.group(1)), path, payload)
    return best


def _match_gate_keys(payload):
    keys = set()
    for pat in GATE_KEYS:
        if "*" in pat:
            head, _, tail = pat.partition("*")
            keys.update(k for k in payload
                        if k.startswith(head) and k.endswith(tail)
                        and isinstance(payload[k], (int, float)))
        elif isinstance(payload.get(pat), (int, float)):
            keys.add(pat)
    return keys


def gate(new_path, against=None, tolerance=0.10):
    """Compare ``new_path`` (an artifact path, or an already-parsed
    result dict — the self-gate in ``main()`` passes its own result)
    against a baseline artifact; returns the report dict (``pass``
    False on any guarded key dropping more than ``tolerance``, going
    missing, or timing out)."""
    if isinstance(new_path, dict):
        new, new_path = new_path, None
    else:
        try:
            new = _gate_payload(new_path)
        except (OSError, ValueError) as e:
            return {"pass": False, "error": "cannot read artifact %s: %s"
                    % (new_path, e)}
        if new is None:
            return {"pass": False, "error": "artifact %s holds no parsed "
                    "result" % new_path}
    if against:
        try:
            base_path, base = against, _gate_payload(against)
        except (OSError, ValueError) as e:
            return {"pass": False, "error": "cannot read baseline %s: %s"
                    % (against, e)}
    else:
        found = _latest_artifact(
            os.path.dirname(os.path.abspath(__file__)), exclude=new_path)
        if found is None:
            return {"pass": True, "baseline": None,
                    "note": "no prior BENCH_*.json — nothing to gate "
                            "against"}
        _, base_path, base = found
    if base is None:
        return {"pass": False, "error": "baseline %s holds no parsed "
                "result" % base_path}
    regressions, checked, skipped = [], [], []
    tier_skipped = []
    base_tier = base.get("device_kind")
    new_tier = new.get("device_kind")
    tier_changed = base_tier != new_tier
    structural = ("flat_by_construction", "unavailable")
    for key in sorted(_match_gate_keys(base)):
        if key in DEVICE_TIER_KEYS and tier_changed:
            # accelerator-tier throughputs are only comparable within
            # one device tier — a changed tier is recorded, not gated
            tier_skipped.append(key)
            continue
        note = SCALING_SHAPE_KEYS.get(key)
        if note is not None and (
                str(base.get(note, "")).startswith(structural)
                or str(new.get(note, "")).startswith(structural)):
            skipped.append(key)
            continue
        old_v = base[key]
        new_v = new.get(key)
        if not isinstance(new_v, (int, float)):
            # a guarded metric that vanished IS a regression — that is
            # precisely how a timed-out model (r05's inception-bn)
            # surfaces in a partial artifact
            regressions.append({"key": key, "baseline": old_v,
                                "status": "missing"})
            continue
        checked.append(key)
        if key in LOWER_IS_BETTER_KEYS:
            if old_v > 0 and new_v > old_v * (1.0 + tolerance):
                regressions.append(
                    {"key": key, "baseline": old_v, "value": new_v,
                     "rise": round(new_v / old_v - 1.0, 3)})
        elif old_v > 0 and new_v < old_v * (1.0 - tolerance):
            regressions.append(
                {"key": key, "baseline": old_v, "value": new_v,
                 "drop": round(1.0 - new_v / old_v, 3)})
    report = {"pass": not regressions, "baseline": base_path,
              "tolerance": tolerance, "checked": checked,
              "regressions": regressions}
    if skipped:
        report["skipped_flat_by_construction"] = skipped
    if tier_skipped:
        report["skipped_device_tier_change"] = {
            "keys": tier_skipped,
            "baseline_device": base_tier, "new_device": new_tier}
    if new.get("incomplete"):
        report["incomplete_modes"] = sorted(new["incomplete"])
    return report


def _gate_main(argv):
    import argparse
    parser = argparse.ArgumentParser(
        prog="bench.py --gate",
        description="fail (rc 1) on >tolerance drops vs the most recent "
                    "BENCH_*.json")
    parser.add_argument("--gate", required=True, metavar="NEW.json",
                        help="the fresh artifact to check")
    parser.add_argument("--against", default=None, metavar="OLD.json",
                        help="explicit baseline (default: newest usable "
                             "BENCH_*.json next to bench.py)")
    parser.add_argument("--gate-tolerance", type=float, default=0.10)
    args = parser.parse_args(argv)
    report = gate(args.gate, against=args.against,
                  tolerance=args.gate_tolerance)
    print(json.dumps(report))
    return 0 if report["pass"] else 1


def main():
    if any(a.startswith("--gate") for a in sys.argv[1:]):
        sys.exit(_gate_main(sys.argv[1:]))
    mode = os.environ.get("BENCH_MODE")
    if mode is None and len(sys.argv) > 1 and sys.argv[1] in KNOWN_MODES:
        # positional single-mode form, e.g. `python bench.py roofline`
        # (docs/how_to/kernels.md) — same path as BENCH_MODE=<mode>.
        # Restricted to the known-mode set: main() is also called
        # IN-PROCESS (tests monkeypatch _collect), where argv belongs
        # to the embedding program, not to bench.
        mode = sys.argv[1]
    if mode:
        _run_mode(mode)
        return

    batch = _env_int("BENCH_BATCH", 32)
    result = {}
    parts = {}
    if os.environ.get("BENCH_PIPELINE", "1") != "0":
        parts.update(_collect("decode"))
        parts.update(_collect("data-service"))
        parts.update(_collect("data-net"))
        parts.update(_collect("fed-cpu"))
        parts.update(_collect("pipeline"))
        # cold vs warm bring-up through the persistent compile cache: two
        # fresh processes sharing one MXTPU_COMPILE_CACHE dir — the first
        # compiles and populates, the second loads from disk
        import shutil
        import tempfile
        cache_dir = tempfile.mkdtemp(prefix="bench_compile_cache_")
        try:
            cache_env = {"MXTPU_COMPILE_CACHE": cache_dir}
            cold = _collect("compile-probe", extra_env=cache_env)
            warm = _collect("compile-probe", extra_env=cache_env)
        finally:
            shutil.rmtree(cache_dir, ignore_errors=True)
        if "compile_bringup_s" in cold:
            parts["compile_cold_s"] = cold["compile_bringup_s"]
        if "compile_bringup_s" in warm:
            parts["compile_warm_s"] = warm["compile_bringup_s"]
        # the resume drill runs WITHOUT any operator-set global compile
        # cache: jax's persistent compilation cache segfaults this
        # backend's save/restore/second-trainer sequence (glibc heap
        # corruption; reproduced on the pre-mxfuse tree, so pre-existing
        # upstream, not a harness property).  Stripping the var costs
        # resume its warm-relaunch amortization — an honesty note, not a
        # masked failure: the mode still measures the full recompile.
        parts.update(_collect("resume",
                              extra_env={"MXTPU_COMPILE_CACHE": None}))
        parts.update(_collect("checkpoint"))
        # sharded-native vs gathered checkpoints on the dp=8 zero3 mesh
        parts.update(_collect("ckpt"))
        parts.update(_collect("serve"))
        parts.update(_collect("hotswap"))
        parts.update(_collect("fleet", timeout=600))
        parts.update(_collect("tail", timeout=600))
        # the sharded front end: reuseport worker scaling, tenant
        # isolation under flood, the drop-free autoscale round trip
        parts.update(_collect("overdrive", timeout=600))
        # the composed region drill (tools/region.py smoke): trainer
        # bring-up + fleet bring-up + the settled storm window
        parts.update(_collect("region", timeout=600))
        # the mxfuse whole-model stanza compiles inception twice
        parts.update(_collect("roofline", timeout=600))
        parts.update(_collect("zero3"))
        parts.update(_collect("plan"))
        # CPU-tier hosts pay the cold resnet-50 fwd+bwd XLA compile
        # (up to ~20 min) inside this mode before the first step runs
        # (cold on purpose — see the compute stanza below)
        parts.update(_collect("fed", timeout=1800))
    parts.update(_collect("analyze", timeout=240))
    # the model compiles dominate these modes on CPU-tier hosts: a cold
    # resnet-50 fwd+bwd build runs ~20 min before the first step, so
    # the budgets assume COLD compiles.  Deliberately not amortized via
    # MXTPU_COMPILE_CACHE: on this backend executables LOADED from the
    # persistent cache compute garbage (non-finite training steps,
    # occasional heap corruption) even though compile-and-run in one
    # process is fine — reproduced on the pre-mxfuse tree, see
    # docs/how_to/performance.md "Persistent compile cache"
    parts.update(_collect("compute", timeout=1800))
    if os.environ.get("BENCH_SWEEP", "1") != "0":
        parts.update(_collect("compute-large", timeout=2400))
        parts.update(_collect("inception-bn", timeout=1800))
        # the deepest compile of the sweep: >40 min cold on this tier
        parts.update(_collect("resnet-152", timeout=3600))
        parts.update(_collect("lstm"))

    # pull timed-out/failed models aside so the numeric consumers below
    # see only real measurements; the statuses ship in the artifact
    statuses = {k: v for k, v in parts.items()
                if isinstance(v, dict) and v.get("status")}
    for k in statuses:
        parts.pop(k)
    if statuses:
        result["incomplete"] = statuses

    baseline = 109.0  # reference: ResNet-50 batch 32 on 1x K80
    fed = parts.get("fed")
    compute = parts.get("compute")
    if fed is not None:
        result.update({
            "metric": "resnet50_train_throughput_fed_batch%d" % batch,
            "value": fed,
            "unit": "images/sec",
            "vs_baseline": round(fed / baseline, 3),
        })
        result["pipeline_note"] = (
            "fed number is bound by this harness's tunneled device link "
            "(~100ms/op RTT under concurrent traffic), not the pipeline: "
            "see pipeline_decode_img_s (host-only, zero device) and "
            "fed_cpu_overlap (feed machinery vs the host's ceiling)")
    if "decode" in parts:
        # reference RecordIO pipeline row: ~3,000 img/s decode+augment
        # (imagenet_full.md:37) — measured here with zero device
        # involvement, per-thread-count scaling rows included
        result["pipeline_decode_img_s"] = parts["decode"]
        result["pipeline_decode_vs_baseline"] = round(
            parts["decode"] / 3000.0, 3)
        result["pipeline_decode_per_core_img_s"] = parts["decode_per_core"]
        result["pipeline_decode_scaling"] = parts["decode_scaling"]
        result["pipeline_decode_scaling_x"] = parts.get("decode_scaling_x")
        result["pipeline_ncores"] = parts["ncores"]
        if "decode_scaling_note" in parts:
            result["decode_scaling_note"] = parts["decode_scaling_note"]
    for k in sorted(parts):
        if k.startswith("data_service_") or k.startswith("data_net_"):
            result[k] = parts[k]
    for k in ("fed_cpu", "fed_cpu_decode", "fed_cpu_step",
              "fed_cpu_ceiling", "fed_cpu_overlap",
              "pipeline_steps_s_depth0", "pipeline_steps_s_depth2",
              "pipeline_speedup", "pipeline_step_ms",
              "pipeline_iter_delay_ms",
              "compile_cold_s", "compile_warm_s",
              "resume_save_s", "resume_restore_s", "resume_refit_s",
              "resume_baseline_s", "resume_overhead_s", "resume_parity",
              "resume_parity_note",
              "ckpt_stall_blocking_s", "ckpt_stall_async_s",
              "ckpt_stall_ratio", "ckpt_parity",
              "ckpt_restore_verified_s", "ckpt_verify_s",
              "ckpt_fsck_s", "ckpt_fsck_rc",
              "ckpt_world", "ckpt_save_ms", "ckpt_gathered_save_ms",
              "ckpt_restore_ms", "ckpt_peak_host_frac",
              "ckpt_peak_host_bytes", "ckpt_total_blob_bytes",
              "ckpt_sharded_parity",
              "mxlint_wall_s", "lint_wall_ms", "mxlint_rc",
              "mxlint_budget_ok",
              "analyze_mlp_collectives", "analyze_zero_collectives",
              "analyze_findings"):
        if k in parts:
            result[k] = parts[k]
    for k in sorted(parts):
        if k.startswith("serve_") or k.startswith("roofline_") \
                or k.startswith("zero3_") or k.startswith("fleet_") \
                or k.startswith("hotswap_") or k.startswith("plan_"):
            result[k] = parts[k]
    if compute is not None:
        if fed is None:
            result.update({
                "metric": "resnet50_train_throughput_batch%d" % batch,
                "value": compute,
                "unit": "images/sec",
                "vs_baseline": round(compute / baseline, 3),
            })
        else:
            result["compute_img_s"] = compute
            result["compute_vs_baseline"] = round(compute / baseline, 3)
            result["pipeline_frac_of_compute"] = round(fed / compute, 3)
    if "inception-bn" in parts:
        result["inception_bn_img_s"] = parts["inception-bn"]
        result["inception_bn_vs_baseline"] = round(
            parts["inception-bn"] / 152.0, 3)
        if compute:
            # the mxfuse headline metric (ROADMAP item 5): inception's
            # speedup-over-its-K80-baseline as a fraction of resnet50's
            # (r04: 61.4x / 137.1x = 0.448) — the plan-optimizer passes
            # exist to narrow this gap, and the gate holds the ratio
            result["inception_gap_frac"] = round(
                (parts["inception-bn"] / 152.0) / (compute / 109.0), 3)
    if "resnet-152" in parts:
        result["resnet152_img_s"] = parts["resnet-152"]
        result["resnet152_vs_baseline"] = round(
            parts["resnet-152"] / 57.0, 3)
    if "lstm" in parts:
        result["lstm_tok_s"] = parts["lstm"]

    # roofline accounting: every on-chip rate carries analytic FLOPs and
    # MFU against the chip's nominal peak; >100% is physically impossible
    # and fails the run loudly instead of shipping a bogus artifact
    if "device_kind" in parts:
        result["device_kind"] = parts["device_kind"]
        result["device_peak_tflops"] = PEAK_TFLOPS.get(parts["device_kind"])
    if "compute-large" in parts:
        result["compute_large_img_s"] = parts["compute-large"]
        result["compute_large_batch"] = parts.get("compute_large_batch")
    violations = []
    for key in ("fed", "compute", "compute-large", "inception-bn",
                "resnet-152", "lstm"):
        roof = parts.get(key + "_roofline")
        if roof:
            # key style matches the sibling *_img_s keys: resnet-152 ->
            # resnet152_img_s, compute-large -> compute_large_img_s
            name = ("resnet152" if key == "resnet-152"
                    else key.replace("-", "_"))
            result[name + "_roofline"] = roof
            if roof.get("mfu", 0) > 1.0:
                violations.append("%s: mfu=%.2f" % (key, roof["mfu"]))
    result["sync_method"] = (
        "dependent-scalar fetch + work-scaling slope (block_until_ready "
        "returns on dispatch ack on this backend; see bench.py docstring)")
    if violations:
        result["mfu_implausible"] = violations
        sys.stderr.write("ROOFLINE VIOLATION (>100%% MFU — measurement "
                         "invalid): %s\n" % "; ".join(violations))

    # self-enforcing regression gate (ROADMAP item 5, final step): a full
    # run compares itself against the newest usable BENCH_*.json on disk
    # and FAILS THE PROCESS on >10% drops or vanished keys, so the
    # driver/CI rc blocks regressions instead of accumulating them.
    # BENCH_GATE=0 opts out; partial runs (BENCH_PIPELINE/BENCH_SWEEP
    # off) never self-gate — they are missing keys by design.
    gate_report = None
    full_run = (os.environ.get("BENCH_PIPELINE", "1") != "0"
                and os.environ.get("BENCH_SWEEP", "1") != "0")
    if os.environ.get("BENCH_GATE", "1") != "0" and full_run:
        gate_report = gate(result)
        result["gate"] = gate_report
        if not gate_report.get("pass", True):
            sys.stderr.write("BENCH GATE FAILED: %s\n"
                             % json.dumps(gate_report))

    print(json.dumps(result))
    if gate_report is not None and not gate_report.get("pass", True):
        sys.exit(1)


if __name__ == "__main__":
    main()
