"""eltwise_chain — collapse private elementwise runs into one entry.

A run of elementwise ops at dispatch granularity is one memory
round-trip PER OP: each stage writes its full tensor and the next reads
it back.  Fused into one region the chain is one read and one write —
the canonical memory-bound fusion (``bench.py roofline``,
``roofline_eltwise_chain_*``).  Under the whole-graph jit the composed
function traces the IDENTICAL op sequence, so the compiled program —
and therefore forward AND gradient values — are bit-identical to the
unfused plan; the win is real on the eager paths (no-jit graphs,
dispatch-granularity execution) and in plan/trace size.

:data:`ELTWISE_OPS` is the fusable catalog: plain, deterministic,
single-output elementwise math.  Ops with RNG (Dropout), train-mode
branches, custom VJPs (the loss layers), or host callbacks are
deliberately absent — their semantics are not position-free.
"""
from __future__ import annotations

__all__ = ["ELTWISE_OPS", "make_chain_fn"]

#: registered op names the chain pass may absorb (docs/how_to/kernels.md)
ELTWISE_OPS = frozenset((
    # unary math
    "Activation", "abs", "sign", "ceil", "floor", "round", "rint",
    "trunc", "fix", "square", "sqrt", "rsqrt", "cbrt", "rcbrt",
    "exp", "log", "log10", "log2", "log1p", "expm1", "clip",
    "smooth_l1", "sin", "cos", "tan", "sinh", "cosh", "tanh",
    "arcsin", "arccos", "arctan", "arcsinh", "arccosh", "arctanh",
    "relu", "sigmoid", "softsign", "negative", "reciprocal", "erf",
    # scalar-attr binary
    "_plus_scalar", "_minus_scalar", "_rminus_scalar", "_mul_scalar",
    "_div_scalar", "_rdiv_scalar", "_power_scalar", "_rpower_scalar",
    "_maximum_scalar", "_minimum_scalar",
    # tensor binary (the second operand rides as an extra ref)
    "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
    "_maximum", "_minimum",
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "broadcast_maximum", "broadcast_minimum",
))


def make_chain_fn(stages):
    """Compose a fused chain body from ``stages`` — a list of
    ``(op_fn, call_attrs, n_side_inputs)`` in chain order.

    The interpreter calls the override at the chain TAIL with the
    tail's own inputs first (the chain value slot plus the tail's side
    operands) followed by the extra refs: the side operands of every
    earlier stage, flattened in chain order.  The tail's ``call_attrs``
    arrive as keywords too; they are ignored in favor of the closed-over
    copy (same values — the interpreter contract passes them always).
    """
    head_to_last = stages[:-1]
    tail_fn, tail_attrs, tail_nside = stages[-1]

    def fused(*vals, **_tail_kw):
        x = vals[0]
        tail_sides = vals[1:1 + tail_nside]
        extras = vals[1 + tail_nside:]
        k = 0
        for fn, attrs, nside in head_to_last:
            sides = extras[k:k + nside]
            k += nside
            x = fn(x, *sides, **attrs)
        return tail_fn(x, *tail_sides, **tail_attrs)

    return fused
