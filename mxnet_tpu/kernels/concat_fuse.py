"""concat_fuse — merge sibling conv→BN(→act) tower heads into one conv.

Inception's towers run parallel convolutions the machine executes as N
narrow GEMMs back to back.  Two merge shapes close that gap
(:func:`mxnet_tpu.mxfuse.pass_concat_fuse`):

- **shared input** (the 1x1 branch + the 3x3/double-3x3 "reduce"
  layers over one tensor): ONE conv over the concatenated filters does
  the identical per-output-channel math with far better
  blocking/parallel efficiency — the TASO-style multi-conv merge.
- **sibling inputs** (the parallel 3x3 convs, whose inputs are
  different tensors — after the shared-input merge, usually adjacent
  slices of one merged body): channel-concatenate the inputs and run
  ONE GROUPED conv (``num_group`` = member count).  Grouped
  convolution assigns input block *i* to output block *i*, so this is
  BITWISE the per-member convs (measured 1.4-1.9x at inception tail
  shapes, where narrow GEMMs are dispatch/efficiency-bound).

The plan pass rewrites each member's BatchNorm entry with
:func:`make_group_member`: every member computes the SHARED merged
body — merged conv, merged per-channel BN (training) or per-member
fold into the merged weights (inference) — then slices its own channel
range.  The member bodies are textually identical HLO over identical
operands, so XLA's CSE collapses them into one; correctness never
depends on that (only speed).

Numerics: convolution is independent per output channel (and per
group), so the merged result IS the member result up to the conv's
float reduction order for the shared-input shape (XLA may block a
wider GEMM differently) and bitwise for the grouped shape — the same
documented reassociation tolerance the ``bn_fold`` pass carries.  BN
batch statistics are per-channel, so the merged-stats slices equal the
member stats under the same tolerance.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["make_group_member"]


def make_group_member(member_ix, n_members, conv_attrs, act_type,
                      offsets, has_bias, do_fold, grouped=False):
    """The override body for member ``member_ix`` of a merged group.

    Called at the member's BatchNorm entry as ``fused(conv_out, gamma,
    beta, mm, mv, *extra, is_train=..., **bn_attrs)`` where ``extra``
    is ``[x]`` (shared-input mode) or ``[x_0..x_{n-1}]`` (grouped
    mode) followed by every member's ``w (, b), gamma, beta, mm, mv``.
    The member's own positional inputs are ignored (the original
    per-branch conv goes dead).  Returns ``(member slice, mm_new,
    mv_new)`` with the member's aux updates sliced from the merged
    statistics.

    Grouped mode requires every member input to carry the same channel
    count (grouped conv splits evenly); the trace-time shapes decide —
    a mismatched group falls back to the member's own unfused math.
    """
    lo, hi = offsets[member_ix], offsets[member_ix + 1]
    call_attrs = {k: v for k, v in conv_attrs.items() if k != "no_bias"}

    def _unpack(extra):
        n_x = n_members if grouped else 1
        xs = list(extra[:n_x])
        ws, bs, gams, bets, mms, mvs = [], [], [], [], [], []
        k = n_x
        for _ in range(n_members):
            ws.append(extra[k])
            k += 1
            if has_bias:
                bs.append(extra[k])
                k += 1
            gams.append(extra[k])
            bets.append(extra[k + 1])
            mms.append(extra[k + 2])
            mvs.append(extra[k + 3])
            k += 4
        return xs, ws, bs, gams, bets, mms, mvs

    def fused(_data, _gamma, _beta, _moving_mean, _moving_var, *extra,
              is_train=False, **bn_attrs):
        # the positional inputs are ignored (declared eval-dead; the
        # original per-branch conv is pruned from the eval trace) —
        # every value rides in via the extra refs
        from ..ops.nn import activation, convolution
        from . import bn_act as _ba
        bn_attrs.pop("output_mean_var", None)   # fusion requires False
        xs, ws, bs, gams, bets, mms, mvs = _unpack(extra)
        attrs = dict(call_attrs)
        if grouped:
            if len({x.shape[1] for x in xs}) != 1 \
                    or len({w.shape for w in ws}) != 1:
                # uneven siblings cannot share a grouped conv — run
                # this member's own (unfused) math instead
                return _member_solo(
                    xs[member_ix], ws[member_ix],
                    bs[member_ix] if has_bias else None,
                    gams[member_ix], bets[member_ix], mms[member_ix],
                    mvs[member_ix], attrs, act_type, is_train,
                    bn_attrs, do_fold)
            x = jnp.concatenate(xs, axis=1)
            attrs["num_group"] = n_members \
                * int(attrs.get("num_group", 1))
        else:
            x = xs[0]
        attrs["num_filter"] = offsets[-1]
        if not is_train and do_fold:
            # inference: fold each member's frozen stats into ITS slice
            # of the merged weights — the BN vanishes from the trace
            folded = [_ba.fold_bn_into_conv(
                w, (bs[i] if has_bias else None), gams[i], bets[i],
                mms[i], mvs[i], eps=bn_attrs.get("eps", 0.001),
                fix_gamma=bn_attrs.get("fix_gamma", True))
                for i, w in enumerate(ws)]
            wm = jnp.concatenate([f[0] for f in folded], axis=0)
            bm = jnp.concatenate([f[1] for f in folded], axis=0)
            out = convolution(x, wm, bm, **attrs)
            if act_type:
                out = activation(out, act_type=act_type)
            return out[:, lo:hi], mms[member_ix], mvs[member_ix]
        wm = jnp.concatenate(ws, axis=0)
        bm = jnp.concatenate(bs, axis=0) if has_bias else None
        conv_out = convolution(x, wm, bm, **attrs)
        full, mm_new, mv_new = _ba.fused_bn_act(
            conv_out, jnp.concatenate(gams), jnp.concatenate(bets),
            jnp.concatenate(mms), jnp.concatenate(mvs),
            act_type=act_type, is_train=is_train, **bn_attrs)
        return full[:, lo:hi], mm_new[lo:hi], mv_new[lo:hi]

    return fused


def _member_solo(x, w, b, gamma, beta, mm, mv, conv_attrs, act_type,
                 is_train, bn_attrs, do_fold):
    """One member's ORIGINAL math (conv + BN (+act)) — the trace-time
    fallback when a grouped merge turns out shape-ineligible."""
    from ..ops.nn import activation, convolution
    from . import bn_act as _ba
    if not is_train and do_fold:
        w2, b2 = _ba.fold_bn_into_conv(
            w, b, gamma, beta, mm, mv,
            eps=bn_attrs.get("eps", 0.001),
            fix_gamma=bn_attrs.get("fix_gamma", True))
        out = convolution(x, w2, b2, **conv_attrs)
        if act_type:
            out = activation(out, act_type=act_type)
        return out, mm, mv
    conv_out = convolution(x, w, b, **conv_attrs)
    return _ba.fused_bn_act(conv_out, gamma, beta, mm, mv,
                            act_type=act_type, is_train=is_train,
                            **bn_attrs)
