"""pool_act — pooling/activation fusion + a faster pooling lowering.

Three rewrites (:func:`mxnet_tpu.mxfuse.pass_pool_act`):

- **act → max-pool reorder** (:func:`make_act_then_maxpool`): every
  registered activation type is monotone non-decreasing, so it commutes
  with max-pooling BITWISE — ``f(max(a, b)) == max(f(a), f(b))`` (the
  pooled maximum is one of the window values and a non-decreasing f
  keeps the argmax; ties pick equal values either way).  Pooling first
  shrinks the tensor the activation touches by the pool stride squared.
  Restricted to the ``valid`` pooling convention: ``full`` (ceil)
  windows can in principle cover only -inf padding, where the commute
  breaks.
- **pool → act collapse** (:func:`make_pool_then_act`): the identical
  composition emitted as ONE plan entry — one dispatch instead of two
  on the eager/no-jit paths.
- **shifted-slice pooling** (:func:`pooling_opt`, applied by every
  override here and to standalone Pooling entries): XLA CPU's
  ``reduce_window`` iterates windows scalar-ily (~2 GFLOP/s measured);
  the same pooling as k² strided slices combined by ``maximum``/``add``
  vectorizes (2.2-3.2x at inception shapes).  Gated to small spatial
  extents (big maps favor ``reduce_window`` — measured), to 2-D
  non-global ``valid`` windows, and for max pooling to the INFERENCE
  path only: the slice lowering's max backward breaks ties on a
  different window element than ``reduce_window``'s select-and-scatter
  (both valid subgradients, but training parity pins would see it).
  Avg/sum stay on for training — the backward is linear, so only
  addition order differs (the documented reassociation tolerance,
  ~1e-7).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

__all__ = ["make_act_then_maxpool", "make_pool_then_act",
           "make_pool_opt", "pooling_opt", "POOL_SLICE_MAX_SPATIAL"]

#: input spatial extent (H*W) above which the slice lowering loses to
#: reduce_window (measured on the bench host: 48² wins 2.5x, 112²
#: loses) — bigger maps fall back
POOL_SLICE_MAX_SPATIAL = 3200


def _slice_pool(data, kernel, stride, pad, op, init):
    n, c, h, w = data.shape
    kh, kw = kernel
    sh, sw = stride
    ph, pw = pad
    xp = jnp.pad(data, ((0, 0), (0, 0), (ph, ph), (pw, pw)),
                 constant_values=init)
    hp, wp = h + 2 * ph, w + 2 * pw
    ho, wo = (hp - kh) // sh + 1, (wp - kw) // sw + 1
    out = None
    for di in range(kh):
        for dj in range(kw):
            v = lax.slice(xp, (0, 0, di, dj),
                          (n, c, di + (ho - 1) * sh + 1,
                           dj + (wo - 1) * sw + 1),
                          (1, 1, sh, sw))
            out = v if out is None else op(out, v)
    return out


def pooling_opt(data, pool_attrs, is_train=False):
    """The routed pooling lowering: the shifted-slice form when
    eligible (see module docstring), the registered ``Pooling`` op
    otherwise.  Decided at trace time from concrete shapes."""
    from ..ops.nn import pooling
    attrs = dict(pool_attrs)
    kernel = attrs.get("kernel") or ()
    stride = attrs.get("stride") or (1,) * len(kernel)
    pad = attrs.get("pad") or (0,) * len(kernel)
    pool_type = str(attrs.get("pool_type", "max"))
    eligible = (
        data.ndim == 4 and len(kernel) == 2
        and not attrs.get("global_pool", False)
        and str(attrs.get("pooling_convention", "valid")) == "valid"
        and pool_type in ("max", "avg", "sum")
        and int(data.shape[2]) * int(data.shape[3])
        <= POOL_SLICE_MAX_SPATIAL
        and not (pool_type == "max" and is_train))
    if not eligible:
        return pooling(data, **pool_attrs)
    kernel = tuple(int(k) for k in kernel)
    stride = tuple(int(s) for s in (stride if len(stride) == 2
                                    else (stride,) * 2))
    pad = tuple(int(p) for p in (pad if len(pad) == 2 else (pad,) * 2))
    if pool_type == "max":
        if jnp.issubdtype(data.dtype, jnp.floating):
            init = -np.inf
        else:
            init = np.iinfo(data.dtype).min
        return _slice_pool(data, kernel, stride, pad, jnp.maximum, init)
    out = _slice_pool(data, kernel, stride, pad, jnp.add, 0)
    if pool_type == "avg":
        out = out / float(kernel[0] * kernel[1])
    return out


def make_act_then_maxpool(act_type):
    """Override body for the Pooling node of an act→max-pool pair: pool
    the PRE-activation input (the act entry is a passthrough), then
    activate the pooled tensor.  Bitwise-equal to act-then-pool."""
    def fused(data, is_train=False, **pool_attrs):
        from ..ops.nn import activation
        return activation(pooling_opt(data, pool_attrs, is_train),
                          act_type=act_type)
    return fused


def make_pool_then_act(pool_attrs):
    """Override body for the Activation node of a pool→act pair: the
    pool entry is a passthrough; this entry runs the original
    pool-then-activate composition in one call."""
    def fused(data, is_train=False, **act_attrs):
        from ..ops.nn import activation
        return activation(pooling_opt(data, pool_attrs, is_train),
                          **act_attrs)
    return fused


def make_pool_opt():
    """Override body for a standalone Pooling entry: same math, the
    routed (possibly shifted-slice) lowering."""
    def fused(data, is_train=False, **pool_attrs):
        return pooling_opt(data, pool_attrs, is_train)
    return fused
