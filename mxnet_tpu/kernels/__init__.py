"""mxkern — fused Pallas/lax kernels for the graphs XLA leaves on the table.

The bench trajectory (BENCH_r04) shows the conv/matmul models near the
machine's ceiling while BatchNorm/concat-heavy (inception-bn) and
gate-heavy (LSTM) graphs trail badly: those graphs spend their time in
memory-bound elementwise chains that benefit from being ONE kernel pass
instead of a dispatch-granularity composition.  Following the
FlashAttention discipline (Dao et al., 2022 — materialize nothing you can
recompute in-tile), every kernel here ships at two tiers:

- **Pallas tier** (TPU): a ``pl.pallas_call`` kernel with a registered
  ``jax.custom_vjp`` backward, per the :mod:`~mxnet_tpu.rtc` contract
  (Pallas has no reverse-mode transpose; an unprotected kernel in a
  differentiated step is a trace-time error — mxlint's
  ``graph-pallas-no-vjp`` rule polices this).
- **fused-lax reference** (CPU tier, and the numeric oracle): the same
  math as the unfused op composition, in one traced function, written so
  the per-element operation sequence is IDENTICAL to the unfused graph —
  bit-comparable where float reassociation permits (asserted in
  tests/test_kernels.py), and faster than the op-by-op composition
  because it compiles to one program instead of a dispatch chain.

Routing is per-kernel via ``MXTPU_FUSED_KERNELS`` (registered in
``base.py``): ``1`` (default) enables everything, ``0`` restores the
exact pre-fusion graphs, a comma list enables individual kernels.  The
env is consulted at trace/bind time (symbol build, executor bind, jit
trace), so toggling it affects the NEXT graph built, never a compiled
program.  ``bench.py roofline`` times each kernel fused-vs-unfused and
against a bytes/FLOPs roofline estimate so every kernel proves its win
in the artifact (docs/how_to/kernels.md).

Kernel catalog (``KNOWN_KERNELS``):

- ``bn_act``   — fused BatchNorm+activation (training one-pass), wired
  into the executor's BN aux-update path (:mod:`.bn_act`).
- ``bn_fold``  — fold BN scale/shift into conv weights for inference
  (:func:`.bn_act.fold_bn_into_conv`; executor eval trace).
- ``lstm_cell`` — one-kernel LSTM gate math consumed by the fused RNN
  op's ``lax.scan`` and by ``rnn_cell.LSTMCell`` (:mod:`.lstm_cell`).
- ``flash_attention`` — tiled online-softmax attention that
  ``parallel/ring_attention.py`` composes with (:mod:`.flash_attention`).
- ``augment``   — in-graph image augmentation (resize/crop/mirror/
  normalize as traced ops, per-image RNG folded from the data
  service's ``chunk_seed``) so the input pipeline ships raw-decoded
  uint8 and augments on-device (:mod:`.augment`; consumed by
  ``ImageRecordIter(device_augment=...)``).
- ``concat_fuse`` — mxfuse plan pass: sibling conv→BN(→act) tower
  heads sharing one input merge into ONE conv over concatenated
  filters (inception's 1x1 branches; :mod:`.concat_fuse`).
- ``pool_act``  — mxfuse plan pass: act→max-pool reorders to
  pool-first (bitwise; the activation touches stride²-fewer elements)
  and pool→act pairs collapse to one entry (:mod:`.pool_act`).
- ``eltwise_chain`` — mxfuse plan pass: private elementwise runs
  collapse into one fused region (:mod:`.eltwise_chain`).
- ``infer_trace`` — inference-trace pass set: dead-node elimination +
  bind-time constant folding over the executor's EVAL interpretation
  (``mxnet_tpu.mxfuse.live_entries``/``fold_constants``) — composes
  with the ``bn_fold`` serving default; values are bit-identical, the
  win is trace/bind time per serving bucket.

The plan-level passes live in :mod:`mxnet_tpu.mxfuse` (the
match-and-rewrite framework over the executor's node plan); this
registry routes them exactly like the kernel bodies.
"""
from __future__ import annotations

import logging

from ..base import ENV_FUSED_KERNELS, get_env, register_env

__all__ = ["KNOWN_KERNELS", "fused_enabled", "enabled_kernels",
           "use_pallas", "ENV_FLASH_BLOCK", "bn_act", "lstm_cell",
           "flash_attention", "roofline", "augment", "concat_fuse",
           "pool_act", "eltwise_chain"]

_LOG = logging.getLogger(__name__)

#: every kernel name the router understands (docs/how_to/kernels.md);
#: the last four are mxfuse plan-optimizer passes, routed identically
KNOWN_KERNELS = ("bn_act", "bn_fold", "lstm_cell", "flash_attention",
                 "augment", "concat_fuse", "pool_act", "eltwise_chain",
                 "infer_trace")

# registered EAGERLY at package import (a lazy registration inside the
# flash module failed the three-way registry==docs==reads sync for the
# data-service knobs — same lesson here)
ENV_FLASH_BLOCK = register_env(
    "MXTPU_FLASH_BLOCK", default=128,
    doc="Tile size (query and key block length) for the flash-attention "
        "kernel; sequences at or below one block use plain attention")

_ON = frozenset(("1", "on", "true", "yes", "all"))
_OFF = frozenset(("", "0", "off", "false", "no", "none"))

_warned_unknown = set()


def enabled_kernels():
    """The set of fused kernels the env currently enables.  Read per
    call — callers consult it at trace/bind time, so the cost is paid
    once per graph build, not per step."""
    raw = str(get_env(ENV_FUSED_KERNELS, "1")).strip().lower()
    if raw in _ON:
        return frozenset(KNOWN_KERNELS)
    if raw in _OFF:
        return frozenset()
    names = set()
    for part in raw.replace(";", ",").split(","):
        part = part.strip()
        if not part:
            continue
        if part in KNOWN_KERNELS:
            names.add(part)
        elif part not in _warned_unknown:
            _warned_unknown.add(part)
            _LOG.warning(
                "MXTPU_FUSED_KERNELS names unknown kernel %r "
                "(known: %s) — ignored", part, ", ".join(KNOWN_KERNELS))
    return frozenset(names)


def fused_enabled(name):
    """Whether the named fused kernel should be used for graphs built
    NOW (``MXTPU_FUSED_KERNELS``; see module docstring for the catalog)."""
    return name in enabled_kernels()


def use_pallas():
    """Tier selection: compiled Pallas kernels on TPU backends, the
    fused-lax reference elsewhere.  Tests force the Pallas tier with
    ``interpret=True`` explicitly (the rtc.py story: same kernel code
    runs interpreted on the virtual CPU mesh)."""
    from ..rtc import on_tpu
    return on_tpu()


from . import roofline            # noqa: E402  (stdlib-light, analytic)
from . import bn_act              # noqa: E402
from . import lstm_cell           # noqa: E402
from . import flash_attention     # noqa: E402
from . import augment             # noqa: E402
from . import concat_fuse         # noqa: E402
from . import pool_act            # noqa: E402
from . import eltwise_chain       # noqa: E402
