"""Fused LSTM cell — all gate math in one kernel.

The unfused cell (ops/nn.py ``_rnn_cell_step``, rnn_cell.py ``LSTMCell``)
splits the (B, 4H) gate pre-activations into four tensors and chains
sigmoid/tanh/mul/add ops — at dispatch granularity that is ~10 memory
passes over (B, H) for ~10 flops/element, squarely memory-bound.  The
fused cell does the whole block in one pass:

    i, f, g, o = gates            # static slices, gate order [i, f, c, o]
    c = sigmoid(f) * c_prev + sigmoid(i) * tanh(g)
    h = sigmoid(o) * tanh(c)

Two tiers (package docstring):

- :func:`lstm_cell_lax` — the fused-lax reference.  The per-element
  operation sequence is IDENTICAL to the unfused composition, so forward
  values are bit-equal and autodiff gradients match the unfused graph's
  (tests/test_kernels.py pins both).  Differentiable by jax as-is.
- :func:`lstm_cell_pallas` — a ``pl.pallas_call`` kernel pair behind
  ``jax.custom_vjp`` (Pallas has no reverse-mode transpose — rtc.py
  contract).  The backward kernel RECOMPUTES the gate activations
  in-tile from the saved pre-activations instead of materializing them
  (the FlashAttention discipline), so residuals are just (gates, c_prev).

:func:`lstm_cell` routes by backend; the symbolic graph consumes the
``_FusedLSTMCell`` op (``rnn_cell.LSTMCell`` emits it when
``MXTPU_FUSED_KERNELS`` enables ``lstm_cell``), and the fused RNN op's
``lax.scan`` (ops/nn.py ``rnn``) calls :func:`lstm_cell` directly.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["lstm_cell", "lstm_cell_lax", "lstm_cell_pallas"]


def lstm_cell_lax(gates, c_prev):
    """Fused-lax reference: one traced function, unfused op order.

    ``gates``: (B, 4H) pre-activations in gate order [i, f, c, o]
    (i2h + h2h + biases already summed); ``c_prev``: (B, H).
    Returns ``(h, c)``.
    """
    h = c_prev.shape[-1]
    i = jax.nn.sigmoid(gates[..., 0 * h:1 * h])
    f = jax.nn.sigmoid(gates[..., 1 * h:2 * h])
    g = jnp.tanh(gates[..., 2 * h:3 * h])
    o = jax.nn.sigmoid(gates[..., 3 * h:4 * h])
    c = f * c_prev + i * g
    new_h = o * jnp.tanh(c)
    return new_h, c


def _fwd_kernel(g_ref, c_ref, h_out, c_out):
    """Pallas forward body: whole-block gate math in VMEM."""
    h = c_ref.shape[-1]
    gates = g_ref[...]
    i = jax.nn.sigmoid(gates[..., 0 * h:1 * h])
    f = jax.nn.sigmoid(gates[..., 1 * h:2 * h])
    g = jnp.tanh(gates[..., 2 * h:3 * h])
    o = jax.nn.sigmoid(gates[..., 3 * h:4 * h])
    c = f * c_ref[...] + i * g
    c_out[...] = c
    h_out[...] = o * jnp.tanh(c)


def _bwd_kernel(g_ref, c_ref, dh_ref, dc_ref, dg_out, dcp_out):
    """Pallas backward body: recompute activations in-tile, emit
    (dgates, dc_prev) from (dh, dc_next)."""
    h = c_ref.shape[-1]
    gates = g_ref[...]
    i = jax.nn.sigmoid(gates[..., 0 * h:1 * h])
    f = jax.nn.sigmoid(gates[..., 1 * h:2 * h])
    g = jnp.tanh(gates[..., 2 * h:3 * h])
    o = jax.nn.sigmoid(gates[..., 3 * h:4 * h])
    c = f * c_ref[...] + i * g
    tanh_c = jnp.tanh(c)
    dh = dh_ref[...]
    # dc accumulates the explicit cotangent and the h = o * tanh(c) path
    dc = dc_ref[...] + dh * o * (1.0 - tanh_c * tanh_c)
    do = dh * tanh_c * o * (1.0 - o)
    di = dc * g * i * (1.0 - i)
    df = dc * c_ref[...] * f * (1.0 - f)
    dg = dc * i * (1.0 - g * g)
    dg_out[...] = jnp.concatenate([di, df, dg, do], axis=-1)
    dcp_out[...] = dc * f


def _pallas_call(kernel, out_shapes, interpret):
    from jax.experimental import pallas as pl

    def call(*arrays):
        kw = {}
        if not interpret:
            # compiled tier: pin operands to VMEM (the default memory
            # space can land blocks in slow HBM — pallas_guide.md
            # pitfall 1); the interpreter ignores memory spaces, so
            # specs are omitted there
            from jax.experimental.pallas import tpu as pltpu
            spec = pl.BlockSpec(memory_space=pltpu.VMEM)
            kw = {"in_specs": [spec] * len(arrays),
                  "out_specs": (spec, spec)}
        return pl.pallas_call(
            kernel,
            out_shape=out_shapes(*arrays),
            interpret=interpret,
            **kw,
        )(*arrays)
    return call


def _make_pallas(interpret):
    fwd_call = _pallas_call(
        _fwd_kernel,
        lambda g, c: (jax.ShapeDtypeStruct(c.shape, c.dtype),) * 2,
        interpret)
    bwd_call = _pallas_call(
        _bwd_kernel,
        lambda g, c, dh, dc: (jax.ShapeDtypeStruct(g.shape, g.dtype),
                              jax.ShapeDtypeStruct(c.shape, c.dtype)),
        interpret)

    @jax.custom_vjp
    def cell(gates, c_prev):
        return fwd_call(gates, c_prev)

    def cell_fwd(gates, c_prev):
        # residuals are the INPUTS only; the backward kernel recomputes
        # every activation in-tile (nothing materialized between passes)
        return fwd_call(gates, c_prev), (gates, c_prev)

    def cell_bwd(res, cot):
        gates, c_prev = res
        dh, dc = cot
        return bwd_call(gates, c_prev, dh, dc)

    cell.defvjp(cell_fwd, cell_bwd)
    return cell


_pallas_compiled = None
_pallas_interpret = None


def lstm_cell_pallas(gates, c_prev, interpret=None):
    """Pallas-tier fused cell (custom_vjp registered).  ``interpret``
    defaults to auto (compiled on TPU, interpreter elsewhere — the
    rtc.py convention, so tests exercise the same kernel code on CPU)."""
    global _pallas_compiled, _pallas_interpret
    if interpret is None:
        from ..rtc import on_tpu
        interpret = not on_tpu()
    if interpret:
        if _pallas_interpret is None:
            _pallas_interpret = _make_pallas(True)
        return _pallas_interpret(gates, c_prev)
    if _pallas_compiled is None:
        _pallas_compiled = _make_pallas(False)
    return _pallas_compiled(gates, c_prev)


def lstm_cell(gates, c_prev):
    """Backend-routed fused LSTM cell: compiled Pallas on TPU, the
    fused-lax reference elsewhere (interpret-mode Pallas is for parity
    tests, not production CPU dispatch).  The compiled tier engages only
    for (sublane, lane)-aligned shapes — H a lane multiple, rows a
    sublane multiple — so tile-unaligned cells (H=200 etc.) take the
    fused-lax path instead of paying Mosaic relayouts."""
    from . import use_pallas
    H = c_prev.shape[-1]
    rows = int(np.prod(c_prev.shape[:-1]))
    if use_pallas() and H % 128 == 0 and rows % 8 == 0:
        return lstm_cell_pallas(gates, c_prev, interpret=False)
    return lstm_cell_lax(gates, c_prev)


# ---------------------------------------------------------------------------
# symbolic surface: the op rnn_cell.LSTMCell emits when fusion is enabled
# ---------------------------------------------------------------------------

def _flc_infer(attrs, in_shapes):
    g = in_shapes[0]
    if g is None:
        if len(in_shapes) > 1 and in_shapes[1] is not None:
            c = tuple(in_shapes[1])
            return [(c[0], 4 * c[1]), c], [c, c], []
        return in_shapes, [None, None], []
    c = (g[0], g[1] // 4)
    return [tuple(g), c], [c, c], []


def _register_op():
    from ..ops.registry import OP_REGISTRY, register

    if "_FusedLSTMCell" in OP_REGISTRY:  # idempotent under re-import
        return

    @register("_FusedLSTMCell", input_names=("gates", "prev_c"),
              num_outputs=2, output_names=("h", "c"),
              infer_shape=_flc_infer, hidden=True)
    def _fused_lstm_cell(gates, prev_c):
        """Fused LSTM gate block (mxnet_tpu/kernels/lstm_cell.py):
        (B, 4H) pre-activations + previous cell -> (next_h, next_c)."""
        return lstm_cell(gates, prev_c)

    # late registration: the autogen nd/sym modules were populated at
    # package import — self-inject like rtc.register_kernel does
    from ..rtc import _inject
    _inject("_FusedLSTMCell")


_register_op()
