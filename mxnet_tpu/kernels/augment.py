"""In-graph (on-device) image augmentation — the device side of the
cross-host data plane (docs/how_to/performance.md, "Scaling the input
pipeline").

The host pipelines (in-process pipe, local data service, network tier)
augment on CPU: random crop + mirror + normalize per image, seeded per
global batch.  This module moves that work INTO the compiled graph as
traced ops (``jax.image`` resize + ``lax.dynamic_slice`` crop + flip
behind the ``MXTPU_FUSED_KERNELS`` seam, kernel name ``augment``), so
the hot path can ship RAW-DECODED uint8 canvases — 4x fewer H2D bytes
than f32, zero host augmentation cycles — and the TPU does the rest.

Determinism is inherited, not re-invented: the per-image RNG folds from
the SAME ``common.chunk_seed(seed, global batch, epoch)`` the host
decoders mix, so device-augmented output is a pure function of
(seed, epoch, batch index) — bit-reproducible across worker counts,
server counts and respawns BY CONSTRUCTION (the PR-7 contract, one
level up).  It is NOT numerically identical to the host-augmented
path (different crop geometry: the host crops the variable-size
resized image, the device crops a fixed-margin canvas) — that is why
the seam exists: ``MXTPU_FUSED_KERNELS=0`` (or any list without
``augment``) restores the EXACT host-augmented graphs.

Geometry: the host decodes every image to a fixed CANVAS of
``(H + margin, W + margin)`` (center crop/resize, no host
augmentation); the device then takes a random ``(H, W)`` window
(offsets uniform in ``[0, margin]``; center when ``rand_crop`` is
off), mirrors with probability 1/2 when ``rand_mirror`` is on,
normalizes with mean/std, zeroes pad rows, and casts to the requested
dtype.  A canvas arriving at a different spatial size is first
``jax.image.resize``d — the traced analog of the host's resize knob.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError

__all__ = ["DeviceAugment"]


class DeviceAugment(object):
    """A compiled per-batch augmentation op: ``aug(images, cseed,
    nvalid)`` -> augmented batch.

    ``data_shape`` is the canonical ``(3, H, W)`` OUTPUT shape;
    ``margin`` the extra pixels per spatial dim the input canvas
    carries for the random crop to roam in.  ``mean``/``std`` accept
    the host augmenters' forms (None, scalar, 3-vector, or ``True``
    for the shared ImageNet constants).  Instances are callables whose
    jitted program is cached; ``cseed`` and ``nvalid`` ride as traced
    scalars so every batch hits ONE compiled executable.
    """

    def __init__(self, data_shape, margin=16, rand_crop=False,
                 rand_mirror=False, mean=None, std=None, layout="NCHW",
                 dtype="float32"):
        shape = tuple(int(d) for d in data_shape)
        if len(shape) != 3 or shape[0] != 3:
            raise MXNetError(
                "device augment needs data_shape (3, H, W), got %s"
                % (shape,))
        if layout not in ("NCHW", "NHWC"):
            raise MXNetError("layout must be NCHW or NHWC")
        if int(margin) < 0:
            raise MXNetError("margin must be >= 0")
        self.out_shape = shape                 # canonical (c, h, w)
        self.margin = int(margin)
        self.rand_crop = bool(rand_crop)
        self.rand_mirror = bool(rand_mirror)
        self.layout = layout
        self.dtype = str(dtype)
        if self.dtype not in ("float32", "uint8", "bfloat16"):
            raise MXNetError(
                "device augment dtype must be float32/uint8/bfloat16, "
                "got %r" % (dtype,))
        self.mean = self._c3(mean, "mean")
        self.std = self._c3(std, "std")
        if self.dtype == "uint8" and (self.mean is not None
                                      or self.std is not None):
            raise MXNetError(
                "uint8 device augmentation cannot normalize (mean/std "
                "produce fractional values); normalize on-device after "
                "the cast, or request a float dtype")
        c, h, w = shape
        self.canvas_shape = (c, h + self.margin, w + self.margin)
        self._fn = None

    @staticmethod
    def _c3(v, what):
        if v is None or v is False:
            return None
        from ..data_service import common as dsc
        if v is True:
            v = dsc.IMAGENET_MEAN if what == "mean" else dsc.IMAGENET_STD
        a = np.asarray(v, np.float32).reshape(-1)
        if a.size == 1:
            a = np.repeat(a, 3)
        if a.size != 3:
            raise MXNetError("%s must be a scalar or 3 values" % what)
        return a

    # -- layout helpers ------------------------------------------------------
    def _axes(self):
        """(h_axis, w_axis) of ONE image (no batch dim)."""
        return (1, 2) if self.layout == "NCHW" else (0, 1)

    def per_layout(self, canonical):
        c, h, w = canonical
        return (c, h, w) if self.layout == "NCHW" else (h, w, c)

    # -- the traced op -------------------------------------------------------
    def _build(self):
        import jax
        import jax.numpy as jnp

        c, oh, ow = self.out_shape
        m = self.margin
        h_ax, w_ax = self._axes()
        img_shape = list(self.per_layout(self.canvas_shape))
        out_sizes = list(img_shape)
        out_sizes[h_ax], out_sizes[w_ax] = oh, ow
        if self.mean is not None:
            mean = jnp.asarray(self.mean)
            mean = mean.reshape((3, 1, 1) if self.layout == "NCHW"
                                else (3,))
        else:
            mean = None
        if self.std is not None:
            std = jnp.asarray(self.std)
            std = std.reshape((3, 1, 1) if self.layout == "NCHW"
                              else (3,))
        else:
            std = None

        def one(img, key):
            if self.rand_crop and m > 0:
                oy = jax.random.randint(jax.random.fold_in(key, 1), (),
                                        0, m + 1)
                ox = jax.random.randint(jax.random.fold_in(key, 2), (),
                                        0, m + 1)
            else:
                oy = ox = jnp.int32(m // 2)
            starts = [jnp.int32(0)] * 3
            starts[h_ax], starts[w_ax] = oy, ox
            img = jax.lax.dynamic_slice(img, starts, out_sizes)
            if self.rand_mirror:
                bit = jax.random.randint(jax.random.fold_in(key, 3), (),
                                         0, 2)
                img = jnp.where(bit > 0, jnp.flip(img, axis=w_ax), img)
            return img

        def apply(imgs, cseed, nvalid):
            bs = imgs.shape[0]
            f = imgs.astype(jnp.float32)
            if tuple(f.shape[1:]) != tuple(img_shape):
                # traced resize to the canvas — the jax.image analog of
                # the host resize knob (only engages when the producer
                # ships a different decode size)
                f = jax.image.resize(f, (bs,) + tuple(img_shape),
                                     method="linear")
            key = jax.random.PRNGKey(cseed)
            keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(
                jnp.arange(bs))
            out = jax.vmap(one)(f, keys)
            if mean is not None:
                out = out - mean
            if std is not None:
                out = out / std
            # pad rows are exact zeros, matching the host decoders'
            # padded-final-batch contract
            rows = jnp.arange(bs).reshape((bs,) + (1,) * (out.ndim - 1))
            out = jnp.where(rows < nvalid, out, 0.0)
            if self.dtype == "uint8":
                out = jnp.clip(out, 0, 255)
            out_dt = {"float32": jnp.float32, "uint8": jnp.uint8,
                      "bfloat16": jnp.bfloat16}[self.dtype]
            return out.astype(out_dt)

        return jax.jit(apply)

    def __call__(self, imgs, cseed, nvalid):
        if self._fn is None:
            self._fn = self._build()
        import jax.numpy as jnp
        return self._fn(imgs, jnp.uint32(int(cseed) & 0xffffffff),
                        jnp.int32(int(nvalid)))
