"""Analytic roofline accounting for the fused kernels.

A kernel's best-case time on a device is bounded below by
``max(flops / peak_flops, bytes_moved / mem_bandwidth)`` — the classic
roofline.  ``bench.py roofline`` times each kernel fused-vs-unfused and
reports the measured time against this bound, so the artifact shows not
just "fused beat unfused" but HOW CLOSE to the machine each kernel runs
and which side (compute or memory) binds it.

``workload(name, **shape)`` returns the analytic ``(flops, bytes)`` for
one kernel invocation at the given shapes, counting ideal traffic: every
input read once, every output written once — exactly what a perfectly
fused single pass moves.  The unfused composition's traffic is also
reported (``unfused_bytes``): each intermediate materialized to memory
and read back, which is the whole reason the fused kernels exist.

Pure python/analytic on purpose — importable with no accelerator
runtime (the CLI and docs examples use it standalone).
"""
from __future__ import annotations

__all__ = ["workload", "roofline_seconds", "bound_side"]


def _bn_act(n, c, hw, itemsize):
    """Fused BatchNorm(+activation) training pass over NCHW data.

    flops: ~2 passes over the data for the batch stats (sum, sumsq) and
    ~4 ops/element for normalize+scale+shift+activate.
    fused bytes: read x once for stats, read x once for normalize, write
    y once, plus the tiny per-channel vectors.
    unfused bytes: the composition additionally materializes the
    normalized output and re-reads it for the activation (+2 passes).
    """
    elems = n * c * hw
    flops = 6 * elems
    chan = 6 * c * itemsize                 # gamma/beta/stats vectors
    fused = (2 * elems + elems) * itemsize + chan
    unfused = fused + 2 * elems * itemsize
    return flops, fused, unfused


def _lstm_cell(b, h, itemsize):
    """Fused LSTM cell elementwise block: gates (B, 4H) + c_prev (B, H)
    -> h, c.  ~10 transcendental-ish ops per hidden element.

    fused: read gates + c_prev, write h + c.
    unfused: the split/sigmoid/tanh/mul/add chain materializes ~7
    intermediate (B, H) tensors (4 activated gates, candidate product,
    forget product, tanh(c)) and re-reads each.
    """
    elems = b * h
    flops = 10 * elems
    fused = (4 * elems + elems + 2 * elems) * itemsize
    unfused = fused + 2 * 7 * elems * itemsize
    return flops, fused, unfused


def _flash_attention(b, t, heads, d, itemsize):
    """Attention over (B, T, H, D): 2 matmuls of 2*B*H*T*T*D flops plus
    softmax (~5 flops/score).

    fused (flash): q/k/v read once, output written once — the T x T
    score matrix never exists.
    unfused: scores and probabilities each materialized AND re-read
    (4 passes over B*H*T*T).
    """
    scores = b * heads * t * t
    flops = 2 * 2 * scores * d + 5 * scores
    qkv = 3 * b * t * heads * d * itemsize
    out = b * t * heads * d * itemsize
    fused = qkv + out
    unfused = fused + 4 * scores * itemsize
    return flops, fused, unfused


def _eltwise_chain(n, c, hw, depth, itemsize):
    """A ``depth``-op private elementwise run over an (N, C, HW)
    tensor, ~1 flop per element per op.

    fused: one read + one write for the whole chain.
    unfused: EVERY stage materializes its output and the next reads it
    back — ``depth`` read+write round trips, the entire reason the
    chain pass exists.
    """
    elems = n * c * hw
    flops = depth * elems
    fused = 2 * elems * itemsize
    unfused = 2 * depth * elems * itemsize
    return flops, fused, unfused


def _concat_fuse(n, c, hw, widths, itemsize):
    """``len(widths)`` sibling 1x1 convs over one (N, C, HW) input
    merged into a single GEMM of ``sum(widths)`` output channels.

    flops are identical (per-output-channel math is unchanged); the
    fused form reads the input ONCE instead of once per sibling — plus
    the (dominant, unmodeled) GEMM-efficiency win of one wide matmul
    over several narrow ones, which is why the measured speedup beats
    this bytes-only bound.
    """
    total = sum(widths)
    elems_in = n * c * hw
    flops = 2 * c * total * n * hw
    w_bytes = c * total * itemsize
    out_bytes = n * total * hw * itemsize
    fused = elems_in * itemsize + w_bytes + out_bytes
    unfused = len(widths) * elems_in * itemsize + w_bytes + out_bytes
    return flops, fused, unfused


def _pool_act(n, c, hw, stride, itemsize):
    """act→max-pool reordered to pool-first over (N, C, HW), pool
    stride ``stride`` per spatial dim (output HW/stride² elements).

    flops: ~window compares per output + 1 act op per element touched.
    fused (pool first): read x, write pooled, activate in-register —
    the activation touches stride²-fewer elements.
    unfused (act first): activate AND materialize the full tensor,
    read it back for pooling, write pooled.
    """
    elems = n * c * hw
    pooled = elems // (stride * stride)
    flops = 9 * pooled + pooled          # compares + act on pooled
    fused = (elems + pooled) * itemsize
    unfused = (elems + elems + elems + pooled) * itemsize
    return flops, fused, unfused


_WORKLOADS = {
    "bn_act": _bn_act,
    "lstm_cell": _lstm_cell,
    "flash_attention": _flash_attention,
    "eltwise_chain": _eltwise_chain,
    "concat_fuse": _concat_fuse,
    "pool_act": _pool_act,
}


def workload(name, itemsize=4, **shape):
    """Analytic cost of one fused-kernel invocation.

    Returns ``{"flops", "fused_bytes", "unfused_bytes"}``.  Shapes:
    ``bn_act(n, c, hw)``, ``lstm_cell(b, h)``,
    ``flash_attention(b, t, heads, d)``,
    ``eltwise_chain(n, c, hw, depth)``,
    ``concat_fuse(n, c, hw, widths)``, ``pool_act(n, c, hw, stride)``.
    """
    if name not in _WORKLOADS:
        raise KeyError("unknown kernel workload %r (have: %s)"
                       % (name, sorted(_WORKLOADS)))
    flops, fused, unfused = _WORKLOADS[name](itemsize=itemsize, **shape)
    return {"flops": int(flops), "fused_bytes": int(fused),
            "unfused_bytes": int(unfused)}


def roofline_seconds(flops, nbytes, peak_flops, mem_bw):
    """Lower-bound seconds for a kernel moving ``nbytes`` and computing
    ``flops`` on a machine with the given peaks (flops/s, bytes/s)."""
    t_c = flops / peak_flops if peak_flops else 0.0
    t_m = nbytes / mem_bw if mem_bw else 0.0
    return max(t_c, t_m)


def bound_side(flops, nbytes, peak_flops, mem_bw):
    """Which roofline side binds: 'compute' or 'memory'."""
    t_c = flops / peak_flops if peak_flops else 0.0
    t_m = nbytes / mem_bw if mem_bw else 0.0
    return "compute" if t_c >= t_m else "memory"
