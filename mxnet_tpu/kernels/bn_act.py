"""Fused BatchNorm + activation, and BN-into-conv folding.

inception-bn spends its non-matmul time in dozens of BatchNorm ->
Activation pairs: at dispatch granularity that is five memory passes per
pair (normalize read+write, activate read+write, plus the stats pass).
Two fusions close the gap:

- **Training** (:func:`fused_bn_act`): normalize + scale/shift +
  activate in ONE pass over the data.  The batch statistics stay lax
  reductions (XLA's reduction codegen is already roofline-bound); the
  elementwise pass — the memory-bound part fusion actually buys — is the
  kernel.  The fused-lax reference literally composes the registered
  ``BatchNorm``/``Activation`` lowerings in one traced function, so it
  is bit-identical to the unfused graph; the Pallas tier runs the
  normalize+activate block as a ``pl.pallas_call`` pair behind
  ``jax.custom_vjp`` (backward recomputes the activation in-tile and
  emits per-block partial sums for the scale/shift gradients).
- **Inference** (:func:`fold_bn_into_conv`): with frozen moving stats,
  ``BN(conv(x, W) + b)`` is exactly ``conv(x, W * s) + (b - mean) * s +
  beta`` with ``s = gamma * rsqrt(var + eps)`` — the BN op vanishes from
  the graph for the price of one O(weights) rescale.  The executor's
  eval trace applies this when ``MXTPU_FUSED_KERNELS`` enables
  ``bn_fold`` (executor.py ``_fuse_bn_plan``); folding reassociates
  float math, so parity with the unfused graph is tolerance-checked,
  not bitwise (tests/test_kernels.py pins the tolerance).

The executor's BatchNorm aux-update path is preserved untouched: both
tiers return ``(out, new_moving_mean, new_moving_var)`` exactly like the
registered ``BatchNorm`` op, and the executor writes the trailing
outputs back to aux storage as before.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["fused_bn_act", "fused_bn_act_lax", "fused_bn_act_pallas",
           "fold_bn_into_conv"]


def fused_bn_act_lax(data, gamma, beta, moving_mean, moving_var,
                     act_type=None, eps=0.001, momentum=0.9,
                     fix_gamma=True, use_global_stats=False,
                     is_train=False):
    """Fused-lax reference: the registered BatchNorm lowering plus the
    registered Activation lowering in one traced function — the same
    per-element op sequence as the unfused graph (bit-identical), fused
    by XLA because it is one program."""
    from ..ops import nn as _nn
    out, new_mm, new_mv = _nn.batch_norm(
        data, gamma, beta, moving_mean, moving_var, eps=eps,
        momentum=momentum, fix_gamma=fix_gamma,
        use_global_stats=use_global_stats, output_mean_var=False,
        is_train=is_train)
    if act_type:
        out = _nn.activation(out, act_type=act_type)
    return out, new_mm, new_mv


# ---------------------------------------------------------------------------
# Pallas tier: the normalize+activate elementwise block as a kernel pair
# ---------------------------------------------------------------------------

#: activations the Pallas block supports (act' expressible from y alone);
#: anything else routes to the lax tier
_PALLAS_ACTS = ("relu", "sigmoid", "tanh")


def _apply_act(y, act_type):
    if act_type == "relu":
        return jax.nn.relu(y)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(y)
    if act_type == "tanh":
        return jnp.tanh(y)
    return y


def _act_grad_from_y(y, act_type):
    """act'(pre) expressed from the POST-activation value y."""
    if act_type == "relu":
        return (y > 0).astype(y.dtype)
    if act_type == "sigmoid":
        return y * (1.0 - y)
    if act_type == "tanh":
        return 1.0 - y * y
    return jnp.ones_like(y)


def _make_norm_act(act_type, interpret):
    """custom_vjp'd ``y = act(x * scale + shift)`` over (N, C, M) blocks
    with per-channel scale/shift shaped (1, C, 1); grid over N."""
    from jax.experimental import pallas as pl

    def specs(x):
        """(row, chan, part) BlockSpecs for the compiled tier: grid over
        N, one (1, C, M) data row per program, channel vectors shared."""
        from jax.experimental.pallas import tpu as pltpu
        _, C, M = x.shape
        row = pl.BlockSpec((1, C, M), lambda n: (n, 0, 0),
                           memory_space=pltpu.VMEM)
        chan = pl.BlockSpec((1, C, 1), lambda n: (0, 0, 0),
                            memory_space=pltpu.VMEM)
        part = pl.BlockSpec((1, C, 1), lambda n: (n, 0, 0),
                            memory_space=pltpu.VMEM)
        return row, chan, part

    def fwd_kernel(x_ref, s_ref, b_ref, y_ref):
        y_ref[...] = _apply_act(x_ref[...] * s_ref[...] + b_ref[...],
                                act_type)

    def bwd_kernel(x_ref, s_ref, b_ref, dy_ref, dx_ref, ds_ref, db_ref):
        # recompute y in-tile (nothing saved between passes), then the
        # pre-activation cotangent and this block's partial reductions
        y = _apply_act(x_ref[...] * s_ref[...] + b_ref[...], act_type)
        dpre = dy_ref[...] * _act_grad_from_y(y, act_type)
        dx_ref[...] = dpre * s_ref[...]
        ds_ref[...] = jnp.sum(dpre * x_ref[...], axis=-1, keepdims=True)
        db_ref[...] = jnp.sum(dpre, axis=-1, keepdims=True)

    def fwd_call(x, s, b):
        kw = {}
        if not interpret:
            row, chan, _ = specs(x)
            kw = {"grid": (x.shape[0],), "in_specs": [row, chan, chan],
                  "out_specs": row}
        return pl.pallas_call(
            fwd_kernel, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            interpret=interpret, **kw)(x, s, b)

    def bwd_call(x, s, b, dy):
        kw = {}
        N, C, _ = x.shape
        if not interpret:
            row, chan, part = specs(x)
            kw = {"grid": (N,),
                  "in_specs": [row, chan, chan, row],
                  "out_specs": (row, part, part)}
        dx, ds_p, db_p = pl.pallas_call(
            bwd_kernel,
            out_shape=(jax.ShapeDtypeStruct(x.shape, x.dtype),
                       jax.ShapeDtypeStruct((N, C, 1), x.dtype),
                       jax.ShapeDtypeStruct((N, C, 1), x.dtype)),
            interpret=interpret, **kw)(x, s, b, dy)
        # fold the per-block partials across the grid dimension in lax
        ds = jnp.sum(ds_p, axis=0, keepdims=True)
        db = jnp.sum(db_p, axis=0, keepdims=True)
        return dx, ds, db

    @jax.custom_vjp
    def norm_act(x, scale, shift):
        return fwd_call(x, scale, shift)

    def na_fwd(x, scale, shift):
        return fwd_call(x, scale, shift), (x, scale, shift)

    def na_bwd(res, dy):
        return bwd_call(*res, dy)

    norm_act.defvjp(na_fwd, na_bwd)
    return norm_act


_norm_act_cache = {}


def _norm_act(x3, scale3, shift3, act_type, interpret):
    key = (act_type or "", bool(interpret))
    fn = _norm_act_cache.get(key)
    if fn is None:
        fn = _norm_act_cache[key] = _make_norm_act(act_type, interpret)
    return fn(x3, scale3, shift3)


def fused_bn_act_pallas(data, gamma, beta, moving_mean, moving_var,
                        act_type=None, eps=0.001, momentum=0.9,
                        fix_gamma=True, use_global_stats=False,
                        is_train=False, interpret=None):
    """Pallas-tier fused BN(+act): lax batch statistics + one
    normalize+activate kernel pass (custom_vjp registered).  Semantics
    and return shape match the registered BatchNorm op exactly."""
    if interpret is None:
        from ..rtc import on_tpu
        interpret = not on_tpu()
    if act_type and act_type not in _PALLAS_ACTS:
        return fused_bn_act_lax(
            data, gamma, beta, moving_mean, moving_var, act_type=act_type,
            eps=eps, momentum=momentum, fix_gamma=fix_gamma,
            use_global_stats=use_global_stats, is_train=is_train)
    axes = (0,) + tuple(range(2, data.ndim))
    if fix_gamma:
        gamma = jnp.ones_like(gamma)
    if is_train and not use_global_stats:
        mean = jnp.mean(data, axis=axes)
        var = jnp.var(data, axis=axes)
        new_mm = moving_mean * momentum + mean * (1 - momentum)
        new_mv = moving_var * momentum + var * (1 - momentum)
    else:
        mean, var = moving_mean, moving_var
        new_mm, new_mv = moving_mean, moving_var
    inv = lax.rsqrt(var + eps)
    scale = (inv * gamma).astype(data.dtype)
    shift = (beta - mean * inv * gamma).astype(data.dtype)
    n, c = data.shape[0], data.shape[1]
    x3 = data.reshape(n, c, -1)
    out = _norm_act(x3, scale.reshape(1, c, 1), shift.reshape(1, c, 1),
                    act_type, interpret)
    return out.reshape(data.shape), new_mm, new_mv


def fused_bn_act(data, gamma, beta, moving_mean, moving_var, **kw):
    """Backend-routed fused BN(+activation): compiled Pallas on TPU,
    fused-lax elsewhere (same signature/returns as the BatchNorm op,
    plus ``act_type``).  The compiled kernel engages only for
    (sublane, lane)-aligned (C, H*W) blocks; unaligned shapes take the
    fused-lax path rather than paying Mosaic relayouts."""
    from . import use_pallas
    spatial = 1
    for d in data.shape[2:]:
        spatial *= int(d)
    if use_pallas() and spatial % 128 == 0 and data.shape[1] % 8 == 0:
        return fused_bn_act_pallas(data, gamma, beta, moving_mean,
                                   moving_var, interpret=False, **kw)
    return fused_bn_act_lax(data, gamma, beta, moving_mean, moving_var,
                            **kw)


def fold_bn_into_conv(weight, bias, gamma, beta, moving_mean, moving_var,
                      eps=0.001, fix_gamma=True):
    """Fold frozen BN statistics into the preceding conv's parameters.

    ``weight``: (O, I/g, *k); ``bias``: (O,) or None.  Returns
    ``(weight', bias')`` such that ``conv(x, w') + b'`` equals
    ``BN(conv(x, w) + b)`` with the moving statistics (inference mode),
    up to float reassociation.
    """
    if fix_gamma:
        gamma = jnp.ones_like(gamma)
    scale = gamma * lax.rsqrt(moving_var + eps)
    w = weight * scale.reshape((-1,) + (1,) * (weight.ndim - 1)) \
        .astype(weight.dtype)
    b = bias if bias is not None else jnp.zeros_like(moving_mean)
    b = ((b - moving_mean) * scale + beta).astype(w.dtype)
    return w, b
