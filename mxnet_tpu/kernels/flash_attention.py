"""Flash attention — tiled online-softmax attention.

Plain attention materializes the (T x T) score and probability matrices:
4 extra memory passes over B*H*T^2 elements that dwarf the useful q/k/v
traffic for long sequences.  The flash formulation (Dao et al., 2022)
streams over key blocks keeping a running (max, sum-of-exp, accumulator)
triple per query row — nothing quadratic ever exists.

Shared core: :func:`online_update` is ONE streaming-softmax accumulation
step.  The lax flash scan uses it per key block, and
``parallel/ring_attention.py`` composes with it per ring hop — ring
attention IS this kernel's accumulation run across devices, so the two
paths cannot drift numerically.

Tiers (package docstring):

- :func:`flash_attention_lax` — ``lax.scan`` over key blocks; pure lax,
  differentiable by jax (the scan transposes to the standard recompute
  backward), O(T) memory.
- :func:`flash_attention_pallas` — a ``pl.pallas_call`` kernel (grid
  over batch x heads x query blocks, ``fori_loop`` over key blocks with
  the running triple in registers/VMEM) behind ``jax.custom_vjp``; the
  registered backward recomputes through the fused-lax tier (O(T)
  memory, the FlashAttention recompute discipline) — Pallas has no
  reverse-mode transpose (rtc.py contract; mxlint ``graph-pallas-no-vjp``
  polices unprotected kernels).

Numerics: the streaming softmax reassociates the sum of exponentials, so
parity with :func:`~mxnet_tpu.parallel.ring_attention.full_attention` is
tolerance-checked (f32 ~1e-5 relative), not bitwise — the documented
tolerance in tests/test_kernels.py.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["flash_attention", "flash_attention_lax",
           "flash_attention_pallas", "online_update", "default_block"]


def default_block():
    from ..base import get_env
    from . import ENV_FLASH_BLOCK
    try:
        return max(8, int(get_env(ENV_FLASH_BLOCK, 128)))
    except (TypeError, ValueError):
        return 128


def online_update(acc, m_run, s_run, q, k, v, scale, mask):
    """One streaming-softmax accumulation step.

    ``acc`` (B, Tq, H, D) f32, ``m_run``/``s_run`` (B, H, Tq); ``q``
    (B, Tq, H, D); ``k``/``v`` (B, Tk, H, D); ``mask`` broadcastable to
    (B, H, Tq, Tk), True = attend.  Returns the updated triple.  Shared
    verbatim by the flash scan (per key block) and ring attention (per
    ring hop) so the two compositions stay numerically identical.
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    scores = jnp.where(mask, scores, -jnp.inf)
    m_blk = jnp.max(scores, axis=-1)
    # guard fully-masked rows
    m_safe = jnp.where(jnp.isfinite(m_blk), m_blk, 0.0)
    p = jnp.exp(scores - m_safe[..., None])
    p = jnp.where(mask, p, 0.0)
    s_blk = jnp.sum(p, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    # rescale both running state and the new block to the common max; a
    # fully-masked block (s_blk == 0) must not move the running max
    m_new = jnp.maximum(m_run, jnp.where(s_blk > 0, m_safe, m_run))
    alpha = jnp.where(jnp.isfinite(m_run), jnp.exp(m_run - m_new), 0.0)
    beta = jnp.where(jnp.isfinite(m_blk) & (s_blk > 0),
                     jnp.exp(m_safe - m_new), 0.0)
    s_new = s_run * alpha + s_blk * beta
    acc_new = acc * alpha.transpose(0, 2, 1)[..., None] + \
        out.astype(acc.dtype) * beta.transpose(0, 2, 1)[..., None]
    return acc_new, m_new, s_new


def _finalize(acc, s_run, dtype):
    s = jnp.maximum(s_run, 1e-20)
    return (acc / s.transpose(0, 2, 1)[..., None]).astype(dtype)


def flash_attention_lax(q, k, v, causal=False, scale=None, block_k=None):
    """Tiled online-softmax attention in pure lax: ``lax.scan`` over key
    blocks.  q/k/v (B, T, H, D) -> (B, Tq, H, D).  Memory O(B*T*H*D) —
    the (Tq x Tk) score matrix never materializes beyond one
    (Tq x block_k) tile."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = scale or (1.0 / np.sqrt(D))
    bk = min(block_k or default_block(), Tk)
    nk = -(-Tk // bk)
    pad = nk * bk - Tk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    # (nk, B, bk, H, D) blocks for the scan
    kb = jnp.moveaxis(k.reshape(B, nk, bk, H, D), 1, 0)
    vb = jnp.moveaxis(v.reshape(B, nk, bk, H, D), 1, 0)
    # absolute positions: q row i attends k col j iff j - i <= Tk - Tq
    # (the full_attention tril convention)
    q_pos = jnp.arange(Tq) + (Tk - Tq)

    acc0 = jnp.zeros((B, Tq, H, D), dtype=jnp.float32)
    m0 = jnp.full((B, H, Tq), -jnp.inf)
    s0 = jnp.zeros((B, H, Tq))

    def body(carry, blk):
        acc, m_run, s_run, idx = carry
        kblk, vblk = blk
        k_pos = idx * bk + jnp.arange(bk)
        valid = k_pos < Tk                                # padding tail
        if causal:
            mask = (q_pos[:, None] >= k_pos[None, :]) & valid[None, :]
        else:
            mask = jnp.broadcast_to(valid[None, :], (Tq, bk))
        acc, m_run, s_run = online_update(
            acc, m_run, s_run, q, kblk, vblk, scale, mask[None, None])
        return (acc, m_run, s_run, idx + 1), None

    (acc, _, s_run, _), _ = lax.scan(body, (acc0, m0, s0, 0), (kb, vb))
    return _finalize(acc, s_run, q.dtype)


# ---------------------------------------------------------------------------
# Pallas tier
# ---------------------------------------------------------------------------

def _flash_kernel(causal, scale, Tq, Tk, bk, q_ref, k_ref, v_ref, o_ref):
    """One (batch, head, q-block) program: fori_loop over key blocks
    with the running (acc, m, s) triple held in VMEM values.  ``Tq``/
    ``Tk`` are the TRUE (unpadded) lengths — causal offsets must not
    see the block padding."""
    from jax.experimental import pallas as pl

    bq = q_ref.shape[2]
    D = q_ref.shape[3]
    qi = pl.program_id(2)
    q = q_ref[0, 0, :, :].astype(jnp.float32)          # (bq, D)
    q_pos = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, 1), 0) \
        + (Tk - Tq)
    nk = -(-Tk // bk)

    def body(j, carry):
        acc, m_run, s_run = carry
        kb = k_ref[0, 0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        vb = v_ref[0, 0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        k_pos = j * bk + lax.broadcasted_iota(jnp.int32, (1, bk), 1)
        mask = k_pos < Tk
        if causal:
            mask = mask & (q_pos >= k_pos)
        scores = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) \
            * scale
        scores = jnp.where(mask, scores, -jnp.inf)
        m_blk = jnp.max(scores, axis=-1, keepdims=True)
        m_safe = jnp.where(jnp.isfinite(m_blk), m_blk, 0.0)
        p = jnp.where(mask, jnp.exp(scores - m_safe), 0.0)
        s_blk = jnp.sum(p, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_run, jnp.where(s_blk > 0, m_safe, m_run))
        alpha = jnp.where(jnp.isfinite(m_run), jnp.exp(m_run - m_new), 0.0)
        beta = jnp.where(jnp.isfinite(m_blk) & (s_blk > 0),
                         jnp.exp(m_safe - m_new), 0.0)
        s_new = s_run * alpha + s_blk * beta
        acc_new = acc * alpha + \
            jnp.dot(p, vb, preferred_element_type=jnp.float32) * beta
        return acc_new, m_new, s_new

    acc0 = jnp.zeros((bq, D), jnp.float32)
    m0 = jnp.full((bq, 1), -jnp.inf, jnp.float32)
    s0 = jnp.zeros((bq, 1), jnp.float32)
    acc, _, s_run = lax.fori_loop(0, nk, body, (acc0, m0, s0))
    o_ref[0, 0, :, :] = (acc / jnp.maximum(s_run, 1e-20)) \
        .astype(o_ref.dtype)


def _flash_pallas_fwd(q, k, v, causal, scale, block, interpret):
    """pallas_call over a (B, H, nq) grid in (B, H, T, D) layout."""
    from jax.experimental import pallas as pl

    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    bq = min(block, Tq)
    nq = -(-Tq // bq)
    pad_q = nq * bq - Tq
    qt = jnp.moveaxis(q, 1, 2)                          # (B, H, Tq, D)
    kt = jnp.moveaxis(k, 1, 2)
    vt = jnp.moveaxis(v, 1, 2)
    if pad_q:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    bk = min(block, Tk)
    pad_k = (-(-Tk // bk)) * bk - Tk
    if pad_k:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))

    kernel = functools.partial(_flash_kernel, causal, scale, Tq, Tk, bk)
    kw = {"grid": (B, H, nq),
          "in_specs": [
              pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
              pl.BlockSpec((1, 1, kt.shape[2], D),
                           lambda b, h, i: (b, h, 0, 0)),
              pl.BlockSpec((1, 1, vt.shape[2], D),
                           lambda b, h, i: (b, h, 0, 0))],
          "out_specs": pl.BlockSpec((1, 1, bq, D),
                                    lambda b, h, i: (b, h, i, 0))}
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, H, nq * bq, D), q.dtype),
        interpret=interpret, **kw)(qt, kt, vt)
    if pad_q:
        out = out[:, :, :Tq, :]
    return jnp.moveaxis(out, 2, 1)                      # (B, Tq, H, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_pallas(q, k, v, causal, scale, block, interpret):
    return _flash_pallas_fwd(q, k, v, causal, scale, block, interpret)


def _fp_fwd(q, k, v, causal, scale, block, interpret):
    return _flash_pallas_fwd(q, k, v, causal, scale, block, interpret), \
        (q, k, v)


def _fp_bwd(causal, scale, block, interpret, res, g):
    # registered backward: recompute through the fused-lax tier — O(T)
    # memory, no quadratic residuals (the FlashAttention recompute rule)
    q, k, v = res
    _, vjp_fn = jax.vjp(
        lambda a, b, c: flash_attention_lax(a, b, c, causal=causal,
                                            scale=scale, block_k=block),
        q, k, v)
    return vjp_fn(g)


_flash_pallas.defvjp(_fp_fwd, _fp_bwd)


def flash_attention_pallas(q, k, v, causal=False, scale=None, block=None,
                           interpret=None):
    """Pallas-tier flash attention (custom_vjp registered)."""
    if interpret is None:
        from ..rtc import on_tpu
        interpret = not on_tpu()
    D = q.shape[-1]
    scale = scale or (1.0 / np.sqrt(D))
    return _flash_pallas(q, k, v, bool(causal), float(scale),
                         int(block or default_block()), bool(interpret))


def flash_attention(q, k, v, causal=False, scale=None, block=None):
    """Backend-routed flash attention: compiled Pallas on TPU, the lax
    scan elsewhere.  Same contract as
    :func:`~mxnet_tpu.parallel.ring_attention.full_attention`."""
    from . import use_pallas
    if use_pallas():
        return flash_attention_pallas(q, k, v, causal=causal, scale=scale,
                                      block=block, interpret=False)
    return flash_attention_lax(q, k, v, causal=causal, scale=scale,
                               block_k=block)
