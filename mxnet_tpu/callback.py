"""Training callbacks (reference python/mxnet/callback.py, 163 LoC)."""
from __future__ import annotations

import logging
import math
import sys
import time

__all__ = ["Speedometer", "ProgressBar", "do_checkpoint", "log_train_metric",
           "module_checkpoint", "PreemptionCheckpoint"]


class PreemptionCheckpoint(object):
    """Batch-end callback giving CUSTOM training loops the graceful-
    preemption exit ``fit(preemption_safe=True)`` has built in: installs
    a :class:`~mxnet_tpu.resilience.PreemptionHandler`, and at the first
    batch boundary after SIGTERM/SIGINT saves a mid-epoch checkpoint
    (step + RNG state in the manifest) through ``manager`` and exits
    with ``resilience.PREEMPT_EXIT_CODE`` for a supervisor to relaunch.

    Use it as a context manager (or call :meth:`close`) so the signal
    handlers are restored when the loop finishes WITHOUT a preemption —
    leaked handlers would swallow the process's next Ctrl-C::

        man = mx.CheckpointManager("ckpt/")
        with mx.callback.PreemptionCheckpoint(mod, man) as cb:
            for epoch in ...:
                for nbatch, batch in enumerate(train_iter):
                    mod.forward_backward(batch); mod.update()
                    cb(mx.model.BatchEndParam(epoch, nbatch, metric,
                                              locals()))
    """

    def __init__(self, mod, manager, handler=None):
        from .resilience import PreemptionHandler
        self.mod = mod
        self.manager = manager
        self.handler = handler or PreemptionHandler()
        self.handler.install()

    def __call__(self, param):
        if not self.handler.triggered:
            return
        from .resilience import preempted_exit
        self.mod._save_preemption_checkpoint(self.manager, param.epoch,
                                             param.nbatch + 1)
        self.handler.uninstall()
        preempted_exit()

    def close(self):
        """Restore the original signal handlers (idempotent)."""
        self.handler.uninstall()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.close()
        return False


def do_checkpoint(prefix, period=1):
    """Epoch-end callback saving prefix-symbol.json + prefix-%04d.params
    (reference callback.py:do_checkpoint).  Writes are atomic; ``prefix``
    may also be a :class:`~mxnet_tpu.resilience.CheckpointManager`, which
    adds manifest discovery, per-file checksums and keep_last retention.
    Under ``MXTPU_CKPT_ASYNC=1`` both forms return after the host
    snapshot and a background writer does the file IO — drain with
    ``manager.wait()`` / ``resilience.wait_checkpoints()``."""
    from .model import save_checkpoint
    period = int(max(1, period))
    managed = hasattr(prefix, "save") and hasattr(prefix, "latest")

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            if managed:
                prefix.save(iter_no + 1, sym, arg, aux)
            else:
                save_checkpoint(prefix, iter_no + 1, sym, arg, aux)
    return _callback


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Epoch-end callback checkpointing a Module (reference
    callback.py:module_checkpoint)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)
    return _callback


def log_train_metric(period, auto_reset=False):
    """Batch-end callback logging the metric every ``period`` batches."""
    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()
    return _callback


class Speedometer(object):
    """Samples/sec throughput logging (reference callback.py:Speedometer) —
    the logging format the reference benchmarks are read from."""

    def __init__(self, batch_size, frequent=50):
        self.batch_size = batch_size
        self.frequent = frequent
        self.init = False
        self.tic = 0
        self.last_count = 0

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                speed = self.frequent * self.batch_size / \
                    (time.time() - self.tic)
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    param.eval_metric.reset()
                    for name, value in name_value:
                        logging.info(
                            "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec"
                            "\tTrain-%s=%f", param.epoch, count, speed, name,
                            value)
                else:
                    logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                                 param.epoch, count, speed)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


class ProgressBar(object):
    """Text progress bar (reference callback.py:ProgressBar)."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = math.ceil(100.0 * count / float(self.total))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        sys.stdout.write("[%s] %s%s\r" % (prog_bar, percents, "%"))
