"""Automatic symbol naming (reference python/mxnet/name.py)."""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix", "current"]


class NameManager(object):
    """Assigns default names to symbols (NameManager, name.py:8-60)."""

    _state = threading.local()

    def __init__(self):
        self._counter = {}
        self._old_manager = None

    def get(self, name, hint):
        if name:
            return name
        if hint not in self._counter:
            self._counter[hint] = 0
        name = "%s%d" % (hint, self._counter[hint])
        self._counter[hint] += 1
        return name

    def __enter__(self):
        if not hasattr(NameManager._state, "current"):
            NameManager._state.current = NameManager()
        self._old_manager = NameManager._state.current
        NameManager._state.current = self
        return self

    def __exit__(self, ptype, value, trace):
        NameManager._state.current = self._old_manager


class Prefix(NameManager):
    """Prepends a prefix to all auto-generated names (name.py:63-78)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        name = super().get(name, hint)
        return self._prefix + name


def current():
    if not hasattr(NameManager._state, "current"):
        NameManager._state.current = NameManager()
    return NameManager._state.current
