"""Profiler control (reference python/mxnet/profiler.py over MXSetProfilerConfig/
MXSetProfilerState/MXDumpProfile, src/engine/profiler.{h,cc}).

Two collectors feed one Chrome ``traceEvents`` dump, matching the reference's
format (profiler.cc:134-216):
- the host dependency engine's per-op timings (data pipeline, engine ops) via
  the native profiler (mxnet_tpu/native/engine.cc);
- XLA device traces via ``jax.profiler`` when a trace_dir is configured
  (mode='all_xla') — viewable in TensorBoard/Perfetto, the TPU analog of the
  reference's per-kernel GPU stats.

Env parity: MXNET_PROFILER_AUTOSTART=1 starts profiling at import
(docs/how_to/env_var.md:66-73).
"""
from __future__ import annotations

import os

from .base import MXNetError

__all__ = ["profiler_set_config", "profiler_set_state", "dump_profile",
           "State", "Mode"]


class Mode(object):
    SYMBOLIC = "symbolic"       # kOnlySymbolic
    ALL = "all"                 # kAllOperator
    ALL_XLA = "all_xla"         # + device-side XLA trace via jax.profiler


class State(object):
    STOP = "stop"               # kNotRunning
    RUN = "run"                 # kRunning


_config = {"mode": Mode.ALL, "filename": "profile.json", "trace_dir": None}
_state = [State.STOP]
_xla_tracing = [False]


def profiler_set_config(mode="symbolic", filename="profile.json",
                        trace_dir=None):
    """Set profiler mode and output file (reference profiler.py:
    profiler_set_config / MXSetProfilerConfig)."""
    if mode not in (Mode.SYMBOLIC, Mode.ALL, Mode.ALL_XLA):
        raise MXNetError("invalid profiler mode %r" % (mode,))
    _config["mode"] = mode
    _config["filename"] = filename
    _config["trace_dir"] = trace_dir


def profiler_set_state(state="stop"):
    """Start/stop profiling (reference profiler.py:profiler_set_state /
    MXSetProfilerState)."""
    from . import engine
    if state not in (State.RUN, State.STOP):
        raise MXNetError("invalid profiler state %r" % (state,))
    running = state == State.RUN
    engine.get().set_profiler_state(running)
    if _config["mode"] == Mode.ALL_XLA:
        import jax
        trace_dir = _config["trace_dir"] or \
            os.path.splitext(_config["filename"])[0] + "_xla"
        if running and not _xla_tracing[0]:
            jax.profiler.start_trace(trace_dir)
            _xla_tracing[0] = True
        elif not running and _xla_tracing[0]:
            jax.profiler.stop_trace()
            _xla_tracing[0] = False
    _state[0] = state


def dump_profile(finished=True):
    """Write the collected host-engine trace as Chrome traceEvents JSON to
    the configured filename (reference profiler.py:dump_profile /
    MXDumpProfile)."""
    from . import engine
    data = engine.get().dump_profile()
    with open(_config["filename"], "w") as f:
        f.write(data)
    return _config["filename"]


if os.environ.get("MXNET_PROFILER_AUTOSTART", "0") == "1":
    profiler_set_state(State.RUN)
