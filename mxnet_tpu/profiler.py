"""Profiler control (reference python/mxnet/profiler.py over MXSetProfilerConfig/
MXSetProfilerState/MXDumpProfile, src/engine/profiler.{h,cc}).

Two collectors feed one Chrome ``traceEvents`` dump, matching the reference's
format (profiler.cc:134-216):
- the host dependency engine's per-op timings (data pipeline, engine ops) via
  the native profiler (mxnet_tpu/native/engine.cc);
- XLA device traces via ``jax.profiler`` when a trace_dir is configured
  (mode='all_xla') — viewable in TensorBoard/Perfetto, the TPU analog of the
  reference's per-kernel GPU stats.

Env parity: MXNET_PROFILER_AUTOSTART=1 starts profiling at import
(docs/how_to/env_var.md:66-73).
"""
from __future__ import annotations

import os

from .base import MXNetError, get_env, register_env

__all__ = ["profiler_set_config", "profiler_set_state", "dump_profile",
           "dumps", "get_op_stats", "State", "Mode", "StepTraceCapture",
           "ENV_PROFILE_DIR"]

#: when set, fit() captures a jax.profiler trace of steps 10-15 of the
#: first epoch into this directory (viewable in TensorBoard/Perfetto)
ENV_PROFILE_DIR = register_env(
    "MXTPU_PROFILE_DIR",
    doc="fit() captures a jax.profiler trace of steps 10-15 of the first "
        "epoch into this directory")
ENV_PROFILER_AUTOSTART = register_env(
    "MXNET_PROFILER_AUTOSTART", default=0,
    doc="1 starts the host profiler at import (reference parity)")


class StepTraceCapture(object):
    """Window-bounded ``jax.profiler`` trace for a training loop.

    Captures steps ``[start_step, stop_step]`` (default 10-15) of the
    epoch it is driven through: the caller invokes :meth:`on_batch` with
    the 0-based batch index before each step and :meth:`stop` at epoch
    end (closing a window the epoch cut short).  A steady-state window —
    not step 0 — so the trace shows the pipeline, not compilation."""

    def __init__(self, directory, start_step=10, stop_step=15):
        self.directory = os.fspath(directory)
        self.start_step = int(start_step)
        self.stop_step = int(stop_step)
        self._active = False
        self._done = False

    @classmethod
    def from_env(cls):
        """A capture configured from MXTPU_PROFILE_DIR, or None."""
        directory = get_env(ENV_PROFILE_DIR)
        return cls(directory) if directory else None

    def on_batch(self, nbatch):
        if self._done:
            return
        if not self._active and nbatch >= self.start_step:
            import jax
            os.makedirs(self.directory, exist_ok=True)
            jax.profiler.start_trace(self.directory)
            self._active = True
        elif self._active and nbatch > self.stop_step:
            self.stop()

    def stop(self):
        if not self._active:
            return
        import jax
        jax.profiler.stop_trace()
        self._active = False
        self._done = True
        import logging
        logging.getLogger(__name__).info(
            "StepTraceCapture: wrote steps %d-%d trace to %s",
            self.start_step, self.stop_step, self.directory)


class Mode(object):
    SYMBOLIC = "symbolic"       # kOnlySymbolic
    ALL = "all"                 # kAllOperator
    ALL_XLA = "all_xla"         # + device-side XLA trace via jax.profiler


class State(object):
    STOP = "stop"               # kNotRunning
    RUN = "run"                 # kRunning


_config = {"mode": Mode.ALL, "filename": "profile.json", "trace_dir": None}
_state = [State.STOP]
_xla_tracing = [False]


def profiler_set_config(mode="symbolic", filename="profile.json",
                        trace_dir=None):
    """Set profiler mode and output file (reference profiler.py:
    profiler_set_config / MXSetProfilerConfig)."""
    if mode not in (Mode.SYMBOLIC, Mode.ALL, Mode.ALL_XLA):
        raise MXNetError("invalid profiler mode %r" % (mode,))
    _config["mode"] = mode
    _config["filename"] = filename
    _config["trace_dir"] = trace_dir


def profiler_set_state(state="stop"):
    """Start/stop profiling (reference profiler.py:profiler_set_state /
    MXSetProfilerState)."""
    from . import engine
    if state not in (State.RUN, State.STOP):
        raise MXNetError("invalid profiler state %r" % (state,))
    running = state == State.RUN
    engine.get().set_profiler_state(running)
    if _config["mode"] == Mode.ALL_XLA:
        import jax
        trace_dir = _config["trace_dir"] or \
            os.path.splitext(_config["filename"])[0] + "_xla"
        if running and not _xla_tracing[0]:
            jax.profiler.start_trace(trace_dir)
            _xla_tracing[0] = True
        elif not running and _xla_tracing[0]:
            jax.profiler.stop_trace()
            _xla_tracing[0] = False
    _state[0] = state


def dump_profile(finished=True):
    """Write the collected host-engine trace as Chrome traceEvents JSON to
    the configured filename (reference profiler.py:dump_profile /
    MXDumpProfile)."""
    from . import engine
    data = engine.get().dump_profile()
    with open(_config["filename"], "w") as f:
        f.write(data)
    return _config["filename"]


def _latest_device_trace(trace_dir=None):
    """Newest <trace_dir>/plugins/profile/*/*.trace.json.gz written by
    jax.profiler (already Chrome traceEvents format)."""
    import glob
    trace_dir = trace_dir or _config["trace_dir"] or \
        os.path.splitext(_config["filename"])[0] + "_xla"
    cands = glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*", "*.trace.json.gz"))
    if not cands:
        raise MXNetError(
            "no XLA device trace under %r — profile with "
            "mode='all_xla' first" % (trace_dir,))
    return max(cands, key=os.path.getmtime)


def _scope_of(event):
    """Graph-node name for one device HLO event.

    XLA stamps the jax named_scope path into the event's ``tf_op``
    metadata (e.g. ``jit(step)/conv2/conv_general_dilated:``); the
    executor wraps every symbol node in named_scope(node.name), so the
    middle path segments ARE graph node names.  Events without tf_op
    (DMA copies, infeed) fall back to their HLO category."""
    args = event.get("args") or {}
    tf_op = args.get("tf_op", "")
    parts = [p for p in tf_op.rstrip(":").split("/") if p]
    if parts and parts[0].startswith("jit("):
        parts = parts[1:]
    if len(parts) >= 2:
        name = "/".join(parts[:-1])     # named-scope path, primitive off
    elif parts:
        name = parts[0]
    else:
        return args.get("hlo_category", event.get("name", "?"))
    # autodiff wrappers -> the reference's fwd/bwd naming: jvp(conv1) is
    # the forward op, transpose(jvp(conv1)) its backward
    # (_backward_Convolution in the reference's profile)
    import re
    m = re.fullmatch(r"transpose\(jvp\((.+)\)\)", name)
    if m:
        return "_backward_" + m.group(1)
    m = re.fullmatch(r"jvp\((.+)\)", name)
    if m:
        return m.group(1)
    return name


def get_op_stats(trace_dir=None):
    """Per-graph-node device-time stats from the newest XLA trace:
    {name: {"count": n, "total_us": t, "avg_us": a, "min_us": m,
    "max_us": M}}.  Works on fused (jit) programs — the reference's
    per-op profile needed per-op engine dispatch; here HLO metadata
    attributes fused-program time back to symbol nodes."""
    import gzip
    import json
    path = _latest_device_trace(trace_dir)
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    stats = {}
    for ev in data.get("traceEvents", []):
        args = ev.get("args") or {}
        if "device_duration_ps" not in args:
            continue    # host-side event
        if "tf_op" not in args and "hlo_category" not in args:
            continue    # step marker / whole-module span, not an HLO op
        us = int(args["device_duration_ps"]) / 1e6
        s = stats.setdefault(_scope_of(ev), {
            "count": 0, "total_us": 0.0,
            "min_us": float("inf"), "max_us": 0.0})
        s["count"] += 1
        s["total_us"] += us
        s["min_us"] = min(s["min_us"], us)
        s["max_us"] = max(s["max_us"], us)
    for s in stats.values():
        s["total_us"] = round(s["total_us"], 3)
        s["min_us"] = round(s["min_us"], 3)
        s["max_us"] = round(s["max_us"], 3)
        s["avg_us"] = round(s["total_us"] / s["count"], 3)
    return stats


def dumps(reset=False, trace_dir=None):
    """Per-op device-time table from the newest XLA trace (reference
    mx.profiler.dumps / profiler.cc:134-216 per-op stats, over the FUSED
    program).  ``reset`` is accepted for API parity (traces are
    per-start_trace already)."""
    del reset
    stats = get_op_stats(trace_dir)
    order = sorted(stats.items(), key=lambda kv: -kv[1]["total_us"])
    w = max([len("Name")] + [len(k) for k, _ in order]) + 2
    lines = ["Profile Statistics (device time, fused program)",
             "%-*s %10s %12s %12s %12s %12s" % (
                 w, "Name", "Count", "Total-us", "Min-us", "Max-us",
                 "Avg-us")]
    for name, s in order:
        lines.append("%-*s %10d %12.3f %12.3f %12.3f %12.3f" % (
            w, name, s["count"], s["total_us"], s["min_us"], s["max_us"],
            s["avg_us"]))
    return "\n".join(lines) + "\n"


if str(get_env(ENV_PROFILER_AUTOSTART, "0")) == "1":
    profiler_set_state(State.RUN)
