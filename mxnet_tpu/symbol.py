"""Symbol — declarative graph construction.

Re-design of the reference's nnvm::Symbol + python/mxnet/symbol.py (1,424
LoC).  A Symbol is a list of (node, output_index) heads over a DAG of
``_Node`` objects.  Graph compilation happens at bind time: the executor
traces the DAG into one JAX function and jits it — the NNVM pass pipeline
(InferShape/InferType/PlanMemory/bulk segmentation,
src/executor/graph_executor.cc:372-690) collapses into XLA's compiler.

API parity: Variable/Group/compose, list_arguments/outputs/auxiliary_states,
infer_shape(_partial), infer_type, attr scoping, save/load JSON
(format-compatible with the reference's graph JSON), bind/simple_bind, grad.
"""
from __future__ import annotations

import builtins
import json

import numpy as np

from . import attribute, name as _name_mod
from .base import MXNetError, attr_to_string, parse_attr_value
from .ops.registry import OP_REGISTRY, get_op

__all__ = ["Symbol", "Variable", "Group", "load", "load_json", "var"]

# attrs that belong to the framework, not to the op's kernel
_RESERVED_ATTRS = frozenset((
    "ctx_group", "lr_mult", "wd_mult", "force_mirroring", "__shape__",
    "__dtype__", "__init__",
))


class _Node(object):
    __slots__ = ("op", "name", "attrs", "inputs", "_uid")
    _uid_counter = [0]

    def __init__(self, op, name, attrs=None, inputs=None):
        self.op = op          # OpDef or None for variables
        self.name = name
        self.attrs = dict(attrs) if attrs else {}
        self.inputs = list(inputs) if inputs else []  # [(node, out_idx)]
        _Node._uid_counter[0] += 1
        self._uid = _Node._uid_counter[0]

    @property
    def is_variable(self):
        return self.op is None

    def op_attrs(self):
        """Attrs passed to the op function (reserved/meta attrs stripped)."""
        return {k: v for k, v in self.attrs.items()
                if k not in _RESERVED_ATTRS and not k.startswith("__")}

    def num_outputs(self):
        if self.is_variable:
            return 1
        return self.op.get_num_outputs(self.op.normalize_attrs(self.op_attrs()))


def _topo_sort(heads):
    """Post-order DFS over the DAG."""
    visited = set()
    order = []
    stack = [(h, False) for h in reversed(heads)]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in visited:
            continue
        visited.add(id(node))
        stack.append((node, True))
        for src, _idx in reversed(node.inputs):
            if id(src) not in visited:
                stack.append((src, False))
    return order


class Symbol(object):
    __slots__ = ("_outputs",)

    def __init__(self, outputs):
        self._outputs = list(outputs)  # [(node, out_idx)]

    # -- introspection ----------------------------------------------------
    def _nodes(self):
        return _topo_sort([n for n, _ in self._outputs])

    def _aux_var_names(self):
        """Variable names that feed aux-state slots of ops (the NNVM
        FMutateInputs analog)."""
        aux = set()
        for node in self._nodes():
            if node.is_variable:
                continue
            attrs = node.op.normalize_attrs(node.op_attrs())
            n_in = len(node.op.get_input_names(attrs))
            aux_names = node.op.get_aux_names(attrs)
            for k, (src, _idx) in enumerate(node.inputs):
                if k >= n_in and k < n_in + len(aux_names) and src.is_variable:
                    aux.add(src.name)
        return aux

    def list_arguments(self):
        aux = self._aux_var_names()
        out, seen = [], set()
        for node in self._nodes():
            if node.is_variable and node.name not in aux and node.name not in seen:
                seen.add(node.name)
                out.append(node.name)
        return out

    def list_auxiliary_states(self):
        aux = self._aux_var_names()
        out, seen = [], set()
        for node in self._nodes():
            if node.is_variable and node.name in aux and node.name not in seen:
                seen.add(node.name)
                out.append(node.name)
        return out

    def list_outputs(self):
        names = []
        for node, idx in self._outputs:
            if node.is_variable:
                names.append(node.name)
                continue
            attrs = node.op.normalize_attrs(node.op_attrs())
            out_names = node.op.get_output_names(attrs)
            if node.num_outputs() == 1:
                names.append(node.name + "_output")
            else:
                names.append("%s_%s" % (node.name, out_names[idx]))
        return names

    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def attr(self, key):
        if len(self._outputs) == 1:
            v = self._outputs[0][0].attrs.get(key)
            return attr_to_string(v) if v is not None else None
        return None

    def list_attr(self):
        if len(self._outputs) == 1:
            return {k: attr_to_string(v)
                    for k, v in self._outputs[0][0].attrs.items()}
        return {}

    def attr_dict(self):
        out = {}
        for node in self._nodes():
            if node.attrs:
                out[node.name] = {k: attr_to_string(v)
                                  for k, v in node.attrs.items()}
        return out

    def _set_attr(self, **kwargs):
        for node, _ in self._outputs:
            node.attrs.update(kwargs)

    def get_internals(self):
        outs = []
        for node in self._nodes():
            for i in range(node.num_outputs()):
                outs.append((node, i))
        return Symbol(outs)

    def get_children(self):
        if len(self._outputs) != 1:
            return None
        node = self._outputs[0][0]
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise MXNetError("cannot find output %r; outputs=%s" % (index, names))
            index = names.index(index)
        if isinstance(index, builtins.slice):
            return Symbol(self._outputs[index])
        return Symbol([self._outputs[index]])

    def __len__(self):
        return len(self._outputs)

    def __iter__(self):
        for i in range(len(self._outputs)):
            yield self[i]

    def __repr__(self):
        if len(self._outputs) == 1:
            return "<Symbol %s>" % self._outputs[0][0].name
        return "<Symbol group [%s]>" % ", ".join(self.list_outputs())

    # -- composition ------------------------------------------------------
    def __call__(self, **kwargs):
        """Compose: replace variables by other symbols (symbol.py __call__)."""
        mapping = {}
        for k, v in kwargs.items():
            if not isinstance(v, Symbol):
                raise TypeError("compose expects Symbols")
            mapping[k] = v._outputs[0]
        memo = {}

        def rewrite_pair(node, idx):
            if node.is_variable and node.name in mapping:
                return mapping[node.name]
            if id(node) not in memo:
                new = _Node(node.op, node.name, node.attrs, [])
                memo[id(node)] = new
                new.inputs = [rewrite_pair(s, i) for s, i in node.inputs]
            return (memo[id(node)], idx)

        return Symbol([rewrite_pair(n, i) for n, i in self._outputs])

    # -- arithmetic -------------------------------------------------------
    def _binary(self, other, op_name, scalar_op, reverse=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _create(op_name, [a, b], {})
        if isinstance(other, (int, float, np.number)):
            return _create(scalar_op, [self], {"scalar": float(other)})
        return NotImplemented

    def __add__(self, o):
        return self._binary(o, "elemwise_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "elemwise_sub", "_minus_scalar")

    def __rsub__(self, o):
        return self._binary(o, "elemwise_sub", "_rminus_scalar", reverse=True)

    def __mul__(self, o):
        return self._binary(o, "elemwise_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __div__(self, o):
        return self._binary(o, "elemwise_div", "_div_scalar")

    __truediv__ = __div__

    def __rdiv__(self, o):
        return self._binary(o, "elemwise_div", "_rdiv_scalar", reverse=True)

    __rtruediv__ = __rdiv__

    def __pow__(self, o):
        return self._binary(o, "_power", "_power_scalar")

    def __neg__(self):
        return _create("_mul_scalar", [self], {"scalar": -1.0})

    def __eq__(self, o):
        if isinstance(o, (Symbol, int, float)):
            return self._binary(o, "broadcast_equal", "_equal_scalar")
        return NotImplemented

    def __ne__(self, o):
        if isinstance(o, (Symbol, int, float)):
            return self._binary(o, "broadcast_not_equal", "_not_equal_scalar")
        return NotImplemented

    def __gt__(self, o):
        return self._binary(o, "broadcast_greater", "_greater_scalar")

    def __ge__(self, o):
        return self._binary(o, "broadcast_greater_equal", "_greater_equal_scalar")

    def __lt__(self, o):
        return self._binary(o, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, o):
        return self._binary(o, "broadcast_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    # -- shape / type inference ------------------------------------------
    def infer_shape(self, *args, **kwargs):
        arg_shapes, out_shapes, aux_shapes = self.infer_shape_partial(*args, **kwargs)
        if arg_shapes is not None and any(s is None for s in arg_shapes):
            unknown = [n for n, s in zip(self.list_arguments(), arg_shapes)
                       if s is None]
            raise MXNetError(
                "InferShape incomplete: cannot infer shapes of %s" % unknown)
        return arg_shapes, out_shapes, aux_shapes

    def infer_shape_partial(self, *args, **kwargs):
        if args:
            kwargs = dict(zip(self.list_arguments(), args))
            kwargs = {k: v for k, v in kwargs.items() if v is not None}
        known = {k: tuple(v) for k, v in kwargs.items() if v is not None}
        shapes = {}   # id(node) -> list of output shapes
        for node in self._nodes():
            if node.is_variable:
                s = known.get(node.name)
                if s is None and "__shape__" in node.attrs:
                    s = tuple(parse_attr_value(node.attrs["__shape__"]))
                shapes[id(node)] = [tuple(s) if s is not None else None]
                continue
            attrs = node.op.normalize_attrs(node.op_attrs())
            in_shapes = [shapes[id(src)][idx] for src, idx in node.inputs]
            new_in, out_sh = _infer_node(node, attrs, in_shapes)
            # back-fill variable shapes learned by the op's shape function
            for (src, idx), s in zip(node.inputs, new_in):
                if s is not None and src.is_variable and shapes[id(src)][0] is None:
                    shapes[id(src)][0] = tuple(s)
                    known[src.name] = tuple(s)
            shapes[id(node)] = list(out_sh)
        args_order = self.list_arguments()
        aux_order = self.list_auxiliary_states()
        by_name = {}
        for node in self._nodes():
            if node.is_variable:
                by_name[node.name] = shapes[id(node)][0]
        arg_shapes = [by_name.get(n) for n in args_order]
        aux_shapes = [by_name.get(n) for n in aux_order]
        out_shapes = [shapes[id(n)][i] for n, i in self._outputs]
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        """Basic dtype inference: float32 default; honors explicit hints and
        ``Variable(dtype=...)`` declarations (stored as __dtype__ attr)."""
        if args:
            kwargs = dict(zip(self.list_arguments(), args))
        dt = {k: np.dtype(v) for k, v in kwargs.items() if v is not None}
        for node in self._nodes():
            if node.is_variable and "__dtype__" in node.attrs:
                dt.setdefault(node.name, np.dtype(str(node.attrs["__dtype__"])))
        arg_types = [np.dtype(dt.get(n, np.float32)).type
                     for n in self.list_arguments()]
        aux_types = [np.float32 for _ in self.list_auxiliary_states()]
        out_types = [np.float32 for _ in self._outputs]
        return arg_types, out_types, aux_types

    # -- serialization ----------------------------------------------------
    def tojson(self):
        nodes = self._nodes()
        nid = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        arg_nodes = []
        for i, node in enumerate(nodes):
            if node.is_variable:
                arg_nodes.append(i)
            jnodes.append({
                "op": "null" if node.is_variable else node.op.name,
                "name": node.name,
                "attrs": {k: attr_to_string(v) for k, v in node.attrs.items()},
                "inputs": [[nid[id(s)], idx, 0] for s, idx in node.inputs],
            })
        heads = [[nid[id(n)], idx, 0] for n, idx in self._outputs]
        return json.dumps({
            "nodes": jnodes, "arg_nodes": arg_nodes, "heads": heads,
            "attrs": {"mxnet_tpu_version": "0.1.0"},
        }, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # -- binding ----------------------------------------------------------
    def simple_bind(self, ctx, grad_req="write", type_dict=None, group2ctx=None,
                    shared_exec=None, **kwargs):
        from .executor import Executor
        return Executor._simple_bind(self, ctx, grad_req=grad_req,
                                     type_dict=type_dict, group2ctx=group2ctx,
                                     shared_exec=shared_exec, shapes=kwargs)

    def bind(self, ctx, args, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from .executor import Executor
        return Executor(self, ctx, args, args_grad=args_grad,
                        grad_req=grad_req, aux_states=aux_states,
                        group2ctx=group2ctx, shared_exec=shared_exec)

    def grad(self, wrt):
        raise NotImplementedError(
            "Symbol.grad: use bind(...).backward() or autograd")

    # -- eval convenience -------------------------------------------------
    def eval(self, ctx=None, **kwargs):
        from .context import current_context
        ctx = ctx or current_context()
        ex = self.bind(ctx, kwargs)
        return ex.forward()


def _infer_node(node, attrs, in_shapes):
    """Shape inference for one node: custom fn, else jax.eval_shape fallback."""
    op = node.op
    if op.infer_shape is not None:
        new_in, out_sh, _aux = op.infer_shape(attrs, in_shapes)
        # custom infers cover declared inputs; aux inputs trail
        n_declared = len(new_in)
        full_in = list(new_in) + list(in_shapes[n_declared:])
        if _aux:
            n_in = len(op.get_input_names(attrs))
            for k, s in enumerate(_aux):
                if n_in + k < len(full_in) and full_in[n_in + k] is None:
                    full_in[n_in + k] = s
        return full_in, out_sh
    if any(s is None for s in in_shapes):
        return in_shapes, [None] * op.get_num_outputs(attrs)
    import jax
    import jax.numpy as jnp
    from .executor import _filter_attrs

    call_attrs = _filter_attrs(op, attrs)
    structs = [jax.ShapeDtypeStruct(s, jnp.float32) for s in in_shapes]
    kw = {}
    if op.needs_is_train:
        kw["is_train"] = False
    if op.needs_rng:
        kw["rng"] = jax.ShapeDtypeStruct((2,), jnp.uint32)

    def f(*xs):
        return op.fn(*xs, **call_attrs, **kw)
    try:
        if op.needs_rng:
            kwr = dict(kw)
            kwr.pop("rng")

            def f2(rng, *xs):
                return op.fn(*xs, rng=rng, **call_attrs, **kwr)
            out = jax.eval_shape(f2, jax.ShapeDtypeStruct((2,), jnp.uint32),
                                 *structs)
        else:
            out = jax.eval_shape(f, *structs)
    except Exception as e:
        raise MXNetError("InferShape failed for op %s(%s): %s"
                         % (op.name, node.name, e)) from e
    if not isinstance(out, (tuple, list)):
        out = (out,)
    n_out = op.get_num_outputs(attrs)
    return in_shapes, [tuple(o.shape) for o in out][:n_out]


# ---------------------------------------------------------------------------
# construction helpers
# ---------------------------------------------------------------------------

def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, **kwargs):
    """Create a variable symbol (symbol.py Variable)."""
    attrs = attribute.current().get(attr)
    if shape is not None:
        attrs["__shape__"] = str(tuple(shape))
    if dtype is not None:
        attrs["__dtype__"] = str(np.dtype(dtype))
    if lr_mult is not None:
        attrs["lr_mult"] = str(lr_mult)
    if wd_mult is not None:
        attrs["wd_mult"] = str(wd_mult)
    attrs.update({k: str(v) for k, v in kwargs.items()})
    return Symbol([(_Node(None, name, attrs), 0)])


var = Variable


def Group(symbols):
    outs = []
    for s in symbols:
        outs.extend(s._outputs)
    return Symbol(outs)


def _create(op_name, input_syms, attrs, name=None, extra_attr=None,
            named_inputs=None):
    """Create an op node.  ``input_syms`` are positional inputs (used for
    variadic ops and operator sugar); ``named_inputs`` maps input-name ->
    Symbol.  Missing parameter/aux inputs are auto-created as Variables
    named ``{node}_{input}`` — the reference's auto-created weight/bias/aux
    variables (python/mxnet/symbol.py compose)."""
    op = get_op(op_name)
    op.validate_attrs(attrs, where="symbol")
    norm = op.normalize_attrs(attrs)
    hint = op.name.lstrip("_").lower()
    node_name = _name_mod.current().get(name, hint)
    node_attrs = dict(attrs)
    if extra_attr:
        node_attrs.update(extra_attr)
    scope_attrs = attribute.current().get(None)
    for k, v in scope_attrs.items():
        node_attrs.setdefault(k, v)

    def head(s):
        if len(s._outputs) != 1:
            raise MXNetError("op %s input must be single-output symbol" % op_name)
        return s._outputs[0]

    in_names = op.get_input_names(norm)
    aux_names = op.get_aux_names(norm)
    if op.variable_inputs:
        inputs = [head(s) for s in input_syms]
        # some variadic ops still declare named parameter inputs beyond the
        # user-supplied ones (UpSampling bilinear's weight) — auto-create them
        for nm in list(op.get_input_names(norm))[len(inputs):]:
            inputs.append(Variable("%s_%s" % (node_name, nm))._outputs[0])
    else:
        by_name = dict(named_inputs or {})
        for nm, s in zip(in_names, input_syms):
            if nm in by_name:
                raise MXNetError(
                    "op %s: input %r given both positionally and by keyword"
                    % (op_name, nm))
            by_name[nm] = s
        unknown = set(by_name) - set(in_names) - set(aux_names)
        if unknown:
            raise MXNetError("op %s: unknown input name(s) %s; inputs are %s"
                             % (op_name, sorted(unknown),
                                list(in_names) + list(aux_names)))
        inputs = []
        for nm in list(in_names) + list(aux_names):
            if nm in by_name:
                inputs.append(head(by_name[nm]))
            else:
                inputs.append(Variable("%s_%s" % (node_name, nm))._outputs[0])
    node = _Node(op, node_name, node_attrs, inputs)
    n_out = node.num_outputs()
    return Symbol([(node, i) for i in range(n_out)]) if n_out > 1 \
        else Symbol([(node, 0)])


def _make_symbol_function(opdef, func_name):
    def creator(*args, **kwargs):
        name = kwargs.pop("name", None)
        attr = kwargs.pop("attr", None)
        sym_kwargs = {}
        attrs = {}
        for k, v in kwargs.items():
            if isinstance(v, Symbol):
                sym_kwargs[k] = v
            else:
                attrs[k] = v
        if opdef.variable_inputs:
            inputs = [a for a in args if isinstance(a, Symbol)]
            if not inputs and sym_kwargs:
                inputs = list(sym_kwargs.values())
            attrs.setdefault("num_args", len(inputs))
            named = None
        else:
            inputs = []
            for a in args:
                if not isinstance(a, Symbol):
                    raise TypeError(
                        "positional args to sym.%s must be Symbols" % func_name)
                inputs.append(a)
            named = sym_kwargs
        extra = attribute.current().get(attr)
        return _create(opdef.name, inputs, attrs, name=name, extra_attr=extra,
                       named_inputs=named)

    creator.__name__ = func_name
    creator.__doc__ = opdef.doc
    return creator


def _init_symbol_module():
    module = globals()
    for reg_name, opdef in list(OP_REGISTRY.items()):
        if reg_name not in module:
            module[reg_name] = _make_symbol_function(opdef, reg_name)


# ---------------------------------------------------------------------------
# JSON load
# ---------------------------------------------------------------------------

def load_json(json_str):
    data = json.loads(json_str)
    jnodes = data["nodes"]
    nodes = []
    for jn in jnodes:
        attrs = jn.get("attrs", jn.get("attr", jn.get("param", {}))) or {}
        if jn["op"] == "null":
            nodes.append(_Node(None, jn["name"], attrs))
        else:
            op = get_op(jn["op"])
            nodes.append(_Node(op, jn["name"], attrs))
    for jn, node in zip(jnodes, nodes):
        node.inputs = [(nodes[i[0]], i[1]) for i in jn["inputs"]]
    heads = data.get("heads")
    if not heads:
        heads = [[len(nodes) - 1, 0, 0]]
    return Symbol([(nodes[h[0]], h[1]) for h in heads])


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def pow(base, exp):  # noqa: A001 - parity with mx.sym.pow
    return base ** exp


def maximum(left, right):
    if isinstance(left, Symbol) and isinstance(right, Symbol):
        return _create("_maximum", [left, right], {})
    if isinstance(left, Symbol):
        return _create("_maximum_scalar", [left], {"scalar": float(right)})
    return _create("_maximum_scalar", [right], {"scalar": float(left)})


def minimum(left, right):
    if isinstance(left, Symbol) and isinstance(right, Symbol):
        return _create("_minimum", [left, right], {})
    if isinstance(left, Symbol):
        return _create("_minimum_scalar", [left], {"scalar": float(right)})
    return _create("_minimum_scalar", [right], {"scalar": float(left)})
