"""Network visualization (reference python/mxnet/visualization.py):
``print_summary`` table and graphviz ``plot_network``."""
from __future__ import annotations

from .base import MXNetError
from .symbol import Symbol

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Print a layer-by-layer summary with output shapes and param counts
    (reference visualization.py:print_summary)."""
    if not isinstance(symbol, Symbol):
        raise MXNetError("symbol must be a Symbol")
    if positions is None:
        positions = [0.44, 0.64, 0.74, 1.0]
    show_shape = shape is not None
    shape_dict = {}
    if show_shape:
        arg_shapes, _, _ = symbol.infer_shape(**shape)
        shape_dict = dict(zip(symbol.list_arguments(), arg_shapes))

    nodes = list(symbol._nodes())
    positions = [int(line_length * p) for p in positions]
    to_display = ["Layer (type)", "Output Shape", "Param #",
                  "Previous Layer"]

    def print_row(fields, positions):
        line = ""
        for field, pos in zip(fields, positions):
            line += str(field)
            line = line[:pos - 1]
            line += " " * (pos - len(line))
        print(line)

    print("_" * line_length)
    print_row(to_display, positions)
    print("=" * line_length)

    total_params = 0
    # per-node output shape via whole-graph inference
    out_shape_by_node = {}
    if show_shape:
        try:
            internals = symbol.get_internals()
            _, out_shapes, _ = internals.infer_shape(**shape)
            for (node, idx), s in zip(internals._outputs, out_shapes):
                out_shape_by_node.setdefault(id(node), {})[idx] = s
        except Exception:
            pass

    for node in nodes:
        if node.is_variable:
            continue
        name = node.name
        op_name = node.op.name if node.op is not None else "null"
        pre = [src.name for src, _ in node.inputs
               if not (src.is_variable and src.name.startswith(name))]
        cur_param = 0
        for src, _ in node.inputs:
            if src.is_variable and src.name in shape_dict and \
                    src.name != "data" and not src.name.endswith("label"):
                n = 1
                for d in shape_dict[src.name]:
                    n *= d
                cur_param += n
        out_s = ""
        if show_shape:
            s = out_shape_by_node.get(id(node), {}).get(0)
            if s is not None:
                out_s = "x".join(map(str, s))
        fields = ["%s(%s)" % (name, op_name), out_s, cur_param,
                  ",".join(pre[:3])]
        print_row(fields, positions)
        total_params += cur_param
    print("=" * line_length)
    print("Total params: {params}".format(params=total_params))
    print("_" * line_length)
    return total_params


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Build a graphviz digraph of the network (reference
    visualization.py:plot_network).  Requires the ``graphviz`` package."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise MXNetError("plot_network requires the graphviz python package")
    if not isinstance(symbol, Symbol):
        raise MXNetError("symbol must be a Symbol")
    node_attrs = node_attrs or {}
    node_attr = {"shape": "box", "fixedsize": "true", "width": "1.3",
                 "height": "0.8034", "style": "filled"}
    node_attr.update(node_attrs)
    dot = Digraph(name=title, format=save_format)

    fill = {
        "FullyConnected": "#fb8072", "Convolution": "#fb8072",
        "Deconvolution": "#fb8072", "Activation": "#ffffb3",
        "LeakyReLU": "#ffffb3", "BatchNorm": "#bebada",
        "Pooling": "#80b1d3", "Concat": "#fdb462", "Flatten": "#fdb462",
        "Reshape": "#fdb462", "SoftmaxOutput": "#b3de69",
    }
    for node in symbol._nodes():
        name = node.name
        if node.is_variable:
            if hide_weights and name != "data" and \
                    not name.endswith("label"):
                continue
            dot.node(name, label=name, shape="oval", style="filled",
                     fillcolor="#8dd3c7")
            continue
        op_name = node.op.name
        label = op_name
        attrs = node.op_attrs()
        if op_name == "Convolution":
            label = "Convolution\n%s/%s, %s" % (
                attrs.get("kernel", "?"), attrs.get("stride", "1"),
                attrs.get("num_filter", "?"))
        elif op_name == "FullyConnected":
            label = "FullyConnected\n%s" % attrs.get("num_hidden", "?")
        elif op_name == "Activation":
            label = "Activation\n%s" % attrs.get("act_type", "?")
        elif op_name == "Pooling":
            label = "Pooling\n%s, %s/%s" % (
                attrs.get("pool_type", "?"), attrs.get("kernel", "?"),
                attrs.get("stride", "1"))
        dot.node(name, label=label,
                 fillcolor=fill.get(op_name, "#fccde5"), **node_attr)
        for src, _idx in node.inputs:
            if src.is_variable and hide_weights and \
                    src.name != "data" and not src.name.endswith("label"):
                continue
            dot.edge(src.name, name)
    return dot
