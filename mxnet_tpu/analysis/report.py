"""Finding/Report containers shared by both analyzer levels.

Stdlib-only on purpose: ``tools/mxlint.py`` and the AST level must stay
importable and fast in contexts where no accelerator runtime exists
(pre-commit hooks, CI containers without a device plugin).

The JSON report format is a STABLE contract (``REPORT_VERSION``): CI and
bench diff reports across commits, so findings are emitted in a
deterministic order and no timing/host-specific data lives inside the
``findings`` array.
"""
from __future__ import annotations

import json

__all__ = ["Finding", "Report", "REPORT_VERSION"]

#: bump only with a migration note in docs/how_to/static_analysis.md
REPORT_VERSION = 1

_SEVERITIES = ("error", "warning")


class Finding(object):
    """One rule violation.

    ``rule`` is the stable kebab-case identifier (what inline
    suppressions name), ``message`` the human line, ``file``/``line`` the
    anchor when the rule has one (AST rules always do; graph rules point
    at traced source when jaxpr source info is available), and ``data``
    an optional JSON-serializable dict for machine consumers (byte
    counts, op tallies).
    """

    __slots__ = ("rule", "message", "file", "line", "severity", "data")

    def __init__(self, rule, message, file=None, line=None,
                 severity="error", data=None):
        if severity not in _SEVERITIES:
            raise ValueError("severity must be one of %s" % (_SEVERITIES,))
        self.rule = rule
        self.message = message
        self.file = file
        self.line = None if line is None else int(line)
        self.severity = severity
        self.data = data

    def sort_key(self):
        return (self.file or "", self.line or 0, self.rule, self.message)

    def to_dict(self):
        out = {"rule": self.rule, "severity": self.severity,
               "message": self.message}
        if self.file is not None:
            out["file"] = self.file
        if self.line is not None:
            out["line"] = self.line
        if self.data is not None:
            out["data"] = self.data
        return out

    def __repr__(self):
        loc = ""
        if self.file:
            loc = "%s:%s: " % (self.file, self.line if self.line else "?")
        return "%s[%s] %s" % (loc, self.rule, self.message)


class Report(object):
    """An ordered collection of findings plus scan metadata."""

    def __init__(self, tool="mxlint"):
        self.tool = tool
        self.findings = []
        self.files_scanned = 0
        self.stats = {}   # free-form machine data (collective tallies...)

    def add(self, *args, **kwargs):
        """``add(finding)`` or ``add(rule, message, ...)``."""
        if len(args) == 1 and isinstance(args[0], Finding) and not kwargs:
            self.findings.append(args[0])
        else:
            self.findings.append(Finding(*args, **kwargs))
        return self

    def extend(self, findings):
        for f in findings:
            self.add(f)
        return self

    def merge(self, other):
        self.findings.extend(other.findings)
        self.files_scanned += other.files_scanned
        for k, v in other.stats.items():
            self.stats.setdefault(k, v)
        return self

    @property
    def errors(self):
        return [f for f in self.findings if f.severity == "error"]

    @property
    def warnings(self):
        return [f for f in self.findings if f.severity == "warning"]

    def by_rule(self):
        out = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return dict(sorted(out.items()))

    @property
    def ok(self):
        return not self.findings

    def to_dict(self):
        return {
            "report_version": REPORT_VERSION,
            "tool": self.tool,
            "files_scanned": self.files_scanned,
            "summary": {"findings": len(self.findings),
                        "errors": len(self.errors),
                        "warnings": len(self.warnings),
                        "by_rule": self.by_rule()},
            "stats": self.stats,
            "findings": [f.to_dict()
                         for f in sorted(self.findings,
                                         key=Finding.sort_key)],
        }

    def to_json(self, indent=2):
        return json.dumps(self.to_dict(), indent=indent, sort_keys=True)

    def format_text(self):
        """Human-readable listing, one line per finding."""
        lines = []
        for f in sorted(self.findings, key=Finding.sort_key):
            lines.append(repr(f))
        lines.append("%d file(s) scanned, %d finding(s) (%d error, "
                     "%d warning)" % (self.files_scanned,
                                      len(self.findings),
                                      len(self.errors),
                                      len(self.warnings)))
        return "\n".join(lines)
