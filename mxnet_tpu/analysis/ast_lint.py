"""Level-2 static analysis: AST rules encoding this repo's invariants.

Pure stdlib (``ast``) — no jax, no package imports — so the CLI can lint
the tree in milliseconds and run where no accelerator runtime exists.

Rules (ids are what ``# mxlint: disable=<rule>`` names, inline or on the
line above):

- ``traced-host-call``: ``float()``/``bool()``/``.item()``/
  ``time.time()`` & friends inside a function that is passed to
  ``jax.jit`` (or decorated with it) — on a traced value these force a
  device sync or a tracer error, and even when they "work" they freeze a
  runtime value at trace time.
- ``lock-order``: the acquisition graph over the repo's known lock set
  (``threading.Lock``/``RLock`` attributes and module globals) contains
  a cycle — two code paths that take the same pair of locks in opposite
  orders will eventually deadlock a background thread.  Edges come from
  lexically nested ``with`` blocks plus one level of same-class method
  calls made while a lock is held.
- ``bare-except``: a bare ``except:`` swallows device errors,
  ``KeyboardInterrupt`` and watchdog/preemption ``SystemExit`` alike;
  catch a concrete type (``Exception`` at the broadest).
- ``env-direct-read``: an ``MXTPU_*``/``MXNET_*`` env var read through
  ``os.environ``/``os.getenv`` instead of ``base.get_env`` — bypasses
  the registry, so typos and undocumented knobs go unnoticed.
- ``env-unregistered``: a ``get_env`` read of a framework-prefixed name
  that no ``register_env`` call in the scanned tree (or the provided
  registry) declares — either a typo'd knob silently yielding its
  default, or a new knob missing its catalog row (and docs table).
"""
from __future__ import annotations

import ast
import os
import re

from .report import Finding, Report

__all__ = ["lint_paths", "collect_env_reads", "collect_registered",
           "collect_fault_points", "iter_py_files", "load_modules",
           "RULES", "ENV_PREFIXES"]

ENV_PREFIXES = ("MXTPU_", "MXNET_")

RULES = ("traced-host-call", "lock-order", "bare-except",
         "env-direct-read", "env-unregistered")

#: host calls that must not run on traced values
_HOST_CASTS = ("float", "bool")
_HOST_CLOCKS = ("time", "monotonic", "perf_counter", "process_time")

_SUPPRESS_RE = re.compile(
    r"#\s*mxlint:\s*disable(?:=([A-Za-z0-9_,\- ]+))?")

_ALL = object()


def iter_py_files(paths):
    """Expand files/directories into a sorted list of .py files."""
    out = []
    for path in paths:
        path = os.fspath(path)
        if os.path.isfile(path):
            out.append(path)
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__"
                                 and not d.startswith("."))
            for name in sorted(filenames):
                if name.endswith(".py"):
                    out.append(os.path.join(dirpath, name))
    return sorted(dict.fromkeys(out))


#: functions whose import aliases must be tracked (``from .base import
#: register_env as _register_env`` — metric.py's idiom — must still
#: register, and aliased get_env reads must still count as reads)
_TRACKED_FUNCS = ("register_env", "get_env", "getenv")


class _Module(object):
    """One parsed file plus its suppression map and import aliases."""

    def __init__(self, path, source):
        self.path = path
        self.tree = ast.parse(source, filename=path)
        self.suppress = {}
        for lineno, line in enumerate(source.splitlines(), 1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = m.group(1)
            self.suppress[lineno] = _ALL if rules is None else \
                {r.strip() for r in rules.split(",") if r.strip()}
        # canonical function name -> local names it is bound to here
        self.aliases = {name: {name} for name in _TRACKED_FUNCS}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    if alias.name in self.aliases and alias.asname:
                        self.aliases[alias.name].add(alias.asname)

    def is_func(self, node, name):
        """Does a call's ``func`` node refer to tracked function
        ``name`` — directly, via attribute, or via an import alias?"""
        if isinstance(node, ast.Name):
            return node.id in self.aliases.get(name, (name,))
        return isinstance(node, ast.Attribute) and node.attr == name

    def suppressed(self, line, rule):
        """True when ``rule`` is disabled on ``line`` (comment inline or
        on the line directly above)."""
        for ln in (line, (line or 0) - 1):
            rules = self.suppress.get(ln)
            if rules is _ALL or (rules is not None and rule in rules):
                return True
        return False


def _is_name_or_attr(node, name):
    return (isinstance(node, ast.Name) and node.id == name) or \
        (isinstance(node, ast.Attribute) and node.attr == name)


# ---------------------------------------------------------------------------
# pass 1: cross-file constant / registration tables
# ---------------------------------------------------------------------------

def _collect_constants(modules):
    """``NAME -> "MXTPU_..."`` for module-level string assignments and
    ``NAME = register_env("MXTPU_...")`` forms, keyed by the bare name so
    ``resilience.ENV_RESUME``-style attribute references resolve too
    (env constant names are unique across this repo)."""
    consts = {}
    registered = set()
    for mod in modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and \
                    mod.is_func(node.func, "register_env") and \
                    node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                registered.add(node.args[0].value)
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            if not isinstance(target, ast.Name):
                continue
            value = node.value
            if isinstance(value, ast.Constant) and \
                    isinstance(value.value, str):
                consts[target.id] = value.value
            elif isinstance(value, ast.Call) and \
                    mod.is_func(value.func, "register_env") and \
                    value.args and \
                    isinstance(value.args[0], ast.Constant) and \
                    isinstance(value.args[0].value, str):
                consts[target.id] = value.args[0].value
    return consts, registered


def _resolve_env_name(node, consts):
    """Best-effort string value of an env-name argument."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    if isinstance(node, ast.Attribute):
        return consts.get(node.attr)
    return None


# ---------------------------------------------------------------------------
# env rules
# ---------------------------------------------------------------------------

def _is_environ(node):
    """``os.environ`` (or ``environ`` imported bare)."""
    return _is_name_or_attr(node, "environ")


def _env_reads(mod, consts):
    """Yield (name, line, via) for every env read in one module:
    via='get_env' for registry-routed reads, 'direct' for
    os.environ/os.getenv."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            func = node.func
            if mod.is_func(func, "get_env") and node.args:
                name = _resolve_env_name(node.args[0], consts)
                if name:
                    yield name, node.lineno, "get_env"
            elif mod.is_func(func, "getenv") and node.args:
                name = _resolve_env_name(node.args[0], consts)
                if name:
                    yield name, node.lineno, "direct"
            elif isinstance(func, ast.Attribute) and \
                    func.attr in ("get", "setdefault") and \
                    _is_environ(func.value) and node.args:
                name = _resolve_env_name(node.args[0], consts)
                if name:
                    yield name, node.lineno, "direct"
        elif isinstance(node, ast.Subscript) and \
                isinstance(node.ctx, ast.Load) and \
                _is_environ(node.value):
            name = _resolve_env_name(node.slice, consts)
            if name:
                yield name, node.lineno, "direct"


def _lint_env(mod, consts, registered, report):
    for name, line, via in _env_reads(mod, consts):
        if not name.startswith(ENV_PREFIXES):
            continue
        if via == "direct":
            if not mod.suppressed(line, "env-direct-read"):
                report.add("env-direct-read",
                           "%s read through os.environ — route it "
                           "through base.get_env so the registry (and "
                           "docs/env_vars.md sync) sees it" % name,
                           file=mod.path, line=line)
            continue
        if name not in registered and \
                not mod.suppressed(line, "env-unregistered"):
            report.add("env-unregistered",
                       "get_env(%r) reads a knob no register_env() "
                       "declares — typo, or missing from the "
                       "base.ENV_REGISTRY catalog (and docs/"
                       "env_vars.md)" % name,
                       file=mod.path, line=line)


# ---------------------------------------------------------------------------
# traced-host rule
# ---------------------------------------------------------------------------

def _is_jit(node):
    return _is_name_or_attr(node, "jit")


def _jitted_function_names(tree):
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit(node.func) and \
                node.args and isinstance(node.args[0], ast.Name):
            names.add(node.args[0].id)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit(dec):
                    names.add(node.name)
                elif isinstance(dec, ast.Call) and (
                        _is_jit(dec.func) or
                        (_is_name_or_attr(dec.func, "partial") and
                         dec.args and _is_jit(dec.args[0]))):
                    names.add(node.name)
    return names


def _decorated_jit(node):
    for dec in node.decorator_list:
        if _is_jit(dec) or (isinstance(dec, ast.Call) and (
                _is_jit(dec.func) or
                (_is_name_or_attr(dec.func, "partial") and
                 dec.args and _is_jit(dec.args[0])))):
            return True
    return False


def _lint_traced_host(mod, report):
    jitted = _jitted_function_names(mod.tree)
    if not jitted:
        return
    # class METHODS are referenced as self.x / obj.x, never as the bare
    # Name a `jit(step, ...)` call passes — a method that merely shares
    # a jitted closure's name (SPMDTrainer.step vs the inner fused
    # `step`) must not be scanned.  Methods jitted via their own
    # decorator are still covered by _decorated_jit below.
    methods = {fn for node in ast.walk(mod.tree)
               if isinstance(node, ast.ClassDef)
               for fn in node.body
               if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        if node.name not in jitted and not _decorated_jit(node):
            continue
        if node in methods and not _decorated_jit(node):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            func = sub.func
            bad = None
            if isinstance(func, ast.Name) and func.id in _HOST_CASTS \
                    and sub.args and not isinstance(sub.args[0],
                                                    ast.Constant):
                bad = "%s() forces a traced value to the host" % func.id
            elif isinstance(func, ast.Attribute) and \
                    func.attr == "item" and not sub.args:
                bad = ".item() forces a device sync"
            elif isinstance(func, ast.Attribute) and \
                    func.attr in _HOST_CLOCKS and \
                    _is_name_or_attr(func.value, "time"):
                bad = "time.%s() reads the host clock at trace time " \
                    "(a constant in the compiled step)" % func.attr
            if bad and not mod.suppressed(sub.lineno,
                                          "traced-host-call"):
                report.add("traced-host-call",
                           "inside %r (passed to jax.jit): %s"
                           % (node.name, bad),
                           file=mod.path, line=sub.lineno)


# ---------------------------------------------------------------------------
# lock-order rule
# ---------------------------------------------------------------------------

_LOCK_TYPES = ("Lock", "RLock")


def _is_lock_ctor(node):
    return isinstance(node, ast.Call) and any(
        _is_name_or_attr(node.func, t) for t in _LOCK_TYPES)


class _LockScan(object):
    """Per-module lock definitions and acquisition edges.

    Lock identity: ``(module, class, attr)`` for ``self.X`` locks,
    ``(module, None, name)`` for module globals.  Edges are added for a
    ``with`` nested (lexically) under another ``with``, and — one level
    deep — for same-class method calls made while a lock is held, using
    each method's transitive same-class acquisition set.
    """

    def __init__(self, mod):
        self.mod = mod
        base = os.path.basename(mod.path)
        self.modkey = base
        self.locks = set()
        self.method_acquires = {}   # (class, method) -> set(lock ids)
        self.method_calls = {}      # (class, method) -> set(method names)
        self.edges = {}             # (a, b) -> (file, line)
        self._collect_defs()

    def _lock_id(self, cls, attr):
        return "%s::%s.%s" % (self.modkey, cls or "<module>", attr)

    def _collect_defs(self):
        for node in self.mod.tree.body:
            if isinstance(node, ast.Assign) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    _is_lock_ctor(node.value):
                self.locks.add(self._lock_id(None, node.targets[0].id))
            if isinstance(node, ast.ClassDef):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign) and \
                            len(sub.targets) == 1 and \
                            isinstance(sub.targets[0], ast.Attribute) and \
                            isinstance(sub.targets[0].value, ast.Name) and \
                            sub.targets[0].value.id == "self" and \
                            _is_lock_ctor(sub.value):
                        self.locks.add(
                            self._lock_id(node.name, sub.targets[0].attr))

    def _resolve(self, expr, cls):
        """Lock id for a with-item context expression, or None."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and cls is not None:
            lid = self._lock_id(cls, expr.attr)
            return lid if lid in self.locks else None
        if isinstance(expr, ast.Name):
            lid = self._lock_id(None, expr.id)
            return lid if lid in self.locks else None
        return None

    def scan(self):
        for node in self.mod.tree.body:
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, ast.FunctionDef):
                        self._scan_function(item, node.name)
            elif isinstance(node, ast.FunctionDef):
                self._scan_function(node, None)
        self._expand_method_calls()
        return self.edges

    def _scan_function(self, fn, cls):
        acquires = set()
        calls = set()

        def walk(node, held):
            if isinstance(node, ast.With):
                got = []
                for item in node.items:
                    lid = self._resolve(item.context_expr, cls)
                    if lid is None:
                        continue
                    # a multi-item ``with a, b:`` acquires sequentially —
                    # locks earlier in the SAME statement are already
                    # held when this one is taken
                    for h in held + got:
                        if h != lid:
                            self.edges.setdefault(
                                (h, lid),
                                (self.mod.path, node.lineno))
                    got.append(lid)
                    acquires.add(lid)
                held = held + got
                for child in node.body:
                    walk(child, held)
                return
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "self":
                calls.add((node.func.attr, node.lineno, tuple(held)))
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    # nested defs run later (threads/callbacks) — their
                    # acquisitions are not nested under the current hold
                    walk_body_fresh(child)
                    continue
                walk(child, held)

        def walk_body_fresh(fn_node):
            for child in fn_node.body:
                walk(child, [])

        walk_body_fresh(fn)
        if cls is not None:
            self.method_acquires[(cls, fn.name)] = acquires
            self.method_calls[(cls, fn.name)] = calls

    def _transitive_acquires(self, cls, name, seen):
        key = (cls, name)
        if key in seen:
            return set()
        seen.add(key)
        out = set(self.method_acquires.get(key, ()))
        for callee, _line, _held in self.method_calls.get(key, ()):
            out |= self._transitive_acquires(cls, callee, seen)
        return out

    def _expand_method_calls(self):
        for (cls, name), calls in self.method_calls.items():
            for callee, line, held in calls:
                if not held:
                    continue
                for lid in self._transitive_acquires(cls, callee, set()):
                    for h in held:
                        if h != lid:
                            self.edges.setdefault(
                                (h, lid), (self.mod.path, line))


def _find_cycles(edges):
    """Cycles in the acquisition digraph, deduped by node set."""
    graph = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    cycles = []
    seen_sets = set()

    def dfs(node, path, on_path):
        for nxt in graph.get(node, ()):
            if nxt in on_path:
                cyc = path[path.index(nxt):] + [nxt]
                key = frozenset(cyc)
                if key not in seen_sets:
                    seen_sets.add(key)
                    cycles.append(cyc)
                continue
            dfs(nxt, path + [nxt], on_path | {nxt})

    for start in sorted(graph):
        dfs(start, [start], {start})
    return cycles


def _lint_locks(modules, report):
    edges = {}
    for mod in modules:
        edges.update(_LockScan(mod).scan())
    for cyc in _find_cycles(edges):
        first_edge = (cyc[0], cyc[1]) if len(cyc) > 1 else None
        file, line = edges.get(first_edge, (None, None))
        mod = next((m for m in modules if m.path == file), None)
        if mod is not None and mod.suppressed(line, "lock-order"):
            continue
        report.add("lock-order",
                   "lock acquisition cycle: %s — two threads taking "
                   "these in opposite orders will deadlock"
                   % " -> ".join(cyc),
                   file=file, line=line)


# ---------------------------------------------------------------------------
# bare-except rule
# ---------------------------------------------------------------------------

def _lint_bare_except(mod, report):
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None \
                and not mod.suppressed(node.lineno, "bare-except"):
            report.add("bare-except",
                       "bare 'except:' swallows device errors, "
                       "KeyboardInterrupt and watchdog SystemExit — "
                       "catch a concrete type",
                       file=mod.path, line=node.lineno)


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def _load_modules(paths, cache=None, overrides=None):
    """Parse every .py file under ``paths`` into :class:`_Module`\\ s.

    ``cache`` (``{abspath: _Module}``) is shared across the lint passes
    so the CLI parses each file exactly once per run.  ``overrides``
    maps paths to replacement SOURCE TEXT — the contract-lint regression
    fixtures use it to lint a file "as if" an old bug were still there
    without touching the tree.
    """
    modules, broken = [], []
    overrides = {os.path.abspath(p): src
                 for p, src in (overrides or {}).items()}
    for path in iter_py_files(paths):
        full = os.path.abspath(path)
        if cache is not None and path in cache and full not in overrides:
            modules.append(cache[path])
            continue
        try:
            if full in overrides:
                modules.append(_Module(path, overrides[full]))
                continue
            with open(path, "r", encoding="utf-8") as f:
                mod = _Module(path, f.read())
            modules.append(mod)
            if cache is not None:
                cache[path] = mod
        except (OSError, SyntaxError) as e:
            broken.append((path, e))
    return modules, broken


#: public names for the machinery the whole-repo passes (race_lint,
#: contract_lint) build on — one parser, one suppression syntax, one
#: lock model across every level
load_modules = _load_modules
Module = _Module
LockScan = _LockScan
collect_constants = _collect_constants
resolve_const_string = _resolve_env_name


def collect_registered(paths, cache=None):
    """Env names declared by ``register_env`` calls under ``paths`` —
    the purely static registry (what the CLI uses instead of importing
    the package)."""
    modules, _ = _load_modules(paths, cache=cache)
    return _collect_constants(modules)[1]


def collect_env_reads(paths):
    """``name -> [(file, line, via)]`` for every resolvable
    ``MXTPU_*``/``MXNET_*`` env read under ``paths`` (the doc-sync
    oracle used by tests and the registry audit)."""
    modules, _ = _load_modules(paths)
    consts, _ = _collect_constants(modules)
    out = {}
    for mod in modules:
        for name, line, via in _env_reads(mod, consts):
            if name.startswith(ENV_PREFIXES):
                out.setdefault(name, []).append((mod.path, line, via))
    return out


#: ``resilience.FaultInjector`` consume methods — a call
#: ``faults.<method>("<point>")`` IS a production fault site
_FAULT_READS = ("maybe_fail", "maybe_trip", "maybe_hang", "consume")
#: arming entry points (tests/tools side of the contract)
_FAULT_ARMS = ("arm", "arm_hang")


def _param_string_defaults(node, name):
    """String defaults of parameters called ``name`` on a function def
    (``atomic_path(path, fault_point="checkpoint_write")``)."""
    out = []
    a = node.args
    positional = list(a.posonlyargs) + list(a.args)
    for param, default in zip(positional[len(positional)
                                         - len(a.defaults):], a.defaults):
        if param.arg == name and isinstance(default, ast.Constant) \
                and isinstance(default.value, str):
            out.append((default.value, default.lineno))
    for param, default in zip(a.kwonlyargs, a.kw_defaults):
        if default is not None and param.arg == name and \
                isinstance(default, ast.Constant) and \
                isinstance(default.value, str):
            out.append((default.value, default.lineno))
    return out


def collect_fault_points(paths, arms=False, cache=None):
    """``point -> [(file, line, via)]`` for every statically resolvable
    fault-injection site under ``paths`` — the mechanical registry that
    ``tools/mxlint.py --list-faults`` prints and the docs-sync test
    asserts against ``docs/how_to/fault_tolerance.md``.

    A site is a ``faults.maybe_fail/maybe_trip/maybe_hang/consume`` call
    whose point resolves statically (string literal, or a module-level
    string constant like ``SERVE_FORWARD_FAULT``), plus the
    ``fault_point=`` routing idiom of ``resilience.atomic_path`` /
    ``atomic_write`` (both the call-site keyword strings and the
    parameter defaults).  With ``arms=True`` it instead collects
    ``faults.arm``/``arm_hang`` call points — the test/tool side, used
    to catch typo'd armings that would silently never fire.
    """
    modules, _ = _load_modules(paths, cache=cache)
    consts, _ = _collect_constants(modules)
    methods = _FAULT_ARMS if arms else _FAULT_READS
    out = {}

    def add(name, mod, line, via):
        out.setdefault(name, []).append((mod.path, line, via))

    for mod in modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                func = node.func
                if isinstance(func, ast.Attribute) and \
                        func.attr in methods and node.args:
                    name = _resolve_env_name(node.args[0], consts)
                    if name:
                        add(name, mod, node.lineno, func.attr)
                if not arms:
                    for kw in node.keywords:
                        if kw.arg == "fault_point" and \
                                isinstance(kw.value, ast.Constant) and \
                                isinstance(kw.value.value, str):
                            add(kw.value.value, mod, node.lineno,
                                "fault_point=")
            elif not arms and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for name, line in _param_string_defaults(
                        node, "fault_point"):
                    add(name, mod, line, "fault_point=")
    return out


def lint_paths(paths, env_registry=None, select=None, cache=None):
    """Run every AST rule over ``paths`` (files or directories).

    ``env_registry``: extra registered env names to union with the
    ``register_env`` calls found statically in the scanned tree (pass
    ``set(mxnet_tpu.base.ENV_REGISTRY)`` when linting files outside the
    package, e.g. tools/).  ``select``: restrict to a subset of RULES.
    ``cache``: shared ``{path: _Module}`` parse cache (see
    :func:`load_modules`).
    """
    rules = set(RULES if select is None else select)
    report = Report(tool="mxlint.ast")
    modules, broken = _load_modules(paths, cache=cache)
    report.files_scanned = len(modules)
    for path, err in broken:
        report.add("parse-error", "cannot parse: %s" % (err,), file=path)
    consts, registered = _collect_constants(modules)
    if env_registry:
        registered |= set(env_registry)
    for mod in modules:
        if "env-direct-read" in rules or "env-unregistered" in rules:
            _lint_env(mod, consts, registered, report)
        if "traced-host-call" in rules:
            _lint_traced_host(mod, report)
        if "bare-except" in rules:
            _lint_bare_except(mod, report)
    if "lock-order" in rules:
        _lint_locks(modules, report)
    if select is not None:
        report.findings = [f for f in report.findings
                           if f.rule in rules or f.rule == "parse-error"]
    return report
