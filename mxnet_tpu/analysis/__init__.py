"""mxlint — static analysis for the fused step graph and the repo's own
concurrency/knob invariants.

Two levels (the NNVM-graph-pass analog for this codebase):

- :mod:`~mxnet_tpu.analysis.graph_lint` — lint one jitted step program
  (donation coverage, host callbacks, a collective audit, dtype drift).
  Runs automatically at the first compile inside ``SPMDTrainer`` when
  ``MXTPU_ANALYZE=1`` (warn) or ``strict`` (raise), and on demand via
  ``SPMDTrainer.analyze`` / :func:`graph_lint.lint_jit`.
- :mod:`~mxnet_tpu.analysis.ast_lint` — AST rules over the source tree
  (traced-host calls in jitted fns, lock-order cycles, bare excepts,
  env-registry discipline).  ``tools/mxlint.py`` is the CLI.
- level 3, cross-module: :mod:`~mxnet_tpu.analysis.race_lint` (shared
  mutations across thread roots without a held lock, check-then-act)
  and :mod:`~mxnet_tpu.analysis.contract_lint` (drift between the
  producers and consumers of every declared cross-process JSON
  surface).  Same CLI, same suppression syntax.

See docs/how_to/static_analysis.md for the rule catalog and suppression
syntax (``# mxlint: disable=<rule>``).
"""
from __future__ import annotations

from ..base import register_env
from .report import Finding, Report, REPORT_VERSION
from . import ast_lint
from . import contract_lint
from . import fixtures
from . import graph_lint
from . import race_lint

__all__ = ["Finding", "Report", "REPORT_VERSION", "ast_lint",
           "contract_lint", "fixtures", "graph_lint", "race_lint",
           "ENV_ANALYZE", "ENV_ANALYZE_REPORT"]

ENV_ANALYZE = register_env(
    "MXTPU_ANALYZE",
    doc="1 runs the graph lint at the first compile inside SPMDTrainer "
        "and warns on findings; 'strict' raises MXNetError instead")
ENV_ANALYZE_REPORT = register_env(
    "MXTPU_ANALYZE_REPORT", scope="tools",
    doc="Path for the machine-readable JSON report written by "
        "tools/mxlint.py (same as --json)")
