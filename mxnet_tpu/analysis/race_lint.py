"""Level-3 static analysis, pass 1: whole-repo shared-mutation lint.

The fleet/serving/resilience tier is a dozen modules of threads mutating
shared dicts, and until now only lock-order *cycles* were linted — not
lock *coverage*.  This pass closes that gap with two rules (pure stdlib,
same CLI/suppression discipline as :mod:`ast_lint`):

- ``repo-shared-mutation``: for every class that owns a thread root
  (``threading.Thread(target=self.m)``, ``Timer``, an HTTP handler
  method calling into it, a ``Supervisor`` callback), compute which
  ``self.<attr>`` each concurrency domain COMPOUND-mutates (``+=``,
  ``d[k] = v``, ``.append``/``.update``/``.pop``/...), intersect the
  domains, and flag any mutation not covered by a held lock.  Plain
  rebinds (``self.x = expr``) are exempt — a single reference store is
  atomic under the GIL; it is the read-modify-write forms that interleave.
- ``repo-check-then-act``: ``if k in self.d: ... self.d[k]`` sequences
  on a shared attr outside a lock — the gap between the test and the
  act is where another thread deletes the key.

Design notes (what keeps the pass honest on this tree):

- **Aliases**: ``view = self._views.get(rid)`` followed by
  ``view.probes += 1`` mutates ``self._views``'s contents; a per-
  function alias map tracks one level of derivation (subscript,
  ``.get``, ``for ... in self.d.items()``), so the router's per-replica
  counter bumps are seen.
- **Transitive lock coverage**: ``check_once`` doing ``with self._lock:
  return self._check_locked(...)`` protects every mutation inside
  ``_check_locked`` (and its callees) — a private method is *protected*
  when every same-class call site holds a lock or sits in a protected
  method (a fixed point, same spirit as ``_LockScan``'s transitive
  acquisition sets).
- **Thread-safe types are not shared state**: attrs initialized to
  ``threading.Event``/``Condition``/``Semaphore``/``queue.Queue`` (and
  the locks themselves) are internally synchronized and never flagged.
"""
from __future__ import annotations

import ast

from .report import Report
from .ast_lint import load_modules

__all__ = ["lint_modules", "lint_paths", "RULES"]

RULES = ("repo-shared-mutation", "repo-check-then-act")

#: container methods that mutate the receiver in place
_MUTATORS = frozenset((
    "append", "appendleft", "add", "insert", "extend", "update",
    "pop", "popitem", "remove", "discard", "clear", "setdefault",
    "sort", "reverse",
))

#: constructors whose instances synchronize internally (never "shared
#: mutable state" for this rule); Lock/RLock/Condition double as locks
_SAFE_CTORS = frozenset((
    "Lock", "RLock", "Event", "Condition", "Semaphore",
    "BoundedSemaphore", "Barrier", "local", "Queue", "LifoQueue",
    "PriorityQueue", "SimpleQueue",
))
_LOCK_CTORS = frozenset(("Lock", "RLock", "Condition"))

#: keyword names whose ``self.m`` value is a callback invoked from a
#: foreign thread (Thread/Timer targets, Supervisor's on_exit, ...)
_CALLBACK_KWARGS = frozenset(("target", "function", "on_exit",
                              "callback", "cb"))


def _callee_name(func):
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _is_self_attr(node):
    return isinstance(node, ast.Attribute) and \
        isinstance(node.value, ast.Name) and node.value.id == "self"


class _ClassInfo(object):
    """One class's methods, locks, and thread-safe attrs."""

    def __init__(self, mod, node):
        self.mod = mod
        self.node = node
        self.name = node.name
        self.methods = {}
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[item.name] = item
        self.locks = set()
        self.safe_attrs = set()
        for sub in ast.walk(node):
            if not (isinstance(sub, ast.Assign) and
                    len(sub.targets) == 1 and
                    _is_self_attr(sub.targets[0])):
                continue
            value = sub.value
            if isinstance(value, ast.Call):
                ctor = _callee_name(value.func)
                if ctor in _LOCK_CTORS:
                    self.locks.add(sub.targets[0].attr)
                if ctor in _SAFE_CTORS:
                    self.safe_attrs.add(sub.targets[0].attr)
            elif isinstance(value, (ast.List, ast.Tuple, ast.ListComp)):
                # a container OF synchronized objects (the prefetcher's
                # [threading.Event() ...] handshake lists) is itself
                # only ever indexed, each element synchronizing
                elts = [value.elt] if isinstance(value, ast.ListComp) \
                    else value.elts
                if elts and all(
                        isinstance(e, ast.Call) and
                        _callee_name(e.func) in _SAFE_CTORS
                        for e in elts):
                    self.safe_attrs.add(sub.targets[0].attr)

    def is_handler(self):
        """An HTTP request handler: its do_* methods run on server
        threads and whatever they call into runs there too."""
        for base in self.node.bases:
            name = _callee_name(base) or ""
            if name.endswith("HTTPRequestHandler"):
                return True
        return False


class _Facts(object):
    """What one function does: mutations, reads, same-class calls,
    check-then-act sites — each tagged with whether a lock was held."""

    __slots__ = ("mutations", "reads", "calls", "cta")

    def __init__(self):
        self.mutations = []   # (attr, line, locked, how)
        self.reads = set()    # attr names touched (read OR written)
        self.calls = []       # (method name, line, locked)
        self.cta = []         # (attr, line, locked)

    @property
    def touched(self):
        return self.reads | {m[0] for m in self.mutations}


def _lockish(expr, cls):
    """Is a ``with`` context expression a lock?  ``self.X`` for a known
    class lock, else any name/attr that *looks* like one (``_lock``,
    ``router._lock`` — cross-object locking is deliberate in this tree
    and still counts as "a lock is held")."""
    e = expr
    if isinstance(e, ast.Call):
        e = e.func
    if _is_self_attr(e) and e.attr in cls.locks:
        return True
    if isinstance(e, ast.Attribute):
        return "lock" in e.attr.lower() or e.attr in ("mu", "mutex")
    if isinstance(e, ast.Name):
        return "lock" in e.id.lower() or e.id in ("mu", "mutex")
    return False


def _scan_function(fn, cls, skip_defs, aliases=None):
    """Collect :class:`_Facts` for one function body.

    ``skip_defs``: nested defs that are thread roots — scanned
    separately as their own domains, not as part of this body.
    ``aliases`` seeds the alias map (a nested root inherits its
    enclosing function's aliases — closure variables still refer to the
    same objects on the new thread).
    """
    facts = _Facts()
    aliases = dict(aliases or {})
    fresh = set()   # attrs (re)constructed in THIS function: stores
    #                 that follow are initialization, not sharing

    def base_attr(expr):
        """The ``self`` attr an expression reads from / derives from:
        ``self.a``/``self.a[k]``/``self.a.b``/``alias[k]`` -> ``a``.
        Calls are only peeled through element ACCESSORS (``.get``,
        ``.items``, ...) — an arbitrary method call returns a fresh
        object, not a view into the attr."""
        e = expr
        chain = []
        while True:
            if isinstance(e, ast.Subscript):
                e = e.value
            elif isinstance(e, ast.Call):
                func = e.func
                if isinstance(func, ast.Name) and \
                        func.id in ("list", "tuple", "sorted") and \
                        e.args:
                    # element-preserving wrappers: list(d.items())
                    # still yields the live values
                    e = e.args[0]
                    continue
                if not (isinstance(func, ast.Attribute) and func.attr
                        in ("get", "setdefault", "items", "values",
                            "keys")):
                    return None
                e = func
            elif isinstance(e, ast.Attribute):
                chain.append(e.attr)
                e = e.value
            else:
                break
        if isinstance(e, ast.Name):
            if e.id == "self" and chain:
                return chain[-1]
            if e.id in aliases:
                return aliases[e.id]
        return None

    def bind_names(target, attr):
        for node in ast.walk(target):
            if isinstance(node, ast.Name):
                aliases[node.id] = attr

    def note_reads(node):
        for sub in ast.walk(node):
            if _is_self_attr(sub):
                facts.reads.add(sub.attr)

    def mutation(attr, node, locked, how):
        if attr is not None and attr not in fresh:
            facts.mutations.append((attr, node.lineno, locked, how))

    def scan_if(node, locked):
        """``if k in self.d: ... self.d[k] ...`` — same attr, same key
        expression, no lock between the test and the act."""
        test = node.test
        if not (isinstance(test, ast.Compare) and len(test.ops) == 1
                and isinstance(test.ops[0], (ast.In, ast.NotIn))):
            return
        attr = base_attr(test.comparators[0])
        if attr is None:
            return
        key = ast.dump(test.left)
        for stmt in node.body + node.orelse:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Subscript) and \
                        base_attr(sub.value) == attr and \
                        ast.dump(sub.slice) == key:
                    facts.cta.append((attr, node.lineno, locked))
                    return

    def walk(node, locked):
        if isinstance(node, ast.With):
            note_reads(node.items[0].context_expr)
            got = locked or any(_lockish(item.context_expr, cls)
                                for item in node.items)
            for child in node.body:
                walk(child, got)
            return
        if isinstance(node, ast.Assign):
            note_reads(node.value)
            src = base_attr(node.value)
            for target in node.targets:
                if _is_self_attr(target) and isinstance(
                        node.value, (ast.Call, ast.Dict, ast.List,
                                     ast.ListComp, ast.DictComp,
                                     ast.Set, ast.Tuple)):
                    # self.x = <fresh object>: later stores into it in
                    # this function configure the new object before
                    # anything else can have grabbed a reference
                    fresh.add(target.attr)
                elif isinstance(target, ast.Name) and src is not None:
                    aliases[target.id] = src
                elif isinstance(target, ast.Subscript):
                    mutation(base_attr(target.value), node, locked,
                             "[...] = store")
                elif isinstance(target, ast.Attribute) and \
                        not _is_self_attr(target):
                    # x.field = v on an alias / chained attr; a DIRECT
                    # self.x = v is a plain (atomic) rebind — exempt
                    mutation(base_attr(target.value), node, locked,
                             ".%s = store" % target.attr)
                elif isinstance(target, (ast.Tuple, ast.List)) and \
                        src is not None:
                    bind_names(target, src)
            note_reads(node)
            return
        if isinstance(node, ast.AugAssign):
            note_reads(node)
            target = node.target
            if _is_self_attr(target):
                mutation(target.attr, node, locked,
                         "augmented assign (read-modify-write)")
            elif isinstance(target, (ast.Subscript, ast.Attribute)):
                mutation(base_attr(target.value), node, locked,
                         "augmented assign (read-modify-write)")
            return
        if isinstance(node, ast.Delete):
            for target in node.targets:
                if isinstance(target, (ast.Subscript, ast.Attribute)) \
                        and not _is_self_attr(target):
                    mutation(base_attr(target.value), node, locked,
                             "del")
            note_reads(node)
            return
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute):
                if _is_self_attr(func):
                    facts.calls.append((func.attr, node.lineno, locked))
                elif func.attr in _MUTATORS:
                    mutation(base_attr(func.value), node, locked,
                             ".%s()" % func.attr)
            note_reads(node)
            for child in ast.iter_child_nodes(node):
                walk(child, locked)
            return
        if isinstance(node, ast.For):
            note_reads(node.iter)
            src = base_attr(node.iter)
            if src is not None:
                bind_names(node.target, src)
            for child in node.body + node.orelse:
                walk(child, locked)
            return
        if isinstance(node, ast.If):
            scan_if(node, locked)
            note_reads(node.test)
            walk(node.test, locked)
            for child in node.body + node.orelse:
                walk(child, locked)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node in skip_defs:
                return
            # a nested def runs later (callback) — locks held NOW are
            # not held THEN
            for child in node.body:
                walk(child, False)
            return
        if isinstance(node, ast.Lambda):
            walk(node.body, False)
            return
        if _is_self_attr(node):
            facts.reads.add(node.attr)
        for child in ast.iter_child_nodes(node):
            walk(child, locked)

    for child in fn.body:
        walk(child, False)
    return facts, aliases


class _ClassScan(object):
    """Concurrency-domain analysis of one class."""

    def __init__(self, cls, handler_roots):
        self.cls = cls
        # thread roots: {entry id: (display name, function node,
        #                           inherited aliases or None)}
        self.roots = {}
        self._nested_roots = set()
        self._discover_roots(handler_roots)
        self.facts = {}
        self._enclosing_aliases = {}
        for name, fn in cls.methods.items():
            facts, aliases = _scan_function(fn, cls, self._nested_roots)
            self.facts[name] = (facts, fn)
            self._enclosing_aliases[name] = aliases
        for entry, (label, fn, encl) in list(self.roots.items()):
            if entry in self.facts:
                continue
            seed = self._enclosing_aliases.get(encl, {})
            facts, _ = _scan_function(fn, cls, self._nested_roots,
                                      aliases=seed)
            self.facts[entry] = (facts, fn)

    def _discover_roots(self, handler_roots):
        cls = self.cls
        for mname, method in cls.methods.items():
            nested = {n.name: n for n in ast.walk(method)
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))
                      and n is not method}
            for node in ast.walk(method):
                if not isinstance(node, ast.Call):
                    continue
                callee = _callee_name(node.func)
                targets = []
                if callee == "Thread":
                    targets = [kw.value for kw in node.keywords
                               if kw.arg == "target"] or node.args[:1]
                elif callee == "Timer":
                    targets = [kw.value for kw in node.keywords
                               if kw.arg == "function"] or \
                        node.args[1:2]
                else:
                    targets = [kw.value for kw in node.keywords
                               if kw.arg in _CALLBACK_KWARGS]
                for tgt in targets:
                    if _is_self_attr(tgt) and tgt.attr in cls.methods:
                        self.roots.setdefault(tgt.attr,
                                              (tgt.attr,
                                               cls.methods[tgt.attr],
                                               None))
                    elif isinstance(tgt, ast.Name) and \
                            tgt.id in nested:
                        entry = "%s.<%s>" % (mname, tgt.id)
                        self.roots.setdefault(
                            entry, (entry, nested[tgt.id], mname))
                        self._nested_roots.add(nested[tgt.id])
        for mname in handler_roots:
            if mname in cls.methods:
                self.roots.setdefault(
                    mname, ("%s (via HTTP handler)" % mname,
                            cls.methods[mname], None))
        if cls.is_handler():
            for mname in cls.methods:
                if mname.startswith("do_"):
                    self.roots.setdefault(
                        mname, (mname, cls.methods[mname], None))

    # -- call graph / domains ---------------------------------------------

    def _closure(self, entries):
        seen = set()
        frontier = list(entries)
        while frontier:
            name = frontier.pop()
            if name in seen or name not in self.facts:
                continue
            seen.add(name)
            for callee, _line, _locked in self.facts[name][0].calls:
                if callee not in seen:
                    frontier.append(callee)
        return seen

    def domains(self):
        """``[(label, member function ids)]`` — one per thread root
        plus the external ("main") domain spanning the public API."""
        out = []
        for entry, (label, _fn, _encl) in sorted(self.roots.items()):
            out.append(("thread:%s" % label, self._closure([entry])))
        public = [m for m in self.cls.methods
                  if not m.startswith("_")]
        members = self._closure(public)
        if members:
            out.append(("main", members))
        return out

    def protected(self):
        """Private methods whose every same-class call site holds a
        lock (directly, or via an already-protected caller) — their
        bodies run under the caller's lock, the fixed point of the
        check_once -> _check_locked -> _promote idiom."""
        sites = {}
        for caller, (facts, _fn) in self.facts.items():
            for callee, _line, locked in facts.calls:
                sites.setdefault(callee, []).append((caller, locked))
        prot = set()
        changed = True
        while changed:
            changed = False
            for name in self.facts:
                if name in prot or not name.startswith("_") or \
                        name in self.roots or name not in sites:
                    continue
                if all(locked or caller in prot
                       for caller, locked in sites[name]):
                    prot.add(name)
                    changed = True
        return prot


def _handler_roots(classes):
    """Method names that HTTP handler classes in this module call on
    OTHER objects — server threads enter the owning class there
    (``_Handler.do_GET`` -> ``rt.stats_payload()``)."""
    out = set()
    for cls in classes:
        if not cls.is_handler():
            continue
        for node in ast.walk(cls.node):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and not \
                    _is_self_attr(node.func):
                out.add(node.func.attr)
    return out


def _lint_module(mod, report, rules):
    classes = [_ClassInfo(mod, node) for node in ast.walk(mod.tree)
               if isinstance(node, ast.ClassDef)]
    handler_roots = _handler_roots(classes)
    for cls in classes:
        scan = _ClassScan(cls, handler_roots)
        if not scan.roots:
            continue
        domains = scan.domains()
        if len(domains) < 2:
            # fewer than two concurrency domains: nothing interleaves
            continue
        prot = scan.protected()
        access = {}
        for label, members in domains:
            for member in members:
                for attr in scan.facts[member][0].touched:
                    access.setdefault(attr, set()).add(label)
        fn_domains = {}
        for label, members in domains:
            for member in members:
                fn_domains.setdefault(member, set()).add(label)

        def shared_with(fname, attr):
            """Domains that can interleave with ``fname`` on ``attr``
            (empty = not actually shared).  A function reachable from
            two domains interleaves with itself."""
            mine = fn_domains.get(fname, set())
            if not mine:
                return set()
            everywhere = access.get(attr, set()) | mine
            others = everywhere - mine
            if len(mine) >= 2 and \
                    any(d.startswith("thread:") for d in mine):
                return everywhere - {sorted(mine)[0]}
            if others and \
                    any(d.startswith("thread:") for d in everywhere):
                return others
            return set()

        for fname, (facts, _fn) in scan.facts.items():
            if "repo-shared-mutation" in rules:
                for attr, line, locked, how in facts.mutations:
                    if locked or attr in cls.locks or \
                            attr in cls.safe_attrs or fname in prot:
                        continue
                    others = shared_with(fname, attr)
                    if not others:
                        continue
                    if mod.suppressed(line, "repo-shared-mutation"):
                        continue
                    report.add(
                        "repo-shared-mutation",
                        "%s.%s mutates self.%s (%s) with no lock held "
                        "— the attr is also touched from %s; guard it "
                        "with the class lock (see docs/how_to/"
                        "static_analysis.md level 3)"
                        % (cls.name, fname, attr, how,
                           ", ".join(sorted(others))),
                        file=mod.path, line=line)
            if "repo-check-then-act" in rules:
                for attr, line, locked in facts.cta:
                    if locked or attr in cls.locks or \
                            attr in cls.safe_attrs or fname in prot:
                        continue
                    others = shared_with(fname, attr)
                    if not others:
                        continue
                    if mod.suppressed(line, "repo-check-then-act"):
                        continue
                    report.add(
                        "repo-check-then-act",
                        "%s.%s tests membership in self.%s and then "
                        "indexes it outside any lock — %s can mutate "
                        "the dict between the check and the act; take "
                        "the lock around both (or .get() once)"
                        % (cls.name, fname, attr,
                           ", ".join(sorted(others))),
                        file=mod.path, line=line)


def lint_modules(modules, select=None):
    """Run the race rules over pre-parsed modules (see
    :func:`ast_lint.load_modules`)."""
    rules = set(RULES if select is None else select) & set(RULES)
    report = Report(tool="mxlint.race")
    report.files_scanned = len(modules)
    if not rules:
        return report
    for mod in modules:
        _lint_module(mod, report, rules)
    return report


def lint_paths(paths, select=None, cache=None):
    """Convenience: load ``paths`` and run :func:`lint_modules`."""
    modules, broken = load_modules(paths, cache=cache)
    report = lint_modules(modules, select=select)
    for path, err in broken:
        report.add("parse-error", "cannot parse: %s" % (err,), file=path)
    return report
