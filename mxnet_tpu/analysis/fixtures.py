"""The ONE definition of the "standard MLP fused step" fixture.

Three consumers assert the same claim — "the standard MLP step lints
clean" — and must lint the same program: ``tools/mxlint.py --graph``
(the CLI gate), ``bench.py``'s ``analyze`` metric (collective
count/bytes per step), and ``tests/test_analysis.py`` (the tier-1
regression gate).  A hand-copied fixture drifting in any of them would
quietly turn one claim into three different ones.

Imports are function-local: the analysis package stays stdlib-only at
import time (the CLI's AST level must run without jax).
"""
from __future__ import annotations

__all__ = ["standard_mlp_sym", "standard_mlp_trainer",
           "standard_mlp_batch",
           "RACE_UNGUARDED_SRC", "RACE_GUARDED_SRC",
           "RACE_CHECK_THEN_ACT_SRC", "RACE_SUPPRESSED_SRC",
           "CONTRACT_DRIFT_SRC", "CONTRACT_CLEAN_SRC",
           "contract_fixture_surface", "PR18_SUPERVISION_KEYS",
           "pr18_broken_router_source"]

#: the canonical dimensions/seed of the fixture — change them HERE only
BATCH, IN_DIM, HIDDEN, NUM_CLASSES, SEED = 64, 32, 64, 10, 7


def standard_mlp_sym(num_classes=NUM_CLASSES, nh=HIDDEN):
    """fc(64) -> relu -> fc(10) -> softmax, the tier-1 pinned model."""
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=nh, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def standard_mlp_batch():
    """The deterministic example batch every consumer lints against."""
    import numpy as np
    rs = np.random.RandomState(0)
    return (rs.randn(BATCH, IN_DIM).astype("f"),
            rs.randint(0, NUM_CLASSES, BATCH).astype("f"))


def standard_mlp_trainer(cls=None, grad_sync=None, **kwargs):
    """A bound + initialized SPMDTrainer of the standard MLP on the dp
    mesh.  ``cls`` lets tests substitute violation-seeding fixture
    subclasses; extra kwargs (compute_dtype, input_transforms, ...) pass
    through to the trainer."""
    import mxnet_tpu as mx
    from ..parallel import SPMDTrainer, local_mesh
    cls = cls or SPMDTrainer
    if grad_sync is not None:
        kwargs["grad_sync"] = grad_sync
    trainer = cls(standard_mlp_sym(), "sgd", {"learning_rate": 0.1},
                  mesh=local_mesh("dp"), **kwargs)
    trainer.bind([("data", (BATCH, IN_DIM))],
                 [("softmax_label", (BATCH,))])
    mx.random.seed(SEED)
    trainer.init_params(mx.initializer.Xavier())
    return trainer


# ---------------------------------------------------------------------------
# Level 3 (cross-module lint) fixtures: one synthetic snippet per rule
# behavior, shared by tests and by anyone reproducing a finding by hand.
# Plain strings + a revert helper — stdlib-only, like the whole module.
# ---------------------------------------------------------------------------

#: two threads mutate ``self.counter`` read-modify-write with no lock —
#: the canonical ``repo-shared-mutation`` finding
RACE_UNGUARDED_SRC = """
import threading

class Worker(object):
    def __init__(self):
        self.counter = 0
        self._thread = threading.Thread(target=self._loop)

    def _loop(self):
        self.counter += 1

    def tick(self):
        self.counter += 1
"""

#: the same shape with both mutations under the class lock — clean
RACE_GUARDED_SRC = """
import threading

class Worker(object):
    def __init__(self):
        self.counter = 0
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._loop)

    def _loop(self):
        with self._lock:
            self.counter += 1

    def tick(self):
        with self._lock:
            self.counter += 1
"""

#: ``if k in d: ... d[k]`` on a thread-shared dict outside any lock —
#: the canonical ``repo-check-then-act`` finding
RACE_CHECK_THEN_ACT_SRC = """
import threading

class Registry(object):
    def __init__(self):
        self.entries = {}
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._loop)

    def _loop(self):
        with self._lock:
            self.entries["x"] = 1

    def lookup(self):
        if "x" in self.entries:
            return self.entries["x"]
        return None
"""

#: an unguarded mutation carrying a justified inline suppression — the
#: escape hatch must keep working or every justified carve-out breaks
RACE_SUPPRESSED_SRC = """
import threading

class Worker(object):
    def __init__(self):
        self.counter = 0
        self._thread = threading.Thread(target=self._loop)

    def _loop(self):
        # benign: test-only counter, torn reads acceptable
        self.counter += 1  # mxlint: disable=repo-shared-mutation

    def tick(self):
        self.counter += 1  # mxlint: disable=repo-shared-mutation
"""

#: producer/consumer pair for ``wire-contract-drift``: the producer
#: emits {a, b}; the consumer reads a and c.  Declared as one surface,
#: this yields BOTH drift directions — ``c`` consumer-read-never-
#: produced (error) and ``b`` producer-key-never-read (warning)
CONTRACT_DRIFT_SRC = """
def produce():
    return {"a": 1, "b": 2}

def consume(doc):
    return doc["a"] + doc.get("c", 0)
"""

#: the aligned version of the same surface — clean
CONTRACT_CLEAN_SRC = """
def produce():
    return {"a": 1, "b": 2}

def consume(doc):
    return doc["a"] + doc.get("b", 0)
"""


def contract_fixture_surface(contract_lint, relpath):
    """The declared surface for the snippet above (producer ``produce``
    and consumer ``consume`` in the same file)."""
    return contract_lint.Surface(
        "fixture-doc", "synthetic fixture surface",
        producers=[(relpath, "produce")],
        consumers=[(relpath, "consume")])


#: the supervision fields PR 18's fix added to ``view_export`` — the
#: exact keys the regression fixture rips back out
PR18_SUPERVISION_KEYS = ("state", "pid", "restarts", "last_rc")


def pr18_broken_router_source():
    """Re-create the PR 18 wire-contract bug: return ``router.py``'s
    source with ``view_export``'s supervision fields reverted (the
    sharded front end again silently dropping ``state/pid/restarts/
    last_rc`` from the published view).  Feed the result to
    ``contract_lint.lint_paths(..., overrides=...)`` — the lint must go
    red with one consumer-read-never-produced error per key.  Raises if
    the source has drifted so far the revert no longer applies (then
    the fixture — not the lint — needs updating)."""
    import os
    import re
    here = os.path.dirname(os.path.abspath(__file__))
    relpath = os.path.join("mxnet_tpu", "fleet", "router.py")
    path = os.path.join(os.path.dirname(os.path.dirname(here)),
                        "mxnet_tpu", "fleet", "router.py")
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    broken = src.replace('"state": sup.get("state"),', "")
    broken = re.sub(
        r'\n *# supervision fields travel with the view'
        r'[\s\S]*?"last_rc": sup\.get\("last_rc"\)\}',
        "}", broken)
    if broken == src or any('"%s": sup.get' % k in broken
                            for k in PR18_SUPERVISION_KEYS):
        raise RuntimeError(
            "pr18_broken_router_source: view_export no longer matches "
            "the revert pattern — update the regression fixture")
    return {relpath: broken}
