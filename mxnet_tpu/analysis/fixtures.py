"""The ONE definition of the "standard MLP fused step" fixture.

Three consumers assert the same claim — "the standard MLP step lints
clean" — and must lint the same program: ``tools/mxlint.py --graph``
(the CLI gate), ``bench.py``'s ``analyze`` metric (collective
count/bytes per step), and ``tests/test_analysis.py`` (the tier-1
regression gate).  A hand-copied fixture drifting in any of them would
quietly turn one claim into three different ones.

Imports are function-local: the analysis package stays stdlib-only at
import time (the CLI's AST level must run without jax).
"""
from __future__ import annotations

__all__ = ["standard_mlp_sym", "standard_mlp_trainer",
           "standard_mlp_batch"]

#: the canonical dimensions/seed of the fixture — change them HERE only
BATCH, IN_DIM, HIDDEN, NUM_CLASSES, SEED = 64, 32, 64, 10, 7


def standard_mlp_sym(num_classes=NUM_CLASSES, nh=HIDDEN):
    """fc(64) -> relu -> fc(10) -> softmax, the tier-1 pinned model."""
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=nh, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def standard_mlp_batch():
    """The deterministic example batch every consumer lints against."""
    import numpy as np
    rs = np.random.RandomState(0)
    return (rs.randn(BATCH, IN_DIM).astype("f"),
            rs.randint(0, NUM_CLASSES, BATCH).astype("f"))


def standard_mlp_trainer(cls=None, grad_sync=None, **kwargs):
    """A bound + initialized SPMDTrainer of the standard MLP on the dp
    mesh.  ``cls`` lets tests substitute violation-seeding fixture
    subclasses; extra kwargs (compute_dtype, input_transforms, ...) pass
    through to the trainer."""
    import mxnet_tpu as mx
    from ..parallel import SPMDTrainer, local_mesh
    cls = cls or SPMDTrainer
    if grad_sync is not None:
        kwargs["grad_sync"] = grad_sync
    trainer = cls(standard_mlp_sym(), "sgd", {"learning_rate": 0.1},
                  mesh=local_mesh("dp"), **kwargs)
    trainer.bind([("data", (BATCH, IN_DIM))],
                 [("softmax_label", (BATCH,))])
    mx.random.seed(SEED)
    trainer.init_params(mx.initializer.Xavier())
    return trainer
