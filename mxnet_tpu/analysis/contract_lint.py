"""Level-3 static analysis, pass 2: wire-contract drift lint.

Every nontrivial outage the chaos drills have surfaced lately was a
*wire-contract drift*: one side of a cross-process JSON surface changed
shape and the other side kept reading the old keys.  PR 18's ride-along
fix was the textbook case — the sharded front end's
``FleetRouter.view_export`` silently dropped the controller's
supervision fields, so a worker-served ``/stats`` table lost
``state/pid/restarts/last_rc`` and the region kill-replica drill went
deterministic-red.  A dynamic test only catches that when a drill
happens to traverse the exact payload path; this pass catches it at
lint time.

Rule ``wire-contract-drift``, driven by a *declared registry* of the
repo's wire surfaces (:func:`repo_registry`).  Each
:class:`Surface` names its producer and consumer functions; the pass
extracts the produced key set (dict literals, ``x[k] =`` stores,
``.update({...})``, ``dict(k=...)``, ``setdefault``, dict comprehensions
over constant tuples) and the consumed key set (``x["k"]`` loads,
``.get("k")``/``.pop("k")``, ``for k in ("a", "b"): ... x[k]`` loops —
including tuples resolved through class constants like
``RegionSpec.FIELDS``) and flags:

- **consumer-read-never-produced** (error): a consumer reads a key no
  producer of any of its surfaces writes — the PR 18 bug shape.
- **producer-key-never-read** (warning): a produced key no declared
  consumer reads — dead wire weight, or a consumer the registry is
  missing.

Three surface kinds cover the repo's wire formats:

- ``kind="keys"`` — JSON dict payloads (the default).
- ``kind="attrs"`` — attribute contracts like :class:`RegionSpec`:
  produced = ``self.X`` assigns in ``__init__`` plus class-level
  constant tuples (``FIELDS``); consumed = ``<base>.X`` attribute reads.
- ``kind="faults"`` — the fault-point namespace: every static
  ``faults.arm(...)``/``arm_hang`` name must resolve to a production
  ``maybe_fail``/``maybe_trip``/``maybe_hang``/``consume`` site
  (extends :func:`ast_lint.collect_fault_points`).

Design notes:

- Consumer checks run per consumer *function* against the UNION of the
  produced keys of every surface that names it — a function like
  ``FleetRouter.stats_payload`` legitimately reads the fleet view, the
  replica ``/stats`` payload and the router's own snapshot in one body,
  and splitting the check per surface would drown it in cross-surface
  noise.  Keys the function itself produces are always allowed (reading
  back your own store is not drift).
- The registry is part of the contract: a producer/consumer reference
  that no longer resolves (file gone, function renamed) is itself an
  error, so the registry cannot rot silently.
- ``extra_keys`` declares keys produced dynamically (merged sub-dicts,
  ``**kwargs``) that extraction cannot see; ``unread_ok`` documents
  produced keys that are debugging/forensic surface with no in-repo
  reader.  Both are the reviewed escape valves, same spirit as
  ``# mxlint: disable=`` (which also works, per line).
"""
from __future__ import annotations

import ast

import os

from .report import Report
from .ast_lint import collect_fault_points, load_modules

#: where repo-relative registry paths resolve when the scanned set does
#: not already include them (this file lives at
#: <root>/mxnet_tpu/analysis/contract_lint.py)
_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

__all__ = ["Surface", "repo_registry", "lint_modules", "lint_paths",
           "RULES"]

RULES = ("wire-contract-drift",)

_RULE = "wire-contract-drift"


class Surface(object):
    """One declared cross-process wire surface.

    ``producers`` / ``consumers`` are ``(repo-relative-file, qualname)``
    pairs; ``qualname`` is ``func`` or ``Class.method``, or ``"*"`` for
    a whole module (attrs mode, where reads are recognizable anywhere by
    the ``attr_base`` receiver name).
    """

    def __init__(self, name, doc, producers=(), consumers=(),
                 kind="keys", attr_base=None, extra_keys=(),
                 unread_ok=()):
        if kind not in ("keys", "attrs", "faults"):
            raise ValueError("unknown surface kind %r" % (kind,))
        self.name = name
        self.doc = doc
        self.producers = tuple(producers)
        self.consumers = tuple(consumers)
        self.kind = kind
        self.attr_base = attr_base
        self.extra_keys = frozenset(extra_keys)
        self.unread_ok = frozenset(unread_ok)


# ---------------------------------------------------------------------------
# key extraction
# ---------------------------------------------------------------------------

def _const_str(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _str_tuple(node):
    """``("a", "b")`` / ``["a", "b"]`` -> the strings, else None."""
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)) and node.elts:
        out = [_const_str(e) for e in node.elts]
        if all(s is not None for s in out):
            return out
    return None


def _module_tuples(mod):
    """Constant string-tuple assignments, module level and class level
    (both ``FIELDS`` and ``RegionSpec.FIELDS`` spellings resolve off the
    bare attribute name — unique enough at this repo's scale)."""
    out = {}

    def scan(body):
        for node in body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                keys = _str_tuple(node.value)
                if keys:
                    out[node.targets[0].id] = keys

    scan(mod.tree.body)
    for node in mod.tree.body:
        if isinstance(node, ast.ClassDef):
            scan(node.body)
    return out


def _resolve_tuple(node, tuples):
    keys = _str_tuple(node)
    if keys is not None:
        return keys
    if isinstance(node, ast.Name):
        return tuples.get(node.id)
    if isinstance(node, ast.Attribute):        # self.FIELDS / Spec.FIELDS
        return tuples.get(node.attr)
    return None


def _is_environ(node):
    """``os.environ[...]`` — an env read, not a wire surface."""
    return isinstance(node, ast.Attribute) and node.attr == "environ"


def _is_self_receiver(node):
    """``self.<attr>`` — a read off the object's own state (``self.sups
    ["trainer"]``, ``self._recon["base"]``) is internal bookkeeping,
    not a wire payload; counting it would demand every in-memory dict
    key be declared on some surface."""
    return isinstance(node, ast.Attribute) and \
        isinstance(node.value, ast.Name) and node.value.id == "self"


def _scan_keys(fn, tuples):
    """``(produced {key: line}, read {key: line}, comp_keys)`` for one
    function (nested defs and lambdas included — they are part of its
    logic).  ``comp_keys`` marks keys produced only by dict
    comprehensions over key tuples: those FORWARD another payload's
    keys (``{k: ent[k] for k in (...)}``) rather than originate them,
    so they must not self-exempt the reads they wrap — that exemption
    would have hidden the PR 18 view_export revert."""
    produced, read, comp_keys = {}, {}, set()
    bound = {}                        # loop var -> constant key tuple
    for node in ast.walk(fn):
        gens = []
        if isinstance(node, ast.For):
            gens.append((node.target, node.iter))
        elif isinstance(node, (ast.DictComp, ast.ListComp, ast.SetComp,
                               ast.GeneratorExp)):
            gens.extend((g.target, g.iter) for g in node.generators)
        for target, it in gens:
            keys = _resolve_tuple(it, tuples)
            if keys and isinstance(target, ast.Name):
                bound[target.id] = keys

    def keys_of(node):
        s = _const_str(node)
        if s is not None:
            return [s]
        if isinstance(node, ast.Name):
            return bound.get(node.id)
        return None

    def note(table, keys, line):
        for k in keys:
            table.setdefault(k, line)

    for node in ast.walk(fn):
        if isinstance(node, ast.Dict):
            for k in node.keys:               # None key = ** spread
                s = _const_str(k)
                if s is not None:
                    produced.setdefault(s, node.lineno)
        elif isinstance(node, ast.DictComp):
            keys = keys_of(node.key)
            if keys:
                note(produced, keys, node.lineno)
                comp_keys.update(keys)
        elif isinstance(node, ast.Subscript) and not _is_environ(node.value):
            keys = keys_of(node.slice)
            if keys:
                if isinstance(node.ctx, ast.Store):
                    note(produced, keys, node.lineno)
                elif not _is_self_receiver(node.value):
                    note(read, keys, node.lineno)  # Load / Del
        elif isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) and node.args:
                keys = keys_of(node.args[0])
                if keys and func.attr in ("get", "pop") and \
                        not _is_self_receiver(func.value):
                    note(read, keys, node.lineno)
                elif keys and func.attr == "setdefault":
                    note(produced, keys, node.lineno)
            elif isinstance(func, ast.Name) and func.id == "dict":
                for kw in node.keywords:
                    if kw.arg is not None:
                        produced.setdefault(kw.arg, node.lineno)
        # membership tests (`"k" in x`) are deliberately NOT counted as
        # reads: `in` on a *string* receiver is substring search, and
        # the AST cannot tell the two apart — the subscript inside the
        # guarded branch is counted instead
    return produced, read, comp_keys


def _scan_attr_producer(mod, class_name):
    """Attrs-mode producer: ``self.X =`` in ``__init__`` plus class-level
    constant string tuples (the ``FIELDS`` declaration)."""
    produced = {}
    for node in mod.tree.body:
        if not (isinstance(node, ast.ClassDef) and node.name == class_name):
            continue
        for sub in node.body:
            if isinstance(sub, ast.Assign):
                keys = _str_tuple(sub.value)
                if keys:
                    for k in keys:
                        produced.setdefault(k, sub.lineno)
            elif isinstance(sub, ast.FunctionDef) and \
                    sub.name == "__init__":
                for inner in ast.walk(sub):
                    if isinstance(inner, ast.Assign):
                        for tgt in inner.targets:
                            if isinstance(tgt, ast.Attribute) and \
                                    isinstance(tgt.value, ast.Name) and \
                                    tgt.value.id == "self":
                                produced.setdefault(tgt.attr, inner.lineno)
        return produced, node
    return None, None


def _scan_attr_reads(tree, base):
    """Attrs-mode consumer: ``<base>.X`` / ``anything.<base>.X`` loads,
    method calls excluded (``spec.as_dict()`` is not a field read)."""
    read = {}
    called = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            called.add(id(node.func))
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and \
                isinstance(node.ctx, ast.Load) and id(node) not in called:
            v = node.value
            if (isinstance(v, ast.Name) and v.id == base) or \
                    (isinstance(v, ast.Attribute) and v.attr == base):
                read.setdefault(node.attr, node.lineno)
    return read


# ---------------------------------------------------------------------------
# registry resolution
# ---------------------------------------------------------------------------

def _functions(mod):
    """``qualname -> def node`` (module level and one class level)."""
    out = {}
    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out["%s.%s" % (node.name, sub.name)] = sub
    return out


class _Index(object):
    """Per-run resolution cache over the loaded modules."""

    def __init__(self, modules):
        self._by_suffix = {}
        for mod in modules:
            self._by_suffix[mod.path.replace("\\", "/")] = mod
        self._functions = {}
        self._tuples = {}

    def module(self, relpath):
        for path, mod in self._by_suffix.items():
            if path == relpath or path.endswith("/" + relpath):
                return mod
        return None

    def function(self, mod, qualname):
        if mod.path not in self._functions:
            self._functions[mod.path] = _functions(mod)
        return self._functions[mod.path].get(qualname)

    def tuples(self, mod):
        if mod.path not in self._tuples:
            self._tuples[mod.path] = _module_tuples(mod)
        return self._tuples[mod.path]


# ---------------------------------------------------------------------------
# the lint
# ---------------------------------------------------------------------------

def _add(report, mod, line, message, severity="error"):
    if mod is not None and mod.suppressed(line, _RULE):
        return
    report.add(_RULE, message, file=mod.path if mod else None,
               line=line, severity=severity)


def _lint_surfaces(surfaces, index, report):
    produced_by_surface = {}     # id(surface) -> {key: (mod, line)}
    consumers = {}               # entry key -> consumer record

    def resolve(surface, relpath, qualname, role):
        mod = index.module(relpath)
        if mod is None:
            report.add(_RULE,
                       "surface %r %s %s:%s references a file the lint "
                       "did not load — fix the registry in "
                       "analysis/contract_lint.py" %
                       (surface.name, role, relpath, qualname),
                       file=relpath)
            return None, None
        if qualname == "*":
            return mod, mod.tree
        fn = index.function(mod, qualname)
        if fn is None:
            report.add(_RULE,
                       "surface %r %s %s:%s no longer resolves (renamed "
                       "or deleted?) — update the registry in "
                       "analysis/contract_lint.py" %
                       (surface.name, role, relpath, qualname),
                       file=mod.path)
            return mod, None
        return mod, fn

    for surface in surfaces:
        if surface.kind == "faults":
            continue
        produced = {}
        for relpath, qualname in surface.producers:
            if surface.kind == "attrs":
                mod = index.module(relpath)
                keys = _scan_attr_producer(mod, qualname)[0] \
                    if mod is not None else None
                if keys is None:
                    report.add(_RULE,
                               "surface %r producer class %s:%s not "
                               "found — update the registry in "
                               "analysis/contract_lint.py"
                               % (surface.name, relpath, qualname),
                               file=mod.path if mod else relpath)
                    continue
            else:
                mod, fn = resolve(surface, relpath, qualname, "producer")
                if fn is None:
                    continue
                keys = _scan_keys(fn, index.tuples(mod))[0]
            for k, line in keys.items():
                produced.setdefault(k, (mod, line))
        produced_by_surface[id(surface)] = produced

        for relpath, qualname in surface.consumers:
            mod, fn = resolve(surface, relpath, qualname, "consumer")
            if fn is None:
                continue
            entry = (mod.path, qualname, surface.kind, surface.attr_base)
            rec = consumers.get(entry)
            if rec is None:
                if surface.kind == "attrs":
                    reads, self_produced = \
                        _scan_attr_reads(fn, surface.attr_base), set()
                else:
                    made, reads, comp = _scan_keys(fn, index.tuples(mod))
                    self_produced = set(made) - comp
                rec = consumers[entry] = {
                    "mod": mod, "qualname": qualname, "reads": reads,
                    "self": self_produced, "surfaces": [],
                    # a keys-mode whole-module consumer is a *read
                    # sink*: it proves keys are read (tests, drill
                    # harnesses) but is too coarse for the missing-key
                    # check — a test module legitimately reads many
                    # surfaces at once.  attrs-mode wildcards stay
                    # precise (reads are receiver-name filtered).
                    "sink": qualname == "*" and surface.kind == "keys"}
            rec["surfaces"].append(surface)

    # consumer-read-never-produced: one check per consumer function,
    # against the union of everything its surfaces produce
    for rec in consumers.values():
        if rec["sink"]:
            continue
        allowed = set(rec["self"])
        names = []
        for surface in rec["surfaces"]:
            names.append(surface.name)
            allowed |= set(produced_by_surface[id(surface)])
            allowed |= surface.extra_keys
        for key, line in sorted(rec["reads"].items()):
            if key in allowed:
                continue
            _add(report, rec["mod"], line,
                 "%s reads %r but no producer of surface(s) %s writes "
                 "it — wire-contract drift (the PR 18 view_export bug "
                 "shape); produce the key, or fix the registry in "
                 "analysis/contract_lint.py (see docs/how_to/"
                 "static_analysis.md level 3)"
                 % (rec["qualname"], key, "/".join(sorted(names))))

    # producer-key-never-read: per surface, against all its consumers
    for surface in surfaces:
        if surface.kind == "faults":
            continue
        read = set()
        for rec in consumers.values():
            if surface in rec["surfaces"]:
                read |= set(rec["reads"])
        for key, (mod, line) in sorted(produced_by_surface[id(surface)]
                                       .items()):
            if key in read or key in surface.unread_ok:
                continue
            _add(report, mod, line,
                 "surface %r produces %r but no declared consumer reads "
                 "it — dead wire weight, or a missing consumer in the "
                 "registry; read it, drop it, or list it in unread_ok "
                 "with a why" % (surface.name, key),
                 severity="warning")


def _lint_faults(surfaces, paths, cache, report):
    """Fault-point namespace check: every statically armed name must hit
    a production injection site (typo'd armings silently never fire)."""
    if not any(s.kind == "faults" for s in surfaces):
        return
    points = set(collect_fault_points(paths, cache=cache))
    arms = collect_fault_points(paths, arms=True, cache=cache)
    for surface in surfaces:
        if surface.kind != "faults":
            continue
        for name, sites in sorted(arms.items()):
            if name in points or name in surface.extra_keys:
                continue
            for path, line, via in sites:
                report.add(_RULE,
                           "%s arms fault point %r but no production "
                           "site reads it (known points: tools/mxlint.py "
                           "--list-faults) — the arming silently never "
                           "fires" % (via, name),
                           file=path, line=line)


def lint_modules(modules, surfaces=None, select=None):
    """Run the contract rule over pre-parsed modules.  ``surfaces``
    defaults to the repo registry; pass a custom list for fixtures.
    (Faults surfaces need path context — see :func:`lint_paths`.)"""
    rules = set(RULES if select is None else select) & set(RULES)
    report = Report(tool="mxlint.contract")
    report.files_scanned = len(modules)
    if not rules:
        return report
    if surfaces is None:
        surfaces = repo_registry()
    _lint_surfaces(surfaces, _Index(modules), report)
    return report


def lint_paths(paths, surfaces=None, select=None, cache=None,
               overrides=None):
    """Load ``paths`` and run :func:`lint_modules`, plus the
    fault-namespace check (which needs path context).  ``overrides``
    maps file paths to replacement source — how the PR 18 regression
    fixture replays the broken ``view_export`` against today's
    registry."""
    modules, broken = load_modules(paths, cache=cache,
                                   overrides=overrides)
    if surfaces is None:
        surfaces = repo_registry()
    # the registry is repo-global: pull in referenced files the scan
    # set missed (e.g. `--changed` touched only one side of a surface,
    # or a drill-harness consumer lives under tests/)
    index = _Index(modules)
    extra = []
    for surface in surfaces:
        for relpath, _q in tuple(surface.producers) + tuple(
                surface.consumers):
            full = os.path.join(_REPO_ROOT, relpath)
            if index.module(relpath) is None and relpath not in extra \
                    and os.path.isfile(full):
                extra.append(relpath)
    if extra:
        more, broken2 = load_modules(
            [os.path.join(_REPO_ROOT, p) for p in extra],
            cache=cache, overrides=overrides)
        modules = list(modules) + list(more)
        broken = list(broken) + list(broken2)
    report = lint_modules(modules, surfaces=surfaces, select=select)
    if RULES[0] in (set(RULES if select is None else select)):
        _lint_faults(surfaces, paths, cache if not overrides else None,
                     report)
    for path, err in broken:
        report.add("parse-error", "cannot parse: %s" % (err,), file=path)
    return report


# ---------------------------------------------------------------------------
# the repo's declared wire surfaces
# ---------------------------------------------------------------------------

def repo_registry():
    """The declared registry of this repo's cross-process JSON surfaces.

    Declaring a new surface: name the producer and consumer functions as
    ``(repo-relative file, qualname)`` pairs, run ``tools/mxlint.py``,
    and tune ``extra_keys`` (dynamically produced keys extraction cannot
    see) / ``unread_ok`` (forensic keys with no in-repo reader, each
    needs a why) until the findings are the real ones.  How-to:
    docs/how_to/static_analysis.md, "Declaring a wire surface".
    """
    R = "mxnet_tpu/fleet/router.py"
    V = "mxnet_tpu/fleet/view.py"
    F = "mxnet_tpu/serving/frontend.py"
    RES = "mxnet_tpu/resilience.py"
    REG = "tools/region.py"
    T_FLEET = "tests/test_fleet.py"
    T_SERVE = "tests/test_serving.py"
    T_CHAOS = "tests/test_chaos.py"
    return [
        Surface(
            "fleet-view-doc",
            "The published fleet-view snapshot document "
            "(run/fleet-view.json): controller-side publisher -> "
            "router workers.",
            producers=[(V, "FleetViewPublisher.publish_once")],
            consumers=[(V, "FleetViewReader.doc"),
                       (V, "FleetViewReader.age_s"),
                       (V, "FleetViewReader.replicas"),
                       (V, "FleetViewReader.fenced"),
                       (R, "FleetRouter._sync_view"),
                       (R, "FleetRouter.stats_payload"),
                       (T_FLEET, "*")],
            # the doc doubles as a live debugging surface (`cat
            # run/fleet-view.json`); these two annotate it for humans
            unread_ok=("heartbeat_s", "evict_s"),
        ),
        Surface(
            "fleet-view-replica",
            "One replica entry inside the view's `replicas` map "
            "(FleetRouter.view_export) — the PR 18 drift site: the "
            "supervision fields must travel with the view so a sharded "
            "worker's /stats table matches the controller-side one.",
            producers=[(R, "FleetRouter.view_export")],
            consumers=[(V, "FleetViewReader.replicas"),
                       (R, "FleetRouter._sync_view"),
                       (R, "FleetRouter.stats_payload"),
                       (T_FLEET, "*")],
            # the view file doubles as `cat run/fleet-view.json`
            # forensics; per-replica forward_errors travels for that
            unread_ok=("forward_errors",),
        ),
        Surface(
            "worker-stats-dump",
            "Per-worker counter dump next to the view file "
            "(rworker-*.stats.json): any worker answers /stats for the "
            "whole front end by merging the sibling dumps.",
            producers=[(R, "FleetRouter.dump_worker_stats")],
            consumers=[(R, "FleetRouter._merged_worker_stats")],
        ),
        Surface(
            "router-snapshot",
            "The Stats snapshot/export/merge shapes shared by the "
            "serving front end and the fleet router tier.",
            producers=[(F, "Stats.snapshot"), (F, "Stats.export"),
                       (F, "Stats.merged_snapshot")],
            consumers=[(F, "Stats.merged_snapshot"),
                       (R, "FleetRouter.stats_payload"),
                       (T_SERVE, "*"), (T_FLEET, "*"),
                       ("bench.py", "*")],
            # batches.avg_ms is a human gauge next to the machine-read
            # fill_ratio/count fields; p99_recent travels on the
            # router's OWN snapshot only because the one snapshot shape
            # serves both tiers — its machine reader (the outlier
            # detector) consumes it from replica /stats, not here
            unread_ok=("avg_ms", "p99_recent"),
        ),
        Surface(
            "replica-stats",
            "A serving replica's /stats payload: what the fleet "
            "router's prober stores as view.stats and the routing/"
            "autoscale/rollout policies read.",
            producers=[(F, "ServingFrontend.stats_payload"),
                       (F, "Stats.snapshot"),
                       ("mxnet_tpu/serving/deploy.py",
                        "CheckpointWatcher.stats"),
                       ("mxnet_tpu/serving/deploy.py",
                        "CheckpointWatcher.__init__")],
            consumers=[(R, "FleetRouter.stats_payload"),
                       (R, "FleetRouter._load"),
                       (R, "FleetRouter._update_outliers"),
                       (R, "FleetRouter.pressure_ms"),
                       (R, "FleetRouter._flooder_tenant"),
                       ("mxnet_tpu/fleet/autoscale.py",
                        "Autoscaler._pressure_ms"),
                       ("mxnet_tpu/fleet/deploy.py",
                        "RollingSwap._replica_epoch"),
                       (T_SERVE, "*"), (T_FLEET, "*"),
                       ("bench.py", "*")],
            # the watcher deploy block is promote forensics (which
            # model/dir, last outcome, error counters) for operators
            # reading /stats; draining is mirrored machine-readably on
            # /healthz (what the router prober actually uses)
            unread_ok=("avg_ms", "directory", "draining",
                       "last_outcome", "model", "poll_s", "polls",
                       "swap_errors", "watching"),
        ),
        Surface(
            "router-stats",
            "The fleet front end's /stats payload (single-process and "
            "sharded): what the region drill polls and the kill-replica "
            "storm reads pids from.",
            producers=[(R, "FleetRouter.stats_payload"),
                       ("mxnet_tpu/fleet/deploy.py",
                        "RollingSwap.stats"),
                       ("mxnet_tpu/fleet/deploy.py",
                        "RollingSwap.__init__")],
            consumers=[(REG, "Region._poll_once"),
                       (REG, "Region._fire"),
                       (REG, "Region.stats_payload"),
                       (REG, "Region._replica_epochs"),
                       (T_FLEET, "*"), (T_CHAOS, "*"),
                       ("bench.py", "*")],
            # the per-replica table and view block are the operator's
            # triage surface (why is this replica slow/evicted/dead);
            # machine consumers key off healthy/epochs/restarts instead.
            # the brownout block (slo_ms/pressure_ms next to the
            # machine-read `active` bit) shows an operator how close
            # the fleet is to shedding — and WHY it already is
            unread_ok=("age_s", "draining", "est_wait_ms",
                       "forward_errors", "heartbeat_age_s", "inflight",
                       "last_rc", "probe_retries", "read_errors",
                       "replicas_total", "pressure_ms", "slo_ms"),
        ),
        Surface(
            "fleet-manifest",
            "The fleet manifest file: `serve` writes it, every replica "
            "and router worker process re-reads it.",
            producers=[("mxnet_tpu/fleet/manifest.py",
                        "FleetManifest.to_doc")],
            consumers=[("mxnet_tpu/fleet/manifest.py",
                        "FleetManifest.from_file"),
                       ("mxnet_tpu/fleet/manifest.py",
                        "FleetManifest.__init__"),
                       ("mxnet_tpu/fleet/manifest.py",
                        "FleetManifest.serve_argv"),
                       ("tools/fleet.py", "_cmd_serve"),
                       ("tools/fleet.py", "_serve_sharded")],
        ),
        Surface(
            "trainer-status",
            "The region trainer's status file (REGION_STATUS): written "
            "by the embedded trainer script's write_status (a source "
            "STRING in tools/region.py — extraction cannot see it, so "
            "the keys are declared here), read by the region daemon.",
            producers=[],
            extra_keys=("epoch", "world", "pid", "reconnects",
                        "batches", "time", "uptime_s"),
            consumers=[(REG, "Region._trainer_status"),
                       (REG, "Region._reconnect_total"),
                       (REG, "Region.stats_payload")],
        ),
        Surface(
            "region-spec",
            "RegionSpec: the declarative region topology every "
            "tools/region.py phase reads.",
            kind="attrs", attr_base="spec",
            producers=[(REG, "RegionSpec")],
            consumers=[(REG, "*")],
        ),
        Surface(
            "region-stats",
            "The region daemon's /region/stats payload: the drill "
            "scoreboard (consumed by the chaos-drill harness and "
            "operators).",
            producers=[(REG, "Region.stats_payload")],
            consumers=[(REG, "Region.report"), (T_CHAOS, "*")],
            # /region/stats IS the drill scoreboard: the composed-drill
            # report embeds it wholesale and operators read it raw; the
            # harness asserts only the gating keys (trainer progress,
            # served epochs, rollout verdicts)
            unread_ok=("batches", "data_reconnects", "fired", "fleet",
                       "first_served_epoch", "healthy", "labels",
                       "polls", "published_epoch", "roles", "rollouts",
                       "scheduled", "storm", "window_s"),
        ),
        Surface(
            "ckpt-manifest",
            "Checkpoint manifest entries, formats 1 (whole-blob) and 2 "
            "(sharded, incl. the per-shard blob docs): trainer-side "
            "save -> restore/promotion/fsck readers in other processes.",
            producers=[(RES, "CheckpointManager.save"),
                       (RES, "CheckpointManager.save_sharded"),
                       (RES, "CheckpointManager._write_checkpoint"),
                       (RES, "CheckpointManager._shard_parts"),
                       (RES, "CheckpointManager._scan_directory")],
            consumers=[(RES, "CheckpointManager.entry"),
                       (RES, "CheckpointManager.restore"),
                       (RES, "CheckpointManager._restore_from_shards"),
                       (RES, "CheckpointManager._delete_entry_files"),
                       (RES, "verify_promotion"),
                       (RES, "publish_mark"),
                       ("tools/ckpt_fsck.py", "_check_entry"),
                       ("tools/ckpt_fsck.py", "_check_file"),
                       ("tools/ckpt_fsck.py", "audit"),
                       ("tests/test_resilience.py", "*")],
            # the manifest header names its own prefix so a bare
            # `cat manifest.json` identifies the checkpoint family;
            # readers re-derive it from their own config
            unread_ok=("prefix",),
        ),
        Surface(
            "fault-points",
            "The fault-injection namespace: armed names must resolve "
            "to production injection sites.",
            kind="faults",
        ),
    ]
