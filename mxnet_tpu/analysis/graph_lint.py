"""Level-1 static analysis: lint one jitted step program.

The reference got its graph-level guarantees from NNVM passes
(infer_shape, plan_memory); the TPU-native analog inspects the three
artifacts every jitted step already produces — the jaxpr (host-callback
and dtype rules), the lowering's arg/out metadata (donation rules) and
the compiled HLO module (the collective audit) — and reports violations
of the invariants the runtime relies on:

- ``graph-donation-missing``: a large array argument whose shape/dtype
  matches an output (a carry: params, optimizer state, metric/guard
  accumulators) is not covered by ``donate_argnums`` — each step then
  pays an extra HBM copy and doubles the buffer's footprint.
- ``graph-donation-unused``: a donated argument matches NO output, so
  XLA cannot alias it anywhere — the donation is silently wasted and the
  caller's array is still invalidated (a likely bug at the call site).
- ``graph-callback``: a ``pure_callback``/``io_callback``/
  ``debug_callback`` equation inside the step — a host sync point that
  serializes the device pipeline every single step.
- ``graph-collective-allgather``: all-gather traffic in a step whose
  declared sharding should not need it (replicated params under plain dp
  'allreduce'), at or above a meaningful fraction of the parameter
  bytes — the GSPMD signature of an accidental full-parameter regather.
- ``graph-collective-schedule``: the inverse direction — a step that
  DECLARED fully-sharded training (grad_sync='zero3') must actually
  all-gather ~param bytes and reduce-scatter its gradients; missing
  gathers or a param-scale all-reduce mean the sharding silently never
  happened.  The reduce-scatter requirement covers the manual tier on
  every backend AND the gspmd tier on TPU/GPU pipelines (where XLA's
  ReduceScatterCreator must rewrite all-reduce+slice; CPU keeps the
  all-reduce form as a documented tier note).  ``trainer.analyze()``
  under zero3 is thereby the PROOF the collective schedule matches the
  declared strategy.
- ``graph-dtype-drift``: dot/conv equations computing in a wider float
  than the declared ``compute_dtype`` — silent f32 math inside a bf16
  step costs ~2x FLOP time on the MXU.
- ``graph-pallas-no-vjp``: a ``pallas_call`` not protected by a
  registered ``custom_vjp``/``custom_jvp`` — Pallas has no reverse-mode
  transpose, so a differentiated step reaching it dies at trace time
  (or the op is silently forward-only); rtc.py documents the contract.
- ``plan-fusion-parity``: the mxfuse plan-optimizer rewrite for a
  symbol must keep the plain-plan monitored path intact — every pass
  may only FILL override slots: entry count, node identity/order and
  slots 0-4 (attrs, output counts, aux names, RNG fold positions) must
  be byte-identical to the unoptimized plan, no extra ref may read a
  value-rewritten passthrough, and the original plan object must be
  left untouched (monitored runs interpret it verbatim).
  ``audit_plan_fusion(symbol)`` is the check; ``trainer.analyze()``
  and ``PooledModel.analyze()`` run it on their bound symbols.

All jax imports are function-local so importing this module costs
nothing in host-only contexts (the AST level and the CLI).
"""
from __future__ import annotations

import re

from .report import Finding, Report

__all__ = ["iter_eqns", "find_callbacks", "audit_dtype", "audit_donation",
           "collective_stats", "audit_collectives",
           "audit_collective_schedule", "find_unprotected_pallas",
           "audit_plan_fusion", "lint_lowered", "lint_jit",
           "CALLBACK_PRIMITIVES", "COLLECTIVE_OPS", "PALLAS_PRIMITIVES",
           "RS_PLATFORMS"]

#: jaxpr primitives that re-enter the host mid-step
CALLBACK_PRIMITIVES = frozenset((
    "pure_callback", "io_callback", "debug_callback", "callback",
    "host_callback_call", "outside_call",
))

#: Pallas kernel-call primitives — no reverse-mode transpose exists for
#: these (rtc.py's documented contract), so one reachable from a
#: differentiated step MUST sit under a registered custom_vjp
PALLAS_PRIMITIVES = frozenset(("pallas_call",))

#: primitives whose body is differentiation-protected: jax never
#: transposes THROUGH these (the registered rules apply instead), so a
#: pallas_call inside them is safe and the walk does not descend
_CUSTOM_DIFF_WRAPPERS = frozenset((
    "custom_vjp_call", "custom_vjp_call_jaxpr", "custom_jvp_call",
    "custom_jvp_call_jaxpr", "custom_jvp_generic_call",
))

#: primitives whose dtype decides where the MXU/VPU math happens
_COMPUTE_PRIMITIVES = frozenset(("dot_general", "conv_general_dilated"))

#: HLO instruction names of cross-device traffic (the ``-start`` async
#: forms count once; ``-done`` carries no payload of its own)
COLLECTIVE_OPS = ("all-gather", "all-reduce", "reduce-scatter",
                  "all-to-all", "collective-permute")

_WIDER_THAN = {
    "bfloat16": ("float32", "float64"),
    "float16": ("float32", "float64"),
    "float32": ("float64",),
}

# f32[128,64]{1,0} / bf16[8]{0} / pred[] ... inside an HLO result type
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# `%name = <result type> <collective>(` — the result type is everything
# between '= ' and the op name; matching on the instruction form keeps
# op_name metadata strings from false-matching
_COLLECTIVE_RE = re.compile(
    r"=\s*(?P<type>(?:\([^)]*\)|[a-z][a-z0-9]*\[[^\]]*\][^\s]*))\s*"
    r"(?P<op>" + "|".join(re.escape(o) for o in COLLECTIVE_OPS) + r")"
    r"(?P<suffix>-start|-done)?\(")

# replica_groups={{0,1},{2,3}} (explicit) or [2,4]<=[8] (iota v2:
# num_groups, devices_per_group) on the same instruction line
_REPLICA_GROUPS_RE = re.compile(
    r"replica_groups=(?:\{\{(?P<first>[0-9, ]*)\}|"
    r"\[(?P<groups>\d+),(?P<size>\d+)\]<=)")


def _is_degenerate_groups(line):
    """True when the instruction's replica_groups are singletons (each
    device alone) — the partitioner's representation of a NO-OP
    collective that moves zero bytes across devices.  GSPMD emits these
    to materialize per-device partial values; counting them as traffic
    would make the schedule audit see phantom all-reduces.  Lines with
    no replica_groups at all (hand-written fixtures) count as real."""
    m = _REPLICA_GROUPS_RE.search(line)
    if m is None:
        return False
    if m.group("size") is not None:
        return int(m.group("size")) <= 1
    return "," not in (m.group("first") or "")


def _eqn_location(eqn):
    """(file, line) of the traced user code for one equation, best
    effort (source info is jax-internal; absent on synthesized eqns)."""
    try:
        frame = eqn.source_info.traceback.frames[0]
        return frame.file_name, frame.start_line
    except Exception:  # noqa: BLE001 — diagnostics only
        return None, None


def iter_eqns(jaxpr, prune=frozenset()):
    """Yield every equation in ``jaxpr`` including nested sub-jaxprs
    (pjit bodies, scan/while bodies, cond branches, remat, custom_vjp).

    ``prune``: primitive names whose equations are yielded but whose
    sub-jaxprs are NOT descended into (the pallas rule prunes at
    custom-vjp wrappers — their bodies are differentiation-protected)."""
    import jax

    def _walk(jxp):
        for eqn in jxp.eqns:
            yield eqn
            if eqn.primitive.name in prune:
                continue
            for v in eqn.params.values():
                items = v if isinstance(v, (list, tuple)) else (v,)
                for item in items:
                    if isinstance(item, jax.core.ClosedJaxpr):
                        yield from _walk(item.jaxpr)
                    elif isinstance(item, jax.core.Jaxpr):
                        yield from _walk(item)
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    return _walk(inner)


def find_callbacks(closed_jaxpr):
    """``graph-callback`` findings for every host-callback equation."""
    out = []
    for eqn in iter_eqns(closed_jaxpr):
        name = eqn.primitive.name
        if name in CALLBACK_PRIMITIVES:
            fname, line = _eqn_location(eqn)
            out.append(Finding(
                "graph-callback",
                "host callback %r inside the jitted step — a per-step "
                "host sync point (move it out of the step or behind a "
                "deferred metric/guard carry)" % name,
                file=fname, line=line))
    return out


def find_unprotected_pallas(closed_jaxpr):
    """``graph-pallas-no-vjp``: a ``pallas_call`` NOT wrapped in a
    ``custom_vjp``/``custom_jvp`` rule.  Pallas has no reverse-mode
    transpose, so differentiating through such a kernel is a trace-time
    error at best — and in a step assembled from many ops the failure
    surfaces far from the kernel that caused it (rtc.py documents the
    hazard; kernels/ pairs every Pallas forward with a backward kernel
    behind ``jax.custom_vjp``).  The walk descends into ordinary
    sub-jaxprs (pjit/scan/while/cond/remat) but NOT into custom-vjp
    wrappers, whose bodies are differentiation-protected by the
    registered rule."""
    out = []
    for eqn in iter_eqns(closed_jaxpr, prune=_CUSTOM_DIFF_WRAPPERS):
        if eqn.primitive.name not in PALLAS_PRIMITIVES:
            continue
        fname, line = _eqn_location(eqn)
        out.append(Finding(
            "graph-pallas-no-vjp",
            "pallas_call without a registered custom_vjp is "
            "reachable from this step — Pallas kernels have no "
            "reverse-mode transpose, so differentiation fails "
            "at trace time (or silently degrades); pair the "
            "forward kernel with a backward kernel via "
            "jax.custom_vjp (rtc.register_kernel(vjp=...), "
            "kernels/ pattern)",
            file=fname, line=line))
    return out


def audit_dtype(closed_jaxpr, compute_dtype):
    """``graph-dtype-drift``: dot/conv eqns whose inputs are wider floats
    than the declared compute dtype.  Returns (findings, tally) where
    tally maps primitive name -> {dtype_name: count} for reporting."""
    import numpy as np
    tally = {}
    offenders = []
    compute_dtype = np.dtype(compute_dtype) if compute_dtype else None
    wider = _WIDER_THAN.get(compute_dtype.name, ()) if compute_dtype \
        else ()
    for eqn in iter_eqns(closed_jaxpr):
        name = eqn.primitive.name
        if name not in _COMPUTE_PRIMITIVES:
            continue
        in_dtypes = sorted({str(v.aval.dtype) for v in eqn.invars
                            if hasattr(v, "aval")
                            and hasattr(v.aval, "dtype")})
        slot = tally.setdefault(name, {})
        for d in in_dtypes:
            slot[d] = slot.get(d, 0) + 1
        if wider and any(d in wider for d in in_dtypes):
            offenders.append((eqn, in_dtypes))
    findings = []
    if offenders:
        fname, line = _eqn_location(offenders[0][0])
        findings.append(Finding(
            "graph-dtype-drift",
            "%d dot/conv equation(s) compute in %s inside a "
            "compute_dtype=%s step (first at the reported location) — "
            "a widening cast upstream is defeating the mixed-precision "
            "path" % (len(offenders),
                      "/".join(sorted({d for _, ds in offenders
                                       for d in ds if d in wider})),
                      compute_dtype.name),
            file=fname, line=line,
            data={"offending_eqns": len(offenders)}))
    return findings, tally


def _leaf_bytes(shape, dtype):
    import numpy as np
    n = 1
    for d in shape:
        n *= int(d)
    return n * np.dtype(dtype).itemsize


def _leading_argnum(path):
    """Positional index of the top-level argument a leaf path belongs
    to.  ``args_info`` is the ``(args, kwargs)`` pair, so a positional
    leaf's path is ``[0][argnum]...`` — the argnum is the SECOND key;
    kwargs leaves (path ``[1][name]...``) have no argnum."""
    try:
        if getattr(path[0], "idx", None) != 0:
            return None
        return getattr(path[1], "idx", None)
    except Exception:  # noqa: BLE001 — unexpected path shape
        return None


def audit_donation(lowered, min_bytes=1 << 20, carry_argnums=None):
    """Donation findings from a ``jax.stages.Lowered``'s arg/out info.

    An argument leaf is a *carry* when some output leaf has its exact
    (shape, dtype) — params vs updated params, accumulators vs updated
    accumulators.  Carries at or above ``min_bytes`` must be donated
    (``graph-donation-missing``); donated leaves that match no output
    cannot alias anywhere and are flagged ``graph-donation-unused``.
    Output slots are consumed greedily by donated args first, so a
    non-donated copy of an already-claimed output does not double-count.

    ``carry_argnums``: when the caller knows which positional arguments
    hold the step's carries (SPMDTrainer: params/aux/opt_state/extras),
    the missing-donation check is restricted to leaves under them — a
    DATA batch that happens to share an output's shape/dtype (an
    autoencoder's reconstruction, a per-example loss matching the label
    vector) must not be flagged as an un-donated carry.
    """
    import jax.tree_util as jtu

    arg_leaves = [(jtu.keystr(path), _leading_argnum(path), info)
                  for path, info in
                  jtu.tree_flatten_with_path(lowered.args_info)[0]]
    out_slots = {}
    for info in jtu.tree_leaves(lowered.out_info):
        key = (tuple(info.shape), str(info.dtype))
        out_slots[key] = out_slots.get(key, 0) + 1

    findings = []
    donated = [(p, i) for p, n, i in arg_leaves if i.donated]
    undonated = [(p, n, i) for p, n, i in arg_leaves if not i.donated]
    for path, info in donated:
        key = (tuple(info.shape), str(info.dtype))
        if out_slots.get(key, 0) > 0:
            out_slots[key] -= 1
        else:
            findings.append(Finding(
                "graph-donation-unused",
                "argument %s (%s%s, %d bytes) is donated but matches no "
                "output — XLA cannot alias it, the donation is wasted "
                "and the caller's buffer is invalidated anyway"
                % (path, info.dtype, list(info.shape),
                   _leaf_bytes(info.shape, info.dtype))))
    for path, argnum, info in undonated:
        if carry_argnums is not None and argnum not in carry_argnums:
            continue
        nbytes = _leaf_bytes(info.shape, info.dtype)
        if nbytes < min_bytes:
            continue
        key = (tuple(info.shape), str(info.dtype))
        if out_slots.get(key, 0) > 0:
            out_slots[key] -= 1
            findings.append(Finding(
                "graph-donation-missing",
                "argument %s (%s%s, %d bytes) looks like a carry (an "
                "output has the same shape/dtype) but is not donated — "
                "the step pays an avoidable HBM copy and holds two "
                "copies live" % (path, info.dtype, list(info.shape),
                                 nbytes)))
    return findings


def collective_stats(hlo_text):
    """Tally cross-device traffic in compiled (post-SPMD) HLO text.

    Returns ``{op: {"count": n, "bytes": b}}`` where ``bytes`` sums each
    instruction's per-device OUTPUT bytes (the shard this device
    materializes; async ``-start`` forms count once, ``-done`` not at
    all).  A sync instruction with a tuple result is a fused multi-tensor
    collective, so its shapes SUM.  An async ``-start`` result tuple is
    ``(operand-alias, result, context...)``: the payload is the RESULT —
    the largest shape for gathers (result = N x operand), the
    second-largest for reduce-scatter (result = operand / N; the tiny
    context buffers rank below both), and either of the two for the
    size-preserving ops.  A byte figure of 0 with nonzero count means
    shapes were unparseable (report still useful for counts).

    Degenerate instructions — ``replica_groups`` of singletons, the
    partitioner's zero-traffic way of materializing per-device partial
    values — are skipped entirely: they move no bytes between devices,
    and the schedule audit must not mistake them for real traffic.
    """
    stats = {op: {"count": 0, "bytes": 0} for op in COLLECTIVE_OPS}
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if m is None or m.group("suffix") == "-done":
            continue
        if _is_degenerate_groups(line):
            continue
        op = m.group("op")
        stats[op]["count"] += 1
        sizes = []
        for dtype, dims in _SHAPE_RE.findall(m.group("type")):
            if dtype not in _DTYPE_BYTES:
                continue
            n = 1
            for d in filter(None, dims.split(",")):
                n *= int(d)
            sizes.append(n * _DTYPE_BYTES[dtype])
        if sizes:
            if m.group("suffix") != "-start":
                nbytes = sum(sizes)
            else:
                ranked = sorted(sizes, reverse=True)
                if op == "reduce-scatter" and len(ranked) > 1:
                    nbytes = ranked[1]
                else:
                    nbytes = ranked[0]
            stats[op]["bytes"] += nbytes
    return {op: s for op, s in stats.items() if s["count"]}


def audit_collectives(stats, param_bytes=None, expect_allgather=False,
                      allgather_fraction=0.5):
    """``graph-collective-allgather``: all-gather traffic in a step that
    declared replicated parameters (plain dp 'allreduce') — GSPMD only
    emits one when something un-replicated sneaks into the param path.
    With ``param_bytes`` given, only traffic >= ``allgather_fraction`` of
    it flags (an incidental small gather is not a regather storm);
    without it, any all-gather flags."""
    if expect_allgather:
        return []
    ag = stats.get("all-gather", {"count": 0, "bytes": 0})
    if not ag["count"]:
        return []
    if param_bytes and ag["bytes"] < allgather_fraction * param_bytes:
        return []
    detail = "%d all-gather(s), %d bytes/step per device" \
        % (ag["count"], ag["bytes"])
    if param_bytes:
        detail += " (params total %d bytes)" % param_bytes
    return [Finding(
        "graph-collective-allgather",
        "unexpected all-gather under a sharding that declares replicated "
        "parameters: %s — a full-parameter regather erases the point of "
        "dp sharding (check param_shardings / with_sharding_constraint "
        "placement)" % detail,
        data={"all_gather": ag, "param_bytes": param_bytes})]


#: platforms whose XLA pipeline runs ReduceScatterCreator — on these
#: the GSPMD tier's gradient reduction MUST compile to reduce-scatter
#: (ROADMAP item 2's previously-unverified claim, now a lint assertion);
#: CPU keeps the all-reduce+slice form and stays a documented tier note
RS_PLATFORMS = frozenset(("tpu", "gpu", "cuda", "rocm"))


def audit_collective_schedule(stats, schedule, expect_gather_bytes,
                              tolerance=0.25, platform=None):
    """``graph-collective-schedule``: under a DECLARED fully-sharded
    strategy the compiled schedule must actually be sharded.

    ``schedule`` is ``'zero3-manual'`` or ``'zero3-gspmd'`` (None
    disables the rule); ``expect_gather_bytes`` is the per-step forward
    gather traffic a correct step must move (the full-size comm-dtype
    bytes of every dp-sharded parameter — the trainer computes it from
    base sharding rules and shapes, so a broken override cannot lower
    the bar).  ``platform`` is the compiled backend (``'cpu'``/
    ``'tpu'``/``'gpu'``...; None = unknown).  Checks:

    - all-gather traffic >= (1 - tolerance) x expected — a zero3 step
      that moves less is NOT gathering its parameters, i.e. they were
      silently left replicated and the sharding never happened;
    - a stray full all-reduce: all-reduce traffic at or above HALF the
      expected gather bytes means gradients left the backward as a
      full all-reduce instead of reduce-scatter.  The manual tier owes
      this on EVERY backend (its psum_scatter is explicit); the gspmd
      tier owes it on :data:`RS_PLATFORMS`, where ReduceScatterCreator
      rewrites all-reduce+slice — on CPU the all-reduce form is the
      documented backend placement, reported in ``stats`` not flagged;
    - at least one real reduce-scatter instruction: always for the
      manual tier (it emits one per gather bucket by construction),
      and for the gspmd tier on :data:`RS_PLATFORMS` — the
      ReduceScatterCreator claim is thereby PROVEN per compile instead
      of assumed from XLA documentation.
    """
    if not schedule:
        return []
    findings = []
    ag = stats.get("all-gather", {"count": 0, "bytes": 0})
    rs = stats.get("reduce-scatter", {"count": 0, "bytes": 0})
    ar = stats.get("all-reduce", {"count": 0, "bytes": 0})
    expect = int(expect_gather_bytes or 0)
    # the gspmd tier's gradient reduction is backend-placed; only on
    # RS-pipeline platforms is its shape an assertable contract
    owes_rs = schedule == "zero3-manual" or (
        schedule == "zero3-gspmd" and platform in RS_PLATFORMS)
    if expect and ag["bytes"] < (1.0 - tolerance) * expect:
        findings.append(Finding(
            "graph-collective-schedule",
            "declared %s but the compiled step all-gathers only %d "
            "bytes/step of the >= %d expected for its sharded "
            "parameters — the params were left replicated; the "
            "sharding silently never happened" %
            (schedule, ag["bytes"], expect),
            data={"all_gather": ag, "expect_gather_bytes": expect}))
    if expect and ar["bytes"] >= 0.5 * expect and owes_rs:
        findings.append(Finding(
            "graph-collective-schedule",
            "declared %s%s but a param-scale all-reduce (%d bytes/step) "
            "is in the compiled schedule — gradients are leaving the "
            "backward as a full all-reduce instead of reduce-scatter" %
            (schedule,
             (" on %s" % platform) if schedule == "zero3-gspmd" else "",
             ar["bytes"]),
            data={"all_reduce": ar, "expect_gather_bytes": expect,
                  "platform": platform}))
    if owes_rs and expect and not rs["count"]:
        if schedule == "zero3-manual":
            why = ("the manual tier emits one per gather bucket by "
                   "construction, so the step was not built from the "
                   "declared formulation")
        else:
            why = ("on %s XLA's ReduceScatterCreator must rewrite the "
                   "gradient all-reduce+slice into reduce-scatter — "
                   "its absence means the pass did not engage and the "
                   "backward pays full all-reduce bandwidth"
                   % platform)
        findings.append(Finding(
            "graph-collective-schedule",
            "declared %s but the compiled step contains no "
            "reduce-scatter — %s" % (schedule, why),
            data={"reduce_scatter": rs, "platform": platform}))
    return findings


def audit_plan_fusion(symbol):
    """The ``plan-fusion-parity`` rule: run the mxfuse pipeline over
    ``symbol``'s node plan (under the CURRENT ``MXTPU_FUSED_KERNELS``)
    and verify every override kept the plain-plan monitored contract.

    Checks (docs/how_to/performance.md "The plan optimizer"):

    1. the pipeline neither raises nor mutates the plain plan — the
       monitored path interprets that exact object;
    2. the rewritten plan is a PERMUTATION of the plain entries (none
       added or dropped) with byte-identical slots 0-4 — per-node RNG
       fold constants and monitor coordinates ride IN the entries, so
       identity must hold while interpretation order may be re-sorted
       — and the order is topologically valid for the post-override
       dependency graph (op-node values exist before an entry reads
       them; variables bind lazily);
    3. every override is ``(callable, [(plan-node, int)], dead-ins)``
       and no extra ref reads a value-rewriting passthrough (its env
       value is not that node's output);
    4. inference-trace pruning (``live_entries``) keeps every graph
       output and every extra-ref producer interpretable.

    Returns a :class:`Report`; violations are rule
    ``plan-fusion-parity``.
    """
    import copy

    from .. import mxfuse
    from ..executor import _node_plan

    rep = Report(tool="mxlint.graph")

    def flag(msg):
        rep.add("plan-fusion-parity", msg)

    plan = _node_plan(symbol)
    out_refs = [(id(n), i) for n, i in symbol._outputs]
    before = [(id(e[0]),) + tuple(copy.deepcopy(e[1:5])) for e in plan]
    try:
        fused = mxfuse.optimize_plan(plan, out_refs)
    except Exception as e:  # noqa: BLE001 — a broken pass IS the finding
        flag("pass pipeline raised %s: %s" % (type(e).__name__, e))
        return rep
    after = [(id(e[0]),) + tuple(e[1:5]) for e in plan]
    if before != after:
        flag("pass pipeline MUTATED the plain plan — monitored runs "
             "interpret that object verbatim")
    if fused is plan:
        rep.stats["plan_fusion"] = {"overrides": 0,
                                    "entries": len(plan)}
        return rep
    if len(fused) != len(plan):
        flag("rewritten plan has %d entries, plain plan %d — passes "
             "must never add or drop entries (per-node RNG fold "
             "constants travel with them)" % (len(fused), len(plan)))
        return rep
    plain_of = {id(e[0]): e for e in plan}
    if {id(e[0]) for e in fused} != set(plain_of):
        flag("rewritten plan is not a permutation of the plain "
             "entries — nodes were substituted")
        return rep
    n_overrides = 0
    seen = set()
    for fe in fused:
        pe = plain_of[id(fe[0])]
        if tuple(fe[1:5]) != tuple(pe[1:5]):
            flag("entry %r changed outside the override slot"
                 % fe[0].name)
        ov = fe[5]
        if ov is None:
            continue
        n_overrides += 1
        if not callable(ov[0]) or not isinstance(ov[1], (list, tuple)):
            flag("override at %r is not (callable, refs, ...)"
                 % fe[0].name)
            continue
        for ref in ov[1]:
            if id(ref[0]) not in plain_of:
                flag("override at %r references a node outside the "
                     "plan" % fe[0].name)
    # interpretation-order validity: an entry's op-node dependencies
    # (inputs + override extra refs) must already be interpreted when
    # it runs; variables bind lazily
    for fe in fused:
        node, ov = fe[0], fe[5]
        refs = list(node.inputs or ())
        if ov is not None:
            refs += list(ov[1])
        for src, _idx in refs:
            if id(src) in plain_of and src.op is not None \
                    and id(src) not in seen:
                flag("entry %r runs before its dependency %r — the "
                     "rewritten order is not topologically valid"
                     % (node.name, src.name))
                return rep
        seen.add(id(node))
    live = mxfuse.live_entries(fused, out_refs)
    live_ids = {id(e[0]) for e in live}
    for nid, _i in out_refs:
        if nid not in live_ids:
            flag("inference-trace pruning dropped a graph output")
    for e in live:
        ov = e[5]
        if ov is None:
            continue
        for src, _idx in ov[1]:
            if id(src) not in live_ids and src.op is not None:
                flag("pruned eval plan drops op node %r that an "
                     "override's extra refs read" % src.name)
    rep.stats["plan_fusion"] = {"overrides": n_overrides,
                                "entries": len(plan),
                                "eval_live": len(live)}
    return rep


def lint_lowered(lowered, closed_jaxpr=None, compute_dtype=None,
                 param_bytes=None, expect_allgather=True,
                 schedule=None, expect_gather_bytes=None,
                 min_donate_bytes=1 << 20, carry_argnums=None,
                 compiled_text=None, platform=None):
    """Run every graph rule against one lowered step.

    ``lowered`` is a ``jax.stages.Lowered``;  ``closed_jaxpr`` enables
    the callback/dtype rules (pass ``jax.make_jaxpr(fn)(*args)``);
    ``compiled_text`` skips the internal ``lowered.compile()`` when the
    caller already has the executable.  Returns a :class:`Report` whose
    ``stats["collectives"]`` always carries the audit tally (bench reads
    it even when nothing flags).
    """
    rep = Report(tool="mxlint.graph")
    rep.extend(audit_donation(lowered, min_bytes=min_donate_bytes,
                              carry_argnums=carry_argnums))
    if closed_jaxpr is not None:
        rep.extend(find_callbacks(closed_jaxpr))
        rep.extend(find_unprotected_pallas(closed_jaxpr))
        if compute_dtype is not None:
            findings, tally = audit_dtype(closed_jaxpr, compute_dtype)
            rep.extend(findings)
            rep.stats["compute_eqn_dtypes"] = tally
    if compiled_text is None:
        compiled_text = lowered.compile().as_text()
    stats = collective_stats(compiled_text)
    rep.stats["collectives"] = stats
    rep.extend(audit_collectives(stats, param_bytes=param_bytes,
                                 expect_allgather=expect_allgather))
    rep.extend(audit_collective_schedule(
        stats, schedule, expect_gather_bytes, platform=platform))
    if schedule:
        rep.stats["schedule"] = {
            "declared": schedule,
            "expect_gather_bytes": int(expect_gather_bytes or 0),
            "platform": platform}
    return rep


def lint_jit(fn, *args, donate_argnums=(), compute_dtype=None,
             param_bytes=None, expect_allgather=True,
             min_donate_bytes=1 << 20, **kwargs):
    """Convenience wrapper: jit + lower + trace ``fn`` and lint it.

    ``fn`` may already be jitted (then ``donate_argnums`` is ignored —
    the jit's own settings win).  Example::

        report = lint_jit(step, params, batch, donate_argnums=(0,),
                          expect_allgather=False)
        assert report.ok, report.format_text()
    """
    import jax
    jf = fn if hasattr(fn, "lower") else \
        jax.jit(fn, donate_argnums=donate_argnums)
    lowered = jf.lower(*args, **kwargs)
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    return lint_lowered(lowered, closed_jaxpr=closed,
                        compute_dtype=compute_dtype,
                        param_bytes=param_bytes,
                        expect_allgather=expect_allgather,
                        min_donate_bytes=min_donate_bytes)
