// mxtpu native runtime — C ABI surface.
//
// TPU-native re-design of the reference's native runtime layer
// (reference include/mxnet/engine.h:59-229, src/engine/threaded_engine.h,
// dmlc-core recordio):  the XLA runtime owns device-side scheduling, so this
// engine is the *host-side* concurrency authority — it orders IO, data
// pipeline stages, checkpoint writes, kvstore host ops and Python callbacks
// with the same read/write-variable dependency semantics the reference uses
// for every NDArray mutation.
#ifndef MXTPU_H_
#define MXTPU_H_

#include <cstdint>

#if defined(_WIN32)
#define MXTPU_API __declspec(dllexport)
#else
#define MXTPU_API __attribute__((visibility("default")))
#endif

extern "C" {

typedef void (*mxtpu_engine_cb)(void* payload);

// ---- engine ----
// engine_type: 0 = naive (synchronous, debugging), 1 = threaded pool.
MXTPU_API void* MXTPUEngineCreate(int engine_type, int num_workers);
MXTPU_API void MXTPUEngineShutdown(void* handle);
MXTPU_API uint64_t MXTPUEngineNewVar(void* handle);
// Deletion is dependency-safe: performed after all pending ops on the var.
MXTPU_API void MXTPUEngineDeleteVar(void* handle, uint64_t var);
// Returns 0 on success, -1 on error (duplicate vars across lists).
MXTPU_API int MXTPUEnginePushAsync(void* handle, mxtpu_engine_cb cb,
                                   void* payload, const uint64_t* const_vars,
                                   int n_const, const uint64_t* mutable_vars,
                                   int n_mutable, int priority,
                                   const char* opr_name);
MXTPU_API void MXTPUEngineWaitForVar(void* handle, uint64_t var);
MXTPU_API void MXTPUEngineWaitForAll(void* handle);
MXTPU_API int MXTPUEngineNumPending(void* handle);
MXTPU_API const char* MXTPUEngineLastError(void* handle);

// ---- profiler (chrome://tracing traceEvents) ----
// state: 0 = stop, 1 = run.  Dump returns a malloc'd JSON string.
MXTPU_API void MXTPUProfilerSetState(void* handle, int state);
MXTPU_API char* MXTPUProfilerDump(void* handle);

// ---- recordio ----
MXTPU_API void* MXTPURecordIOWriterCreate(const char* path);
MXTPU_API int MXTPURecordIOWriterWrite(void* handle, const char* data,
                                       uint64_t len);
MXTPU_API uint64_t MXTPURecordIOWriterTell(void* handle);
MXTPU_API void MXTPURecordIOWriterClose(void* handle);

MXTPU_API void* MXTPURecordIOReaderCreate(const char* path);
// Returns 1 if a record was read, 0 on EOF, -1 on corrupt stream.
// *out is malloc'd; free with MXTPUFree.
MXTPU_API int MXTPURecordIOReaderRead(void* handle, char** out,
                                      uint64_t* out_len);
MXTPU_API void MXTPURecordIOReaderSeek(void* handle, uint64_t pos);
MXTPU_API uint64_t MXTPURecordIOReaderTell(void* handle);
MXTPU_API void MXTPURecordIOReaderClose(void* handle);

MXTPU_API void MXTPUFree(void* ptr);

}  // extern "C"

#endif  // MXTPU_H_
