// RecordIO binary stream format — byte-compatible with the reference's
// dmlc-core recordio (reference usage: src/io/iter_image_recordio*.cc,
// python/mxnet/recordio.py), so .rec datasets packed for the reference load
// unmodified here.
//
// Format: each record is framed as
//   [kMagic (4B LE)] [lrecord (4B LE)] [payload (len bytes)] [pad to 4B]
//   lrecord = (cflag << 29) | length,  cflag: 0 = whole record,
//   1 = first part, 2 = middle, 3 = last part.
// A payload containing the magic word at a 4-byte-aligned offset is split at
// that point (the magic bytes are elided and re-inserted by the reader), so
// the magic is a valid resync marker anywhere in the file.
#include "mxtpu.h"

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace mxtpu {

static const uint32_t kMagic = 0xced7230a;
static const uint32_t kLenMask = (1U << 29U) - 1U;

inline uint32_t EncodeLRec(uint32_t cflag, uint32_t length) {
  return (cflag << 29U) | length;
}

class RecordWriter {
 public:
  explicit RecordWriter(const char* path) { fp_ = fopen(path, "wb"); }
  bool ok() const { return fp_ != nullptr; }

  int Write(const char* data, uint64_t size) {
    if (size >= (1ULL << 29U)) return -1;
    const char* magic_bytes = reinterpret_cast<const char*>(&kMagic);
    uint32_t len = static_cast<uint32_t>(size);
    uint32_t lower_align = (len >> 2U) << 2U;
    uint32_t upper_align = ((len + 3U) >> 2U) << 2U;
    uint32_t dptr = 0;
    for (uint32_t i = 0; i < lower_align; i += 4) {
      if (data[i] == magic_bytes[0] && data[i + 1] == magic_bytes[1] &&
          data[i + 2] == magic_bytes[2] && data[i + 3] == magic_bytes[3]) {
        uint32_t lrec = EncodeLRec(dptr == 0 ? 1U : 2U, i - dptr);
        if (fwrite(&kMagic, 4, 1, fp_) != 1) return -1;
        if (fwrite(&lrec, 4, 1, fp_) != 1) return -1;
        if (i != dptr && fwrite(data + dptr, i - dptr, 1, fp_) != 1) return -1;
        dptr = i + 4;
      }
    }
    uint32_t lrec = EncodeLRec(dptr != 0 ? 3U : 0U, len - dptr);
    if (fwrite(&kMagic, 4, 1, fp_) != 1) return -1;
    if (fwrite(&lrec, 4, 1, fp_) != 1) return -1;
    if (len != dptr && fwrite(data + dptr, len - dptr, 1, fp_) != 1) return -1;
    if (upper_align != len) {
      uint32_t zero = 0;
      if (fwrite(&zero, upper_align - len, 1, fp_) != 1) return -1;
    }
    return 0;
  }

  uint64_t Tell() { return static_cast<uint64_t>(ftell(fp_)); }

  ~RecordWriter() {
    if (fp_) fclose(fp_);
  }

 private:
  FILE* fp_ = nullptr;
};

class RecordReader {
 public:
  explicit RecordReader(const char* path) { fp_ = fopen(path, "rb"); }
  bool ok() const { return fp_ != nullptr; }

  // 1 = record read into out, 0 = EOF, -1 = corrupt.
  int Read(std::string* out) {
    out->clear();
    bool in_multipart = false;
    for (;;) {
      uint32_t magic = 0;
      size_t got = fread(&magic, 1, 4, fp_);
      if (got == 0 && !in_multipart) return 0;  // clean EOF
      if (got != 4 || magic != kMagic) return -1;
      uint32_t lrec = 0;
      if (fread(&lrec, 1, 4, fp_) != 4) return -1;
      uint32_t cflag = lrec >> 29U;
      uint32_t len = lrec & kLenMask;
      uint32_t upper_align = ((len + 3U) >> 2U) << 2U;
      size_t base = out->size();
      if (in_multipart) {
        // Re-insert the elided magic between parts.
        out->append(reinterpret_cast<const char*>(&kMagic), 4);
        base = out->size();
      }
      out->resize(base + upper_align);
      if (upper_align &&
          fread(&(*out)[base], 1, upper_align, fp_) != upper_align)
        return -1;
      out->resize(base + len);
      if (cflag == 0) return 1;
      if (cflag == 3) return in_multipart ? 1 : -1;
      if (cflag == 1 && in_multipart) return -1;
      in_multipart = true;
    }
  }

  void Seek(uint64_t pos) { fseek(fp_, static_cast<long>(pos), SEEK_SET); }
  uint64_t Tell() { return static_cast<uint64_t>(ftell(fp_)); }

  ~RecordReader() {
    if (fp_) fclose(fp_);
  }

 private:
  FILE* fp_ = nullptr;
};

}  // namespace mxtpu

extern "C" {

void* MXTPURecordIOWriterCreate(const char* path) {
  auto* w = new mxtpu::RecordWriter(path);
  if (!w->ok()) {
    delete w;
    return nullptr;
  }
  return w;
}

int MXTPURecordIOWriterWrite(void* handle, const char* data, uint64_t len) {
  return static_cast<mxtpu::RecordWriter*>(handle)->Write(data, len);
}

uint64_t MXTPURecordIOWriterTell(void* handle) {
  return static_cast<mxtpu::RecordWriter*>(handle)->Tell();
}

void MXTPURecordIOWriterClose(void* handle) {
  delete static_cast<mxtpu::RecordWriter*>(handle);
}

void* MXTPURecordIOReaderCreate(const char* path) {
  auto* r = new mxtpu::RecordReader(path);
  if (!r->ok()) {
    delete r;
    return nullptr;
  }
  return r;
}

int MXTPURecordIOReaderRead(void* handle, char** out, uint64_t* out_len) {
  std::string rec;
  int ret = static_cast<mxtpu::RecordReader*>(handle)->Read(&rec);
  if (ret != 1) {
    *out = nullptr;
    *out_len = 0;
    return ret;
  }
  *out = static_cast<char*>(malloc(rec.size() ? rec.size() : 1));
  memcpy(*out, rec.data(), rec.size());
  *out_len = rec.size();
  return 1;
}

void MXTPURecordIOReaderSeek(void* handle, uint64_t pos) {
  static_cast<mxtpu::RecordReader*>(handle)->Seek(pos);
}

uint64_t MXTPURecordIOReaderTell(void* handle) {
  return static_cast<mxtpu::RecordReader*>(handle)->Tell();
}

void MXTPURecordIOReaderClose(void* handle) {
  delete static_cast<mxtpu::RecordReader*>(handle);
}

}  // extern "C"
