// Host-side dependency engine.
//
// Semantics match the reference engine's observable contract
// (reference include/mxnet/engine.h:75-229, src/engine/threaded_engine.h:44-394):
//   * variables carry FIFO dependency queues; reads on a var may run
//     concurrently, a write excludes everything and serializes in push order;
//   * ops declare const (read) and mutable (write) var sets and run once all
//     grants arrive;
//   * WaitForVar blocks until everything pushed so far that touches the var
//     completed; WaitForAll drains the engine;
//   * variable deletion is itself a dependency-ordered op.
//
// The implementation is new: a single ready-queue thread pool (host work is
// IO/callback bound — device-side scheduling belongs to XLA, so the
// reference's per-device pools/stream manager have no analog here), grant
// bookkeeping via per-var deques, and an inline "naive" mode that runs ops
// synchronously on the pusher thread for debugging
// (reference src/engine/naive_engine.cc:16-198).
#include "mxtpu.h"

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <queue>
#include <sstream>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace mxtpu {

inline int64_t NowMicros() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

struct Opr;

// Per-variable dependency queue.  Protected by its own mutex; grant
// transitions happen under the lock, op scheduling happens outside it.
struct Var {
  struct Block {
    Opr* opr;
    bool write;
  };
  std::mutex mu;
  std::deque<Block> queue;
  int running_reads = 0;
  bool write_granted = false;
  bool to_delete = false;
  uint64_t version = 0;
};

struct Opr {
  std::function<void()> fn;
  std::vector<Var*> const_vars;
  std::vector<Var*> mutable_vars;
  std::atomic<int> wait{0};
  int priority = 0;
  std::string name;
  int64_t push_time_us = 0;
};

struct ProfileEvent {
  std::string name;
  int64_t start_us;
  int64_t end_us;
  uint64_t tid;
};

class Engine {
 public:
  Engine(int engine_type, int num_workers)
      : naive_(engine_type == 0) {
    if (!naive_) {
      if (num_workers <= 0) {
        // Host work is IO/callback bound — keep a floor above core count.
        num_workers = static_cast<int>(std::thread::hardware_concurrency());
        if (num_workers < 4) num_workers = 4;
      }
      for (int i = 0; i < num_workers; ++i) {
        workers_.emplace_back([this] { this->WorkerLoop(); });
      }
    }
  }

  ~Engine() { Shutdown(); }

  void Shutdown() {
    {
      std::unique_lock<std::mutex> lk(ready_mu_);
      if (stop_) return;
      WaitForAllLocked(lk);
      stop_ = true;
      ready_cv_.notify_all();
    }
    for (auto& t : workers_) t.join();
    workers_.clear();
    std::lock_guard<std::mutex> lk(vars_mu_);
    for (auto& kv : vars_) delete kv.second;
    vars_.clear();
  }

  uint64_t NewVar() {
    std::lock_guard<std::mutex> lk(vars_mu_);
    uint64_t id = next_var_id_++;
    vars_[id] = new Var();
    return id;
  }

  Var* FindVar(uint64_t id) {
    std::lock_guard<std::mutex> lk(vars_mu_);
    auto it = vars_.find(id);
    return it == vars_.end() ? nullptr : it->second;
  }

  void DeleteVar(uint64_t id) {
    Var* v = FindVar(id);
    if (v == nullptr) return;
    // Dependency-safe: deletion is a write op on the var; the var object is
    // reclaimed after every already-pushed op on it completed (reference
    // Engine::DeleteVariable contract, include/mxnet/engine.h:146-155).
    Push([this, id, v] {
      {
        std::lock_guard<std::mutex> lk(vars_mu_);
        vars_.erase(id);
      }
      v->to_delete = true;
    },
         {}, {v}, 0, "DeleteVariable");
  }

  int PushAsync(mxtpu_engine_cb cb, void* payload,
                const uint64_t* const_ids, int n_const,
                const uint64_t* mutable_ids, int n_mutable, int priority,
                const char* name) {
    std::vector<Var*> cvars, mvars;
    cvars.reserve(n_const);
    mvars.reserve(n_mutable);
    for (int i = 0; i < n_const; ++i) {
      Var* v = FindVar(const_ids[i]);
      if (v == nullptr) return Fail("unknown const var");
      cvars.push_back(v);
    }
    for (int i = 0; i < n_mutable; ++i) {
      Var* v = FindVar(mutable_ids[i]);
      if (v == nullptr) return Fail("unknown mutable var");
      mvars.push_back(v);
    }
    // Reject duplicates (reference ThreadedEngine::CheckDuplicate,
    // src/engine/threaded_engine.h:351).
    for (Var* c : cvars)
      for (Var* m : mvars)
        if (c == m) return Fail("var appears in both const and mutable list");
    for (size_t i = 0; i < mvars.size(); ++i)
      for (size_t j = i + 1; j < mvars.size(); ++j)
        if (mvars[i] == mvars[j]) return Fail("duplicate mutable var");
    for (size_t i = 0; i < cvars.size(); ++i)
      for (size_t j = i + 1; j < cvars.size(); ++j)
        if (cvars[i] == cvars[j]) return Fail("duplicate const var");
    Push([cb, payload] { cb(payload); }, std::move(cvars), std::move(mvars),
         priority, name ? name : "");
    return 0;
  }

  void Push(std::function<void()> fn, std::vector<Var*> cvars,
            std::vector<Var*> mvars, int priority, std::string name) {
    Opr* op = new Opr();
    op->fn = std::move(fn);
    op->const_vars = std::move(cvars);
    op->mutable_vars = std::move(mvars);
    op->priority = priority;
    op->name = std::move(name);
    op->push_time_us = NowMicros();
    {
      std::lock_guard<std::mutex> lk(ready_mu_);
      ++pending_;
    }
    // +1 guard so the op cannot fire while we are still appending deps.
    op->wait.store(1 + static_cast<int>(op->const_vars.size() +
                                        op->mutable_vars.size()),
                   std::memory_order_relaxed);
    for (Var* v : op->const_vars) AppendDep(v, op, /*write=*/false);
    for (Var* v : op->mutable_vars) AppendDep(v, op, /*write=*/true);
    OnDepGranted(op);  // release the guard
  }

  void WaitForVar(uint64_t id) {
    Var* v = FindVar(id);
    if (v == nullptr) return;
    std::mutex done_mu;
    std::condition_variable done_cv;
    bool done = false;
    Push([&] {
      std::lock_guard<std::mutex> lk(done_mu);
      done = true;
      done_cv.notify_all();
    },
         {v}, {}, 1 << 20, "WaitForVar");
    std::unique_lock<std::mutex> lk(done_mu);
    done_cv.wait(lk, [&] { return done; });
  }

  void WaitForAll() {
    std::unique_lock<std::mutex> lk(ready_mu_);
    WaitForAllLocked(lk);
  }

  int NumPending() {
    std::lock_guard<std::mutex> lk(ready_mu_);
    return pending_;
  }

  void SetProfilerState(int state) {
    std::lock_guard<std::mutex> lk(prof_mu_);
    profiling_ = state != 0;
  }

  static std::string JsonEscape(const std::string& s) {
    std::string out;
    out.reserve(s.size());
    for (char c : s) {
      if (c == '"' || c == '\\') {
        out += '\\';
        out += c;
      } else if (static_cast<unsigned char>(c) < 0x20) {
        char buf[8];
        snprintf(buf, sizeof(buf), "\\u%04x", c);
        out += buf;
      } else {
        out += c;
      }
    }
    return out;
  }

  // Chrome traceEvents JSON (reference src/engine/profiler.cc:134-216).
  char* DumpProfile() {
    std::ostringstream os;
    os << "{\n  \"traceEvents\": [\n";
    {
      std::lock_guard<std::mutex> lk(prof_mu_);
      for (size_t i = 0; i < events_.size(); ++i) {
        ProfileEvent e = events_[i];
        e.name = JsonEscape(e.name);
        if (i) os << ",\n";
        os << "    {\"name\": \"" << e.name
           << "\", \"cat\": \"operator\", \"ph\": \"B\", \"ts\": "
           << e.start_us << ", \"pid\": 0, \"tid\": " << e.tid << "},\n";
        os << "    {\"name\": \"" << e.name
           << "\", \"cat\": \"operator\", \"ph\": \"E\", \"ts\": " << e.end_us
           << ", \"pid\": 0, \"tid\": " << e.tid << "}";
      }
    }
    os << "\n  ],\n  \"displayTimeUnit\": \"ms\"\n}\n";
    std::string s = os.str();
    char* out = static_cast<char*>(malloc(s.size() + 1));
    memcpy(out, s.c_str(), s.size() + 1);
    return out;
  }

  const char* LastError() {
    std::lock_guard<std::mutex> lk(err_mu_);
    return last_error_.c_str();
  }

 private:
  int Fail(const char* msg) {
    std::lock_guard<std::mutex> lk(err_mu_);
    last_error_ = msg;
    return -1;
  }

  void AppendDep(Var* v, Opr* op, bool write) {
    bool grant = false;
    {
      std::lock_guard<std::mutex> lk(v->mu);
      if (write) {
        if (v->queue.empty() && v->running_reads == 0 && !v->write_granted) {
          v->write_granted = true;
          grant = true;
        } else {
          v->queue.push_back({op, true});
        }
      } else {
        if (v->queue.empty() && !v->write_granted) {
          ++v->running_reads;
          grant = true;
        } else {
          v->queue.push_back({op, false});
        }
      }
    }
    if (grant) OnDepGranted(op);
  }

  void CompleteAccess(Var* v, bool write) {
    std::vector<Opr*> granted;
    bool reclaim = false;
    {
      std::lock_guard<std::mutex> lk(v->mu);
      if (write) {
        v->write_granted = false;
        ++v->version;
      } else {
        --v->running_reads;
      }
      // Grant queue heads: one write, or a maximal run of reads.
      while (!v->queue.empty()) {
        Var::Block& b = v->queue.front();
        if (b.write) {
          if (v->running_reads == 0 && !v->write_granted) {
            v->write_granted = true;
            granted.push_back(b.opr);
            v->queue.pop_front();
          }
          break;
        }
        if (v->write_granted) break;
        ++v->running_reads;
        granted.push_back(b.opr);
        v->queue.pop_front();
      }
      reclaim = v->to_delete && v->queue.empty() && v->running_reads == 0 &&
                !v->write_granted;
    }
    for (Opr* op : granted) OnDepGranted(op);
    if (reclaim) delete v;
  }

  void OnDepGranted(Opr* op) {
    if (op->wait.fetch_sub(1, std::memory_order_acq_rel) == 1) Schedule(op);
  }

  void Schedule(Opr* op) {
    if (naive_) {
      ExecuteOpr(op);
    } else {
      std::lock_guard<std::mutex> lk(ready_mu_);
      ready_.push(op);
      ready_cv_.notify_one();
    }
  }

  void ExecuteOpr(Opr* op) {
    int64_t start = profiling_ ? NowMicros() : 0;
    op->fn();
    if (profiling_) {
      ProfileEvent e;
      e.name = op->name.empty() ? "op" : op->name;
      e.start_us = start;
      e.end_us = NowMicros();
      e.tid = std::hash<std::thread::id>()(std::this_thread::get_id());
      std::lock_guard<std::mutex> lk(prof_mu_);
      events_.push_back(std::move(e));
    }
    for (Var* v : op->const_vars) CompleteAccess(v, false);
    for (Var* v : op->mutable_vars) CompleteAccess(v, true);
    delete op;
    {
      std::lock_guard<std::mutex> lk(ready_mu_);
      --pending_;
      if (pending_ == 0) all_done_cv_.notify_all();
    }
  }

  void WorkerLoop() {
    for (;;) {
      Opr* op = nullptr;
      {
        std::unique_lock<std::mutex> lk(ready_mu_);
        ready_cv_.wait(lk, [this] { return stop_ || !ready_.empty(); });
        if (stop_ && ready_.empty()) return;
        op = ready_.top();
        ready_.pop();
      }
      ExecuteOpr(op);
    }
  }

  void WaitForAllLocked(std::unique_lock<std::mutex>& lk) {
    all_done_cv_.wait(lk, [this] { return pending_ == 0; });
  }

  struct OprLess {
    bool operator()(const Opr* a, const Opr* b) const {
      if (a->priority != b->priority) return a->priority < b->priority;
      return a->push_time_us > b->push_time_us;  // FIFO within priority
    }
  };

  bool naive_;
  bool stop_ = false;
  int pending_ = 0;
  std::mutex ready_mu_;
  std::condition_variable ready_cv_;
  std::condition_variable all_done_cv_;
  std::priority_queue<Opr*, std::vector<Opr*>, OprLess> ready_;
  std::vector<std::thread> workers_;

  std::mutex vars_mu_;
  std::unordered_map<uint64_t, Var*> vars_;
  uint64_t next_var_id_ = 1;

  std::mutex prof_mu_;
  std::atomic<bool> profiling_{false};
  std::vector<ProfileEvent> events_;

  std::mutex err_mu_;
  std::string last_error_;
};

}  // namespace mxtpu

extern "C" {

void* MXTPUEngineCreate(int engine_type, int num_workers) {
  return new mxtpu::Engine(engine_type, num_workers);
}

void MXTPUEngineShutdown(void* handle) {
  delete static_cast<mxtpu::Engine*>(handle);
}

uint64_t MXTPUEngineNewVar(void* handle) {
  return static_cast<mxtpu::Engine*>(handle)->NewVar();
}

void MXTPUEngineDeleteVar(void* handle, uint64_t var) {
  static_cast<mxtpu::Engine*>(handle)->DeleteVar(var);
}

int MXTPUEnginePushAsync(void* handle, mxtpu_engine_cb cb, void* payload,
                         const uint64_t* const_vars, int n_const,
                         const uint64_t* mutable_vars, int n_mutable,
                         int priority, const char* opr_name) {
  return static_cast<mxtpu::Engine*>(handle)->PushAsync(
      cb, payload, const_vars, n_const, mutable_vars, n_mutable, priority,
      opr_name);
}

void MXTPUEngineWaitForVar(void* handle, uint64_t var) {
  static_cast<mxtpu::Engine*>(handle)->WaitForVar(var);
}

void MXTPUEngineWaitForAll(void* handle) {
  static_cast<mxtpu::Engine*>(handle)->WaitForAll();
}

int MXTPUEngineNumPending(void* handle) {
  return static_cast<mxtpu::Engine*>(handle)->NumPending();
}

const char* MXTPUEngineLastError(void* handle) {
  return static_cast<mxtpu::Engine*>(handle)->LastError();
}

void MXTPUProfilerSetState(void* handle, int state) {
  static_cast<mxtpu::Engine*>(handle)->SetProfilerState(state);
}

char* MXTPUProfilerDump(void* handle) {
  return static_cast<mxtpu::Engine*>(handle)->DumpProfile();
}

void MXTPUFree(void* ptr) { free(ptr); }

}  // extern "C"
