// Native-speed im2rec: pack an image list into RecordIO.
//
// TPU-native analog of the reference tools/im2rec.cc (its OpenCV
// multithreaded packer): worker threads read+transcode images (libjpeg
// decode -> shorter-edge bilinear resize -> libjpeg encode), a writer
// serializes records in LIST ORDER into the .rec via the framing in
// recordio.cc and emits the .idx (id \t offset) alongside.  Python
// drives it through ctypes (tools/im2rec.py --native); the pure-Python
// path stays as the portable fallback.
//
// Record payload layout matches mxnet_tpu/recordio.py pack():
//   IRHeader = <u32 flag> <f32 label> <u64 id> <u64 id2>  (little endian)
//   followed by the (possibly transcoded) image bytes.
#include <stdio.h>   // jpeglib.h needs FILE declared first

#include <jpeglib.h>
#include <setjmp.h>
#include <stdint.h>
#include <string.h>

#include <atomic>
#include <condition_variable>
#include <cstdlib>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

// from recordio.cc
extern "C" {
void* MXTPURecordIOWriterCreate(const char* path);
int MXTPURecordIOWriterWrite(void* handle, const char* data, uint64_t len);
uint64_t MXTPURecordIOWriterTell(void* handle);
void MXTPURecordIOWriterClose(void* handle);
}

namespace {

struct JErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

void JErrExit(j_common_ptr cinfo) {
  JErr* e = reinterpret_cast<JErr*>(cinfo->err);
  longjmp(e->jb, 1);
}

// full-frame RGB decode (no ROI — im2rec wants the whole image)
bool DecodeFull(const uint8_t* buf, size_t len, std::vector<uint8_t>* rgb,
                int* w, int* h) {
  jpeg_decompress_struct cinfo;
  JErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = JErrExit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(buf),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  *w = static_cast<int>(cinfo.output_width);
  *h = static_cast<int>(cinfo.output_height);
  rgb->resize(static_cast<size_t>(*w) * *h * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = rgb->data() +
        static_cast<size_t>(cinfo.output_scanline) * *w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// shorter-edge resize, bilinear (reference image.py resize_short ints)
void ResizeShort(const std::vector<uint8_t>& src, int sw, int sh,
                 int target, std::vector<uint8_t>* dst, int* dw, int* dh) {
  if (sw <= sh) {
    *dw = target;
    *dh = static_cast<int>(static_cast<int64_t>(target) * sh / sw);
  } else {
    *dh = target;
    *dw = static_cast<int>(static_cast<int64_t>(target) * sw / sh);
  }
  dst->resize(static_cast<size_t>(*dw) * *dh * 3);
  const float fx = static_cast<float>(sw) / *dw;
  const float fy = static_cast<float>(sh) / *dh;
  for (int y = 0; y < *dh; ++y) {
    float syf = (y + 0.5f) * fy - 0.5f;
    int y0 = static_cast<int>(syf);
    if (y0 < 0) y0 = 0;
    int y1 = y0 + 1 < sh ? y0 + 1 : sh - 1;
    float wy = syf - y0;
    if (wy < 0) wy = 0;
    for (int x = 0; x < *dw; ++x) {
      float sxf = (x + 0.5f) * fx - 0.5f;
      int x0 = static_cast<int>(sxf);
      if (x0 < 0) x0 = 0;
      int x1 = x0 + 1 < sw ? x0 + 1 : sw - 1;
      float wx = sxf - x0;
      if (wx < 0) wx = 0;
      for (int c = 0; c < 3; ++c) {
        float v =
            (1 - wy) * ((1 - wx) * src[(static_cast<size_t>(y0) * sw + x0) * 3 + c] +
                        wx * src[(static_cast<size_t>(y0) * sw + x1) * 3 + c]) +
            wy * ((1 - wx) * src[(static_cast<size_t>(y1) * sw + x0) * 3 + c] +
                  wx * src[(static_cast<size_t>(y1) * sw + x1) * 3 + c]);
        int q = static_cast<int>(v + 0.5f);
        (*dst)[(static_cast<size_t>(y) * *dw + x) * 3 + c] =
            static_cast<uint8_t>(q < 0 ? 0 : (q > 255 ? 255 : q));
      }
    }
  }
}

bool EncodeJpeg(const std::vector<uint8_t>& rgb, int w, int h, int quality,
                std::vector<uint8_t>* out) {
  jpeg_compress_struct cinfo;
  JErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = JErrExit;
  // volatile: assigned between setjmp and a potential longjmp — without
  // it the error path would free an indeterminate pointer (C11 7.13.2.1)
  unsigned char* volatile mem = nullptr;
  unsigned long mem_len = 0;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_compress(&cinfo);
    if (mem) free(mem);
    return false;
  }
  jpeg_create_compress(&cinfo);
  jpeg_mem_dest(&cinfo, const_cast<unsigned char**>(&mem), &mem_len);
  cinfo.image_width = static_cast<JDIMENSION>(w);
  cinfo.image_height = static_cast<JDIMENSION>(h);
  cinfo.input_components = 3;
  cinfo.in_color_space = JCS_RGB;
  jpeg_set_defaults(&cinfo);
  jpeg_set_quality(&cinfo, quality, TRUE);
  jpeg_start_compress(&cinfo, TRUE);
  while (cinfo.next_scanline < cinfo.image_height) {
    JSAMPROW row = const_cast<JSAMPROW>(
        rgb.data() + static_cast<size_t>(cinfo.next_scanline) * w * 3);
    jpeg_write_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_compress(&cinfo);
  jpeg_destroy_compress(&cinfo);
  out->assign(mem, mem + mem_len);
  free(mem);
  return true;
}

struct Item {
  uint64_t id;
  float label;
  std::string path;
};

struct Result {
  bool ok;
  std::vector<uint8_t> record;   // IRHeader + payload
};

bool ReadFile(const std::string& path, std::vector<uint8_t>* out) {
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) return false;
  fseek(f, 0, SEEK_END);
  long n = ftell(f);
  fseek(f, 0, SEEK_SET);
  if (n < 0) { fclose(f); return false; }
  out->resize(static_cast<size_t>(n));
  bool ok = n == 0 || fread(out->data(), 1, static_cast<size_t>(n), f) ==
      static_cast<size_t>(n);
  fclose(f);
  return ok;
}

void BuildRecord(const Item& it, const std::vector<uint8_t>& payload,
                 std::vector<uint8_t>* rec) {
  rec->resize(24 + payload.size());
  uint32_t flag = 0;
  memcpy(rec->data(), &flag, 4);
  memcpy(rec->data() + 4, &it.label, 4);
  uint64_t id = it.id, id2 = 0;
  memcpy(rec->data() + 8, &id, 8);
  memcpy(rec->data() + 16, &id2, 8);
  memcpy(rec->data() + 24, payload.data(), payload.size());
}

bool IsJpeg(const std::vector<uint8_t>& b) {
  return b.size() > 3 && b[0] == 0xFF && b[1] == 0xD8;
}

}  // namespace

extern "C" int MXTPUIm2Rec(const char* lst_path, const char* root,
                           const char* rec_path, const char* idx_path,
                           int resize, int quality, int nthreads,
                           int pass_through, uint64_t* out_packed,
                           uint64_t* out_skipped) {
  // ---- parse the list -------------------------------------------------
  std::vector<Item> items;
  {
    FILE* f = fopen(lst_path, "r");
    if (!f) return -1;
    char line[65536];
    while (fgets(line, sizeof(line), f)) {
      // idx \t label... \t path  (path = last field, label = second)
      std::vector<char*> fields;
      char* save = nullptr;
      for (char* tok = strtok_r(line, "\t\n", &save); tok;
           tok = strtok_r(nullptr, "\t\n", &save)) {
        fields.push_back(tok);
      }
      if (fields.size() < 3) continue;
      Item it;
      it.id = strtoull(fields[0], nullptr, 10);
      it.label = strtof(fields[1], nullptr);
      std::string p = fields.back();
      if (root && root[0] && p[0] != '/') {
        it.path = std::string(root) + "/" + p;
      } else {
        it.path = p;
      }
      items.push_back(std::move(it));
    }
    fclose(f);
  }

  void* writer = MXTPURecordIOWriterCreate(rec_path);
  if (!writer) return -2;
  FILE* idx = fopen(idx_path, "w");
  if (!idx) { MXTPURecordIOWriterClose(writer); return -3; }

  // ---- pipeline: workers transcode, writer drains in order ------------
  std::mutex mu;
  std::condition_variable cv;
  std::map<size_t, Result> done;
  size_t next_in = 0;     // next index to claim (under mu)
  size_t next_out = 0;    // writer's cursor (under mu)
  std::atomic<uint64_t> skipped{0};
  std::atomic<bool> abort_all{false};
  const size_t n = items.size();
  const size_t max_inflight = static_cast<size_t>(nthreads) * 8 + 8;

  auto worker = [&]() {
    for (;;) {
      size_t i;
      {
        // backpressure at CLAIM time: a worker may only take an item
        // within max_inflight of the writer's cursor, so depositing a
        // finished item never blocks and the item the in-order writer
        // needs next is always claimable (no deadlock)
        std::unique_lock<std::mutex> lk(mu);
        cv.wait(lk, [&] {
          return abort_all.load() || next_in >= n ||
                 next_in < next_out + max_inflight;
        });
        if (abort_all.load() || next_in >= n) return;
        i = next_in++;
      }
      Result r;
      r.ok = false;
      std::vector<uint8_t> raw;
      if (ReadFile(items[i].path, &raw) && !raw.empty()) {
        if (pass_through || !IsJpeg(raw)) {
          // pack source bytes untouched (non-JPEG sources are always
          // passed through; the python path transcodes them via cv2)
          BuildRecord(items[i], raw, &r.record);
          r.ok = true;
        } else {
          std::vector<uint8_t> rgb;
          int w = 0, h = 0;
          if (DecodeFull(raw.data(), raw.size(), &rgb, &w, &h)) {
            std::vector<uint8_t> enc;
            if (resize > 0 && (w < h ? w : h) != resize) {
              std::vector<uint8_t> rs;
              int rw = 0, rh = 0;
              ResizeShort(rgb, w, h, resize, &rs, &rw, &rh);
              r.ok = EncodeJpeg(rs, rw, rh, quality, &enc);
            } else {
              r.ok = EncodeJpeg(rgb, w, h, quality, &enc);
            }
            if (r.ok) BuildRecord(items[i], enc, &r.record);
          }
        }
      }
      std::unique_lock<std::mutex> lk(mu);
      done.emplace(i, std::move(r));
      cv.notify_all();
    }
  };

  int nt = nthreads > 0 ? nthreads : 1;
  std::vector<std::thread> pool;
  pool.reserve(static_cast<size_t>(nt));
  for (int t = 0; t < nt; ++t) pool.emplace_back(worker);

  uint64_t packed = 0;
  int rc = 0;
  for (size_t i = 0; i < n; ++i) {
    Result r;
    {
      std::unique_lock<std::mutex> lk(mu);
      cv.wait(lk, [&] { return done.count(i) != 0; });
      r = std::move(done[i]);
      done.erase(i);
      next_out = i + 1;
      cv.notify_all();
    }
    if (!r.ok) {
      skipped.fetch_add(1);
      continue;
    }
    uint64_t pos = MXTPURecordIOWriterTell(writer);
    if (MXTPURecordIOWriterWrite(
            writer, reinterpret_cast<const char*>(r.record.data()),
            r.record.size()) != 0) {
      rc = -4;
      break;
    }
    fprintf(idx, "%llu\t%llu\n",
            static_cast<unsigned long long>(items[i].id),
            static_cast<unsigned long long>(pos));
    ++packed;
  }

  abort_all.store(true);
  cv.notify_all();
  for (auto& t : pool) t.join();
  fclose(idx);
  MXTPURecordIOWriterClose(writer);
  if (out_packed) *out_packed = packed;
  if (out_skipped) *out_skipped = skipped.load();
  return rc;
}
