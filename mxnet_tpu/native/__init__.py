"""Loader for the native host-runtime library (libmxtpu.so).

The native layer provides the host-side dependency engine and the RecordIO
codec (see engine.cc / recordio.cc).  It is built on first import if a
compiler is available; all Python callers degrade gracefully to pure-Python
fallbacks when it is not (so the framework stays importable on minimal
systems).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libmxtpu.so")
_SRCS = ("engine.cc", "recordio.cc", "imagedec.cc", "im2rec.cc")

_lock = threading.Lock()
_lib = None
_tried = False


def _build():
    """Compile libmxtpu.so in-place.  Returns True on success.

    Compiles to a per-pid temp name then renames atomically so concurrent
    first-use from multiple processes cannot dlopen a half-written file.
    """
    srcs = [os.path.join(_DIR, s) for s in _SRCS]
    tmp = _LIB_PATH + ".%d.tmp" % os.getpid()
    base = ["g++", "-O2", "-std=c++17", "-fPIC", "-shared", "-pthread",
            "-o", tmp]
    # Preferred build includes the libjpeg image pipeline; hosts without
    # libjpeg still get the engine + recordio codec (image callers fall
    # back to the cv2 path).
    _JPEG_SRCS = ("imagedec.cc", "im2rec.cc")
    attempts = [base + srcs + ["-ljpeg"],
                base + [s for s in srcs
                        if not s.endswith(_JPEG_SRCS)]]
    try:
        built = False
        for cmd in attempts:
            proc = subprocess.run(cmd, capture_output=True, timeout=300)
            if proc.returncode == 0 and os.path.exists(tmp):
                built = True
                break
        if not built:
            return False
        os.replace(tmp, _LIB_PATH)
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    return os.path.exists(_LIB_PATH)


def _stale():
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    for s in _SRCS + ("mxtpu.h",):
        p = os.path.join(_DIR, s)
        if os.path.exists(p) and os.path.getmtime(p) > lib_mtime:
            return True
    return False


def _configure(lib):
    u64 = ctypes.c_uint64
    p = ctypes.c_void_p
    lib.MXTPUEngineCreate.restype = p
    lib.MXTPUEngineCreate.argtypes = [ctypes.c_int, ctypes.c_int]
    lib.MXTPUEngineShutdown.argtypes = [p]
    lib.MXTPUEngineNewVar.restype = u64
    lib.MXTPUEngineNewVar.argtypes = [p]
    lib.MXTPUEngineDeleteVar.argtypes = [p, u64]
    lib.MXTPUEnginePushAsync.restype = ctypes.c_int
    lib.MXTPUEnginePushAsync.argtypes = [
        p, ENGINE_CB, p, ctypes.POINTER(u64), ctypes.c_int,
        ctypes.POINTER(u64), ctypes.c_int, ctypes.c_int, ctypes.c_char_p]
    lib.MXTPUEngineWaitForVar.argtypes = [p, u64]
    lib.MXTPUEngineWaitForAll.argtypes = [p]
    lib.MXTPUEngineNumPending.restype = ctypes.c_int
    lib.MXTPUEngineNumPending.argtypes = [p]
    lib.MXTPUEngineLastError.restype = ctypes.c_char_p
    lib.MXTPUEngineLastError.argtypes = [p]
    lib.MXTPUProfilerSetState.argtypes = [p, ctypes.c_int]
    lib.MXTPUProfilerDump.restype = p  # manually decoded + freed
    lib.MXTPUProfilerDump.argtypes = [p]

    lib.MXTPURecordIOWriterCreate.restype = p
    lib.MXTPURecordIOWriterCreate.argtypes = [ctypes.c_char_p]
    lib.MXTPURecordIOWriterWrite.restype = ctypes.c_int
    lib.MXTPURecordIOWriterWrite.argtypes = [p, ctypes.c_char_p, u64]
    lib.MXTPURecordIOWriterTell.restype = u64
    lib.MXTPURecordIOWriterTell.argtypes = [p]
    lib.MXTPURecordIOWriterClose.argtypes = [p]
    lib.MXTPURecordIOReaderCreate.restype = p
    lib.MXTPURecordIOReaderCreate.argtypes = [ctypes.c_char_p]
    lib.MXTPURecordIOReaderRead.restype = ctypes.c_int
    lib.MXTPURecordIOReaderRead.argtypes = [
        p, ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(u64)]
    lib.MXTPURecordIOReaderSeek.argtypes = [p, u64]
    lib.MXTPURecordIOReaderTell.restype = u64
    lib.MXTPURecordIOReaderTell.argtypes = [p]
    lib.MXTPURecordIOReaderClose.argtypes = [p]
    lib.MXTPUFree.argtypes = [p]

    # Image pipeline (absent when the host lacks libjpeg — callers probe
    # with has_imagedec()).
    try:
        fp = ctypes.POINTER(ctypes.c_float)
        pp = ctypes.POINTER(ctypes.c_void_p)
        lib.MXTPUImgPipeCreate.restype = p
        lib.MXTPUImgPipeCreate.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int, fp, fp,
            ctypes.c_int]
        lib.MXTPUImgPipeDecodeBatch.restype = ctypes.c_int
        lib.MXTPUImgPipeDecodeBatch.argtypes = [
            p, pp, ctypes.POINTER(u64), ctypes.c_int, p,
            ctypes.POINTER(ctypes.c_uint8), u64]
        lib.MXTPUImgPipeDestroy.argtypes = [p]
        lib.MXTPUImgDecodeDims.restype = ctypes.c_int
        lib.MXTPUImgDecodeDims.argtypes = [
            p, u64, ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
        lib.MXTPUImgDecode.restype = ctypes.c_int
        lib.MXTPUImgDecode.argtypes = [p, u64, p, ctypes.c_int]
        lib._has_imagedec = True
    except AttributeError:
        lib._has_imagedec = False
    try:
        lib.MXTPUIm2Rec.restype = ctypes.c_int
        lib.MXTPUIm2Rec.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.POINTER(u64), ctypes.POINTER(u64)]
        lib._has_im2rec = True
    except AttributeError:
        lib._has_im2rec = False
    return lib


ENGINE_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p)

from ..base import get_env, register_env  # noqa: E402 — after ctypes setup

ENV_NO_NATIVE = register_env(
    "MXNET_NO_NATIVE", default=0,
    doc="1 disables the native C runtime entirely (pure-Python fallbacks)")


def get_lib():
    """Return the configured ctypes library, or None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if str(get_env(ENV_NO_NATIVE, "0")) == "1":
            return None
        if _stale() and not _build():
            return None
        try:
            _lib = _configure(ctypes.CDLL(_LIB_PATH))
        except OSError:
            _lib = None
    return _lib
