// Batch JPEG decode + augment pipeline — the TPU-native rebuild of the
// reference's in-engine image pipeline (reference src/io/iter_image_recordio_2.cc:
// ImageRecordIOParser2 decodes record chunks on C++ threads with OpenCV;
// reference src/io/image_aug_default.cc applies crop/mirror/normalize).
//
// Design for a host that feeds a TPU:
//  - libjpeg-turbo with DCT-domain scaling (scale_num/8) and region-limited
//    decode (jpeg_crop_scanline + jpeg_skip_scanlines): only the pixels the
//    crop window needs are entropy-decoded and IDCT'd.
//  - One fused resample pass: decoded window -> bilinear resize -> crop ->
//    mirror -> (x-mean)/std -> dtype cast -> NCHW/NHWC pack.  No intermediate
//    float image, no transpose pass, no second copy.
//  - Output dtype includes bfloat16 so the host->device transfer moves half
//    the bytes of f32 and the device casts for free.
//  - Deterministic augmentation: crop offsets/mirror bits derive from
//    (chunk_seed, image index) via splitmix64 — independent of thread
//    scheduling, reproducible across runs.
//  - Persistent worker pool; the calling thread participates, so on a
//    single-core host there is zero pool overhead (the call degrades to a
//    plain loop).  Python callers invoke through ctypes, which releases the
//    GIL for the duration — decode overlaps the interpreter's train-step
//    dispatch even with one core.
#include <atomic>
#include <condition_variable>
#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

#include <jpeglib.h>

namespace mxtpu {
namespace {

// ---------------------------------------------------------------------------
// deterministic rng (splitmix64) — mirrors the Python pipeline's
// _chunk_seed mixing discipline (image.py): a sample's augmentation is a
// pure function of (chunk_seed, index).
// ---------------------------------------------------------------------------
struct Rng {
  uint64_t state;
  explicit Rng(uint64_t seed) : state(seed) {}
  uint64_t Next() {
    state += 0x9e3779b97f4a7c15ULL;
    uint64_t z = state;
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
    return z ^ (z >> 31);
  }
  // uniform integer in [0, n] (n inclusive); n >= 0
  uint32_t Below(uint32_t n) {
    return n == 0 ? 0 : static_cast<uint32_t>(Next() % (uint64_t(n) + 1));
  }
};

inline uint64_t MixSeed(uint64_t chunk_seed, uint64_t idx) {
  uint64_t z = chunk_seed * 0x9e3779b97f4a7c15ULL +
               idx * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 30)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

// ---------------------------------------------------------------------------
// libjpeg error trampoline: decode errors long-jump back and mark the
// sample invalid (the reference parser likewise tolerates bad images
// per-record instead of failing the batch).
// ---------------------------------------------------------------------------
struct JpegError {
  jpeg_error_mgr pub;
  jmp_buf jump;
};

void ErrorExit(j_common_ptr cinfo) {
  JpegError* err = reinterpret_cast<JpegError*>(cinfo->err);
  longjmp(err->jump, 1);
}

void SilentEmit(j_common_ptr, int) {}
void SilentOutput(j_common_ptr) {}

inline uint16_t FloatToBf16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  // round-to-nearest-even on the dropped 16 bits
  uint32_t rounded = bits + 0x7fffU + ((bits >> 16) & 1U);
  return static_cast<uint16_t>(rounded >> 16);
}

enum DType { kU8 = 0, kF32 = 1, kBf16 = 2 };
enum Layout { kNCHW = 0, kNHWC = 1 };

struct PipeConfig {
  int out_h, out_w;
  int resize;       // shorter-edge resize before crop; 0 = crop from source
  int rand_crop;    // 1 = random offsets, 0 = center
  int rand_mirror;  // 1 = flip horizontally with p=0.5
  int dtype;        // DType
  int layout;       // Layout
  int fast_dct;     // 1 = JDCT_IFAST (~1.5x decode speed, +-2 LSB vs exact)
  float mean[3];
  float std_inv[3];
  bool normalize;
};

size_t DTypeSize(int dt) { return dt == kF32 ? 4 : (dt == kBf16 ? 2 : 1); }

// Per-thread scratch: the decoded source window (RGB u8 rows) + the
// per-output-column bilinear taps (rebuilt per image, allocated once).
struct XTap {
  int ix;
  float fx;
};

struct Scratch {
  std::vector<uint8_t> window;  // win_h * win_stride bytes
  std::vector<JSAMPROW> rows;
  std::vector<XTap> xmap;
};

// byte -> normalized output value, per channel (the pack stage's entire
// arithmetic for unit-scale crops collapses into this table).
union LutVal {
  uint8_t u8;
  float f32;
  uint16_t b16;
};

struct Lut {
  LutVal v[3][256];
};

inline void StoreVal(uint8_t* dst, float v) {
  int q = static_cast<int>(v + 0.5f);
  *dst = static_cast<uint8_t>(q < 0 ? 0 : (q > 255 ? 255 : q));
}
inline void StoreVal(float* dst, float v) { *dst = v; }
inline void StoreVal(uint16_t* dst, float v) { *dst = FloatToBf16(v); }

inline uint8_t LutGet(const LutVal& lv, uint8_t*) { return lv.u8; }
inline float LutGet(const LutVal& lv, float*) { return lv.f32; }
inline uint16_t LutGet(const LutVal& lv, uint16_t*) { return lv.b16; }

// Identity unit-scale pack for raw uint8 NHWC output: rows memcpy straight
// out of the decode window (the TPU feed path — normalization happens on
// device where it fuses into the first conv).
void PackUnitCopyNHWC(const PipeConfig& cfg, const uint8_t* win,
                      int win_stride, int src_x, int src_y, bool mirror,
                      uint8_t* out) {
  const size_t row_bytes = static_cast<size_t>(cfg.out_w) * 3;
  for (int oy = 0; oy < cfg.out_h; ++oy) {
    const uint8_t* row =
        win + static_cast<size_t>(src_y + oy) * win_stride + src_x * 3;
    uint8_t* dst = out + static_cast<size_t>(oy) * row_bytes;
    if (!mirror) {
      std::memcpy(dst, row, row_bytes);
    } else {
      const uint8_t* p = row + (cfg.out_w - 1) * 3;
      for (int ox = 0; ox < cfg.out_w; ++ox, p -= 3, dst += 3) {
        dst[0] = p[0];
        dst[1] = p[1];
        dst[2] = p[2];
      }
    }
  }
}

// Unit-scale pack: the crop maps 1:1 onto decoded pixels, so each output
// channel value is lut[c][source byte].  OutT in {uint8_t,float,uint16_t}.
template <typename OutT, bool kNchw>
void PackUnit(const PipeConfig& cfg, const uint8_t* win, int win_stride,
              int src_x, int src_y, bool mirror, const Lut& lut, OutT* out) {
  const size_t plane = static_cast<size_t>(cfg.out_h) * cfg.out_w;
  for (int oy = 0; oy < cfg.out_h; ++oy) {
    const uint8_t* row =
        win + static_cast<size_t>(src_y + oy) * win_stride + src_x * 3;
    OutT* o0;
    OutT* o1;
    OutT* o2;
    if (kNchw) {
      size_t base = static_cast<size_t>(oy) * cfg.out_w;
      o0 = out + base;
      o1 = out + plane + base;
      o2 = out + 2 * plane + base;
    } else {
      o0 = out + static_cast<size_t>(oy) * cfg.out_w * 3;
      o1 = o0 + 1;
      o2 = o0 + 2;
    }
    const int step = kNchw ? 1 : 3;
    if (mirror) {
      const uint8_t* p = row + (cfg.out_w - 1) * 3;
      for (int ox = 0; ox < cfg.out_w; ++ox, p -= 3) {
        *o0 = LutGet(lut.v[0][p[0]], o0);
        *o1 = LutGet(lut.v[1][p[1]], o1);
        *o2 = LutGet(lut.v[2][p[2]], o2);
        o0 += step;
        o1 += step;
        o2 += step;
      }
    } else {
      const uint8_t* p = row;
      for (int ox = 0; ox < cfg.out_w; ++ox, p += 3) {
        *o0 = LutGet(lut.v[0][p[0]], o0);
        *o1 = LutGet(lut.v[1][p[1]], o1);
        *o2 = LutGet(lut.v[2][p[2]], o2);
        o0 += step;
        o1 += step;
        o2 += step;
      }
    }
  }
}

// Bilinear pack with precomputed x taps; y taps computed per row.
template <typename OutT, bool kNchw>
void PackBilinear(const PipeConfig& cfg, const uint8_t* win, int win_stride,
                  int win_h, const XTap* xmap, double map_y0, double map_dy,
                  OutT* out) {
  const size_t plane = static_cast<size_t>(cfg.out_h) * cfg.out_w;
  const int hmax = win_h - 1;
  const float m0 = cfg.mean[0], m1 = cfg.mean[1], m2 = cfg.mean[2];
  const float i0 = cfg.std_inv[0], i1 = cfg.std_inv[1], i2 = cfg.std_inv[2];
  const bool norm = cfg.normalize;
  for (int oy = 0; oy < cfg.out_h; ++oy) {
    double dy = map_y0 + oy * map_dy;
    if (dy < 0) dy = 0;
    if (dy > hmax) dy = hmax;
    int iy = static_cast<int>(dy);
    if (iy > hmax - 1) iy = hmax > 0 ? hmax - 1 : 0;
    const float fy = static_cast<float>(dy - iy);
    const float ofy = 1.0f - fy;
    const uint8_t* row0 = win + static_cast<size_t>(iy) * win_stride;
    const uint8_t* row1 = hmax == 0 ? row0 : row0 + win_stride;
    OutT* o0;
    OutT* o1;
    OutT* o2;
    if (kNchw) {
      size_t base = static_cast<size_t>(oy) * cfg.out_w;
      o0 = out + base;
      o1 = out + plane + base;
      o2 = out + 2 * plane + base;
    } else {
      o0 = out + static_cast<size_t>(oy) * cfg.out_w * 3;
      o1 = o0 + 1;
      o2 = o0 + 2;
    }
    const int step = kNchw ? 1 : 3;
    for (int ox = 0; ox < cfg.out_w; ++ox) {
      const XTap t = xmap[ox];
      const uint8_t* p00 = row0 + t.ix * 3;
      const uint8_t* p10 = row1 + t.ix * 3;
      const float fx = t.fx, ofx = 1.0f - fx;
      const float w00 = ofx * ofy, w01 = fx * ofy;
      const float w10 = ofx * fy, w11 = fx * fy;
      float v0 = w00 * p00[0] + w01 * p00[3] + w10 * p10[0] + w11 * p10[3];
      float v1 = w00 * p00[1] + w01 * p00[4] + w10 * p10[1] + w11 * p10[4];
      float v2 = w00 * p00[2] + w01 * p00[5] + w10 * p10[2] + w11 * p10[5];
      if (norm) {
        v0 = (v0 - m0) * i0;
        v1 = (v1 - m1) * i1;
        v2 = (v2 - m2) * i2;
      }
      StoreVal(o0, v0);
      StoreVal(o1, v1);
      StoreVal(o2, v2);
      o0 += step;
      o1 += step;
      o2 += step;
    }
  }
}

// reference python/mxnet/image.py:scale_down — shrink the crop if the
// (resized) source is smaller than the requested crop.
inline void ScaleDown(int sw, int sh, int* cw, int* ch) {
  if (sh < *ch) {
    *cw = static_cast<int>(static_cast<float>(*cw) * sh / *ch);
    *ch = sh;
  }
  if (sw < *cw) {
    *ch = static_cast<int>(static_cast<float>(*ch) * sw / *cw);
    *cw = sw;
  }
  if (*cw < 1) *cw = 1;
  if (*ch < 1) *ch = 1;
}

Lut BuildLut(const PipeConfig& cfg) {
  Lut lut;
  for (int c = 0; c < 3; ++c) {
    for (int b = 0; b < 256; ++b) {
      float v = static_cast<float>(b);
      if (cfg.normalize) v = (v - cfg.mean[c]) * cfg.std_inv[c];
      switch (cfg.dtype) {
        case kU8: {
          int q = static_cast<int>(v + 0.5f);
          lut.v[c][b].u8 =
              static_cast<uint8_t>(q < 0 ? 0 : (q > 255 ? 255 : q));
          break;
        }
        case kF32:
          lut.v[c][b].f32 = v;
          break;
        default:
          lut.v[c][b].b16 = FloatToBf16(v);
      }
    }
  }
  return lut;
}

// Decode one JPEG and write the augmented sample into out (one image's
// slot inside the batch buffer).  Returns false on any decode error.
bool DecodeOne(const PipeConfig& cfg, const Lut& lut, const uint8_t* buf,
               uint64_t len, void* out, uint64_t seed, Scratch* scratch) {
  if (len == 0) return false;
  jpeg_decompress_struct cinfo;
  JpegError jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = ErrorExit;
  jerr.pub.emit_message = SilentEmit;
  jerr.pub.output_message = SilentOutput;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(buf),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;  // grayscale sources convert in-decode
  if (cfg.fast_dct) {
    // training profile: IFAST is the fastest SIMD IDCT in libjpeg-turbo
    // (measured ~1.5x the default ISLOW on 256px q90 photos on this
    // host); output differs from the exact path by at most a couple of
    // 8-bit steps, which augmentation noise dwarfs.  Exact mode
    // (MXNET_JPEG_DECODE_FAST=0) keeps byte parity with cv2.
    cinfo.dct_method = JDCT_IFAST;
  }

  const int src_w = static_cast<int>(cinfo.image_width);
  const int src_h = static_cast<int>(cinfo.image_height);
  if (src_w <= 0 || src_h <= 0) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }

  // Resized dims (reference image.py:resize_short integer semantics).
  int rs_w = src_w, rs_h = src_h;
  if (cfg.resize > 0) {
    if (src_h > src_w) {
      rs_w = cfg.resize;
      rs_h = static_cast<int>(static_cast<int64_t>(cfg.resize) * src_h /
                              src_w);
    } else {
      rs_h = cfg.resize;
      rs_w = static_cast<int>(static_cast<int64_t>(cfg.resize) * src_w /
                              src_h);
    }
    // DCT-domain prescale: the largest downscale that still leaves the
    // shorter edge >= the resize target (so the bilinear pass only ever
    // shrinks a little, never invents pixels).
    int m = 8;
    while (m > 1) {
      int cand = m - 1;
      if (static_cast<int64_t>(src_w) * cand / 8 >= rs_w &&
          static_cast<int64_t>(src_h) * cand / 8 >= rs_h) {
        m = cand;
      } else {
        break;
      }
    }
    cinfo.scale_num = static_cast<unsigned>(m);
    cinfo.scale_denom = 8;
  }

  jpeg_calc_output_dimensions(&cinfo);
  const int dec_w = static_cast<int>(cinfo.output_width);
  const int dec_h = static_cast<int>(cinfo.output_height);

  // Crop window in resized space.
  Rng rng(MixSeed(seed, 0));
  int crop_w = cfg.out_w, crop_h = cfg.out_h;
  ScaleDown(rs_w, rs_h, &crop_w, &crop_h);
  int x0, y0;
  if (cfg.rand_crop) {
    x0 = static_cast<int>(rng.Below(static_cast<uint32_t>(rs_w - crop_w)));
    y0 = static_cast<int>(rng.Below(static_cast<uint32_t>(rs_h - crop_h)));
  } else {
    x0 = (rs_w - crop_w) / 2;
    y0 = (rs_h - crop_h) / 2;
  }
  const bool mirror = cfg.rand_mirror && (rng.Next() & 1U);

  // Map the crop window back into decoded space; pad one pixel for the
  // bilinear taps.
  const double sx = static_cast<double>(dec_w) / rs_w;   // resized->decoded
  const double sy = static_cast<double>(dec_h) / rs_h;
  int wx0 = static_cast<int>(x0 * sx) - 1;
  int wy0 = static_cast<int>(y0 * sy) - 1;
  int wx1 = static_cast<int>((x0 + crop_w) * sx) + 2;
  int wy1 = static_cast<int>((y0 + crop_h) * sy) + 2;
  if (wx0 < 0) wx0 = 0;
  if (wy0 < 0) wy0 = 0;
  if (wx1 > dec_w) wx1 = dec_w;
  if (wy1 > dec_h) wy1 = dec_h;

  jpeg_start_decompress(&cinfo);

  // Horizontal region-of-interest decode (iMCU-aligned; the library moves
  // the left edge, we track the shift).
  JDIMENSION roi_x = static_cast<JDIMENSION>(wx0);
  JDIMENSION roi_w = static_cast<JDIMENSION>(wx1 - wx0);
  if (static_cast<int>(roi_w) < dec_w) {
    jpeg_crop_scanline(&cinfo, &roi_x, &roi_w);
  }
  const int win_x0 = static_cast<int>(roi_x);
  const int win_w = static_cast<int>(roi_w);
  const int win_stride = win_w * 3;
  const int win_h = wy1 - wy0;

  // +3 bytes slack: the bilinear inner loop reads tap ix+1 unconditionally
  // (its weight is zero at the right edge of a degenerate 1-px window).
  scratch->window.resize(static_cast<size_t>(win_h) * win_stride + 3);
  scratch->rows.resize(win_h);
  for (int r = 0; r < win_h; ++r) {
    scratch->rows[r] = scratch->window.data() +
                       static_cast<size_t>(r) * win_stride;
  }

  if (wy0 > 0) jpeg_skip_scanlines(&cinfo, static_cast<JDIMENSION>(wy0));
  int got = 0;
  while (got < win_h) {
    JDIMENSION n = jpeg_read_scanlines(&cinfo, scratch->rows.data() + got,
                                       static_cast<JDIMENSION>(win_h - got));
    if (n == 0) break;
    got += static_cast<int>(n);
  }
  jpeg_abort_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  if (got < win_h) return false;

  // Fused resample/pack.  The out->window mapping is affine per axis:
  //   d = (o + 0.5) * g * s + x0 * s - 0.5 - win_origin
  // with g = crop/out (crop resampling) and s = dec/resized (DCT prescale
  // residual).  Unit scale (crop straight from the stored image, the
  // common training case) collapses to a LUT copy.
  const double gx = static_cast<double>(crop_w) / cfg.out_w;
  const double gy = static_cast<double>(crop_h) / cfg.out_h;
  const uint8_t* win = scratch->window.data();
  const int wmax = win_w - 1;

  const bool unit = dec_w == rs_w && dec_h == rs_h && crop_w == cfg.out_w &&
                    crop_h == cfg.out_h;
  if (unit) {
    const int src_x = x0 - win_x0;
    const int src_y = y0 - wy0;
    if (cfg.dtype == kU8 && cfg.layout == kNHWC && !cfg.normalize) {
      PackUnitCopyNHWC(cfg, win, win_stride, src_x, src_y, mirror,
                       static_cast<uint8_t*>(out));
      return true;
    }
    switch (cfg.dtype) {
      case kU8:
        if (cfg.layout == kNCHW)
          PackUnit<uint8_t, true>(cfg, win, win_stride, src_x, src_y, mirror,
                                  lut, static_cast<uint8_t*>(out));
        else
          PackUnit<uint8_t, false>(cfg, win, win_stride, src_x, src_y, mirror,
                                   lut, static_cast<uint8_t*>(out));
        break;
      case kF32:
        if (cfg.layout == kNCHW)
          PackUnit<float, true>(cfg, win, win_stride, src_x, src_y, mirror,
                                lut, static_cast<float*>(out));
        else
          PackUnit<float, false>(cfg, win, win_stride, src_x, src_y, mirror,
                                 lut, static_cast<float*>(out));
        break;
      default:
        if (cfg.layout == kNCHW)
          PackUnit<uint16_t, true>(cfg, win, win_stride, src_x, src_y, mirror,
                                   lut, static_cast<uint16_t*>(out));
        else
          PackUnit<uint16_t, false>(cfg, win, win_stride, src_x, src_y,
                                    mirror, lut, static_cast<uint16_t*>(out));
    }
    return true;
  }

  scratch->xmap.resize(cfg.out_w);
  const double step_x = gx * sx;
  const double c0_x = 0.5 * step_x + x0 * sx - 0.5 - win_x0;
  for (int ox = 0; ox < cfg.out_w; ++ox) {
    int oxs = mirror ? (cfg.out_w - 1 - ox) : ox;
    double dx = c0_x + oxs * step_x;
    if (dx < 0) dx = 0;
    if (dx > wmax) dx = wmax;
    int ix = static_cast<int>(dx);
    if (ix > wmax - 1) ix = wmax > 0 ? wmax - 1 : 0;
    scratch->xmap[ox].ix = ix;
    scratch->xmap[ox].fx = static_cast<float>(dx - ix);
  }
  const double step_y = gy * sy;
  const double c0_y = 0.5 * step_y + y0 * sy - 0.5 - wy0;
  switch (cfg.dtype) {
    case kU8:
      if (cfg.layout == kNCHW)
        PackBilinear<uint8_t, true>(cfg, win, win_stride, win_h,
                                    scratch->xmap.data(), c0_y, step_y,
                                    static_cast<uint8_t*>(out));
      else
        PackBilinear<uint8_t, false>(cfg, win, win_stride, win_h,
                                     scratch->xmap.data(), c0_y, step_y,
                                     static_cast<uint8_t*>(out));
      break;
    case kF32:
      if (cfg.layout == kNCHW)
        PackBilinear<float, true>(cfg, win, win_stride, win_h,
                                  scratch->xmap.data(), c0_y, step_y,
                                  static_cast<float*>(out));
      else
        PackBilinear<float, false>(cfg, win, win_stride, win_h,
                                   scratch->xmap.data(), c0_y, step_y,
                                   static_cast<float*>(out));
      break;
    default:
      if (cfg.layout == kNCHW)
        PackBilinear<uint16_t, true>(cfg, win, win_stride, win_h,
                                     scratch->xmap.data(), c0_y, step_y,
                                     static_cast<uint16_t*>(out));
      else
        PackBilinear<uint16_t, false>(cfg, win, win_stride, win_h,
                                      scratch->xmap.data(), c0_y, step_y,
                                      static_cast<uint16_t*>(out));
  }
  return true;
}

// ---------------------------------------------------------------------------
// Pipeline object: config + persistent worker pool.  DecodeBatch partitions
// images over (workers + caller) via an atomic cursor.
// ---------------------------------------------------------------------------
struct BatchJob {
  const uint8_t* const* bufs;
  const uint64_t* lens;
  int n;
  void* out;
  uint8_t* valid;
  uint64_t chunk_seed;
  size_t sample_bytes;
  std::atomic<int> cursor{0};
  std::atomic<int> done{0};
};

class ImagePipe {
 public:
  ImagePipe(const PipeConfig& cfg, int nthreads)
      : cfg_(cfg), lut_(BuildLut(cfg)) {
    int extra = nthreads - 1;
    if (extra < 0) extra = 0;
    for (int i = 0; i < extra; ++i) {
      workers_.emplace_back([this] { WorkerLoop(); });
    }
  }

  ~ImagePipe() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : workers_) t.join();
  }

  int DecodeBatch(const uint8_t* const* bufs, const uint64_t* lens, int n,
                  void* out, uint8_t* valid, uint64_t chunk_seed) {
    BatchJob job;
    job.bufs = bufs;
    job.lens = lens;
    job.n = n;
    job.out = out;
    job.valid = valid;
    job.chunk_seed = chunk_seed;
    job.sample_bytes = static_cast<size_t>(cfg_.out_h) * cfg_.out_w * 3 *
                       DTypeSize(cfg_.dtype);
    {
      std::lock_guard<std::mutex> lk(mu_);
      job_ = &job;
    }
    cv_.notify_all();
    Work(&job, &caller_scratch_);  // caller participates
    // Wait until every image is done AND no worker still holds the job
    // pointer — `job` lives on this stack frame, so a worker that grabbed
    // job_ must fully exit Work() before we return (working_ guards the
    // window between a worker's last cursor probe and its release).
    {
      std::unique_lock<std::mutex> lk(mu_);
      done_cv_.wait(lk, [&] {
        return job.done.load() >= job.n && working_ == 0;
      });
      job_ = nullptr;
    }
    int nvalid = 0;
    for (int i = 0; i < n; ++i) nvalid += valid[i] ? 1 : 0;
    return nvalid;
  }

 private:
  void Work(BatchJob* job, Scratch* scratch) {
    for (;;) {
      int i = job->cursor.fetch_add(1);
      if (i >= job->n) break;
      void* slot = static_cast<uint8_t*>(job->out) +
                   static_cast<size_t>(i) * job->sample_bytes;
      bool ok = DecodeOne(cfg_, lut_, job->bufs[i], job->lens[i], slot,
                          MixSeed(job->chunk_seed, static_cast<uint64_t>(i)),
                          scratch);
      job->valid[i] = ok ? 1 : 0;
      if (job->done.fetch_add(1) + 1 >= job->n) {
        std::lock_guard<std::mutex> lk(mu_);
        done_cv_.notify_all();
      }
    }
  }

  void WorkerLoop() {
    Scratch scratch;
    for (;;) {
      BatchJob* job;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [&] {
          return stop_ || (job_ != nullptr && job_->cursor.load() < job_->n);
        });
        if (stop_) return;
        job = job_;
        ++working_;  // claimed under the lock: DecodeBatch cannot free the
                     // job until this drops back to zero
      }
      Work(job, &scratch);
      {
        std::lock_guard<std::mutex> lk(mu_);
        --working_;
        done_cv_.notify_all();
      }
    }
  }

  PipeConfig cfg_;
  Lut lut_;
  std::vector<std::thread> workers_;
  Scratch caller_scratch_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::condition_variable done_cv_;
  BatchJob* job_ = nullptr;
  int working_ = 0;
  bool stop_ = false;
};

}  // namespace
}  // namespace mxtpu

extern "C" {

// mean/std: pointers to 3 floats (RGB) or null for no normalization.
void* MXTPUImgPipeCreate(int nthreads, int out_h, int out_w, int resize,
                         int rand_crop, int rand_mirror, int dtype, int layout,
                         const float* mean, const float* stdv, int fast_dct) {
  mxtpu::PipeConfig cfg;
  cfg.out_h = out_h;
  cfg.out_w = out_w;
  cfg.resize = resize;
  cfg.rand_crop = rand_crop;
  cfg.rand_mirror = rand_mirror;
  cfg.dtype = dtype;
  cfg.layout = layout;
  cfg.fast_dct = fast_dct;
  cfg.normalize = (mean != nullptr) || (stdv != nullptr);
  for (int c = 0; c < 3; ++c) {
    cfg.mean[c] = mean ? mean[c] : 0.0f;
    float s = stdv ? stdv[c] : 1.0f;
    cfg.std_inv[c] = s != 0.0f ? 1.0f / s : 1.0f;
  }
  if (out_h <= 0 || out_w <= 0 || dtype < 0 || dtype > 2) return nullptr;
  return new mxtpu::ImagePipe(cfg, nthreads < 1 ? 1 : nthreads);
}

int MXTPUImgPipeDecodeBatch(void* handle, const uint8_t* const* bufs,
                            const uint64_t* lens, int n, void* out,
                            uint8_t* valid, uint64_t chunk_seed) {
  return static_cast<mxtpu::ImagePipe*>(handle)->DecodeBatch(
      bufs, lens, n, out, valid, chunk_seed);
}

void MXTPUImgPipeDestroy(void* handle) {
  delete static_cast<mxtpu::ImagePipe*>(handle);
}

// Single-image decode to a caller-provided HWC u8 buffer of the NATIVE
// size (for mx.nd.imdecode).  Caller first asks for dims with
// MXTPUImgDecodeDims, then decodes.  to_rgb=0 gives BGR byte order
// (reference _cvimdecode default), 1 gives RGB.
int MXTPUImgDecodeDims(const uint8_t* buf, uint64_t len, int* h, int* w) {
  jpeg_decompress_struct cinfo;
  mxtpu::JpegError jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = mxtpu::ErrorExit;
  jerr.pub.emit_message = mxtpu::SilentEmit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(buf),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  *h = static_cast<int>(cinfo.image_height);
  *w = static_cast<int>(cinfo.image_width);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

int MXTPUImgDecode(const uint8_t* buf, uint64_t len, uint8_t* out,
                   int to_rgb) {
  jpeg_decompress_struct cinfo;
  mxtpu::JpegError jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = mxtpu::ErrorExit;
  jerr.pub.emit_message = mxtpu::SilentEmit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(buf),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  const int w = static_cast<int>(cinfo.output_width);
  std::vector<JSAMPROW> rows(1);
  while (cinfo.output_scanline < cinfo.output_height) {
    rows[0] = out + static_cast<size_t>(cinfo.output_scanline) * w * 3;
    jpeg_read_scanlines(&cinfo, rows.data(), 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  if (!to_rgb) {  // swap to BGR in place
    const size_t npix = static_cast<size_t>(w) * cinfo.output_height;
    for (size_t i = 0; i < npix; ++i) {
      uint8_t t = out[i * 3];
      out[i * 3] = out[i * 3 + 2];
      out[i * 3 + 2] = t;
    }
  }
  return 0;
}

}  // extern "C"
