"""Training-statistics loggers and live learning-curve plotting for
notebooks (reference python/mxnet/notebook/callback.py).

``PandasLogger`` keeps the reference's API and dataframe layout (train /
eval / epoch frames, ``callback_args()`` to wire all three callbacks into
``Module.fit``).  The live chart uses matplotlib instead of the
reference's bokeh (matplotlib is the kernel-agnostic choice; bokeh is
not in this image) — ``LiveLearningCurve`` redraws in-place inside
Jupyter and degrades to saving a PNG outside it.
"""
from __future__ import annotations

import datetime
import time

try:
    import pandas as pd
except ImportError:  # pragma: no cover - pandas is in the image
    pd = None


def _add_new_columns(dataframe, metrics):
    """Add new metrics as new columns to selected pandas dataframe
    (reference callback.py:_add_new_columns)."""
    new_columns = set(metrics.keys()) - set(dataframe.columns)
    for col in new_columns:
        dataframe[col] = None


class PandasLogger(object):
    """Logs training statistics into pandas dataframes: ``train_df``
    (every ``frequent`` minibatches), ``eval_df`` (once per epoch over the
    eval set), ``epoch_df`` (epoch wall-clock).  Reference
    notebook/callback.py:PandasLogger."""

    def __init__(self, batch_size, frequent=50):
        if pd is None:
            raise ImportError("PandasLogger needs pandas")
        self.batch_size = batch_size
        self.frequent = frequent
        self._dataframes = {
            "train": pd.DataFrame(),
            "eval": pd.DataFrame(),
            "epoch": pd.DataFrame(),
        }
        self.last_time = time.time()
        self.start_time = datetime.datetime.now()
        self.last_epoch_time = datetime.datetime.now()

    @property
    def train_df(self):
        return self._dataframes["train"]

    @property
    def eval_df(self):
        return self._dataframes["eval"]

    @property
    def epoch_df(self):
        return self._dataframes["epoch"]

    @property
    def all_dataframes(self):
        return self._dataframes

    def elapsed(self):
        return datetime.datetime.now() - self.start_time

    def append_metrics(self, metrics, df_name):
        dataframe = self._dataframes[df_name]
        _add_new_columns(dataframe, metrics)
        dataframe.loc[len(dataframe)] = metrics

    def train_cb(self, param):
        if param.nbatch % self.frequent == 0:
            self._process_batch(param, "train")

    def eval_cb(self, param):
        self._process_batch(param, "eval")

    def _process_batch(self, param, dataframe):
        now = time.time()
        if param.eval_metric is not None:
            metrics = dict(param.eval_metric.get_name_value())
        else:
            metrics = {}
        speed = self.frequent / max(now - self.last_time, 1e-9)
        metrics["batches_per_sec"] = speed
        metrics["records_per_sec"] = speed * self.batch_size
        metrics["elapsed"] = self.elapsed()
        metrics["minibatch_count"] = param.nbatch
        metrics["epoch"] = param.epoch
        self.append_metrics(metrics, dataframe)
        self.last_time = now

    def epoch_cb(self):
        metrics = {}
        metrics["elapsed"] = self.elapsed()
        now = datetime.datetime.now()
        metrics["epoch_time"] = now - self.last_epoch_time
        self.append_metrics(metrics, "epoch")
        self.last_epoch_time = now

    def callback_args(self):
        """kwargs for ``Module.fit`` wiring all three callbacks:
        ``model.fit(train, eval_data=val, **logger.callback_args())``."""
        return {
            "batch_end_callback": self.train_cb,
            "eval_end_callback": self.eval_cb,
            "epoch_end_callback": lambda *a, **kw: self.epoch_cb(),
        }


class LiveLearningCurve(object):
    """Live-updating learning curve of a metric from a PandasLogger
    (reference LiveBokehChart/LiveLearningCurve, matplotlib edition).

    In a Jupyter kernel the figure redraws in place every
    ``display_freq`` seconds; headless, ``savefig(path)`` renders the
    final curve to a PNG."""

    def __init__(self, pandas_logger, metric_name, display_freq=5):
        self.pandas_logger = pandas_logger
        self.metric_name = metric_name
        self.display_freq = display_freq
        self.last_update = time.time()
        self._fig = None

    def _setup(self):
        import matplotlib
        import matplotlib.pyplot as plt
        self._plt = plt
        self._in_ipython = matplotlib.get_backend().lower() \
            .endswith(("nbagg", "ipympl", "inline"))
        self._fig, self._ax = plt.subplots(figsize=(6, 4))

    def _draw(self):
        if self._fig is None:
            self._setup()
        ax = self._ax
        ax.clear()
        # common x-axis: elapsed wall-clock — train rows (every `frequent`
        # batches) and eval rows (once per epoch) land on one timeline
        for df_name, style in (("train", "-"), ("eval", "--")):
            df = self.pandas_logger.all_dataframes[df_name]
            if self.metric_name in getattr(df, "columns", []):
                xs = [td.total_seconds() for td in df["elapsed"]]
                ax.plot(xs, df[self.metric_name].astype(float).values,
                        style, label=df_name)
        ax.set_xlabel("elapsed (s)")
        ax.set_ylabel(self.metric_name)
        ax.legend(loc="best")
        ax.grid(True, alpha=0.3)
        if getattr(self, "_in_ipython", False):  # pragma: no cover
            from IPython import display
            display.clear_output(wait=True)
            display.display(self._fig)

    def batch_cb(self, param):
        self.pandas_logger.train_cb(param)
        if time.time() - self.last_update > self.display_freq:
            self._draw()
            self.last_update = time.time()

    def eval_cb(self, param):
        self.pandas_logger.eval_cb(param)
        self._draw()

    def savefig(self, path):
        """Render the current curve to ``path`` (PNG)."""
        self._draw()
        self._fig.savefig(path, dpi=100, bbox_inches="tight")

    def callback_args(self):
        return {
            "batch_end_callback": self.batch_cb,
            "eval_end_callback": self.eval_cb,
            "epoch_end_callback":
                lambda *a, **kw: self.pandas_logger.epoch_cb(),
        }
