"""Base utilities: errors, env-config, generic registries, attr parsing.

TPU-native re-design of the reference's dmlc-core surface:
- ``MXNetError`` mirrors python/mxnet/base.py:35 in the reference.
- ``get_env`` mirrors dmlc::GetEnv runtime config (reference docs/how_to/env_var.md).
- ``Registry`` mirrors dmlc registry used for initializers/optimizers/iterators
  (reference include/dmlc usage via MXNET_REGISTER_* macros).

No ctypes / C-ABI plumbing: the compute substrate is JAX/XLA, so the Python
layer talks to it directly.  A native C runtime exists for the IO/runtime
components (see mxnet_tpu/native/).
"""
from __future__ import annotations

import ast
import os
import threading

__all__ = [
    "MXNetError", "MXTPUError", "get_env", "Registry", "parse_attr_value",
    "string_types", "numeric_types", "classproperty",
]

string_types = (str,)
numeric_types = (int, float)


class MXNetError(Exception):
    """Framework error type (name kept for API parity with the reference,
    python/mxnet/base.py:35)."""


# Idiomatic alias.
MXTPUError = MXNetError


_TRUE_STRINGS = frozenset(("1", "true", "yes", "on"))
_FALSE_STRINGS = frozenset(("0", "false", "no", "off"))


def get_env(name, default=None, typ=None):
    """Read a runtime config env var (dmlc::GetEnv analog).

    Supported vars follow the reference's catalog (docs/how_to/env_var.md)
    with an ``MXNET_`` prefix, e.g. ``MXNET_ENGINE_TYPE``,
    ``MXNET_EXEC_BULK_EXEC_TRAIN``.
    """
    raw = os.environ.get(name)
    if raw is None:
        return default
    if typ is None and default is not None:
        typ = type(default)
    if typ is bool:
        low = raw.strip().lower()
        if low in _TRUE_STRINGS:
            return True
        if low in _FALSE_STRINGS:
            return False
        raise MXNetError("Invalid boolean env var %s=%r" % (name, raw))
    if typ is not None:
        return typ(raw)
    return raw


def parse_attr_value(value):
    """Parse a string attribute into a Python value.

    The reference serializes op kwargs as strings through dmlc::Parameter
    (src/operator/optimizer_op-inl.h:25-45); symbols store attrs as strings in
    JSON.  We accept both typed python values and their string forms:
    ``"(2, 2)"`` -> (2, 2), ``"1"`` -> 1, ``"True"`` -> True, ``"relu"`` -> "relu".
    """
    if not isinstance(value, str):
        return value
    s = value.strip()
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        low = s.lower()
        if low in _TRUE_STRINGS and s.isalpha():
            return True
        if low in _FALSE_STRINGS and s.isalpha():
            return False
        return s


def attr_to_string(value):
    """Serialize an attr value to the string form used in symbol JSON."""
    if isinstance(value, str):
        return value
    if isinstance(value, bool):
        return "True" if value else "False"
    if isinstance(value, (list, tuple)):
        return "(" + ", ".join(attr_to_string(v) for v in value) + (",)" if len(value) == 1 else ")")
    return str(value)


class Registry(object):
    """Generic name->object registry (dmlc registry analog).

    Used for optimizers, initializers, metrics, data iterators, kvstores.
    """

    def __init__(self, kind):
        self._kind = kind
        self._entries = {}

    def register(self, obj=None, name=None, aliases=()):
        def _do(o):
            key = (name or o.__name__).lower()
            self._entries[key] = o
            for a in aliases:
                self._entries[a.lower()] = o
            return o
        if obj is None:
            return _do
        return _do(obj)

    def get(self, name):
        key = name.lower()
        if key not in self._entries:
            raise MXNetError(
                "Cannot find %s %r. Registered: %s"
                % (self._kind, name, sorted(self._entries)))
        return self._entries[key]

    def find(self, name):
        return self._entries.get(name.lower())

    def list(self):
        return sorted(self._entries)

    def create(self, name, *args, **kwargs):
        return self.get(name)(*args, **kwargs)


class classproperty(object):
    def __init__(self, fget):
        self.fget = fget

    def __get__(self, obj, owner):
        return self.fget(owner)


class _ThreadLocalStack(threading.local):
    """Thread-local scope stack (used by Context / AttrScope / NameManager)."""

    def __init__(self):
        self.stack = []


def check_call(ret):  # pragma: no cover - API-parity shim
    """No-op kept for source compatibility with reference-style code."""
    return ret
