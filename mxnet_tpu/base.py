"""Base utilities: errors, env-config, generic registries, attr parsing.

TPU-native re-design of the reference's dmlc-core surface:
- ``MXNetError`` mirrors python/mxnet/base.py:35 in the reference.
- ``get_env`` mirrors dmlc::GetEnv runtime config (reference docs/how_to/env_var.md).
- ``Registry`` mirrors dmlc registry used for initializers/optimizers/iterators
  (reference include/dmlc usage via MXNET_REGISTER_* macros).

No ctypes / C-ABI plumbing: the compute substrate is JAX/XLA, so the Python
layer talks to it directly.  A native C runtime exists for the IO/runtime
components (see mxnet_tpu/native/).
"""
from __future__ import annotations

import ast
import logging
import os
import threading
from collections import namedtuple

__all__ = [
    "MXNetError", "MXTPUError", "get_env", "Registry", "parse_attr_value",
    "string_types", "numeric_types", "classproperty",
    "EnvSpec", "ENV_REGISTRY", "register_env", "registered_env_names",
]

_LOG = logging.getLogger(__name__)

string_types = (str,)
numeric_types = (int, float)


class MXNetError(Exception):
    """Framework error type (name kept for API parity with the reference,
    python/mxnet/base.py:35)."""


# Idiomatic alias.
MXTPUError = MXNetError


_TRUE_STRINGS = frozenset(("1", "true", "yes", "on"))
_FALSE_STRINGS = frozenset(("0", "false", "no", "off"))


class EnvSpec(namedtuple("EnvSpec", ["name", "default", "doc", "scope"])):
    """One registered runtime knob.  ``scope`` records who reads it:
    ``runtime`` (the package), ``test`` (the test harness), ``tools``
    (launch/supervise/mxlint CLIs) — documentation metadata, not an
    access control."""


#: The single catalog of every ``MXTPU_*``/``MXNET_*`` knob this codebase
#: reads.  All env access goes through :func:`get_env` (enforced by
#: ``tools/mxlint.py``'s ``env-unregistered``/``env-direct-read`` rules),
#: and every registered MXTPU_* name must have a row in
#: ``docs/env_vars.md`` (asserted by tests/test_analysis.py) — so a knob
#: cannot be added, typo'd, or dropped without the analyzer noticing.
ENV_REGISTRY = {}


def register_env(name, default=None, doc="", scope="runtime"):
    """Register one env knob; returns ``name`` so call sites can do
    ``ENV_FOO = register_env("MXTPU_FOO", ...)``.

    Default precedence: a ``get_env`` call that passes its own default
    wins (sites do this deliberately — a STRING default keeps garbage
    values like ``MXTPU_STEP_GUARD=maybe`` readable instead of raising
    in ``int()``); the default registered here applies only when the
    site passes none, and otherwise serves as the documented value the
    docs table mirrors."""
    ENV_REGISTRY[name] = EnvSpec(name, default, doc, scope)
    return name


def registered_env_names(prefix=None, scope=None):
    """Registered knob names, optionally filtered by prefix/scope."""
    return sorted(
        n for n, s in ENV_REGISTRY.items()
        if (prefix is None or n.startswith(prefix))
        and (scope is None or s.scope == scope))


_WARNED_UNREGISTERED = set()


def get_env(name, default=None, typ=None):
    """Read a runtime config env var (dmlc::GetEnv analog).

    Supported vars follow the reference's catalog (docs/how_to/env_var.md)
    with an ``MXNET_`` prefix, e.g. ``MXNET_ENGINE_TYPE``,
    ``MXNET_EXEC_BULK_EXEC_TRAIN``; TPU-era knobs use ``MXTPU_``.  Every
    framework-prefixed name must be in :data:`ENV_REGISTRY` — an
    unregistered read warns once (and is a static-analysis finding, see
    tools/mxlint.py), because a typo'd knob silently reading its default
    is exactly the failure mode the registry exists to catch.
    """
    if name.startswith(("MXTPU_", "MXNET_")) and name not in ENV_REGISTRY \
            and name not in _WARNED_UNREGISTERED:
        _WARNED_UNREGISTERED.add(name)
        _LOG.warning("env var %s is not registered in base.ENV_REGISTRY — "
                     "typo, or a knob missing from the catalog "
                     "(docs/env_vars.md)?", name)
    if default is None and name in ENV_REGISTRY:
        # the registered default is authoritative when the call site
        # doesn't override it — one place to change a knob's default
        default = ENV_REGISTRY[name].default
    raw = os.environ.get(name)
    if raw is None:
        return default
    if typ is None and default is not None:
        typ = type(default)
    if typ is bool:
        low = raw.strip().lower()
        if low in _TRUE_STRINGS:
            return True
        if low in _FALSE_STRINGS:
            return False
        raise MXNetError("Invalid boolean env var %s=%r" % (name, raw))
    if typ is not None:
        return typ(raw)
    return raw


def parse_attr_value(value):
    """Parse a string attribute into a Python value.

    The reference serializes op kwargs as strings through dmlc::Parameter
    (src/operator/optimizer_op-inl.h:25-45); symbols store attrs as strings in
    JSON.  We accept both typed python values and their string forms:
    ``"(2, 2)"`` -> (2, 2), ``"1"`` -> 1, ``"True"`` -> True, ``"relu"`` -> "relu".
    """
    if not isinstance(value, str):
        return value
    s = value.strip()
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        low = s.lower()
        if low in _TRUE_STRINGS and s.isalpha():
            return True
        if low in _FALSE_STRINGS and s.isalpha():
            return False
        return s


def attr_to_string(value):
    """Serialize an attr value to the string form used in symbol JSON."""
    if isinstance(value, str):
        return value
    if isinstance(value, bool):
        return "True" if value else "False"
    if isinstance(value, (list, tuple)):
        return "(" + ", ".join(attr_to_string(v) for v in value) + (",)" if len(value) == 1 else ")")
    return str(value)


class Registry(object):
    """Generic name->object registry (dmlc registry analog).

    Used for optimizers, initializers, metrics, data iterators, kvstores.
    """

    def __init__(self, kind):
        self._kind = kind
        self._entries = {}

    def register(self, obj=None, name=None, aliases=()):
        def _do(o):
            key = (name or o.__name__).lower()
            self._entries[key] = o
            for a in aliases:
                self._entries[a.lower()] = o
            return o
        if obj is None:
            return _do
        return _do(obj)

    def get(self, name):
        key = name.lower()
        if key not in self._entries:
            raise MXNetError(
                "Cannot find %s %r. Registered: %s"
                % (self._kind, name, sorted(self._entries)))
        return self._entries[key]

    def find(self, name):
        return self._entries.get(name.lower())

    def list(self):
        return sorted(self._entries)

    def create(self, name, *args, **kwargs):
        return self.get(name)(*args, **kwargs)


class classproperty(object):
    def __init__(self, fget):
        self.fget = fget

    def __get__(self, obj, owner):
        return self.fget(owner)


class _ThreadLocalStack(threading.local):
    """Thread-local scope stack (used by Context / AttrScope / NameManager)."""

    def __init__(self):
        self.stack = []


def check_call(ret):  # pragma: no cover - API-parity shim
    """No-op kept for source compatibility with reference-style code."""
    return ret


# -- knobs owned by the package root / the test harness (modules register
# their own next to the code that reads them; see ENV_REGISTRY)
ENV_COMPILE_CACHE = register_env(
    "MXTPU_COMPILE_CACHE",
    doc="Directory for XLA's persistent compilation cache (wired to "
        "jax_compilation_cache_dir at package import)")
ENV_TEST_PLATFORM = register_env(
    "MXTPU_TEST_PLATFORM", default="cpu", scope="test",
    doc="Test-suite platform: cpu = 8-device virtual mesh, tpu = real "
        "chip (read by tests/conftest.py and bench tooling)")
# Registered here (not in data_service/) because it is read across
# modules: image.py routes ImageRecordIter through the data service when
# it is set, and data_service.service sizes the worker fleet from it.
ENV_DATA_WORKERS = register_env(
    "MXTPU_DATA_WORKERS", default=0,
    doc="N>0 routes ImageRecordIter through the multi-process "
        "shared-memory data service with N decode worker processes "
        "(same as data_service=True; docs/how_to/performance.md)")
# Registered here for the same cross-module reason: image.py routes
# through the NETWORK tier when it is set.
ENV_DATA_SERVERS = register_env(
    "MXTPU_DATA_SERVERS", default="",
    doc="Comma list of host:port data servers (tools/data_server.py): "
        "routes every eligible ImageRecordIter through the "
        "network-tier data service (same as "
        "data_service='host:port,...'); unset falls back to the local "
        "service / in-process pipelines (docs/how_to/performance.md)")
# Registered here (not in kernels/) because it is read across modules:
# ops/nn.py's RNN scan, rnn/rnn_cell.py's LSTMCell, executor.py's
# BN+activation fusion pass and parallel/ring_attention.py all consult it
# at trace/bind time (docs/how_to/kernels.md).
ENV_FUSED_KERNELS = register_env(
    "MXTPU_FUSED_KERNELS", default="1",
    doc="Fused-kernel + plan-optimizer routing (mxnet_tpu/kernels/, "
        "mxnet_tpu/mxfuse.py): 1 = everything on (default), 0 = exact "
        "pre-fusion graphs, or a comma list from {bn_act, bn_fold, "
        "lstm_cell, flash_attention, augment, concat_fuse, pool_act, "
        "eltwise_chain, infer_trace} to enable individually "
        "(docs/how_to/kernels.md)")
