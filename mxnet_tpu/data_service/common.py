"""Shared, dependency-free pieces of the data service.

This module is imported BOTH by the trainer process (via the package)
and by decode worker processes (loaded by file path under a synthetic
package — see ``_worker_main.py``), so it must stay stdlib+numpy only:
no jax, no package-relative imports.

It owns the three contracts the service's determinism rests on:

- :func:`chunk_seed` — the per-(seed, chunk, epoch) augmentation-seed
  mix shared with ``image.py``'s in-process pipelines AND the native
  C++ decoder (imagedec.cc ``MixSeed`` consumes its output), so a
  sample's augmentation is a pure function of (user seed, global batch
  index, epoch) no matter which process/thread decodes it.
- :func:`epoch_order` — the per-epoch record permutation, replicating
  ``ImageIter``'s semantics exactly (partition slice first, then a
  stateful ``random.Random(seed)`` shuffled once per epoch), so a
  seeded service delivers the same record stream as the in-process
  pipe, and the same stream for ANY worker count.
- the shard assignment: global batch ``i`` (records
  ``order[i*B:(i+1)*B]``) belongs to worker ``i % num_workers``, and the
  collector delivers batches in global order — the ordering contract
  ``workers=1`` vs ``workers=N`` bit-identity tests pin.

Plus the shared-memory ring layout constants ``ring.py`` and the worker
agree on.
"""
from __future__ import annotations

import random as _pyrandom

#: the reference's default ImageNet channel normalization (image.py's
#: ``mean=True`` / ``std=True``) — ONE definition shared by the
#: in-process augmenters, the native-pipe setup and the data-service
#: worker config, so the bit-identity contract cannot drift
IMAGENET_MEAN = (123.68, 116.28, 103.53)
IMAGENET_STD = (58.395, 57.12, 57.375)

__all__ = [
    "chunk_seed", "epoch_order", "worker_batches", "num_batches",
    "stream_batches", "jsonable_aug",
    "IMAGENET_MEAN", "IMAGENET_STD", "np_dtype", "open_native_pipe",
    "CTRL_WORDS", "CTRL_HEAD", "CTRL_TAIL", "CTRL_HB_MS", "CTRL_ACK_EPOCH",
    "CTRL_STALL_MS", "CTRL_ABORT_EPOCH", "CTRL_STOP", "CTRL_BATCHES",
    "SLOT_HDR_WORDS", "HDR_SEQ", "HDR_BATCH_IDX", "HDR_NVALID", "HDR_EPOCH",
    "align64", "slot_layout",
]


def chunk_seed(seed, chunk_idx, epoch=0):
    """Deterministic per-chunk seed (splitmix64-style mix keeps successive
    chunks decorrelated even for seed=0).  epoch and chunk mix through
    separate 64-bit odd multipliers — no bit-packing, so no field-width
    aliasing at any dataset size or epoch count.  (Shared with image.py's
    in-process pipelines; the native decoder mixes the result further
    per image, imagedec.cc:MixSeed.)"""
    m = (1 << 64) - 1
    x = (int(seed) * 0x9e3779b97f4a7c15
         + int(chunk_idx) * 0xbf58476d1ce4e5b9
         + int(epoch) * 0x2545f4914f6cdd1d) & m
    x ^= x >> 30
    x = (x * 0x94d049bb133111eb) & m
    x ^= x >> 31
    return x % (2 ** 31)


def epoch_order(keys, seed, epoch, shuffle, part_index=0, num_parts=1):
    """The record-key order for ``epoch`` (1-based), replicating
    ``ImageIter`` exactly: the partition slice is taken once, then a
    stateful ``random.Random(seed)`` shuffles the slice once per epoch
    (epoch 1 = one shuffle), orders accumulating across epochs.

    O(epoch * len) — callers that advance one epoch at a time should use
    :class:`EpochOrder` instead and only pay the replay on a cold start
    (worker respawn mid-run).
    """
    o = EpochOrder(keys, seed, shuffle, part_index, num_parts)
    for _ in range(max(1, int(epoch))):
        o.advance()
    return o.order


class EpochOrder(object):
    """Stateful epoch-order generator: ``advance()`` moves to the next
    epoch's order (epoch 1 after the first call).  ``seek(epoch)``
    replays from scratch — respawned workers use it to land mid-run."""

    def __init__(self, keys, seed, shuffle, part_index=0, num_parts=1):
        keys = list(keys)
        if num_parts > 1:
            chunk = len(keys) // num_parts
            keys = keys[part_index * chunk:(part_index + 1) * chunk]
        self._keys = keys
        self._seed = int(seed)
        self._shuffle = bool(shuffle)
        self._rng = _pyrandom.Random(self._seed)
        self.order = list(keys)
        self.epoch = 0

    def advance(self):
        if self._shuffle:
            self._rng.shuffle(self.order)
        self.epoch += 1
        return self.order

    def seek(self, epoch):
        """Jump to ``epoch`` (1-based), replaying shuffles from scratch
        if the target is not simply the next epoch."""
        epoch = int(epoch)
        if epoch < self.epoch + 1:
            self._rng = _pyrandom.Random(self._seed)
            self.order = list(self._keys)
            self.epoch = 0
        while self.epoch < epoch:
            self.advance()
        return self.order


def num_batches(n_records, batch_size):
    """Batches per epoch: every record is delivered; the final partial
    batch is padded (matching the in-process native pipe)."""
    return (int(n_records) + int(batch_size) - 1) // int(batch_size)


def worker_batches(order, batch_size, rank, num_workers,
                   stream_offset=0, stream_stride=1):
    """This worker's shard for one epoch: ``[(global_batch_idx,
    [keys...]), ...]`` — batch ``i`` holds records
    ``order[i*B:(i+1)*B]`` and belongs to worker ``i % num_workers``,
    so the union over ranks is exactly the epoch's record stream in
    order, for any worker count.

    ``stream_offset``/``stream_stride`` carve an OUTER shard first (the
    network tier: server ``s`` of ``S`` owns global batches ``i`` with
    ``i % S == s``, i.e. offset ``s`` stride ``S``); this worker then
    owns the rank-th residue of the server's local batch sequence
    ``j = 0, 1, 2, ...`` where ``g = offset + j*stride``.  With the
    defaults (offset 0, stride 1) this is exactly the single-host
    assignment, so the two tiers share ONE partition function and the
    any-worker-count / any-server-count bit-identity contracts are the
    same theorem."""
    out = []
    nb = num_batches(len(order), batch_size)
    j = int(rank)
    while True:
        g = int(stream_offset) + j * int(stream_stride)
        if g >= nb:
            break
        out.append((g, order[g * batch_size:(g + 1) * batch_size]))
        j += int(num_workers)
    return out


def stream_batches(n_batches, stream_offset=0, stream_stride=1):
    """How many of the epoch's ``n_batches`` global batches belong to
    the stream ``(offset, stride)`` — the count a network server (or
    the whole local service, offset 0 stride 1) delivers."""
    return len(range(int(stream_offset), int(n_batches),
                     int(stream_stride)))


# ---------------------------------------------------------------------------
# shared-memory ring layout (one segment per worker).
#
#   [ctrl: CTRL_WORDS x int64]
#   [slot 0: hdr(SLOT_HDR_WORDS x int64) | label bytes | data bytes]
#   [slot 1: ...] ...
#
# Single-producer (the worker) / single-consumer (the collector thread):
# the producer writes HEAD, slot headers and payloads; the consumer
# writes TAIL, ABORT_EPOCH and STOP; both sides only ever read the
# other's words.  Payload publication is seqlock-style: the slot header
# SEQ goes odd (2*batch_idx+1) before the payload is written and even
# (2*batch_idx+2) after, and HEAD is bumped last — the consumer accepts
# a slot only when HEAD covers it AND SEQ equals the even value for the
# exact global batch it expects, so a torn write (worker SIGKILLed
# mid-slot) can never be consumed as data.
# ---------------------------------------------------------------------------

CTRL_WORDS = 8
CTRL_HEAD = 0         # batches produced (producer-owned)
CTRL_TAIL = 1         # batches released (consumer-owned)
CTRL_HB_MS = 2        # producer heartbeat, int milliseconds (monotonic-ish)
CTRL_ACK_EPOCH = 3    # last epoch the producer finished/abandoned
CTRL_STALL_MS = 4     # accumulated producer ring-full wait (stats)
CTRL_ABORT_EPOCH = 5  # consumer: abandon this epoch (reset mid-epoch)
CTRL_STOP = 6         # consumer: shut down
CTRL_BATCHES = 7      # total batches produced across epochs (stats)

SLOT_HDR_WORDS = 8
HDR_SEQ = 0
HDR_BATCH_IDX = 1
HDR_NVALID = 2
HDR_EPOCH = 3


def jsonable_aug(aug):
    """Normalize an augmentation dict for a worker/server config:
    numpy arrays become lists, ``mean=True``/``std=True`` resolve to
    the shared IMAGENET_* defaults.  ONE definition used by the local
    service's worker configs AND the network tier's handshake, so the
    two transports cannot drift on augmentation semantics."""
    import numpy as _np
    out = {}
    for k, v in dict(aug or {}).items():
        if isinstance(v, _np.ndarray):
            v = [float(x) for x in v.reshape(-1)]
        elif v is True and k in ("mean", "std"):
            v = list(IMAGENET_MEAN if k == "mean" else IMAGENET_STD)
        out[k] = v
    return out


def np_dtype(name):
    """The numpy dtype for a service dtype name — shared by the
    coordinator (slot sizing, consumer views) and the worker (decode
    target), so the two sides can never disagree on ring layout."""
    import numpy as _np
    if name == "bfloat16":
        import ml_dtypes
        return _np.dtype(ml_dtypes.bfloat16)
    return _np.dtype(name)


def open_native_pipe(lib, out_h, out_w, resize, rand_crop, rand_mirror,
                     dtype_code, layout_code, mean, std, fast_dct,
                     nthreads):
    """Construct a native image pipe (``MXTPUImgPipeCreate``) — the ONE
    place the ctypes argument marshaling lives, shared by the
    in-process ``_NativePipeline`` (image.py) and the data-service
    worker, so the two decode paths cannot drift apart and break the
    bit-identity contract.  ``mean``/``std`` are 1- or 3-value float
    sequences or None (resolve ``True`` to the IMAGENET_* defaults
    before calling).  Returns ``(pipe_handle_or_None, keepalive)`` —
    hold ``keepalive`` for the pipe's lifetime (the C side keeps
    pointers into it)."""
    import ctypes
    import numpy as _np
    fp = ctypes.POINTER(ctypes.c_float)

    def _c3(v):
        if v is None:
            return None
        a = _np.asarray(v, dtype=_np.float32).reshape(-1)
        if a.size == 1:
            a = _np.repeat(a, 3)
        return (ctypes.c_float * 3)(*a[:3])

    mean_c, std_c = _c3(mean), _c3(std)
    pipe = lib.MXTPUImgPipeCreate(
        int(nthreads), int(out_h), int(out_w), int(resize or 0),
        1 if rand_crop else 0, 1 if rand_mirror else 0,
        int(dtype_code), int(layout_code),
        ctypes.cast(mean_c, fp) if mean_c else None,
        ctypes.cast(std_c, fp) if std_c else None,
        1 if fast_dct else 0)
    return pipe, (mean_c, std_c)


def align64(n):
    return (int(n) + 63) & ~63


def slot_layout(batch_size, data_shape, label_width, itemsize,
                slot_bytes=None):
    """Byte layout of one ring slot: ``(label_bytes, data_bytes,
    slot_stride)``.  ``slot_bytes`` (MXTPU_DATA_SLOT_BYTES) can only
    GROW the data region — a padded batch must always fit."""
    import numpy as _np
    need = int(batch_size) * int(_np.prod(data_shape)) * int(itemsize)
    data_bytes = align64(max(need, int(slot_bytes or 0)))
    label_bytes = align64(int(batch_size) * int(label_width) * 4)
    stride = SLOT_HDR_WORDS * 8 + label_bytes + data_bytes
    return label_bytes, data_bytes, stride
