"""``DataIter`` facade over a data service (local OR network tier).

Split out of :mod:`.service` so the coordinator itself stays jax-free
(``tools/data_server.py`` runs it on remote CPU hosts through the
synthetic-package stub); this module pulls in :mod:`..io`, which sits
on the jax side of the fence.

The facade works over anything with the service collector surface
(``next_batch``/``reset``/``seek``/``stats``/``close`` plus the
``_bs``/``_lw``/``_dtype``/``_ring_shape`` layout attrs) — today that
is :class:`.service.DataService` (shared-memory rings on this host)
and :class:`.net.NetDataService` (TCP frames from a remote server
fleet), so every consumer-side contract is written once.
"""
from __future__ import annotations

import numpy as np

from ..io import DataBatch, DataDesc, DataIter

__all__ = ["DataServiceIter"]


class DataServiceIter(DataIter):
    """`DataIter` facade over :class:`.service.DataService` (or
    :class:`.net.NetDataService`): host numpy batches (the
    ``host_batches`` analog of the in-process native pipe).

    ``copy=True`` (the safe default) hands each consumer a private
    array.  ``copy=False`` hands the transport-owned VIEW itself (a
    shared-memory ring slot locally, a receive buffer on the network
    tier) — fastest, but only for strictly serial consumers: the array
    is valid until ``batch.release()`` or the next pull, and anything
    "uploading" it must truly copy (on the CPU backend
    ``jax.device_put`` ALIASES numpy memory; use
    ``jnp.array(view, copy=True)``).  ``ImageRecordIter``'s
    ``host_batches`` service mode and the decode bench use
    ``copy=False``; wrapping either flavor in
    ``dataflow.DevicePrefetchIter(stage=trainer)`` is safe — the
    prefetcher snapshots slot-backed batches on its background thread
    and releases the slot before running ahead."""

    def __init__(self, service=None, data_name="data",
                 label_name="softmax_label", copy=True, **kwargs):
        if service is None:
            from .service import DataService
            service = DataService(**kwargs)
        self._service = service
        super().__init__(self._service._bs)
        self._copy = bool(copy)
        self.data_name = data_name
        self.label_name = label_name
        self.current_batch = None

    @property
    def provide_data(self):
        svc = self._service
        dt = np.dtype("float32" if svc._dtype == "bfloat16" else svc._dtype)
        return [DataDesc(self.data_name, (svc._bs,) + svc._ring_shape,
                         dtype=dt)]

    @property
    def provide_label(self):
        svc = self._service
        shape = (svc._bs, svc._lw) if svc._lw > 1 else (svc._bs,)
        return [DataDesc(self.label_name, shape)]

    def next(self):
        data, labels, pad, release = self._service.next_batch()
        batch = DataBatch([data], [labels], pad=pad,
                          provide_data=self.provide_data,
                          provide_label=self.provide_label)
        # the device-side augmentation seam reads these: the per-batch
        # chunk seed (same value any worker/server count) and validity
        batch.aug_seed = self._service.last_aug_seed
        if self._copy:
            # already private: copy now, recycle the slot, and do NOT
            # attach the instance-level release — its presence is the
            # "transport-owned buffers" signal DevicePrefetchIter keys
            # its snapshot on, which would re-copy every batch
            batch.data = [np.array(data)]
            release()
        else:
            batch.release = release
        self.current_batch = batch
        return batch

    def iter_next(self):
        try:
            self.next()
            return True
        except StopIteration:
            return False

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getpad(self):
        return self.current_batch.pad

    def reset(self):
        self._service.reset()

    def stats(self):
        return self._service.stats()

    def close(self):
        self.current_batch = None   # drop the last zero-copy view
        self._service.close()
