"""Single-producer / single-consumer shared-memory batch ring.

One ring per decode worker: the worker process decodes straight into a
slot's data region (no intermediate buffer, no pickling, no pipe), the
collector thread in the trainer process reads the slot as a zero-copy
numpy view.  Stays stdlib+numpy only — worker processes load this by
file path without importing the package (see ``_worker_main.py``).

Layout and the seqlock publication protocol are defined in
:mod:`.common` (the producer and consumer must agree byte-for-byte).
Cross-process memory ordering: both sides run CPython (one bytecode at
a time, no compiler reordering) on the platforms this repo targets
(x86-64 TSO / AArch64 via the interpreter's own barriers), and the
consumer additionally validates the per-slot SEQ word against the exact
global batch index it expects — a torn or stale slot reads as
"not ready", never as data.
"""
from __future__ import annotations

import time

import numpy as np
from multiprocessing import shared_memory as _shm

from . import common as C

__all__ = ["Ring"]

#: segments whose mmap could not be closed because a delivered zero-copy
#: view still references it — kept alive (preventing SharedMemory.__del__
#: from raising BufferError at gc) and reclaimed by the OS at process
#: exit; the NAME is unlinked immediately either way
_leaked_segments = []


def _now_ms():
    # CLOCK_MONOTONIC is one system-wide clock on Linux (and QPC on
    # Windows), so producer stamps compare cleanly against consumer
    # reads — and unlike wall time it cannot step forward under NTP and
    # make every worker look hung at once
    return int(time.monotonic() * 1000.0)


class Ring(object):
    """The shared segment + typed views.  ``create=True`` on the
    consumer side allocates; workers attach by name."""

    def __init__(self, name, slots, batch_size, data_shape, label_width,
                 itemsize, slot_bytes=None, create=False):
        self.slots = int(slots)
        self.batch_size = int(batch_size)
        self.data_shape = tuple(int(d) for d in data_shape)
        self.label_width = int(label_width)
        self.itemsize = int(itemsize)
        self.label_bytes, self.data_bytes, self.stride = C.slot_layout(
            batch_size, data_shape, label_width, itemsize, slot_bytes)
        total = C.CTRL_WORDS * 8 + self.slots * self.stride
        if create:
            self._shm = _shm.SharedMemory(name=name, create=True, size=total)
            self._shm.buf[:total] = b"\x00" * total
        else:
            self._shm = _shm.SharedMemory(name=name)
            # the CREATOR owns the segment's lifetime.  Python's
            # per-process resource tracker auto-registers every attach,
            # and when an attached process dies (a SIGKILLed worker —
            # the chaos drill) its tracker "cleans up" by UNLINKING the
            # live segment out from under the coordinator and every
            # respawned worker.  Deregister the attach-side bookkeeping.
            try:
                from multiprocessing import resource_tracker
                resource_tracker.unregister(self._shm._name,
                                            "shared_memory")
            except Exception:  # noqa: BLE001 — tracker internals vary
                pass
        self.name = self._shm.name
        self._owner = bool(create)
        self.ctrl = np.frombuffer(self._shm.buf, dtype=np.int64,
                                  count=C.CTRL_WORDS)
        self._hdrs = []
        base = C.CTRL_WORDS * 8
        for s in range(self.slots):
            off = base + s * self.stride
            self._hdrs.append(np.frombuffer(
                self._shm.buf, dtype=np.int64, count=C.SLOT_HDR_WORDS,
                offset=off))
        self._label_views = [None] * self.slots
        self._data_views = [None] * self.slots

    # -- views --------------------------------------------------------------
    def _slot_off(self, s):
        return C.CTRL_WORDS * 8 + s * self.stride

    def label_view(self, s):
        cached = self._label_views[s]
        if cached is not None:
            return cached
        off = self._slot_off(s) + C.SLOT_HDR_WORDS * 8
        v = np.frombuffer(self._shm.buf, dtype=np.float32,
                          count=self.batch_size * self.label_width,
                          offset=off).reshape(self.batch_size,
                                              self.label_width)
        self._label_views[s] = v
        return v

    def data_view(self, s, dtype):
        cached = self._data_views[s]
        if cached is not None and cached.dtype == dtype:
            return cached
        off = self._slot_off(s) + C.SLOT_HDR_WORDS * 8 + self.label_bytes
        n = self.batch_size * int(np.prod(self.data_shape))
        v = np.frombuffer(self._shm.buf, dtype=dtype, count=n,
                          offset=off).reshape(
                              (self.batch_size,) + self.data_shape)
        self._data_views[s] = v
        return v

    # -- producer side ------------------------------------------------------
    def heartbeat(self):
        self.ctrl[C.CTRL_HB_MS] = _now_ms()

    def stopped(self):
        return bool(self.ctrl[C.CTRL_STOP])

    def abort_epoch(self):
        return int(self.ctrl[C.CTRL_ABORT_EPOCH])

    def acquire(self, poll_s=0.005, on_wait=None):
        """Block until a slot is free (or stop/abort is flagged, or the
        optional ``on_wait()`` callback returns True); returns the slot
        index or None.  Accumulates the wait into the producer stall
        counter and keeps the heartbeat fresh while waiting."""
        waited = False
        t0 = time.monotonic()
        while True:
            self.heartbeat()
            if self.stopped() or (on_wait is not None and on_wait()):
                slot = None
                break
            head = int(self.ctrl[C.CTRL_HEAD])
            if head - int(self.ctrl[C.CTRL_TAIL]) < self.slots:
                slot = head % self.slots
                break
            waited = True
            time.sleep(poll_s)
        if waited:
            self.ctrl[C.CTRL_STALL_MS] += int(
                (time.monotonic() - t0) * 1000.0)
        return slot

    def begin_write(self, slot, batch_idx):
        self._hdrs[slot][C.HDR_SEQ] = 2 * int(batch_idx) + 1

    def commit(self, slot, batch_idx, nvalid, epoch):
        h = self._hdrs[slot]
        h[C.HDR_BATCH_IDX] = int(batch_idx)
        h[C.HDR_NVALID] = int(nvalid)
        h[C.HDR_EPOCH] = int(epoch)
        h[C.HDR_SEQ] = 2 * int(batch_idx) + 2   # even: published
        self.ctrl[C.CTRL_HEAD] += 1
        self.ctrl[C.CTRL_BATCHES] += 1
        self.heartbeat()

    def ack_epoch(self, epoch):
        self.ctrl[C.CTRL_ACK_EPOCH] = int(epoch)
        self.heartbeat()

    # -- consumer side ------------------------------------------------------
    def ready(self, batch_idx, epoch=None):
        """True when the next unreleased slot holds ``batch_idx`` (of
        ``epoch``, when given — batch indices repeat across epochs, so
        the epoch check is what keeps a stale-epoch slot from a
        straggler producer invisible), fully published."""
        head = int(self.ctrl[C.CTRL_HEAD])
        tail = int(self.ctrl[C.CTRL_TAIL])
        if head <= tail:
            return False
        h = self._hdrs[tail % self.slots]
        if int(h[C.HDR_SEQ]) != 2 * int(batch_idx) + 2:
            return False
        return epoch is None or int(h[C.HDR_EPOCH]) == int(epoch)

    def published_mismatch(self, batch_idx, epoch):
        """True when the next unreleased slot is fully PUBLISHED (even
        SEQ) but holds the wrong batch/epoch — production is
        deterministic, so a healthy producer can never do this; it
        means a stale/straggler process wrote into the ring and the
        worker must be respawned rather than waited on."""
        if int(self.ctrl[C.CTRL_HEAD]) <= int(self.ctrl[C.CTRL_TAIL]):
            return False
        h = self._hdrs[int(self.ctrl[C.CTRL_TAIL]) % self.slots]
        seq = int(h[C.HDR_SEQ])
        if seq == 0 or seq % 2:   # empty or mid-write: keep waiting
            return False
        return (seq != 2 * int(batch_idx) + 2
                or int(h[C.HDR_EPOCH]) != int(epoch))

    def peek(self, dtype):
        """Views of the next unreleased slot: ``(hdr, label, data)``.
        Only valid after :meth:`ready` returned True."""
        s = int(self.ctrl[C.CTRL_TAIL]) % self.slots
        return self._hdrs[s], self.label_view(s), self.data_view(s, dtype)

    def release(self):
        self.ctrl[C.CTRL_TAIL] += 1

    def occupancy(self):
        return int(self.ctrl[C.CTRL_HEAD]) - int(self.ctrl[C.CTRL_TAIL])

    def heartbeat_age_s(self):
        hb = int(self.ctrl[C.CTRL_HB_MS])
        if hb == 0:
            return 0.0
        return max(0.0, (_now_ms() - hb) / 1000.0)

    def producer_stall_s(self):
        return int(self.ctrl[C.CTRL_STALL_MS]) / 1000.0

    def batches_produced(self):
        return int(self.ctrl[C.CTRL_BATCHES])

    def acked_epoch(self):
        return int(self.ctrl[C.CTRL_ACK_EPOCH])

    def request_abort(self, epoch):
        self.ctrl[C.CTRL_ABORT_EPOCH] = int(epoch)

    def request_stop(self):
        self.ctrl[C.CTRL_STOP] = 1

    def reset_counters(self):
        """Consumer-side reset before a (re)spawned producer reuses the
        segment: positions zeroed, stop/abort cleared, stats kept."""
        self.ctrl[C.CTRL_HEAD] = 0
        self.ctrl[C.CTRL_TAIL] = 0
        self.ctrl[C.CTRL_ABORT_EPOCH] = 0
        self.ctrl[C.CTRL_STOP] = 0
        self.ctrl[C.CTRL_HB_MS] = 0
        for h in self._hdrs:
            h[C.HDR_SEQ] = 0

    # -- lifecycle ----------------------------------------------------------
    def close(self):
        # drop our own views first, then close the mmap; a consumer still
        # holding a delivered zero-copy view makes close() raise
        # BufferError — park the segment in _leaked_segments (freed at
        # process exit) instead of letting gc retry and warn forever
        self.ctrl = None
        self._hdrs = None
        self._label_views = None
        self._data_views = None
        try:
            self._shm.close()
        except BufferError:
            # a consumer still holds a delivered view: the OS frees the
            # mapping at process exit; neuter close() so gc at
            # interpreter shutdown cannot raise through __del__
            self._shm.close = lambda: None
            _leaked_segments.append(self._shm)
        except OSError:
            pass
        if self._owner:
            try:
                self._shm.unlink()
            except OSError:
                pass

    def __del__(self):
        try:
            if self.ctrl is not None:
                self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
