"""The network tier of the data service (the tf.data-service shape).

PR 7's :class:`.service.DataService` recruits the cores of the ONE
host that owns the devices; this module decouples decode capacity from
the TPU host.  Remote CPU hosts run ``tools/data_server.py`` — a
jax-free CLI that accepts one consumer connection per stream, builds
the SAME sharded-reader/decode-worker service on its own cores, and
streams the published ring slots over TCP as length-prefixed,
crc-checked frames.  The consumer-side :class:`NetDataService` is a
drop-in for ``DataService`` (same collector surface, wrapped by the
same ``DataServiceIter``): it connects to N servers, hands server
``s`` of ``S`` the outer stream shard ``offset=s, stride=S`` (global
batch ``i`` belongs to server ``i % S`` — the PR-7 worker assignment
lifted one level), and delivers frames in global order as zero-copy
numpy views over reusable receive buffers.

Everything PR 7 proved is preserved BY CONSTRUCTION, not re-derived:

- **Determinism**: the epoch permutation is ``common.EpochOrder`` and
  the per-batch augmentation seed is ``common.chunk_seed(seed, global
  batch, epoch)`` on every host, so the delivered stream — augmented
  or plain, padded final batch included — is bit-identical to the
  in-process service for ANY server count and ANY per-server worker
  count.
- **Exactly-once**: every frame carries (epoch, global batch index,
  nvalid, payload length, crc32).  A torn frame (short read, bad
  magic, implausible length, crc mismatch) is never consumed: the
  connection is dropped and re-established, and the handshake
  re-requests the stream at the last CONSUMED batch — deterministic
  production makes the re-decoded tail bit-identical.  SIGKILLing a
  server mid-epoch is the same event as a torn frame plus a refused
  reconnect until the host's supervisor respawns it.
- **Liveness**: servers emit heartbeat frames whenever no batch is
  flowing (including while a legitimately slow worker decodes — the
  server polls its local collector with a timeout).  A connection with
  no frames for ``MXTPU_DATA_NET_TIMEOUT_S`` is evicted and
  reconnected; ``MXTPU_DATA_NET_RETRIES`` consecutive failed
  reconnects (streak reset on every delivered batch) surface as
  ``MXNetError``.
- **Flow control**: the consumer pre-allocates a small pool of receive
  buffers per connection and stops reading the socket when they are
  full — TCP backpressure stalls the server's send, its ring fills,
  its workers block in ``acquire``: the whole pipeline is
  demand-driven with no unbounded queue anywhere.

This module is jax-free (stdlib + numpy + the package's jax-free
leaves) on BOTH sides: the server runs under the synthetic-package
stub, and the consumer half is plain sockets/numpy so the trainer pays
no import cost beyond what PR 7 already paid.
"""
from __future__ import annotations

import json
import logging
import os
import socket
import struct
import threading
import time
import zlib
from collections import deque

import numpy as np

from ..base import MXNetError, get_env
from . import (ENV_DATA_NET_FRAME_BYTES, ENV_DATA_NET_RECONNECT,
               ENV_DATA_NET_RETRIES, ENV_DATA_NET_TIMEOUT)
from . import common as C

__all__ = ["BatchServer", "NetDataService", "parse_servers",
           "FRAME_BATCH", "FRAME_HB", "FRAME_EPOCH_END", "FRAME_ERROR"]

_LOG = logging.getLogger(__name__)

#: frame header: magic, type, epoch, global batch idx, nvalid, payload
#: bytes, crc32(payload).  ``<`` = no padding — both sides agree
#: byte-for-byte like the ring layout in :mod:`.common`.
_HDR = struct.Struct("<IBIqiQI")
_MAGIC = 0x4d584446          # "MXDF"
FRAME_BATCH = 1
FRAME_HB = 2
FRAME_EPOCH_END = 3
FRAME_ERROR = 4

#: config keys a handshake forwards verbatim into the server-side
#: ``DataService`` constructor (ONE list, so consumer and server can
#: never disagree about what a stream's identity includes)
_CFG_KEYS = ("path_imgrec", "path_imgidx", "data_shape", "batch_size",
             "label_width", "shuffle", "seed", "part_index", "num_parts",
             "num_workers", "dtype", "layout", "aug", "fast_dct",
             "slots", "stream_offset", "stream_stride")


def parse_servers(spec):
    """``'host:port,host:port'`` (or an iterable of the same / of
    ``(host, port)`` pairs) -> ``[(host, port), ...]``."""
    if isinstance(spec, str):
        parts = [p.strip() for p in spec.replace(";", ",").split(",")
                 if p.strip()]
    else:
        parts = list(spec or ())
    out = []
    for p in parts:
        if isinstance(p, (tuple, list)):
            host, port = p
        else:
            host, _, port = str(p).rpartition(":")
            if not host:
                raise MXNetError(
                    "data servers must be host:port, got %r" % (p,))
        out.append((str(host), int(port)))
    if not out:
        raise MXNetError("empty data-server list %r" % (spec,))
    return out


def _recv_exact(sock, view, on_progress=None):
    """Fill ``view`` (a writable memoryview) from the socket; returns
    False on a clean EOF at offset 0, raises on a short read anywhere
    else (a torn frame — the consumer never consumes it).
    ``on_progress`` fires after every successful chunk — the consumer's
    liveness clock must count BYTES flowing, not completed frames: a
    multi-MB batch frame on a slow link can legitimately take longer
    than the whole eviction timeout."""
    got = 0
    total = len(view)
    while got < total:
        n = sock.recv_into(view[got:], total - got)
        if n == 0:
            if got == 0:
                return False
            raise ConnectionError("torn frame: EOF after %d/%d bytes"
                                  % (got, total))
        got += n
        if on_progress is not None:
            on_progress()
    return True


def _send_frame(sock, ftype, epoch, batch_idx, nvalid, *payload):
    crc = 0
    total = 0
    for part in payload:
        crc = zlib.crc32(part, crc)
        total += len(memoryview(part).cast("B"))
    sock.sendall(_HDR.pack(_MAGIC, ftype, int(epoch), int(batch_idx),
                           int(nvalid), total, crc & 0xffffffff))
    for part in payload:
        sock.sendall(part)


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------

class BatchServer(object):
    """One decode host's server: accepts consumer connections, builds a
    (jax-free) :class:`.service.DataService` per stream from the
    handshake config, and streams published ring slots as frames.

    Runs inside ``tools/data_server.py`` on remote hosts, or in-process
    for loopback tests/benches.  Concurrent connections each get their
    own service (their own worker processes), so one server process can
    feed several consumers — a consumer that disconnects tears its
    service (and decode workers) down.
    """

    def __init__(self, host="127.0.0.1", port=0, log=None):
        self._log = log or (lambda msg: _LOG.info("%s", msg))
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(16)
        self.host, self.port = self._sock.getsockname()[:2]
        self._stop = threading.Event()

    def serve_forever(self):
        """Accept loop (blocks); one daemon thread per connection."""
        while not self._stop.is_set():
            try:
                conn, addr = self._sock.accept()
            except OSError:
                break       # shutdown() closed the listener
            t = threading.Thread(target=self._handle, args=(conn, addr),
                                 name="mxds-net-%s:%s" % addr[:2],
                                 daemon=True)
            t.start()
        return 0

    def shutdown(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    # -- one connection = one stream ---------------------------------------
    def _handle(self, conn, addr):
        from .service import DataService
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        rfile = conn.makefile("rb")
        svc = None
        try:
            # the handshake is read under a timeout AND a length cap
            # (mirroring the consumer's _recv_line): a half-open probe
            # must not park this thread+fd forever, and a newline-less
            # byte stream must not buffer without bound
            conn.settimeout(30)
            line = rfile.readline(65537)
            conn.settimeout(None)
            if len(line) > 65536:
                raise MXNetError("oversized handshake")
            hello = json.loads(line or "{}")
            cfg = dict(hello.get("cfg") or {})
            unknown = set(cfg) - set(_CFG_KEYS)
            if unknown:
                raise MXNetError("unknown stream config keys %s"
                                 % sorted(unknown))
            hb_s = max(0.2, float(hello.get("hb_s", 2.0)))
            svc = DataService(start_epoch=int(hello.get("epoch", 1)),
                              start_batch=int(hello.get("skip", 0)),
                              **cfg)
            conn.sendall((json.dumps(
                {"ok": True, "nbatches": svc._nbatches,
                 "stream_batches": svc._stream_batches}) + "\n").encode())
        except Exception as e:  # noqa: BLE001 — reported to the consumer
            self._log("data_server: handshake from %s:%s failed: %s"
                      % (addr[0], addr[1], e))
            try:
                conn.sendall((json.dumps(
                    {"ok": False, "error": str(e)}) + "\n").encode())
            except OSError:
                pass
            conn.close()
            return
        ctrl = _CtrlReader(rfile)
        try:
            self._stream(conn, svc, ctrl, hb_s)
        except (OSError, ValueError) as e:
            self._log("data_server: stream to %s:%s ended: %s"
                      % (addr[0], addr[1], e))
        except MXNetError as e:
            # a worker exhausted its respawn budget (broken dataset):
            # tell the consumer WHY before closing, so its error names
            # the cause instead of "connection reset"
            try:
                msg = str(e).encode("utf-8", "replace")[:2000]
                _send_frame(conn, FRAME_ERROR, svc.epoch, -1, 0, msg)
            except OSError:
                pass
        finally:
            svc.close()
            try:
                conn.close()
            except OSError:
                pass

    def _stream(self, conn, svc, ctrl, hb_s):
        # stage each published slot into a scratch buffer and RELEASE
        # it before the (milliseconds-long) crc+send: the decode worker
        # starts the next batch while this thread pushes bytes — a
        # send-while-holding-the-slot serialized ~12% of the pipeline
        # into dead time (measured on the loopback bench)
        label_n = svc._bs * svc._lw
        label_bytes = label_n * 4
        data_n = svc._bs * int(np.prod(svc._ring_shape))
        staging = bytearray(label_bytes + data_n * svc._np_dtype.itemsize)
        stage_lab = np.frombuffer(staging, np.float32, count=label_n)
        stage_dat = np.frombuffer(staging, svc._np_dtype, count=data_n,
                                  offset=label_bytes).reshape(
                                      (svc._bs,) + svc._ring_shape)
        while True:
            cmd = ctrl.pop()
            if cmd is not None:
                if cmd.get("op") == "quit":
                    return
                if cmd.get("op") == "epoch":
                    svc.seek(int(cmd["epoch"]), int(cmd.get("skip", 0)))
                    continue
            if svc.at_epoch_end():
                _send_frame(conn, FRAME_EPOCH_END, svc.epoch, -1, 0)
                # idle until the next epoch/quit command, visibly alive
                while True:
                    cmd = ctrl.pop(timeout=hb_s)
                    if cmd is not None:
                        break
                    _send_frame(conn, FRAME_HB, svc.epoch, -1, 0)
                if cmd.get("op") == "quit":
                    return
                if cmd.get("op") == "epoch":
                    svc.seek(int(cmd["epoch"]), int(cmd.get("skip", 0)))
                continue
            try:
                nb = svc.next_batch(timeout=hb_s)
            except StopIteration:
                continue    # at_epoch_end handles it next loop
            if nb is None:
                # workers still decoding: the consumer must not read
                # silence as death while real work is in flight
                _send_frame(conn, FRAME_HB, svc.epoch, -1, 0)
                continue
            datav, labels, pad, release = nb
            stage_lab[:] = np.asarray(labels, np.float32).reshape(-1)
            stage_dat[:] = datav
            gidx = svc.last_batch_idx
            epoch = svc.epoch
            nvalid = svc._bs - pad
            release()
            _send_frame(conn, FRAME_BATCH, epoch, gidx, nvalid, staging)


class _CtrlReader(object):
    """Background reader for the consumer->server JSON control lines
    (epoch advance, quit).  EOF or garbage reads as ``quit`` — a
    vanished consumer tears the stream down either way, and the
    handler's ``conn.close()`` is what unblocks the thread at
    teardown (readline returns EOF)."""

    def __init__(self, rfile):
        self._q = deque()
        self._cv = threading.Condition()
        self._t = threading.Thread(target=self._loop, args=(rfile,),
                                   name="mxds-net-ctrl", daemon=True)
        self._t.start()

    def _loop(self, rfile):
        while True:
            try:
                line = rfile.readline()
            except (OSError, ValueError):
                line = b""
            if not line:
                self._push({"op": "quit"})
                return
            try:
                self._push(json.loads(line))
            except ValueError:
                self._push({"op": "quit"})
                return

    def _push(self, cmd):
        with self._cv:
            self._q.append(cmd)
            self._cv.notify_all()

    def pop(self, timeout=0.0):
        with self._cv:
            if not self._q and timeout:
                self._cv.wait(timeout)
            return self._q.popleft() if self._q else None


# ---------------------------------------------------------------------------
# consumer side
# ---------------------------------------------------------------------------

class _Conn(object):
    """One server connection: handshake, a reader thread filling a
    small pool of receive buffers (seqlock analog: a frame is either
    fully validated — length, magic, crc — or never published), and
    the eviction bookkeeping."""

    def __init__(self, index, addr, hello_cfg, payload_bytes, slots,
                 frame_cap, hb_s):
        self.index = index
        self.addr = addr
        self._cfg = hello_cfg       # dict; epoch/skip filled per connect
        self._payload = int(payload_bytes)
        self._cap = int(frame_cap)
        self._hb_s = float(hb_s)
        self._bufs = [bytearray(self._payload) for _ in range(int(slots))]
        self._free = deque(range(int(slots)))
        self._ready = deque()       # (epoch, gidx, nvalid, buf_idx)
        self._lock = threading.Lock()
        self.consumed = 0           # stream batches delivered this epoch
        self.reconnects = 0         # lifetime (stats)
        self.attempts = 0           # consecutive failed connects (budget)
        self.frames = 0
        self.bytes_rx = 0
        self.wait_since = None      # set while the collector waits on us
        self.dead = "never connected"
        self.nbatches = None
        self._sock = None
        self._reader = None
        self._gen = 0               # connection generation (see kill())
        self._stop = threading.Event()
        self._last_rx = time.monotonic()

    # -- lifecycle ----------------------------------------------------------
    def connect(self, epoch, skip):
        self.kill("reconnecting")
        old = self._reader
        if old is not None and old.is_alive():
            # the old reader exits promptly (its socket is closed and
            # its stop event set by kill) — but it must be GONE before
            # the buffer pool is recycled: a reader mid-frame could
            # otherwise publish into, or still hold a buffer of, the
            # new connection's pool, and a crc-valid stale frame that
            # matches the resumed batch index would hand the collector
            # a view another thread is overwriting
            old.join(timeout=10)
            if old.is_alive():
                raise ConnectionError(
                    "previous reader thread did not exit")
        stop = threading.Event()
        with self._lock:
            self._gen += 1
            gen = self._gen
            self._free = deque(range(len(self._bufs)))
            self._ready.clear()
        sock = socket.create_connection(self.addr, timeout=10)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        hello = {"v": 1, "cfg": self._cfg, "epoch": int(epoch),
                 "skip": int(skip), "hb_s": self._hb_s}
        sock.sendall((json.dumps(hello) + "\n").encode())
        sock.settimeout(30)
        reply = json.loads(_recv_line(sock))
        if not reply.get("ok"):
            sock.close()
            raise MXNetError("data server %s:%d rejected the stream: %s"
                             % (self.addr[0], self.addr[1],
                                reply.get("error")))
        self.wait_since = None      # fresh connection: fresh clock
        nbatches = int(reply["nbatches"])
        if self.nbatches is not None and nbatches != self.nbatches:
            # a respawned server over a CHANGED dataset: fatal, not a
            # retry — a smaller epoch would hang the collector behind
            # healthy heartbeats, a larger one would serve wrong bytes
            # under matching (epoch, batch) headers
            sock.close()
            raise MXNetError(
                "data server %s:%d now reports %d batches/epoch "
                "(stream started with %d) — did the dataset change "
                "under a respawn?" % (self.addr[0], self.addr[1],
                                      nbatches, self.nbatches))
        self.nbatches = nbatches
        sock.settimeout(None)
        self.consumed = int(skip)
        self._last_rx = time.monotonic()
        with self._lock:
            self._sock = sock
            self._stop = stop
            self.dead = None
        self._reader = threading.Thread(
            target=self._read_loop, args=(sock, stop, gen),
            name="mxds-net-rx-%d" % self.index, daemon=True)
        self._reader.start()

    def kill(self, reason, gen=None):
        """Evict this connection (dead server, torn frame, stale
        heartbeat).  Validated-but-unconsumed frames are DROPPED — the
        reconnect handshake re-requests from the last consumed batch,
        and deterministic production makes the re-sent tail
        bit-identical (exactly-once at the consumer).

        ``gen`` is a reader thread's connection generation: a STALE
        reader waking up with the OSError from its own already-closed
        socket must not tear down the replacement connection the
        collector just established — once ``connect`` bumps the
        generation, the old reader's kill is a no-op."""
        with self._lock:
            if gen is not None and gen != self._gen:
                return
            if self.dead is None:
                self.dead = str(reason)
            stop = self._stop
            sock, self._sock = self._sock, None
        stop.set()
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def send_cmd(self, obj):
        sock = self._sock
        if self.dead is not None or sock is None:
            return False
        try:
            sock.sendall((json.dumps(obj) + "\n").encode())
            return True
        except OSError as e:
            self.kill("command send failed: %s" % e)
            return False

    def _stamp_rx(self):
        self._last_rx = time.monotonic()

    # -- reader thread ------------------------------------------------------
    def _read_loop(self, sock, stop, gen):
        hdr = bytearray(_HDR.size)
        hdrv = memoryview(hdr)
        try:
            while not stop.is_set():
                if not _recv_exact(sock, hdrv):
                    raise ConnectionError("server closed the stream")
                magic, ftype, epoch, gidx, nvalid, nbytes, crc = \
                    _HDR.unpack(hdr)
                if magic != _MAGIC:
                    raise ConnectionError("bad frame magic 0x%x" % magic)
                if nbytes > self._cap:
                    raise ConnectionError(
                        "frame announces %d bytes (cap %d)"
                        % (nbytes, self._cap))
                if ftype == FRAME_BATCH:
                    if nbytes != self._payload:
                        raise ConnectionError(
                            "batch frame payload %d != expected %d"
                            % (nbytes, self._payload))
                    buf_idx = self._take_free(stop)
                    if buf_idx is None:
                        return
                    view = memoryview(self._bufs[buf_idx])
                    if not _recv_exact(sock, view,
                                       on_progress=self._stamp_rx):
                        raise ConnectionError("torn frame: EOF in payload")
                    if zlib.crc32(view) & 0xffffffff != crc:
                        raise ConnectionError(
                            "frame crc mismatch (batch %d)" % gidx)
                    with self._lock:
                        self._ready.append((epoch, gidx, nvalid, buf_idx))
                elif ftype == FRAME_ERROR:
                    msg = bytearray(nbytes)
                    _recv_exact(sock, memoryview(msg))
                    raise ConnectionError(
                        "server error: %s" % msg.decode("utf-8", "replace"))
                elif ftype in (FRAME_HB, FRAME_EPOCH_END):
                    pass
                else:
                    raise ConnectionError("unknown frame type %d" % ftype)
                self._last_rx = time.monotonic()
                self.frames += 1
                self.bytes_rx += _HDR.size + nbytes
        except (OSError, ConnectionError, struct.error) as e:
            self.kill(e, gen=gen)

    def _take_free(self, stop):
        while not stop.is_set():
            with self._lock:
                if self._free:
                    return self._free.popleft()
            # buffers full: stop reading the socket — TCP backpressure
            # IS the cross-host flow control
            time.sleep(0.0005)
        return None

    # -- collector surface --------------------------------------------------
    def pop(self, epoch, gidx):
        """The head frame if it is exactly (epoch, gidx); None when the
        buffer is empty or holds only STALE frames (older epoch, or
        same-epoch batches BEHIND the cursor — a mid-epoch ``seek``
        leaves the pre-seek tail in flight; frames arrive in order per
        connection, so behind-the-cursor is harmless and discarded
        in-band, keeping the server's warm workers).  A frame AHEAD of
        the cursor is a real protocol violation (straggler server) and
        raises."""
        with self._lock:
            while self._ready:
                f_epoch, f_gidx, nvalid, buf_idx = self._ready[0]
                if f_epoch < epoch or (f_epoch == epoch
                                       and f_gidx < gidx):
                    # pre-reset / pre-seek leftovers: recycle and keep
                    # looking
                    self._ready.popleft()
                    self._free.append(buf_idx)
                    continue
                if f_epoch != epoch or f_gidx != gidx:
                    raise ConnectionError(
                        "stale stream: got (epoch %d, batch %d), "
                        "expected (epoch %d, batch %d)"
                        % (f_epoch, f_gidx, epoch, gidx))
                self._ready.popleft()
                return nvalid, buf_idx
            return None

    def release(self, buf_idx):
        with self._lock:
            self._free.append(buf_idx)

    def last_rx_age(self):
        return time.monotonic() - self._last_rx

    def silent_for(self, since):
        """Seconds with no complete frame, measured from
        ``max(last frame, since)`` — eviction must clock silence from
        when the collector STARTED waiting, not from the last frame: a
        consumer that paused past the timeout (checkpoint save, eval
        pass) backpressures both batches AND heartbeats, and absolute
        frame age would evict every healthy connection on resume."""
        return time.monotonic() - max(self._last_rx, since)

    def buffer(self, buf_idx):
        return self._bufs[buf_idx]


def _recv_line(sock, cap=65536):
    out = bytearray()
    while len(out) < cap:
        b = sock.recv(1)
        if not b:
            raise ConnectionError("EOF in handshake reply")
        if b == b"\n":
            return bytes(out)
        out += b
    raise ConnectionError("oversized handshake reply")


class NetDataService(object):
    """Consumer-side collector over N :class:`BatchServer` streams —
    the drop-in ``DataService`` analog for the network tier (same
    ``next_batch``/``reset``/``seek``/``stats``/``close`` surface, same
    zero-copy slot-lifetime contract, wrapped by the same
    ``DataServiceIter``).

    ``servers`` is ``'host:port,host:port'`` or a list; server ``s``
    serves global batches ``i`` with ``i % S == s`` and runs
    ``workers_per_server`` decode worker processes of its own.  The
    dataset paths are the SERVER hosts' paths — the consumer never
    opens them (a TPU host needs no copy of the .rec).
    """

    def __init__(self, servers, path_imgrec, path_imgidx, data_shape,
                 batch_size, label_width=1, shuffle=False, seed=0,
                 part_index=0, num_parts=1, workers_per_server=1,
                 dtype="float32", layout="NCHW", aug=None, slots=None,
                 fast_dct=True, timeout_s=None, retries=None,
                 reconnect_s=None, buffers=2):
        addrs = parse_servers(servers)
        if dtype not in ("uint8", "float32", "bfloat16"):
            raise MXNetError("data_service: unsupported dtype %r"
                             % (dtype,))
        if layout not in ("NCHW", "NHWC"):
            raise MXNetError("layout must be NCHW or NHWC")
        self._shape = tuple(int(d) for d in data_shape)
        if len(self._shape) != 3 or self._shape[0] != 3:
            raise MXNetError(
                "data_shape must be (3, height, width), got %s"
                % (self._shape,))
        c, h, w = self._shape
        self._ring_shape = (c, h, w) if layout == "NCHW" else (h, w, c)
        self._bs = int(batch_size)
        self._lw = int(label_width)
        self._dtype = dtype
        self._np_dtype = C.np_dtype(dtype)
        self._layout = layout
        self._seed = int(seed)
        self._timeout = float(timeout_s if timeout_s is not None
                              else get_env(ENV_DATA_NET_TIMEOUT, 30.0))
        self._retries = int(retries if retries is not None
                            else get_env(ENV_DATA_NET_RETRIES, 10))
        self._reconnect_s = float(
            reconnect_s if reconnect_s is not None
            else get_env(ENV_DATA_NET_RECONNECT, 0.5))
        frame_cap = int(get_env(ENV_DATA_NET_FRAME_BYTES, 1 << 30))
        hb_s = max(0.2, min(2.0, self._timeout / 4.0))
        self._label_bytes = self._bs * self._lw * 4
        data_bytes = (self._bs * int(np.prod(self._ring_shape))
                      * self._np_dtype.itemsize)
        payload = self._label_bytes + data_bytes
        S = len(addrs)
        self._conns = []
        for s, addr in enumerate(addrs):
            cfg = {
                "path_imgrec": path_imgrec, "path_imgidx": path_imgidx,
                "data_shape": list(self._shape),
                "batch_size": self._bs, "label_width": self._lw,
                "shuffle": bool(shuffle), "seed": self._seed,
                "part_index": int(part_index),
                "num_parts": int(num_parts),
                "num_workers": max(1, int(workers_per_server)),
                "dtype": dtype, "layout": layout,
                "aug": C.jsonable_aug(aug),
                "fast_dct": bool(fast_dct),
                "stream_offset": s, "stream_stride": S,
            }
            if slots is not None:
                cfg["slots"] = int(slots)
            self._conns.append(_Conn(s, addr, cfg, payload,
                                     max(2, int(buffers)), frame_cap,
                                     hb_s))
        self.epoch = 1
        self._next_idx = 0
        self._pending = None
        self._closed = False
        self.last_aug_seed = None
        self.last_batch_idx = None
        self._consumer_stall_s = 0.0
        try:
            for conn in self._conns:
                self._reconnect(conn)
            nbs = {conn.nbatches for conn in self._conns}
            if len(nbs) != 1:
                raise MXNetError(
                    "data servers disagree on the epoch's batch count "
                    "(%s) — are they serving the same dataset?"
                    % sorted(nbs))
            self._nbatches = nbs.pop()
        except BaseException:
            self.close()
            raise

    # -- connection supervision ---------------------------------------------
    def _reconnect(self, conn):
        """(Re)establish one server connection at this consumer's
        current position for that stream, within the consecutive-
        failure budget."""
        last_err = conn.dead
        while True:
            conn.attempts += 1
            if conn.attempts > self._retries:
                raise MXNetError(
                    "data server %s:%d unreachable after %d consecutive "
                    "attempts — last failure: %s"
                    % (conn.addr[0], conn.addr[1], self._retries,
                       last_err))
            try:
                conn.connect(self.epoch, conn.consumed)
                if conn.attempts > 1 or conn.reconnects:
                    _LOG.warning(
                        "data_service: reconnected to server %s:%d "
                        "(epoch %d, resuming at stream batch %d)",
                        conn.addr[0], conn.addr[1], self.epoch,
                        conn.consumed)
                conn.reconnects += 1
                return
            except (OSError, ConnectionError, ValueError) as e:
                last_err = e
                conn.dead = str(e)
                time.sleep(self._reconnect_s)

    # -- collector ----------------------------------------------------------
    def next_batch(self, timeout=None):
        """Same contract as ``DataService.next_batch``: zero-copy data
        view + fresh labels + pad + release, in global batch order."""
        if self._closed:
            raise MXNetError("data_service: closed")
        self._release_pending()
        if self._next_idx >= self._nbatches:
            raise StopIteration
        i = self._next_idx
        conn = self._conns[i % len(self._conns)]
        t0 = time.monotonic()
        give_up = None if timeout is None else t0 + float(timeout)
        waited = False
        while True:
            if conn.dead is not None:
                _LOG.warning(
                    "data_service: server %s:%d connection died (%s) — "
                    "evicting and reconnecting", conn.addr[0],
                    conn.addr[1], conn.dead)
                self._reconnect(conn)
            # the eviction clock persists across timeout-polling calls
            # (conn.wait_since, cleared on delivery and by a fresh
            # connect — stamped AFTER the reconnect above so a new
            # connection starts a fresh clock) — keying it off THIS
            # call's t0 would reset it every poll and a silent
            # connection would never be evicted under a polling
            # consumer
            if conn.wait_since is None:
                conn.wait_since = time.monotonic()
            try:
                item = conn.pop(self.epoch, i)
            except ConnectionError as e:
                conn.kill(e)
                continue
            if item is not None:
                break
            if conn.silent_for(conn.wait_since) > self._timeout:
                conn.kill("no frames for %.1fs (heartbeat timeout)"
                          % conn.silent_for(conn.wait_since))
                continue
            if give_up is not None and time.monotonic() >= give_up:
                self._consumer_stall_s += time.monotonic() - t0
                return None
            waited = True
            time.sleep(0.0005)
        conn.wait_since = None
        if waited:
            self._consumer_stall_s += time.monotonic() - t0
        nvalid, buf_idx = item
        nvalid = max(0, min(self._bs, int(nvalid)))
        buf = conn.buffer(buf_idx)
        labels = np.frombuffer(buf, np.float32,
                               count=self._bs * self._lw).reshape(
                                   self._bs, self._lw)
        labels = np.array(labels[:, 0] if self._lw == 1 else labels)
        datav = np.frombuffer(
            buf, self._np_dtype,
            count=self._bs * int(np.prod(self._ring_shape)),
            offset=self._label_bytes).reshape(
                (self._bs,) + self._ring_shape)
        self._next_idx += 1
        conn.consumed += 1
        conn.attempts = 0    # delivered: not a dead server
        self.last_aug_seed = C.chunk_seed(self._seed, i, epoch=self.epoch)
        self.last_batch_idx = i
        released = [False]

        def release(_conn=conn, _idx=buf_idx, _released=released):
            if not _released[0]:
                _released[0] = True
                _conn.release(_idx)
        self._pending = release
        return datav, labels, self._bs - nvalid, release

    def _release_pending(self):
        if self._pending is not None:
            self._pending()
            self._pending = None

    def at_epoch_end(self):
        return self._next_idx >= self._nbatches

    def reset(self):
        self.seek(self.epoch + 1)

    def seek(self, epoch, consumed=0):
        """Land every stream at ``epoch`` with the first ``consumed``
        GLOBAL batches already delivered (the ``DataService.seek``
        surface; ``reset()`` is ``seek(epoch + 1)``).  Live connections
        get an in-band epoch command (their server aborts the current
        epoch and reuses its warm workers); dead ones resume lazily on
        the next pull.  Stale-epoch frames still in flight are
        discarded by the collector's epoch filter."""
        if self._closed:
            raise MXNetError("data_service: closed")
        self._release_pending()
        self.epoch = max(1, int(epoch))
        self._next_idx = min(max(0, int(consumed)), self._nbatches)
        S = len(self._conns)
        for conn in self._conns:
            # this stream's share of the first `consumed` global
            # batches: global i belongs to server i % S
            conn.consumed = len(range(conn.index, self._next_idx, S))
            conn.send_cmd({"op": "epoch", "epoch": self.epoch,
                           "skip": conn.consumed})

    # -- observability ------------------------------------------------------
    def stats(self):
        if self._closed:
            return self._final_stats
        per = {}
        for conn in self._conns:
            per[conn.index] = {
                "server": "%s:%d" % conn.addr,
                "frames": conn.frames,
                "bytes_rx": conn.bytes_rx,
                "reconnects": max(0, conn.reconnects - 1),
                "alive": conn.dead is None,
                "last_rx_age_s": round(conn.last_rx_age(), 3),
            }
        return {
            "num_servers": len(self._conns),
            "num_workers": len(self._conns),   # stats-surface parity
            "epoch": self.epoch,
            "batches_delivered": self._next_idx,
            "consumer_stall_s": round(self._consumer_stall_s, 3),
            "producer_stall_s": 0.0,
            "ring_occupancy": 0.0,
            "servers": per,
        }

    # -- lifecycle ----------------------------------------------------------
    def close(self):
        if self._closed:
            return
        try:
            self._final_stats = self.stats()
        except Exception:  # noqa: BLE001 — mid-construction close
            self._final_stats = None
        self._closed = True
        self._pending = None
        for conn in getattr(self, "_conns", []):
            conn.send_cmd({"op": "quit"})
            conn.kill("closed")

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
