"""The trainer-process side of the data service.

``DataService`` owns N decode worker PROCESSES (``_worker_main.py`` —
each with its own recordio handle, its own native decode pipe and its
own shared-memory ring; no shared GIL, no shared pipe lock) and a
collector that delivers batches in GLOBAL order: batch ``i`` comes from
worker ``i % N``'s ring as a zero-copy numpy view.  The delivered
stream is a pure function of (seed, epoch): the same records, the same
augmentation, the same bytes for ANY worker count — see
``common.epoch_order`` / ``common.worker_batches`` for the contract.

Robustness is part of the design, not a bolt-on:

- workers heartbeat through their ring control words; a dead worker
  (crash, SIGKILL) is detected by ``Popen.poll`` immediately, a HUNG
  worker by heartbeat age (``MXTPU_DATA_HEARTBEAT_S``),
- either way the worker is respawned and its shard resumes at the last
  CONSUMED record (production is deterministic, so re-decoded batches
  are bit-identical — no duplicated or dropped records), with the
  ``data_worker``/``hang_data_worker`` fault points stripped from the
  child environment so an injected fault fires once per drill, not on
  every respawn,
- a worker that keeps dying exhausts its respawn budget and surfaces
  as an ``MXNetError`` carrying its stderr tail.

Per-stage counters (ring occupancy, producer/consumer stall, batches
and respawns per worker) are exposed via :meth:`DataService.stats` and
the ``bench.py data_service`` mode.

Slot lifetime contract: with ``copy=False`` the arrays a delivered
batch holds ALIAS the ring slot; the slot is recycled when the batch's
``release()`` is called, or automatically when the NEXT batch is
pulled — so zero-copy views are for STRICTLY SERIAL consumers that
finish with batch N before pulling N+1 (the decode bench, a plain
training loop).  Anything that runs ahead of its consumer must
snapshot before the next pull: ``dataflow.DevicePrefetchIter`` does
exactly that (copies on its background thread, then releases), and
``DataServiceIter``'s default ``copy=True`` hands out private arrays.

IMPORT DISCIPLINE: this module stays jax-free (stdlib + numpy + the
package's jax-free leaves) — ``tools/data_server.py`` runs a
DataService on remote CPU hosts through the synthetic-package stub,
where an accidental jax import would drag XLA into every decode host.
The ``DataIter`` facade (which needs the jax-side ``io`` module) lives
in :mod:`.iter`.
"""
from __future__ import annotations

import atexit
import json
import logging
import os
import subprocess
import sys
import tempfile
import time
import weakref

import numpy as np

from ..base import ENV_DATA_WORKERS, MXNetError, get_env  # noqa: F401 — re-exported knob
from ..resilience import strip_faults_env
from . import ENV_DATA_HEARTBEAT, ENV_DATA_RING_SLOTS, ENV_DATA_SLOT_BYTES
from . import common as C
from .ring import Ring

__all__ = ["DataService"]

_LOG = logging.getLogger(__name__)

_WORKER_MAIN = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "_worker_main.py")

#: CONSECUTIVE respawns (no batch delivered in between) per worker
#: before the service gives up — a worker that dies on every attempt is
#: a bug or a broken dataset, not a flaky host.  The streak resets the
#: moment a respawned worker delivers a consumed batch, so transient
#: deaths spread over a long run never accumulate into an abort
#: (wk.respawns stays a lifetime counter for stats())
MAX_RESPAWNS = 5

#: fault points stripped from a respawned worker's environment (the
#: supervise.py relaunch discipline: the injected fault must not
#: re-fire forever)
_WORKER_FAULT_POINTS = ("data_worker", "hang_data_worker")

_live_services = None


def _register_service(svc):
    global _live_services
    if _live_services is None:
        _live_services = weakref.WeakSet()

        def _stop_all():
            for s in list(_live_services):
                s.close()
        atexit.register(_stop_all)
    _live_services.add(svc)


_DTYPE_CODES = {"uint8": 0, "float32": 1, "bfloat16": 2}


class _Worker(object):
    def __init__(self, rank):
        self.rank = rank
        self.proc = None
        self.ring = None
        self.consumed = 0      # shard batches consumed this epoch
        self.respawns = 0        # lifetime (stats)
        self.respawn_streak = 0  # consecutive, reset on delivery (budget)
        self.stderr_path = None
        self.consumer_stall_s = 0.0
        self.occupancy_sum = 0
        self.occupancy_n = 0

    def stderr_tail(self, nbytes=2000):
        if self.stderr_path is None:
            return ""
        try:
            with open(self.stderr_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - nbytes))
                return f.read().decode("utf-8", "replace")
        except OSError:
            return ""


class DataService(object):
    """See the module docstring.  ``aug`` takes the native pipeline's
    knob subset (resize, rand_crop, rand_mirror, mean, std)."""

    def __init__(self, path_imgrec, path_imgidx, data_shape, batch_size,
                 label_width=1, shuffle=False, seed=0, part_index=0,
                 num_parts=1, num_workers=None, dtype="float32",
                 layout="NCHW", aug=None, slots=None, slot_bytes=None,
                 heartbeat_s=None, fast_dct=True, stream_offset=0,
                 stream_stride=1, start_epoch=1, start_batch=0):
        from .. import recordio
        if dtype not in _DTYPE_CODES:
            raise MXNetError("data_service: unsupported dtype %r" % (dtype,))
        if layout not in ("NCHW", "NHWC"):
            raise MXNetError("layout must be NCHW or NHWC")
        self._rec = os.path.abspath(path_imgrec)
        self._idx = os.path.abspath(path_imgidx)
        self._shape = tuple(int(d) for d in data_shape)   # canonical (c,h,w)
        if len(self._shape) != 3 or self._shape[0] != 3:
            raise MXNetError("data_shape must be (3, height, width), got %s"
                             % (self._shape,))
        c, h, w = self._shape
        self._ring_shape = (c, h, w) if layout == "NCHW" else (h, w, c)
        self._bs = int(batch_size)
        self._lw = int(label_width)
        self._dtype = dtype
        self._np_dtype = C.np_dtype(dtype)
        self._layout = layout
        self._seed = int(seed)
        self._shuffle = bool(shuffle)
        self._aug = dict(aug or {})
        self._fast_dct = bool(fast_dct)
        self.num_workers = max(1, int(num_workers or 1))
        self._slots = max(2, int(slots if slots is not None
                                 else get_env(ENV_DATA_RING_SLOTS, 4)))
        self._slot_bytes = int(slot_bytes if slot_bytes is not None
                               else get_env(ENV_DATA_SLOT_BYTES, 0))
        self._hb_timeout = float(heartbeat_s if heartbeat_s is not None
                                 else get_env(ENV_DATA_HEARTBEAT, 30.0))
        keys = [k for k, _ in recordio.read_index(self._idx)]
        if not keys:
            raise MXNetError("data_service: empty index %s" % self._idx)
        self._part_index = int(part_index)
        self._num_parts = int(num_parts)
        # the outer stream shard (the network tier): this service owns
        # global batches g = offset + j*stride only — offset 0 stride 1
        # (the local default) is the whole epoch
        self._stream_offset = int(stream_offset)
        self._stream_stride = max(1, int(stream_stride))
        if not (0 <= self._stream_offset < self._stream_stride):
            raise MXNetError(
                "data_service: stream_offset %d out of range for "
                "stream_stride %d" % (stream_offset, stream_stride))
        self._order = C.EpochOrder(keys, self._seed, self._shuffle,
                                   self._part_index, self._num_parts)
        self.epoch = max(1, int(start_epoch))
        self._order.seek(self.epoch)
        self._nbatches = C.num_batches(len(self._order.order), self._bs)
        self._stream_batches = C.stream_batches(
            self._nbatches, self._stream_offset, self._stream_stride)
        self._next_j = min(max(0, int(start_batch)), self._stream_batches)
        self.last_aug_seed = None             # chunk seed of the last batch
        self.last_batch_idx = None            # its global batch index
        self._pending = None                  # worker with an unreleased slot
        self._closed = False
        self._uid = "%d-%x" % (os.getpid(), id(self) & 0xffffff)
        self._workers = [_Worker(r) for r in range(self.num_workers)]
        try:
            for wk in self._workers:
                wk.ring = Ring("mxds-%s-r%d" % (self._uid, wk.rank),
                               self._slots, self._bs, self._ring_shape,
                               self._lw, self._np_dtype.itemsize,
                               slot_bytes=self._slot_bytes, create=True)
                wk.consumed = self._worker_consumed(wk.rank, self._next_j)
                self._spawn(wk)
                self._command(wk, self.epoch, wk.consumed)
        except BaseException:
            self.close()
            raise
        _register_service(self)

    def _worker_consumed(self, rank, next_j):
        """How many of its shard batches worker ``rank`` has already had
        consumed when the service's local batch cursor is ``next_j``
        (batch j belongs to worker j % N)."""
        return len(range(int(rank), int(next_j), self.num_workers))

    # -- workers ------------------------------------------------------------
    def _config(self, rank):
        return {
            "rec": self._rec, "idx": self._idx,
            "shm_name": self._workers[rank].ring.name,
            "slots": self._slots, "batch_size": self._bs,
            "data_shape": list(self._shape),
            "ring_shape": list(self._ring_shape),
            "label_width": self._lw, "dtype": self._dtype,
            "dtype_code": _DTYPE_CODES[self._dtype],
            "layout": self._layout, "aug": C.jsonable_aug(self._aug),
            "fast_dct": self._fast_dct, "seed": self._seed,
            "shuffle": self._shuffle,
            "part_index": self._part_index,
            "num_parts": self._num_parts,
            "rank": rank, "num_workers": self.num_workers,
            "stream_offset": self._stream_offset,
            "stream_stride": self._stream_stride,
            "slot_bytes": self._slot_bytes,
            "coordinator_pid": os.getpid(),
        }

    def _spawn(self, wk, strip_faults=False):
        if wk.stderr_path is None:
            fd, wk.stderr_path = tempfile.mkstemp(
                prefix="mxds-w%d-" % wk.rank, suffix=".err")
            os.close(fd)
        env = dict(os.environ)
        if strip_faults:
            stripped = strip_faults_env(env.get("MXTPU_FAULTS"),
                                        _WORKER_FAULT_POINTS)
            if stripped:
                env["MXTPU_FAULTS"] = stripped
            else:
                env.pop("MXTPU_FAULTS", None)
        # the CONSUMER stamps the first heartbeat: a worker that wedges
        # during bootstrap (before its own first stamp) must still age
        # out against MXTPU_DATA_HEARTBEAT_S — with hb=0 meaning "no
        # age" it would never be declared hung
        wk.ring.heartbeat()
        stderr_f = open(wk.stderr_path, "ab")
        try:
            wk.proc = subprocess.Popen(
                [sys.executable, _WORKER_MAIN, json.dumps(self._config(
                    wk.rank))],
                stdin=subprocess.PIPE, stdout=subprocess.DEVNULL,
                stderr=stderr_f, env=env)
        finally:
            stderr_f.close()

    def _command(self, wk, epoch, skip):
        try:
            wk.proc.stdin.write(("E %d %d\n" % (epoch, skip)).encode())
            wk.proc.stdin.flush()
        except (BrokenPipeError, OSError) as e:
            raise MXNetError(
                "data_service: worker %d rejected a command (%s); stderr: %s"
                % (wk.rank, e, wk.stderr_tail())) from e

    def _respawn(self, wk, reason):
        wk.respawns += 1
        wk.respawn_streak += 1
        tail = wk.stderr_tail()
        if wk.respawn_streak > MAX_RESPAWNS:
            raise MXNetError(
                "data_service: worker %d exceeded its respawn budget "
                "(%d consecutive) — last failure: %s; stderr: %s"
                % (wk.rank, MAX_RESPAWNS, reason, tail))
        _LOG.warning(
            "data_service: worker %d %s (respawn %d/%d, resuming shard at "
            "batch %d)%s", wk.rank, reason, wk.respawn_streak, MAX_RESPAWNS,
            wk.consumed,
            ("; stderr tail: %s" % tail.strip()[-300:]) if tail.strip()
            else "")
        if wk.proc is not None and wk.proc.poll() is None:
            wk.proc.kill()
            try:
                wk.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        wk.ring.reset_counters()
        self._spawn(wk, strip_faults=True)
        self._command(wk, self.epoch, wk.consumed)

    # -- collector ----------------------------------------------------------
    def next_batch(self, timeout=None):
        """``(data_view, labels, pad, release)`` for the next batch of
        this service's stream, in order; raises StopIteration at epoch
        end.  ``labels`` is a fresh (tiny) copy; ``data_view`` aliases
        the ring slot — see the module docstring for the lifetime
        contract.  With ``timeout`` (seconds), returns ``None`` when no
        batch became ready in time — the network server uses this to
        keep heartbeats flowing while a legitimately slow worker
        decodes (None consumes nothing; call again)."""
        if self._closed:
            raise MXNetError("data_service: closed")
        self._release_pending()
        if self._next_j >= self._stream_batches:
            raise StopIteration
        j = self._next_j
        g = self._stream_offset + j * self._stream_stride
        wk = self._workers[j % self.num_workers]
        deadline_poll = 0.0
        t0 = time.monotonic()
        give_up = None if timeout is None else t0 + float(timeout)
        waited = False
        while not wk.ring.ready(g, self.epoch):
            waited = True
            now = time.monotonic()
            if give_up is not None and now >= give_up:
                wk.consumer_stall_s += now - t0
                return None
            if now >= deadline_poll:
                deadline_poll = now + 0.2
                if wk.proc.poll() is not None:
                    self._respawn(wk, "died (rc=%s)" % wk.proc.returncode)
                elif wk.ring.published_mismatch(g, self.epoch):
                    # a published slot with the wrong batch/epoch can
                    # only come from a straggler that missed an abort
                    # (e.g. thawed after the reset handshake timed out)
                    self._respawn(wk, "produced a stale slot")
                elif wk.ring.heartbeat_age_s() > self._hb_timeout:
                    self._respawn(
                        wk, "hung (no heartbeat for %.1fs)"
                        % wk.ring.heartbeat_age_s())
            time.sleep(0.0005)
        if waited:
            wk.consumer_stall_s += time.monotonic() - t0
        wk.occupancy_sum += wk.ring.occupancy()
        wk.occupancy_n += 1
        hdr, labv, datav = wk.ring.peek(self._np_dtype)
        nvalid = int(hdr[C.HDR_NVALID])
        labels = np.array(labv[:, 0] if self._lw == 1 else labv)
        self._next_j += 1
        wk.consumed += 1
        wk.respawn_streak = 0   # delivered: not a crash loop
        # the in-graph augmentation seam (kernels/augment.py) folds its
        # per-image RNG from this — the SAME per-(seed, global batch,
        # epoch) value the host-side decoders mix, so device-augmented
        # output is a pure function of (seed, epoch, batch) no matter
        # which worker/server/host decoded the bytes
        self.last_aug_seed = C.chunk_seed(self._seed, g, epoch=self.epoch)
        self.last_batch_idx = g
        released = [False]

        def release(_wk=wk, _released=released):
            if not _released[0]:
                _released[0] = True
                _wk.ring.release()
        self._pending = release
        return datav, labels, self._bs - nvalid, release

    def _release_pending(self):
        if self._pending is not None:
            self._pending()
            self._pending = None

    def at_epoch_end(self):
        return self._next_j >= self._stream_batches

    def reset(self):
        """Advance to the next epoch (abandoning the current one if it
        was not fully consumed), like ``DataIter.reset``."""
        self.seek(self.epoch + 1, 0)

    def seek(self, epoch, consumed=0):
        """Land the service at ``epoch`` (1-based) with the first
        ``consumed`` stream batches already delivered — the network
        tier's reconnect resume (a fresh connection re-requests the
        tail of a partially consumed epoch; deterministic production
        makes the re-decoded stream bit-identical).  ``reset()`` is
        ``seek(epoch + 1, 0)``."""
        if self._closed:
            raise MXNetError("data_service: closed")
        epoch = max(1, int(epoch))
        self._release_pending()
        mid_epoch = self._next_j < self._stream_batches
        for wk in self._workers:
            if mid_epoch:
                wk.ring.request_abort(self.epoch)
            # wait for the producer to leave the epoch loop before the
            # ring counters are reset under it
            deadline = time.monotonic() + max(5.0, self._hb_timeout)
            while (wk.proc.poll() is None
                    and wk.ring.acked_epoch() < self.epoch):
                if time.monotonic() > deadline:
                    # unresponsive to the abort (frozen/SIGSTOPped): it
                    # must NOT thaw later and write the old epoch into
                    # the reset ring — kill it and respawn below
                    wk.proc.kill()
                    try:
                        wk.proc.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        pass
                    break
                time.sleep(0.001)
            wk.ring.reset_counters()
            if wk.proc.poll() is not None:
                # dead between epochs (or killed above): bring it back
                wk.respawns += 1
                wk.respawn_streak += 1
                if wk.respawn_streak > MAX_RESPAWNS:
                    raise MXNetError(
                        "data_service: worker %d exceeded its respawn "
                        "budget (%d consecutive); stderr: %s"
                        % (wk.rank, MAX_RESPAWNS, wk.stderr_tail()))
                self._spawn(wk, strip_faults=True)
            else:
                # alive and idle until the next epoch command: stamp
                # the heartbeat so a worker that wedges between epochs
                # still ages out (reset_counters zeroed the stamp)
                wk.ring.heartbeat()
        self.epoch = epoch
        self._order.seek(epoch)
        self._next_j = min(max(0, int(consumed)), self._stream_batches)
        for wk in self._workers:
            wk.consumed = self._worker_consumed(wk.rank, self._next_j)
            self._command(wk, self.epoch, wk.consumed)

    # -- observability ------------------------------------------------------
    def stats(self):
        """Per-stage counters since construction.  After close() the
        final pre-teardown snapshot is returned (monitoring hooks poll
        stats at shutdown)."""
        if self._closed:
            return self._final_stats
        per = {}
        prod_stall = cons_stall = occ_sum = occ_n = batches = 0.0
        for wk in self._workers:
            ring = wk.ring
            per[wk.rank] = {
                "batches": ring.batches_produced(),
                "respawns": wk.respawns,
                "producer_stall_s": round(ring.producer_stall_s(), 3),
                "consumer_stall_s": round(wk.consumer_stall_s, 3),
                "ring_occupancy": round(
                    wk.occupancy_sum / max(1, wk.occupancy_n), 2),
                "alive": wk.proc is not None and wk.proc.poll() is None,
            }
            prod_stall += ring.producer_stall_s()
            cons_stall += wk.consumer_stall_s
            occ_sum += wk.occupancy_sum
            occ_n += wk.occupancy_n
            batches += ring.batches_produced()
        return {
            "num_workers": self.num_workers,
            "epoch": self.epoch,
            "batches_produced": int(batches),
            "producer_stall_s": round(prod_stall, 3),
            "consumer_stall_s": round(cons_stall, 3),
            "ring_occupancy": round(occ_sum / max(1, occ_n), 2),
            "ring_slots": self._slots,
            "workers": per,
        }

    def worker_pids(self):
        """Live worker pids (chaos drills kill these)."""
        return [wk.proc.pid for wk in self._workers
                if wk.proc is not None and wk.proc.poll() is None]

    # -- lifecycle ----------------------------------------------------------
    def close(self):
        if self._closed:
            return
        try:
            self._final_stats = self.stats()
        except Exception:  # noqa: BLE001 — mid-construction close
            self._final_stats = None
        self._closed = True
        self._pending = None
        for wk in getattr(self, "_workers", []):
            if wk.ring is not None:
                try:
                    wk.ring.request_stop()
                except TypeError:  # ring already torn down
                    pass
            if wk.proc is not None:
                try:
                    wk.proc.stdin.write(b"Q\n")
                    wk.proc.stdin.flush()
                except (BrokenPipeError, OSError, ValueError):
                    pass
                try:
                    wk.proc.stdin.close()
                except (OSError, ValueError):
                    pass
        for wk in getattr(self, "_workers", []):
            if wk.proc is not None:
                try:
                    wk.proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    wk.proc.kill()
                    try:
                        wk.proc.wait(timeout=5)
                    except subprocess.TimeoutExpired:
                        pass
            if wk.ring is not None:
                wk.ring.close()
                wk.ring = None
            if wk.stderr_path is not None:
                try:
                    os.remove(wk.stderr_path)
                except OSError:
                    pass

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
