"""Decode-worker entrypoint: ``python _worker_main.py '<config json>'``.

One OS process per worker, launched by ``service.DataService`` with a
plain ``subprocess.Popen`` (NOT multiprocessing: no pickling, no
``__main__`` re-import contract, and the coordinator can SIGKILL a pid
in chaos drills exactly like a real crash).  The worker NEVER imports
the ``mxnet_tpu`` package — that would drag in jax/XLA (seconds of
startup, hundreds of MB, and on a TPU host a fight over the chip the
trainer owns).  Instead it installs a stub ``mxnet_tpu`` package whose
``__path__`` points at the real package directory WITHOUT executing
``__init__.py`` (the ``tools/mxlint.py`` synthetic-package idiom), then
imports only the dependency-light leaves: ``base`` (env registry),
``native`` (ctypes loader), ``recordio``, ``resilience`` (fault
injection) and ``data_service.{common,ring}``.

Per epoch the worker derives its shard from (seed, epoch, rank,
num_workers) — identical math to the coordinator, see
``common.worker_batches`` — reads its records from its OWN
``MXIndexedRecordIO`` handle, and decodes each batch straight into a
shared-memory ring slot through its OWN native ``MXTPUImgPipe`` (no
shared GIL, no shared pipe lock).  Augmentation is seeded per GLOBAL
batch index, so output bytes are a pure function of (seed, epoch,
batch) — independent of worker count, respawns, and scheduling.

Protocol: commands on stdin (``E <epoch> <skip>`` = produce the epoch,
skipping the first <skip> already-consumed shard batches; ``Q`` = quit);
flow control, abort, stop and heartbeats through the ring's control
words; errors on stderr + a nonzero exit code (the coordinator respawns
and resumes the shard).
"""
from __future__ import annotations

import importlib.machinery
import json
import os
import sys
import types

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_PKG_DIR = os.path.dirname(_HERE)


def _bootstrap():
    """Install the package-path stub and import the jax-free leaves."""
    if "mxnet_tpu" not in sys.modules:
        pkg = types.ModuleType("mxnet_tpu")
        pkg.__path__ = [_PKG_DIR]
        pkg.__spec__ = importlib.machinery.ModuleSpec(
            "mxnet_tpu", None, is_package=True)
        pkg.__spec__.submodule_search_locations = [_PKG_DIR]
        sys.modules["mxnet_tpu"] = pkg
    from mxnet_tpu import recordio, resilience  # noqa: F401
    from mxnet_tpu.data_service import common, ring  # noqa: F401
    from mxnet_tpu import native
    return recordio, resilience, common, ring, native


class _NativeDecoder(object):
    """Per-worker native libjpeg pipe (imagedec.cc): decode+augment+
    normalize+pack for a whole batch in one GIL-released C++ call,
    writing DIRECTLY into the ring slot's data region."""

    def __init__(self, native, common, cfg):
        import ctypes
        lib = native.get_lib()
        if lib is None or not getattr(lib, "_has_imagedec", False):
            raise RuntimeError("native image pipeline unavailable")
        self._ct = ctypes
        self._lib = lib
        aug = cfg["aug"]
        c, h, w = cfg["data_shape"]   # canonical (c, h, w)
        self._pipe, self._keepalive = common.open_native_pipe(
            lib, h, w, aug.get("resize"), aug.get("rand_crop"),
            aug.get("rand_mirror"), cfg["dtype_code"],
            0 if cfg["layout"] == "NCHW" else 1,
            aug.get("mean"), aug.get("std"),
            cfg.get("fast_dct", True), cfg.get("decode_threads", 1))
        if not self._pipe:
            raise RuntimeError("MXTPUImgPipeCreate failed")

    def decode(self, raws, out, valid, cseed, heartbeat=None):
        """Decode ``raws`` into ``out`` (a (bs, ...) view); returns the
        per-image validity mask count.  (One GIL-released C call — fast
        enough that ``heartbeat`` is not needed mid-batch.)"""
        ct = self._ct
        n = len(raws)
        bufs = (ct.c_void_p * n)(
            *[ct.cast(ct.c_char_p(r), ct.c_void_p) for r in raws])
        lens = (ct.c_uint64 * n)(*[len(r) for r in raws])
        return self._lib.MXTPUImgPipeDecodeBatch(
            self._pipe, bufs, lens, n, out.ctypes.data_as(ct.c_void_p),
            valid.ctypes.data_as(ct.POINTER(ct.c_uint8)), cseed)

    def close(self):
        if self._pipe:
            self._lib.MXTPUImgPipeDestroy(self._pipe)
            self._pipe = None


class _PythonDecoder(object):
    """cv2/PIL fallback for hosts without the native pipe.  Deterministic
    per (cseed, image index) like the native path, but NOT bit-identical
    to it (different JPEG decoder) — parity tests skip on such hosts."""

    def __init__(self, common, cfg):
        self._C = common
        self._cfg = cfg
        try:
            import cv2
            self._cv2 = cv2
        except ImportError:
            self._cv2 = None
            from PIL import Image  # noqa: F401 — fail now, not per image
        aug = cfg["aug"]
        self._resize = int(aug.get("resize", 0) or 0)
        self._rand_crop = bool(aug.get("rand_crop"))
        self._rand_mirror = bool(aug.get("rand_mirror"))
        self._mean = (np.asarray(aug["mean"], np.float32)
                      if aug.get("mean") is not None else None)
        self._std = (np.asarray(aug["std"], np.float32)
                     if aug.get("std") is not None else None)

    def _imdecode(self, raw):
        if self._cv2 is not None:
            img = self._cv2.imdecode(np.frombuffer(raw, np.uint8), 1)
            if img is None:
                return None
            return img[..., ::-1]  # BGR -> RGB (native pipe emits RGB)
        import io as _io

        from PIL import Image
        try:
            return np.asarray(Image.open(_io.BytesIO(raw)).convert("RGB"))
        except Exception:  # noqa: BLE001 — per-image tolerance
            return None

    def _one(self, raw, rng, th, tw):
        img = self._imdecode(raw)
        if img is None:
            return None
        h, w = img.shape[:2]
        if self._resize:
            if h > w:
                nh, nw = self._resize * h // w, self._resize
            else:
                nh, nw = self._resize, self._resize * w // h
            if self._cv2 is not None:
                img = self._cv2.resize(img, (nw, nh))
            else:
                from PIL import Image
                img = np.asarray(Image.fromarray(img).resize((nw, nh)))
            h, w = nh, nw
        cw, ch = min(tw, w), min(th, h)
        if self._rand_crop:
            x0 = int(rng.randint(0, w - cw + 1))
            y0 = int(rng.randint(0, h - ch + 1))
        else:
            x0, y0 = (w - cw) // 2, (h - ch) // 2
        img = img[y0:y0 + ch, x0:x0 + cw]
        if (ch, cw) != (th, tw):
            if self._cv2 is not None:
                img = self._cv2.resize(img, (tw, th))
            else:
                from PIL import Image
                img = np.asarray(Image.fromarray(img).resize((tw, th)))
        if self._rand_mirror and rng.randint(0, 2):
            img = img[:, ::-1]
        img = img.astype(np.float32)
        if self._mean is not None:
            img -= self._mean
        if self._std is not None:
            img /= self._std
        return img

    def decode(self, raws, out, valid, cseed, heartbeat=None):
        cfg = self._cfg
        c, th, tw = cfg["data_shape"]   # canonical (c, h, w)
        nv = 0
        for i, raw in enumerate(raws):
            if heartbeat is not None:
                heartbeat()   # python decode is slow; stay visibly alive
            rng = np.random.RandomState(
                self._C.chunk_seed(cseed, i) % (2 ** 31))
            img = self._one(raw, rng, th, tw)
            if img is None:
                continue
            if cfg["layout"] == "NCHW":
                img = img.transpose(2, 0, 1)
            if cfg["dtype_code"] == 0:
                img = np.clip(img, 0, 255)
            out[i] = img.astype(out.dtype, copy=False)
            valid[i] = 1
            nv += 1
        return nv

    def close(self):
        pass


def _run_epoch(cfg, ring_, reader, decoder, faults, common, unpack,
               epoch, skip):
    bs = int(cfg["batch_size"])
    lw = int(cfg["label_width"])
    dtype = common.np_dtype(cfg["dtype"])
    order = cfg["_order"].seek(epoch)
    shard = common.worker_batches(order, bs, int(cfg["rank"]),
                                  int(cfg["num_workers"]),
                                  int(cfg.get("stream_offset", 0)),
                                  int(cfg.get("stream_stride", 1)))
    valid = np.empty(bs, np.uint8)
    coord_pid = int(cfg["coordinator_pid"])
    # posix_fadvise readahead keyed off the epoch order: declare the
    # exact record sequence this epoch's (resumed) shard will read so
    # the OS stays MXTPU_DATA_READAHEAD records ahead of the cursor
    reader.set_read_plan(
        k for j, (_g, keys) in enumerate(shard) if j >= int(skip)
        for k in keys)

    def abandoned():
        # the coordinator is gone (we got reparented away from it —
        # compared against ITS pid, not literal 1: the trainer may
        # legitimately BE pid 1 in a container) or asked this epoch to
        # be abandoned (mid-epoch reset): stop producing
        return os.getppid() != coord_pid or ring_.abort_epoch() >= epoch

    for j, (gidx, keys) in enumerate(shard):
        if j < int(skip):
            continue
        if ring_.stopped() or abandoned():
            break
        # deterministic fault points (docs/how_to/fault_tolerance.md):
        # hang_data_worker stalls the worker (heartbeat goes stale -> the
        # collector kills+respawns), data_worker raises (process exits
        # nonzero -> respawn); either way the shard resumes at the last
        # consumed record
        faults.maybe_hang("hang_data_worker")
        faults.maybe_fail("data_worker")
        slot = ring_.acquire(on_wait=abandoned)
        if slot is None:
            break
        raws, labs = [], []
        for k in keys:
            hdr, img = unpack(reader.read_idx(k))
            raws.append(img)
            labs.append(hdr.label)
            # stamp DURING the batch too: a legitimately slow batch
            # (cold storage, the python fallback decoder) must not age
            # past MXTPU_DATA_HEARTBEAT_S and get respawned into an
            # identical slow batch forever
            ring_.heartbeat()
        n = len(raws)
        ring_.begin_write(slot, gidx)
        labv = ring_.label_view(slot)
        datav = ring_.data_view(slot, dtype)
        if n < bs:
            datav[:] = 0
        valid[:] = 0
        cseed = common.chunk_seed(int(cfg["seed"]), gidx, epoch=epoch)
        nv = decoder.decode(raws, datav, valid, cseed,
                            heartbeat=ring_.heartbeat)
        if nv == 0:
            raise RuntimeError(
                "data_service worker %d: every record in batch %d failed "
                "to decode — is this a non-JPEG .rec?"
                % (int(cfg["rank"]), gidx))
        keep = np.flatnonzero(valid[:n])
        labv[:] = 0
        labv[:nv] = np.asarray(labs, np.float32).reshape(n, -1)[keep][:, :lw]
        if nv < n:
            datav[:nv] = datav[keep]
            datav[nv:] = 0
        ring_.commit(slot, gidx, nv, epoch)
    ring_.ack_epoch(epoch)


def main():
    cfg = json.loads(sys.argv[1])
    recordio, resilience, common, ring_mod, native = _bootstrap()
    ring_ = ring_mod.Ring(
        cfg["shm_name"], cfg["slots"], cfg["batch_size"],
        cfg["ring_shape"], cfg["label_width"],
        common.np_dtype(cfg["dtype"]).itemsize,
        slot_bytes=cfg.get("slot_bytes"), create=False)
    ring_.heartbeat()
    reader = recordio.MXIndexedRecordIO(cfg["idx"], cfg["rec"], "r")
    cfg["_order"] = common.EpochOrder(
        reader.keys, cfg["seed"], cfg["shuffle"], cfg["part_index"],
        cfg["num_parts"])
    try:
        decoder = _NativeDecoder(native, common, cfg)
    except RuntimeError:
        decoder = _PythonDecoder(common, cfg)
    try:
        for line in sys.stdin:
            parts = line.split()
            if not parts or parts[0] == "Q":
                break
            if parts[0] == "E":
                _run_epoch(cfg, ring_, reader, decoder, resilience.faults,
                           common, recordio.unpack,
                           int(parts[1]), int(parts[2]))
    finally:
        decoder.close()
        reader.close()
        ring_.close()


if __name__ == "__main__":
    try:
        main()
    except KeyboardInterrupt:
        sys.exit(130)
    except Exception:  # noqa: BLE001 — exit code + stderr is the contract
        import traceback
        traceback.print_exc()
        sys.exit(3)
