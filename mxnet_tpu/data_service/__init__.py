"""mxdata — the multi-process, shared-memory input-data service.

The reference fed trainers from ONE C++ parser inside the trainer
process (src/io/iter_image_recordio_2.cc); this package takes input
processing out of the trainer process entirely (the tf.data-service /
DALI lineage): a sharded recordio reader splits the ``.rec``/``.idx``
across N decode worker PROCESSES, each decoding straight into a
shared-memory ring, and a collector in the trainer process hands
zero-copy numpy views to the device-staging path.

Layering (worker processes must never import jax — see
``_worker_main.py``):

- :mod:`.common` — seeds, epoch order, shard assignment, ring layout
  (stdlib+numpy; shared between both processes).
- :mod:`.ring` — the single-producer/single-consumer shared-memory
  batch ring (stdlib+numpy).
- :mod:`._worker_main` — the worker entrypoint script (loads the
  jax-free package leaves by path).
- :mod:`.service` — ``DataService`` (coordinator: spawn, collect,
  heartbeat-monitor, respawn, stats; jax-free — it also runs inside
  ``tools/data_server.py`` on remote decode hosts).
- :mod:`.net` — the network tier (jax-free): ``BatchServer`` streams a
  DataService's published ring slots over TCP as length-prefixed
  crc-checked frames; ``NetDataService`` is the consumer-side
  collector over N such servers, with heartbeat-age eviction and
  reconnect-resume of dead connections.
- :mod:`.iter` — ``DataServiceIter`` (the ``DataIter`` facade over
  either service flavor; jax side).  Imported lazily so the jax-free
  modules stay loadable without the package.

Use it through ``mx.io``-style iterators:
``ImageRecordIter(..., data_service=True)`` (or ``MXTPU_DATA_WORKERS=N``)
routes through the local service;
``ImageRecordIter(..., data_service='host:port,host:port')`` (or
``MXTPU_DATA_SERVERS``) through the network tier; see
docs/how_to/performance.md ("Scaling the input pipeline").
"""
from __future__ import annotations

from ..base import register_env
from .common import chunk_seed  # noqa: F401 — shared with image.py

__all__ = ["DataService", "DataServiceIter", "NetDataService",
           "BatchServer", "chunk_seed"]

# Registered here (the package root, imported eagerly via image.py's
# chunk_seed import) rather than in service.py, which loads lazily —
# the env registry must know every knob before anything reads it.
# MXTPU_DATA_WORKERS lives in base.py (read across modules).
ENV_DATA_RING_SLOTS = register_env(
    "MXTPU_DATA_RING_SLOTS", default=4,
    doc="Shared-memory ring slots per data-service worker (one slot = "
        "one padded batch)")
ENV_DATA_SLOT_BYTES = register_env(
    "MXTPU_DATA_SLOT_BYTES", default=0,
    doc="Override (grow) the per-slot data-region bytes; 0 derives "
        "batch_size x prod(data_shape) x itemsize")
ENV_DATA_HEARTBEAT = register_env(
    "MXTPU_DATA_HEARTBEAT_S", default=30.0,
    doc="Seconds without a data-service worker heartbeat before the "
        "collector declares it hung and respawns it")
ENV_DATA_NET_TIMEOUT = register_env(
    "MXTPU_DATA_NET_TIMEOUT_S", default=30.0,
    doc="Seconds without any frame (batches or heartbeats) from a data "
        "server before the consumer evicts the connection and "
        "reconnects (resume is exactly-once at the last consumed "
        "batch)")
ENV_DATA_NET_RETRIES = register_env(
    "MXTPU_DATA_NET_RETRIES", default=10,
    doc="Consecutive reconnect attempts per data server before the "
        "network-tier consumer gives up (the streak resets on every "
        "delivered batch — the local-service respawn-budget lesson)")
ENV_DATA_NET_RECONNECT = register_env(
    "MXTPU_DATA_NET_RECONNECT_S", default=0.5,
    doc="Delay between data-server reconnect attempts (the remote "
        "host's supervisor needs time to respawn a killed server)")
ENV_DATA_NET_FRAME_BYTES = register_env(
    "MXTPU_DATA_NET_FRAME_BYTES", default=1 << 30,
    doc="Upper bound on one network-tier frame payload; a header "
        "announcing more is treated as a torn/corrupt frame and the "
        "connection is re-established rather than consumed")


def __getattr__(name):
    # service/net/iter pull in resilience (and iter the jax-side io);
    # keep them lazy so importing the package for `common` stays cheap
    # and cycle-free during mxnet_tpu's own import
    if name == "DataService":
        from .service import DataService
        return DataService
    if name == "DataServiceIter":
        from .iter import DataServiceIter
        return DataServiceIter
    if name in ("NetDataService", "BatchServer"):
        from . import net
        return getattr(net, name)
    raise AttributeError(name)
