"""RecordIO: sequential + indexed binary record files.

API parity with the reference's python/mxnet/recordio.py (MXRecordIO,
MXIndexedRecordIO, IRHeader, pack/unpack, pack_img/unpack_img); the on-disk
format is byte-compatible with the reference's dmlc recordio framing
(magic 0xced7230a, (cflag<<29)|len lrecords, 4-byte alignment, magic-elision
record splitting), so .rec/.idx datasets move between the two frameworks
unmodified.

Two backends: the native codec (mxnet_tpu/native/recordio.cc) via ctypes,
or a pure-Python implementation when the native library is unavailable.
"""
from __future__ import annotations

import ctypes
import numbers
import os
import struct
from collections import namedtuple

import numpy as np

from . import native
from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "read_index",
           "pack", "unpack", "pack_img", "unpack_img"]


def read_index(idx_path, key_type=int):
    """Parse a ``.idx`` file into an ordered ``[(key, position), ...]``
    without opening the ``.rec`` it indexes — sharded readers (the data
    service coordinator) plan shard assignments from the index alone."""
    out = []
    with open(idx_path) as fin:
        for line in fin:
            line = line.strip()
            if not line:
                continue
            fields = line.split("\t")
            # tolerate trailing extra columns (some external im2rec
            # variants append a size field) like the historical parser
            out.append((key_type(fields[0]), int(fields[1])))
    return out

_MAGIC = 0xced7230a
_LEN_MASK = (1 << 29) - 1
_MAGIC_BYTES = struct.pack("<I", _MAGIC)


class _PyRecordWriter(object):
    def __init__(self, path):
        self._f = open(path, "wb")

    def write(self, data):
        if len(data) >= (1 << 29):
            raise MXNetError("record too large")
        n = len(data)
        lower = (n >> 2) << 2
        upper = ((n + 3) >> 2) << 2
        out = bytearray()
        dptr = 0
        for i in range(0, lower, 4):
            if data[i:i + 4] == _MAGIC_BYTES:
                out += _MAGIC_BYTES
                out += struct.pack("<I", ((1 if dptr == 0 else 2) << 29)
                                   | (i - dptr))
                out += data[dptr:i]
                dptr = i + 4
        out += _MAGIC_BYTES
        out += struct.pack("<I", ((3 if dptr else 0) << 29) | (n - dptr))
        out += data[dptr:n]
        out += b"\x00" * (upper - n)
        self._f.write(out)

    def tell(self):
        return self._f.tell()

    def close(self):
        self._f.close()


class _PyRecordReader(object):
    def __init__(self, path):
        self._f = open(path, "rb")

    def read(self):
        """Returns record bytes or None at EOF."""
        out = bytearray()
        multipart = False
        while True:
            head = self._f.read(4)
            if not head and not multipart:
                return None
            if len(head) != 4 or struct.unpack("<I", head)[0] != _MAGIC:
                raise MXNetError("invalid record stream")
            lrec = struct.unpack("<I", self._f.read(4))[0]
            cflag, n = lrec >> 29, lrec & _LEN_MASK
            upper = ((n + 3) >> 2) << 2
            if multipart:
                out += _MAGIC_BYTES
            chunk = self._f.read(upper)
            if len(chunk) != upper:
                raise MXNetError("truncated record")
            out += chunk[:n]
            if cflag == 0:
                return bytes(out)
            if cflag == 3:
                # 'last part' is only valid inside a multipart record
                # (same strictness as the native reader).
                if not multipart:
                    raise MXNetError("invalid record stream")
                return bytes(out)
            if cflag == 1 and multipart:
                raise MXNetError("invalid record stream")
            multipart = True

    def seek(self, pos):
        self._f.seek(pos)

    def tell(self):
        return self._f.tell()

    def close(self):
        self._f.close()


class _NativeRecordWriter(object):
    def __init__(self, path):
        self._lib = native.get_lib()
        self._h = self._lib.MXTPURecordIOWriterCreate(path.encode())
        if not self._h:
            raise MXNetError("cannot open %s for writing" % path)

    def write(self, data):
        if self._lib.MXTPURecordIOWriterWrite(self._h, data, len(data)) != 0:
            raise MXNetError("record write failed")

    def tell(self):
        return self._lib.MXTPURecordIOWriterTell(self._h)

    def close(self):
        if self._h:
            self._lib.MXTPURecordIOWriterClose(self._h)
            self._h = None


class _NativeRecordReader(object):
    def __init__(self, path):
        self._lib = native.get_lib()
        self._h = self._lib.MXTPURecordIOReaderCreate(path.encode())
        if not self._h:
            raise MXNetError("cannot open %s for reading" % path)

    def read(self):
        out = ctypes.c_void_p()
        out_len = ctypes.c_uint64()
        ret = self._lib.MXTPURecordIOReaderRead(
            self._h, ctypes.byref(out), ctypes.byref(out_len))
        if ret == 0:
            return None
        if ret < 0:
            raise MXNetError("invalid record stream")
        try:
            return ctypes.string_at(out, out_len.value)
        finally:
            self._lib.MXTPUFree(out)

    def seek(self, pos):
        self._lib.MXTPURecordIOReaderSeek(self._h, pos)

    def tell(self):
        return self._lib.MXTPURecordIOReaderTell(self._h)

    def close(self):
        if self._h:
            self._lib.MXTPURecordIOReaderClose(self._h)
            self._h = None


class MXRecordIO(object):
    """Sequential RecordIO reader/writer (reference
    python/mxnet/recordio.py:MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.is_open = False
        self.open()

    def _make(self):
        use_native = native.get_lib() is not None
        if self.flag == "w":
            return (_NativeRecordWriter if use_native
                    else _PyRecordWriter)(self.uri)
        elif self.flag == "r":
            return (_NativeRecordReader if use_native
                    else _PyRecordReader)(self.uri)
        raise MXNetError("invalid flag %r (use 'r' or 'w')" % self.flag)

    def open(self):
        self.record = self._make()
        self.is_open = True

    def close(self):
        if self.is_open:
            self.record.close()
            self.is_open = False

    def reset(self):
        self.close()
        self.open()

    def write(self, buf):
        assert self.flag == "w"
        self.record.write(buf)

    def read(self):
        assert self.flag == "r"
        return self.record.read()

    def tell(self):
        return self.record.tell()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class MXIndexedRecordIO(MXRecordIO):
    """Indexed RecordIO with keyed random access (reference
    python/mxnet/recordio.py:MXIndexedRecordIO; .idx = "key\\tpos" lines)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super(MXIndexedRecordIO, self).__init__(uri, flag)

    def open(self):
        super(MXIndexedRecordIO, self).open()
        self.idx = {}
        self.keys = []
        self.fidx = open(self.idx_path, self.flag)
        if self.flag == "r":
            for key, pos in read_index(self.idx_path, self.key_type):
                self.idx[key] = pos
                self.keys.append(key)

    def close(self):
        if self.is_open:
            super(MXIndexedRecordIO, self).close()
            self.fidx.close()

    def seek(self, idx):
        assert self.flag == "r"
        self.record.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write("%s\t%d\n" % (str(key), pos))
        self.idx[key] = pos
        self.keys.append(key)


IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack an IRHeader + payload into a record string (reference
    recordio.py:pack).  ``flag``>0 means ``label`` is an array of ``flag``
    float32s stored after the fixed header."""
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        header = header._replace(label=float(header.label))
        s = struct.pack(_IR_FORMAT, *header) + s
    else:
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0.0)
        s = struct.pack(_IR_FORMAT, *header) + label.tobytes() + s
    return s


def unpack(s):
    """Unpack a record into (IRHeader, payload) (reference recordio.py:unpack)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack a header + image array into a record (reference
    recordio.py:pack_img).  Uses cv2 when available, else PIL."""
    encoded = _imencode(img, quality, img_fmt)
    return pack(header, encoded)


def unpack_img(s, iscolor=-1):
    """Unpack a record into (IRHeader, image ndarray) (reference
    recordio.py:unpack_img)."""
    header, s = unpack(s)
    img = _imdecode(s, iscolor)
    return header, img


def _imencode(img, quality=95, img_fmt=".jpg"):
    try:
        import cv2
        fmt = img_fmt.lower()
        if fmt in (".jpg", ".jpeg"):
            params = [cv2.IMWRITE_JPEG_QUALITY, quality]
        elif fmt == ".png":
            params = [cv2.IMWRITE_PNG_COMPRESSION, quality // 10]
        else:
            params = []
        ret, buf = cv2.imencode(img_fmt, img, params)
        if not ret:
            raise MXNetError("failed to encode image")
        return buf.tobytes()
    except ImportError:
        import io as _io
        from PIL import Image
        arr = np.asarray(img)
        if arr.ndim == 3 and arr.shape[-1] == 3:
            arr = arr[..., ::-1]  # BGR -> RGB (channel axis only)
        pimg = Image.fromarray(arr)
        bio = _io.BytesIO()
        fmt = "JPEG" if img_fmt.lower() in (".jpg", ".jpeg") else "PNG"
        pimg.save(bio, format=fmt, quality=quality)
        return bio.getvalue()


def _imdecode(buf, iscolor=-1):
    try:
        import cv2
        return cv2.imdecode(np.frombuffer(buf, dtype=np.uint8), iscolor)
    except ImportError:
        import io as _io
        from PIL import Image
        pimg = Image.open(_io.BytesIO(buf))
        if iscolor == 0:
            return np.asarray(pimg.convert("L"))
        img = np.asarray(pimg.convert("RGB"))
        return img[..., ::-1]  # RGB -> BGR to match cv2 convention
