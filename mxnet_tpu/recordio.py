"""RecordIO: sequential + indexed binary record files.

API parity with the reference's python/mxnet/recordio.py (MXRecordIO,
MXIndexedRecordIO, IRHeader, pack/unpack, pack_img/unpack_img); the on-disk
format is byte-compatible with the reference's dmlc recordio framing
(magic 0xced7230a, (cflag<<29)|len lrecords, 4-byte alignment, magic-elision
record splitting), so .rec/.idx datasets move between the two frameworks
unmodified.

Two backends: the native codec (mxnet_tpu/native/recordio.cc) via ctypes,
or a pure-Python implementation when the native library is unavailable.
"""
from __future__ import annotations

import ctypes
import numbers
import os
import struct
from collections import namedtuple

import numpy as np

from . import native
from .base import MXNetError, get_env, register_env

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "read_index",
           "pack", "unpack", "pack_img", "unpack_img"]

#: io_uring-style readahead for indexed readers that follow a known
#: order (the data-service workers set their epoch-order plan): keep
#: the OS page cache this many RECORDS ahead of the read cursor via
#: posix_fadvise(WILLNEED) — sequential-speed reads out of a
#: random-access (shuffled) plan.  Registered here (the owner module)
#: per the eager-registration lesson.
ENV_DATA_READAHEAD = register_env(
    "MXTPU_DATA_READAHEAD", default=64,
    doc="Readahead window (records) for planned indexed reads "
        "(MXIndexedRecordIO.set_read_plan; the data-service workers "
        "plan each epoch's shard): byte ranges of the next N planned "
        "records are posix_fadvise(WILLNEED)d ahead of the cursor; "
        "0 disables")


def read_index(idx_path, key_type=int):
    """Parse a ``.idx`` file into an ordered ``[(key, position), ...]``
    without opening the ``.rec`` it indexes — sharded readers (the data
    service coordinator) plan shard assignments from the index alone."""
    out = []
    with open(idx_path) as fin:
        for line in fin:
            line = line.strip()
            if not line:
                continue
            fields = line.split("\t")
            # tolerate trailing extra columns (some external im2rec
            # variants append a size field) like the historical parser
            out.append((key_type(fields[0]), int(fields[1])))
    return out

_MAGIC = 0xced7230a
_LEN_MASK = (1 << 29) - 1
_MAGIC_BYTES = struct.pack("<I", _MAGIC)


class _PyRecordWriter(object):
    def __init__(self, path):
        self._f = open(path, "wb")

    def write(self, data):
        if len(data) >= (1 << 29):
            raise MXNetError("record too large")
        n = len(data)
        lower = (n >> 2) << 2
        upper = ((n + 3) >> 2) << 2
        out = bytearray()
        dptr = 0
        for i in range(0, lower, 4):
            if data[i:i + 4] == _MAGIC_BYTES:
                out += _MAGIC_BYTES
                out += struct.pack("<I", ((1 if dptr == 0 else 2) << 29)
                                   | (i - dptr))
                out += data[dptr:i]
                dptr = i + 4
        out += _MAGIC_BYTES
        out += struct.pack("<I", ((3 if dptr else 0) << 29) | (n - dptr))
        out += data[dptr:n]
        out += b"\x00" * (upper - n)
        self._f.write(out)

    def tell(self):
        return self._f.tell()

    def close(self):
        self._f.close()


class _PyRecordReader(object):
    def __init__(self, path):
        self._f = open(path, "rb")

    def read(self):
        """Returns record bytes or None at EOF."""
        out = bytearray()
        multipart = False
        while True:
            head = self._f.read(4)
            if not head and not multipart:
                return None
            if len(head) != 4 or struct.unpack("<I", head)[0] != _MAGIC:
                raise MXNetError("invalid record stream")
            lrec = struct.unpack("<I", self._f.read(4))[0]
            cflag, n = lrec >> 29, lrec & _LEN_MASK
            upper = ((n + 3) >> 2) << 2
            if multipart:
                out += _MAGIC_BYTES
            chunk = self._f.read(upper)
            if len(chunk) != upper:
                raise MXNetError("truncated record")
            out += chunk[:n]
            if cflag == 0:
                return bytes(out)
            if cflag == 3:
                # 'last part' is only valid inside a multipart record
                # (same strictness as the native reader).
                if not multipart:
                    raise MXNetError("invalid record stream")
                return bytes(out)
            if cflag == 1 and multipart:
                raise MXNetError("invalid record stream")
            multipart = True

    def seek(self, pos):
        self._f.seek(pos)

    def tell(self):
        return self._f.tell()

    def close(self):
        self._f.close()


class _NativeRecordWriter(object):
    def __init__(self, path):
        self._lib = native.get_lib()
        self._h = self._lib.MXTPURecordIOWriterCreate(path.encode())
        if not self._h:
            raise MXNetError("cannot open %s for writing" % path)

    def write(self, data):
        if self._lib.MXTPURecordIOWriterWrite(self._h, data, len(data)) != 0:
            raise MXNetError("record write failed")

    def tell(self):
        return self._lib.MXTPURecordIOWriterTell(self._h)

    def close(self):
        if self._h:
            self._lib.MXTPURecordIOWriterClose(self._h)
            self._h = None


class _NativeRecordReader(object):
    def __init__(self, path):
        self._lib = native.get_lib()
        self._h = self._lib.MXTPURecordIOReaderCreate(path.encode())
        if not self._h:
            raise MXNetError("cannot open %s for reading" % path)

    def read(self):
        out = ctypes.c_void_p()
        out_len = ctypes.c_uint64()
        ret = self._lib.MXTPURecordIOReaderRead(
            self._h, ctypes.byref(out), ctypes.byref(out_len))
        if ret == 0:
            return None
        if ret < 0:
            raise MXNetError("invalid record stream")
        try:
            return ctypes.string_at(out, out_len.value)
        finally:
            self._lib.MXTPUFree(out)

    def seek(self, pos):
        self._lib.MXTPURecordIOReaderSeek(self._h, pos)

    def tell(self):
        return self._lib.MXTPURecordIOReaderTell(self._h)

    def close(self):
        if self._h:
            self._lib.MXTPURecordIOReaderClose(self._h)
            self._h = None


class MXRecordIO(object):
    """Sequential RecordIO reader/writer (reference
    python/mxnet/recordio.py:MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.is_open = False
        self.open()

    def _make(self):
        use_native = native.get_lib() is not None
        if self.flag == "w":
            return (_NativeRecordWriter if use_native
                    else _PyRecordWriter)(self.uri)
        elif self.flag == "r":
            return (_NativeRecordReader if use_native
                    else _PyRecordReader)(self.uri)
        raise MXNetError("invalid flag %r (use 'r' or 'w')" % self.flag)

    def open(self):
        self.record = self._make()
        self.is_open = True

    def close(self):
        if self.is_open:
            self.record.close()
            self.is_open = False

    def reset(self):
        self.close()
        self.open()

    def write(self, buf):
        assert self.flag == "w"
        self.record.write(buf)

    def read(self):
        assert self.flag == "r"
        return self.record.read()

    def tell(self):
        return self.record.tell()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class MXIndexedRecordIO(MXRecordIO):
    """Indexed RecordIO with keyed random access (reference
    python/mxnet/recordio.py:MXIndexedRecordIO; .idx = "key\\tpos" lines)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        self._ra_fd = None          # readahead-advice fd (any fd works)
        self._ra_plan = None        # deque of upcoming keys
        self._ra_window = 0
        self._ra_ahead = 0          # plan entries already advised
        self._ra_lens = None        # key -> approx record byte length
        self.readahead_advised = 0  # records advised (observability)
        super(MXIndexedRecordIO, self).__init__(uri, flag)

    def open(self):
        super(MXIndexedRecordIO, self).open()
        self.idx = {}
        self.keys = []
        self.fidx = open(self.idx_path, self.flag)
        if self.flag == "r":
            for key, pos in read_index(self.idx_path, self.key_type):
                self.idx[key] = pos
                self.keys.append(key)

    def close(self):
        if self.is_open:
            super(MXIndexedRecordIO, self).close()
            self.fidx.close()
        # the readahead plan dies with the fd: a reset() (close+open)
        # must not leave a live plan advising through a closed fd
        self._ra_plan = None
        self._ra_ahead = 0
        if self._ra_fd is not None:
            try:
                os.close(self._ra_fd)
            except OSError:
                pass
            self._ra_fd = None

    def seek(self, idx):
        assert self.flag == "r"
        self.record.seek(self.idx[idx])

    # -- planned readahead ---------------------------------------------------
    def set_read_plan(self, keys, window=None):
        """Declare the order upcoming ``read_idx`` calls will follow
        (e.g. the data service's per-epoch shard) so the reader can
        keep the OS page cache ``window`` records ahead of the cursor
        (``MXTPU_DATA_READAHEAD``; ``posix_fadvise(WILLNEED)`` — the
        io_uring-style prefetch a shuffled epoch order defeats the
        kernel's own sequential readahead on).  Reads that deviate
        from the plan resynchronize or quietly fall off it; no plan,
        window 0, or a platform without ``posix_fadvise`` means plain
        reads."""
        from collections import deque
        if window is None:
            window = int(get_env(ENV_DATA_READAHEAD, 64))
        self._ra_window = max(0, int(window))
        self._ra_plan = deque(keys)
        self._ra_ahead = 0
        if (self._ra_window <= 0 or not hasattr(os, "posix_fadvise")
                or self.flag != "r"):
            self._ra_plan = None
            return
        if self._ra_fd is None:
            try:
                self._ra_fd = os.open(self.uri, os.O_RDONLY)
            except OSError:
                self._ra_plan = None
                return
        if self._ra_lens is None:
            # record length ≈ gap to the next start position (.idx
            # positions are monotonic); the final record runs to EOF
            pairs = sorted(self.idx.items(), key=lambda kv: kv[1])
            try:
                end = os.fstat(self._ra_fd).st_size
            except OSError:
                end = 0
            lens = {}
            for (k, pos), nxt in zip(
                    pairs, [p for _, p in pairs[1:]] + [end]):
                lens[k] = max(0, nxt - pos)
            self._ra_lens = lens

    def _maybe_readahead(self, idx):
        plan = self._ra_plan
        if plan is None or self._ra_fd is None:
            return
        if not plan or plan[0] != idx:
            # off-plan read (respawn resume, random access): drop plan
            # entries until the cursor matches again, else give up on
            # this plan — correctness never depends on the advice
            while plan and plan[0] != idx:
                plan.popleft()
                self._ra_ahead = max(0, self._ra_ahead - 1)
            if not plan:
                self._ra_plan = None
                return
        plan.popleft()
        self._ra_ahead = max(0, self._ra_ahead - 1)
        if self._ra_ahead <= self._ra_window // 2:
            from itertools import islice
            # islice, not list(plan)[...]: copying the whole remaining
            # deque every window/2 reads would make a large shard's
            # epoch O(N^2/window) in the decode hot path
            for k in islice(plan, self._ra_ahead, self._ra_window):
                pos = self.idx.get(k)
                if pos is None:
                    continue
                try:
                    os.posix_fadvise(self._ra_fd, pos,
                                     self._ra_lens.get(k, 1 << 16),
                                     os.POSIX_FADV_WILLNEED)
                except OSError:
                    self._ra_plan = None
                    return
                self.readahead_advised += 1
            self._ra_ahead = min(len(plan), self._ra_window)

    def read_idx(self, idx):
        self._maybe_readahead(idx)
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write("%s\t%d\n" % (str(key), pos))
        self.idx[key] = pos
        self.keys.append(key)


IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack an IRHeader + payload into a record string (reference
    recordio.py:pack).  ``flag``>0 means ``label`` is an array of ``flag``
    float32s stored after the fixed header."""
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        header = header._replace(label=float(header.label))
        s = struct.pack(_IR_FORMAT, *header) + s
    else:
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0.0)
        s = struct.pack(_IR_FORMAT, *header) + label.tobytes() + s
    return s


def unpack(s):
    """Unpack a record into (IRHeader, payload) (reference recordio.py:unpack)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack a header + image array into a record (reference
    recordio.py:pack_img).  Uses cv2 when available, else PIL."""
    encoded = _imencode(img, quality, img_fmt)
    return pack(header, encoded)


def unpack_img(s, iscolor=-1):
    """Unpack a record into (IRHeader, image ndarray) (reference
    recordio.py:unpack_img)."""
    header, s = unpack(s)
    img = _imdecode(s, iscolor)
    return header, img


def _imencode(img, quality=95, img_fmt=".jpg"):
    try:
        import cv2
        fmt = img_fmt.lower()
        if fmt in (".jpg", ".jpeg"):
            params = [cv2.IMWRITE_JPEG_QUALITY, quality]
        elif fmt == ".png":
            params = [cv2.IMWRITE_PNG_COMPRESSION, quality // 10]
        else:
            params = []
        ret, buf = cv2.imencode(img_fmt, img, params)
        if not ret:
            raise MXNetError("failed to encode image")
        return buf.tobytes()
    except ImportError:
        import io as _io
        from PIL import Image
        arr = np.asarray(img)
        if arr.ndim == 3 and arr.shape[-1] == 3:
            arr = arr[..., ::-1]  # BGR -> RGB (channel axis only)
        pimg = Image.fromarray(arr)
        bio = _io.BytesIO()
        fmt = "JPEG" if img_fmt.lower() in (".jpg", ".jpeg") else "PNG"
        pimg.save(bio, format=fmt, quality=quality)
        return bio.getvalue()


def _imdecode(buf, iscolor=-1):
    try:
        import cv2
        return cv2.imdecode(np.frombuffer(buf, dtype=np.uint8), iscolor)
    except ImportError:
        import io as _io
        from PIL import Image
        pimg = Image.open(_io.BytesIO(buf))
        if iscolor == 0:
            return np.asarray(pimg.convert("L"))
        img = np.asarray(pimg.convert("RGB"))
        return img[..., ::-1]  # RGB -> BGR to match cv2 convention
