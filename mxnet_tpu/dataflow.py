"""Device-side input pipelining.

The reference overlapped host IO with compute through the dependency
engine: ThreadedIter staged decoded batches while executors ran
(src/io/iter_prefetcher.h).  On TPU the equivalent critical-path hazard
is the host->device transfer itself: a synchronous per-step
``jax.device_put`` of the batch serializes the upload of batch N+1
behind the execution of batch N.  :class:`DevicePrefetchIter` closes
that gap — a background thread pulls batches from any ``DataIter``
(including a ``PrefetchingIter`` doing the decode-side overlap) and
STAGES them onto the mesh ahead of time: sharded ``device_put``, compute
dtype cast, and the multihost global-array conversion, exactly as
``SPMDTrainer._shard_batch`` would do per-step.  The consumer then feeds
:class:`~mxnet_tpu.io.StagedBatch` objects straight into
``SPMDTrainer.step`` / ``Module.forward_backward``, which skip the
transfer entirely.

Resilience: source pulls go through the shared
:func:`~mxnet_tpu.resilience.retrying_next` ladder (MXTPU_DATA_RETRIES),
errors surface on the consuming thread (never a silent hang), and
``reset()``/``close()`` shut the worker down cleanly mid-epoch.
"""
from __future__ import annotations

import logging
import queue
import threading

from .base import MXNetError
from .io import DataBatch, DataIter, StagedBatch

__all__ = ["DevicePrefetchIter"]

_LOG = logging.getLogger(__name__)

#: queue sentinel: the source raised StopIteration (epoch end)
_END = object()


class _WorkerError(object):
    def __init__(self, exc):
        self.exc = exc


def _resolve_stage(stage):
    """Accept a callable, an SPMDTrainer, or a Module-like object owning a
    trainer; None means 'prefetch only, no device staging'."""
    if stage is None or callable(stage):
        return stage
    for attr in ("stage_batch",):
        fn = getattr(stage, attr, None)
        if callable(fn):
            return fn
    for attr in ("_fused", "_trainer"):
        owner = getattr(stage, attr, None)
        fn = getattr(owner, "stage_batch", None)
        if callable(fn):
            return fn
    raise MXNetError(
        "DevicePrefetchIter: stage must be a callable, an SPMDTrainer, or "
        "a module with a fused trainer (got %r)" % (stage,))


class DevicePrefetchIter(DataIter):
    """Stage the NEXT batch onto the mesh while the current step executes.

    Parameters
    ----------
    data_iter : DataIter
        Source iterator (wrap a ``PrefetchingIter`` to also overlap the
        decode side).
    stage : callable | SPMDTrainer | Module, optional
        ``stage(*arrays) -> {name: device_array}`` — normally
        ``SPMDTrainer.stage_batch`` (pass the trainer or the module and
        it is resolved).  None yields un-staged batches (pure prefetch).
    depth : int
        Number of batches staged ahead (default 2).  ``depth=0`` stages
        synchronously on the consuming thread — same batches, no
        overlap — which is the bench's baseline mode.

    Semantics: batches come out byte-identical and in order vs the
    source; a source error (after the retry ladder is exhausted) is
    raised from ``next()`` on the consuming thread, after which
    ``reset()`` realigns and restarts the worker.
    """

    def __init__(self, data_iter, stage=None, depth=2):
        super().__init__(getattr(data_iter, "batch_size", 0))
        self._iter = data_iter
        self._stage = _resolve_stage(stage)
        self.depth = max(0, int(depth))
        self._gen = 0
        self._done = False
        self._stop = threading.Event()
        self._thread = None
        if self.depth > 0:
            self._queue = queue.Queue(maxsize=self.depth)
            self._start()
        else:
            self._queue = None
        self.current_batch = None

    # -- worker ------------------------------------------------------------
    def _start(self):
        # each worker owns its OWN stop event: if a stuck worker outlives
        # its join timeout in _shutdown(), its (set) event stays set and
        # it exits whenever the blocked source call returns — it can
        # never race a successor worker for the source iterator
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._worker, args=(self._gen, self._stop),
            name="DevicePrefetchIter", daemon=True)
        self._thread.start()

    def _worker(self, gen, stop):
        from .resilience import retrying_next
        while not stop.is_set():
            try:
                batch = retrying_next(self._iter, name="device_prefetch.next")
            except StopIteration:
                self._put(gen, _END, stop)
                return
            except Exception as e:  # noqa: BLE001 — surfaced to consumer
                self._put(gen, _WorkerError(e), stop)
                return
            try:
                item = self._stage_one(batch)
            except Exception as e:  # noqa: BLE001 — surfaced to consumer
                self._put(gen, _WorkerError(e), stop)
                return
            self._put(gen, item, stop)

    def _put(self, gen, item, stop):
        """Bounded put that aborts promptly on shutdown (a plain blocking
        put would deadlock close() when the consumer is gone)."""
        while not stop.is_set():
            try:
                self._queue.put((gen, item), timeout=0.05)
                return
            except queue.Full:
                continue

    def _stage_one(self, batch):
        # deterministic fault points for the staging path: "stage_batch"
        # raises (surfaced to the consumer like a failed device_put),
        # "hang_stage" stalls the worker — the consumer then blocks in
        # next() exactly like a wedged host->device transfer, which is
        # what the fit() watchdog window is armed to catch
        from .resilience import faults
        faults.maybe_hang("hang_stage")
        faults.maybe_fail("stage_batch")
        # Transport-owned buffers (shared-memory data-service ring
        # slots override release() per instance): this worker runs
        # AHEAD of the consumer, so by the time a queued batch is
        # consumed its slot views may have been recycled — and a CPU
        # backend device_put can ALIAS numpy memory rather than copy
        # it, so even the staged arrays aren't safe.  Snapshot on this
        # background thread (off the step's critical path) and hand the
        # slot back to the producer immediately.
        release = batch.__dict__.get("release")
        if release is not None:
            import numpy as _np
            batch = DataBatch(
                [_np.array(d) for d in batch.data],
                [_np.array(l) for l in batch.label]
                if batch.label is not None else None,
                pad=batch.pad, index=batch.index,
                provide_data=batch.provide_data,
                provide_label=batch.provide_label)
            release()
        if self._stage is None:
            return batch
        arrays = list(batch.data) + list(batch.label or [])
        staged = self._stage(*arrays)
        return StagedBatch(staged, data=batch.data, label=batch.label,
                           pad=batch.pad, index=batch.index,
                           provide_data=batch.provide_data,
                           provide_label=batch.provide_label)

    # -- DataIter protocol -------------------------------------------------
    @property
    def provide_data(self):
        return self._iter.provide_data

    @property
    def provide_label(self):
        return self._iter.provide_label

    def reset(self):
        """Realign with the source: stop the worker (dropping in-flight
        staged batches), reset the source, and restart — safe mid-epoch
        and after a surfaced error."""
        self._shutdown()
        self._gen += 1
        self._done = False
        self._iter.reset()
        if self.depth > 0:
            self._queue = queue.Queue(maxsize=self.depth)
            self._start()

    def next(self):
        if self._done:
            raise StopIteration
        if self.depth == 0:
            from .resilience import retrying_next
            try:
                batch = retrying_next(self._iter,
                                      name="device_prefetch.next")
            except StopIteration:
                self._done = True
                raise
            self.current_batch = self._stage_one(batch)
            return self.current_batch
        while True:
            try:
                gen, item = self._queue.get(timeout=1.0)
            except queue.Empty:
                if self._thread is not None and not self._thread.is_alive():
                    raise MXNetError(
                        "DevicePrefetchIter: worker thread died without "
                        "reporting a result")
                continue
            if gen != self._gen:
                continue  # stale item from before a reset()
            if item is _END:
                self._done = True
                raise StopIteration
            if isinstance(item, _WorkerError):
                # the worker stopped after the error; reset() restarts it
                self._done = True
                raise item.exc
            self.current_batch = item
            return item

    # NOTE: no `__next__ = next` here — DataIter.__next__ dispatches to
    # self.next() dynamically, so subclass overrides stay reachable from
    # for-loops (the io.py DataIter contract)

    def iter_next(self):
        try:
            self.next()
            return True
        except StopIteration:
            return False

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getpad(self):
        return self.current_batch.pad

    # -- lifecycle ---------------------------------------------------------
    def _shutdown(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            # unblock a worker stuck in put(): drain one slot
            try:
                while True:
                    self._queue.get_nowait()
            except (queue.Empty, AttributeError):
                pass
            t.join(timeout=5.0)
            if t.is_alive():  # pragma: no cover — diagnostics only
                _LOG.warning("DevicePrefetchIter: worker did not stop "
                             "within 5s")

    def close(self):
        """Stop the background worker and release queued device batches.
        Safe to call twice; the iterator raises StopIteration afterwards
        until reset()."""
        self._shutdown()
        self._done = True
        self._queue = queue.Queue(maxsize=max(1, self.depth)) \
            if self.depth > 0 else None

    def __del__(self):
        try:
            self._stop.set()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
