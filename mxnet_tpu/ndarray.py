"""NDArray — the imperative tensor type, backed by jax.Array.

Re-design of the reference NDArray (include/mxnet/ndarray.h:40-531,
src/ndarray/ndarray.cc).  The reference pushes every mutation through a
threaded dependency engine; on TPU the same observable contract — async
dispatch, serialization of conflicting writes, WaitToRead/WaitToWrite —
is provided by XLA's async execution model: every op is an XLA computation
dispatched asynchronously; data dependencies order them; ``wait_to_read``
is ``jax.Array.block_until_ready``.  In-place mutation on immutable
jax.Arrays is a handle swap (the NDArray is the mutable cell, like the
reference's Chunk), so ``a += b`` and ``a[:] = x`` behave identically to
the reference without an explicit engine.

Serialization is byte-compatible with the reference's ``.params`` format
(src/ndarray/ndarray.cc:593-676, kMXAPINDArrayListMagic=0x112) so reference
checkpoints load unmodified.
"""
from __future__ import annotations

import builtins
import struct
import weakref

import numpy as np

import jax
import jax.numpy as jnp

from .base import MXNetError
from .context import Context, current_context
from .ops.registry import OP_REGISTRY, apply_op, get_op

__all__ = [
    "NDArray", "array", "empty", "zeros", "ones", "full", "arange",
    "concatenate", "load", "save", "imresize", "waitall", "onehot_encode",
]

# dtype <-> reference mshadow type_flag (mshadow/base.h kFloat32=0 ...)
_DTYPE_TO_FLAG = {
    np.dtype("float32"): 0,
    np.dtype("float64"): 1,
    np.dtype("float16"): 2,
    np.dtype("uint8"): 3,
    np.dtype("int32"): 4,
}
_FLAG_TO_DTYPE = {v: k for k, v in _DTYPE_TO_FLAG.items()}
# TPU-native extension flags (not in the reference; > any reference flag)
_DTYPE_TO_FLAG[np.dtype(jnp.bfloat16)] = 100
_FLAG_TO_DTYPE[100] = np.dtype(jnp.bfloat16)

_LIVE = weakref.WeakSet()

# autograd tape hook — set by mxnet_tpu.autograd while recording; called as
# hook(opdef, attrs, input_ndarrays, output_ndarrays, is_train, rng) after
# every imperative op (the TPU analog of AutogradRuntime::RecordImperative*,
# src/ndarray/autograd.cc:85-114).
_RECORD_HOOK = [None]

# is_train default override for imperative ops: None = op default (train
# behavior, matching this package's historical imperative semantics); set to
# True/False by autograd train_section/test_section (the reference derives
# imperative is_train from AutogradRuntime::IsTraining, c_api_ndarray.cc).
_TRAIN_MODE = [None]


class _MutationOp(object):
    """Pseudo-op for tape entries that rebind/mutate an existing NDArray
    (in-place ops, __setitem__, out=) — the reference versions the engine
    var instead (ThreadedVar write dependency); here the tape replays the
    mutation functionally."""
    needs_is_train = False
    needs_rng = False
    name = "_mutation"

    def __init__(self, fn):
        self.fn = fn

    def normalize_attrs(self, attrs):
        return {}


def _record_mutation(fn, inputs, outputs):
    hook = _RECORD_HOOK[0]
    if hook is not None:
        hook(_MutationOp(fn), {}, inputs, outputs, False, None)


def _invoke(opdef, nd_inputs, attrs, is_train=False, ctx=None):
    """Centralized imperative op invocation: jit-cached apply + tape record."""
    rng = None
    if opdef.needs_rng:
        from . import random as _random
        rng = _random.next_key()
    arrays = tuple(a._data for a in nd_inputs)
    results = apply_op(opdef, arrays, attrs, is_train=is_train, rng=rng)
    outs = tuple(NDArray._from_jax(r, ctx) for r in results)
    hook = _RECORD_HOOK[0]
    if hook is not None:
        hook(opdef, attrs, nd_inputs, outs, is_train, rng)
    return outs


def waitall():
    """Block until all outstanding computation on live arrays finishes
    (Engine::WaitForAll analog, include/mxnet/engine.h:180).

    An async compute error surfaces here and propagates, like the
    reference engine's loud fatal (threaded_engine.h:329-337) — after
    draining the remaining arrays so state isn't left half-synced.
    Arrays whose buffers were deleted (e.g. donated) are skipped.
    """
    first_err = None
    for arr in list(_LIVE):
        try:
            arr._data.block_until_ready()
        except RuntimeError as e:
            if "deleted" in str(e).lower() or "donat" in str(e).lower():
                continue  # freed/donated buffer, not a compute failure
            if first_err is None:
                first_err = e
        except Exception as e:  # noqa: BLE001 — propagate after drain
            if first_err is None:
                first_err = e
    if first_err is not None:
        raise MXNetError(
            "async computation failed during waitall: %s" % first_err) \
            from first_err


class NDArray(object):
    """Multi-device tensor with numpy-style API (reference
    python/mxnet/ndarray.py:NDArray)."""

    __slots__ = ("_data", "_ctx", "__weakref__")

    def __init__(self, data, ctx=None):
        if isinstance(data, NDArray):
            data = data._data
        if not isinstance(data, jax.Array):
            data = jnp.asarray(data)
        self._ctx = ctx if ctx is not None else current_context()
        self._data = _to_device(data, self._ctx)
        _LIVE.add(self)

    # -- construction -----------------------------------------------------
    @staticmethod
    def _from_jax(data, ctx=None):
        arr = NDArray.__new__(NDArray)
        arr._ctx = ctx if ctx is not None else current_context()
        arr._data = _to_device(data, arr._ctx) if ctx is not None else data
        _LIVE.add(arr)
        return arr

    # -- basic properties -------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def size(self):
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def dtype(self):
        return np.dtype(self._data.dtype).type

    @property
    def context(self):
        return self._ctx

    ctx = context

    @property
    def T(self):
        return self._traced_view(jnp.transpose)

    @property
    def handle(self):
        """The underlying jax.Array (the PJRT buffer handle)."""
        return self._data

    # -- sync / transfer --------------------------------------------------
    def wait_to_read(self):
        self._data.block_until_ready()

    wait_to_write = wait_to_read

    def asnumpy(self):
        return np.asarray(jax.device_get(self._data))

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def astype(self, dtype):
        dt = np.dtype(dtype)
        return self._traced_view(lambda v: v.astype(dt))

    def copyto(self, other):
        """Copy into another NDArray or to a Context (ndarray.py:copyto)."""
        if isinstance(other, NDArray):
            other._data = _to_device(self._data.astype(other._data.dtype),
                                     other._ctx)
            return other
        if isinstance(other, Context):
            return NDArray._from_jax(self._data, Context(other))
        raise TypeError("copyto does not support type " + str(type(other)))

    def copy(self):
        return NDArray._from_jax(jnp.copy(self._data), self._ctx)

    def as_in_context(self, context):
        if context == self._ctx:
            return self
        return self.copyto(context)

    def _traced_view(self, fn):
        """Apply a pure array fn, recording it on the autograd tape so
        gradients flow through views/reshapes taken inside train_section."""
        out = NDArray._from_jax(fn(self._data), self._ctx)
        _record_mutation(fn, (self,), (out,))
        return out

    # -- shape manipulation ----------------------------------------------
    def reshape(self, shape, reverse=False):
        from .ops.tensor import infer_reshape
        if isinstance(shape, int):
            shape = (shape,)
        new_shape = infer_reshape(self.shape, tuple(shape), reverse)
        return self._traced_view(lambda v: jnp.reshape(v, new_shape))

    def broadcast_to(self, shape):
        shape = tuple(shape)
        return self._traced_view(lambda v: jnp.broadcast_to(v, shape))

    def expand_dims(self, axis):
        return self._traced_view(lambda v: jnp.expand_dims(v, axis))

    def flatten(self):
        n = self.shape[0]
        return self._traced_view(lambda v: jnp.reshape(v, (n, -1)))

    def transpose(self, axes=None):
        return self._traced_view(lambda v: jnp.transpose(v, axes))

    def slice(self, start, stop):
        return self[start:stop]

    def slice_axis(self, axis, begin, end):
        return self._traced_view(
            lambda v: jax.lax.slice_in_dim(v, begin, end, axis=axis))

    # -- indexing ---------------------------------------------------------
    def __getitem__(self, key):
        if isinstance(key, NDArray):
            key = key._data.astype(jnp.int32)
        return self._traced_view(lambda v: v[key])

    def __setitem__(self, key, value):
        value_nd = value if isinstance(value, NDArray) else None
        if isinstance(value, NDArray):
            value = value._data
        value = jnp.asarray(value, dtype=self._data.dtype)
        shape, dtype = self.shape, self._data.dtype
        val_in = value_nd if value_nd is not None else NDArray._from_jax(value)
        if isinstance(key, builtins.slice) and key == builtins.slice(None):
            # record before the handle swap so the tape input is this array's
            # pre-mutation version (the reference bumps the var version)
            _record_mutation(
                lambda _old, v: jnp.broadcast_to(v.astype(dtype), shape),
                (self, val_in), (self,))
            self._data = _to_device(jnp.broadcast_to(value, self.shape),
                                    self._ctx)
        else:
            if isinstance(key, NDArray):
                key = key._data.astype(jnp.int32)
            _record_mutation(
                lambda old, v, _k=key: old.at[_k].set(v.astype(dtype)),
                (self, val_in), (self,))
            self._data = self._data.at[key].set(value)

    def __len__(self):
        return self.shape[0]

    def __iter__(self):
        for i in range(self.shape[0]):
            yield self[i]

    # -- python protocol --------------------------------------------------
    def __repr__(self):
        return "%s\n<%s %s @%s>" % (
            str(self.asnumpy()), self.__class__.__name__,
            "x".join(map(str, self.shape)), self._ctx)

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise ValueError("ambiguous truth value of multi-element NDArray")

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    # -- arithmetic (dispatched through the op registry so imperative and
    #    symbolic share one lowering; reference ndarray.py BinaryOp) -------
    def _binary(self, other, op_name, scalar_op, reverse=False):
        if isinstance(other, NDArray):
            a, b = (other, self) if reverse else (self, other)
            out = _invoke(get_op(op_name), (a, b), {}, ctx=self._ctx)[0]
        elif isinstance(other, (int, float, np.number)):
            out = _invoke(get_op(scalar_op), (self,),
                          {"scalar": float(other)}, ctx=self._ctx)[0]
        else:
            return NotImplemented
        return out

    def __add__(self, o):
        return self._binary(o, "broadcast_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, o):
        return self._binary(o, "broadcast_sub", "_rminus_scalar", reverse=True)

    def __mul__(self, o):
        return self._binary(o, "broadcast_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __div__(self, o):
        return self._binary(o, "broadcast_div", "_div_scalar")

    __truediv__ = __div__

    def __rdiv__(self, o):
        return self._binary(o, "broadcast_div", "_rdiv_scalar", reverse=True)

    __rtruediv__ = __rdiv__

    def __mod__(self, o):
        return self._binary(o, "broadcast_mod", "_mod_scalar")

    def __rmod__(self, o):
        return self._binary(o, "broadcast_mod", "_rmod_scalar", reverse=True)

    def __pow__(self, o):
        return self._binary(o, "broadcast_power", "_power_scalar")

    def __rpow__(self, o):
        return self._binary(o, "broadcast_power", "_rpower_scalar", reverse=True)

    def __neg__(self):
        return self._traced_view(jnp.negative)

    def __abs__(self):
        return self._traced_view(jnp.abs)

    def __eq__(self, o):
        if o is None:
            return False
        return self._binary(o, "broadcast_equal", "_equal_scalar")

    def __ne__(self, o):
        if o is None:
            return True
        return self._binary(o, "broadcast_not_equal", "_not_equal_scalar")

    def __gt__(self, o):
        return self._binary(o, "broadcast_greater", "_greater_scalar")

    def __ge__(self, o):
        return self._binary(o, "broadcast_greater_equal", "_greater_equal_scalar")

    def __lt__(self, o):
        return self._binary(o, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, o):
        return self._binary(o, "broadcast_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    # in-place: handle swap — the NDArray is the mutable cell
    def _inplace(self, other, op_name, scalar_op):
        res = self._binary(other, op_name, scalar_op)
        if res is NotImplemented:
            return res
        self._data = res._data
        _record_mutation(lambda v: v, (res,), (self,))
        return self

    def __iadd__(self, o):
        return self._inplace(o, "broadcast_add", "_plus_scalar")

    def __isub__(self, o):
        return self._inplace(o, "broadcast_sub", "_minus_scalar")

    def __imul__(self, o):
        return self._inplace(o, "broadcast_mul", "_mul_scalar")

    def __idiv__(self, o):
        return self._inplace(o, "broadcast_div", "_div_scalar")

    __itruediv__ = __idiv__


def _to_device(data, ctx):
    dev = ctx.jax_device
    if len(data.devices()) == 1 and next(iter(data.devices())) == dev:
        return data
    return jax.device_put(data, dev)


# ---------------------------------------------------------------------------
# creation functions (reference python/mxnet/ndarray.py)
# ---------------------------------------------------------------------------

def array(source_array, ctx=None, dtype=None):
    if isinstance(source_array, NDArray):
        src = source_array._data
        if dtype is not None:
            src = src.astype(np.dtype(dtype))
        return NDArray._from_jax(src, ctx or source_array._ctx)
    # default dtype is float32 like the reference (python/mxnet/ndarray.py
    # array(): mx_real_t unless dtype given)
    src = np.asarray(source_array,
                     dtype=np.dtype(dtype) if dtype else np.float32)
    return NDArray(src, ctx=ctx)


def empty(shape, ctx=None, dtype="float32"):
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray._from_jax(jnp.zeros(shape, dtype=np.dtype(dtype)), ctx)


def zeros(shape, ctx=None, dtype="float32", **kwargs):
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray._from_jax(jnp.zeros(shape, dtype=np.dtype(dtype)), ctx)


def ones(shape, ctx=None, dtype="float32", **kwargs):
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray._from_jax(jnp.ones(shape, dtype=np.dtype(dtype)), ctx)


def full(shape, val, ctx=None, dtype="float32"):
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray._from_jax(jnp.full(shape, val, dtype=np.dtype(dtype)), ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32"):
    out = jnp.arange(start, stop, step, dtype=np.dtype(dtype))
    if repeat != 1:
        out = jnp.repeat(out, repeat)
    return NDArray._from_jax(out, ctx)


def moveaxis(tensor, source, destination):
    return NDArray._from_jax(jnp.moveaxis(tensor._data, source, destination),
                             tensor._ctx)


def concatenate(arrays, axis=0, always_copy=True):
    if len(arrays) == 1 and not always_copy:
        return arrays[0]
    return NDArray._from_jax(
        jnp.concatenate([a._data for a in arrays], axis=axis), arrays[0]._ctx)


def onehot_encode(indices, out):
    """one-hot into ``out`` (reference ndarray.py onehot_encode)."""
    depth = out.shape[1]
    out._data = jax.nn.one_hot(indices._data.astype(jnp.int32), depth,
                               dtype=out._data.dtype)
    return out


# ---------------------------------------------------------------------------
# serialization — byte-compatible with reference .params files
# (src/ndarray/ndarray.cc:593-676)
# ---------------------------------------------------------------------------

_LIST_MAGIC = 0x112


def _save_one(fo, arr):
    shape = arr.shape
    fo.write(struct.pack("<I", len(shape)))
    fo.write(struct.pack("<%dI" % len(shape), *shape))
    if len(shape) == 0:
        return
    fo.write(struct.pack("<ii", 1, 0))  # Context: kCPU, dev_id 0
    npdata = arr.asnumpy()
    flag = _DTYPE_TO_FLAG.get(np.dtype(npdata.dtype))
    if flag is None:
        npdata = npdata.astype(np.float32)
        flag = 0
    fo.write(struct.pack("<i", flag))
    fo.write(np.ascontiguousarray(npdata).tobytes())


def _load_one(fi, ctx=None):
    ndim, = struct.unpack("<I", fi.read(4))
    if ndim == 0:
        return empty((), ctx)
    shape = struct.unpack("<%dI" % ndim, fi.read(4 * ndim))
    fi.read(8)  # Context (dev_type, dev_id) — ignored on load
    flag, = struct.unpack("<i", fi.read(4))
    dtype = _FLAG_TO_DTYPE[flag]
    count = int(np.prod(shape)) if shape else 1
    buf = fi.read(count * dtype.itemsize)
    data = np.frombuffer(buf, dtype=dtype).reshape(shape)
    return array(data, ctx=ctx, dtype=dtype)


def save(fname, data):
    """Save list/dict of NDArrays in reference ``.params`` format."""
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        names, arrays = zip(*sorted(data.items())) if data else ((), ())
    else:
        names, arrays = (), tuple(data)
    with open(fname, "wb") as fo:
        fo.write(struct.pack("<QQ", _LIST_MAGIC, 0))
        fo.write(struct.pack("<Q", len(arrays)))
        for arr in arrays:
            _save_one(fo, arr)
        fo.write(struct.pack("<Q", len(names)))
        for name in names:
            encoded = name.encode("utf-8")
            fo.write(struct.pack("<Q", len(encoded)))
            fo.write(encoded)


def _load_stream(fi, ctx=None):
    """Parse a ``.params``-format stream -> (names, arrays); names is empty
    for unnamed lists.  Shared by nd.load and predict.load_ndarray_file."""
    magic, _ = struct.unpack("<QQ", fi.read(16))
    if magic != _LIST_MAGIC:
        raise MXNetError("Invalid NDArray stream format")
    num, = struct.unpack("<Q", fi.read(8))
    arrays = [_load_one(fi, ctx) for i in range(num)]
    num_names, = struct.unpack("<Q", fi.read(8))
    names = []
    for _i in range(num_names):
        ln, = struct.unpack("<Q", fi.read(8))
        names.append(fi.read(ln).decode("utf-8"))
    return names, arrays


def load(fname, ctx=None):
    """Load a reference-format ``.params`` file → dict or list of NDArray."""
    with open(fname, "rb") as fi:
        try:
            names, arrays = _load_stream(fi, ctx)
        except MXNetError as e:
            raise MXNetError("%s: %s" % (e, fname))
    if names:
        return dict(zip(names, arrays))
    return arrays


def imresize(src, w, h, interp=1, **kwargs):
    """Image resize (reference src/io/image_io.cc _cvimresize) — delegates
    to the registered `imresize` op (jax.image.resize on device)."""
    op = get_op("imresize")
    out, = apply_op(op, (NDArray(src)._data,),
                    {"w": int(w), "h": int(h), "interp": int(interp)})
    return NDArray._from_jax(out, getattr(src, "_ctx", None))


# ---------------------------------------------------------------------------
# autogenerated op functions — every registered op becomes mx.nd.<op>
# (reference _init_ndarray_module, python/mxnet/ndarray.py)
# ---------------------------------------------------------------------------

def _make_ndarray_function(opdef, func_name):
    def generic_op(*args, **kwargs):
        out = kwargs.pop("out", None)
        kwargs.pop("name", None)
        ctx = kwargs.pop("ctx", None)
        default_train = _TRAIN_MODE[0] if _TRAIN_MODE[0] is not None else \
            bool(opdef.needs_is_train)
        is_train = kwargs.pop("is_train", default_train)
        nd_inputs = []
        for a in args:
            if isinstance(a, NDArray):
                nd_inputs.append(a)
            elif isinstance(a, (int, float)) and "scalar" not in kwargs and \
                    not opdef.get_input_names(kwargs):
                kwargs["scalar"] = a
            else:
                nd_inputs.append(NDArray._from_jax(jnp.asarray(a)))
        # named tensor inputs (data=..., weight=...)
        in_names = opdef.get_input_names(kwargs) + opdef.get_aux_names(kwargs)
        for nm in in_names:
            if nm in kwargs and isinstance(kwargs[nm], NDArray):
                nd_inputs.append(kwargs.pop(nm))
        if ctx is not None:
            ctx = ctx if isinstance(ctx, Context) else Context(ctx)
        ndarrays = _invoke(opdef, tuple(nd_inputs), kwargs, is_train=is_train,
                           ctx=ctx)
        if out is not None:
            outs = out if isinstance(out, (list, tuple)) else (out,)
            for o, r in zip(outs, ndarrays):
                dt = o._data.dtype
                _record_mutation(lambda v, _dt=dt: v.astype(_dt), (r,), (o,))
                o._data = _to_device(r._data.astype(o._data.dtype), o._ctx)
            return out
        if len(ndarrays) == 1:
            return ndarrays[0]
        return list(ndarrays)

    generic_op.__name__ = func_name
    generic_op.__doc__ = opdef.doc
    return generic_op


def _init_ndarray_module():
    module = globals()
    for reg_name, opdef in list(OP_REGISTRY.items()):
        if reg_name in ("zeros", "ones", "full", "arange"):
            continue  # python creation fns above already cover these
        if reg_name not in module:
            module[reg_name] = _make_ndarray_function(opdef, reg_name)
            __all__.append(reg_name)


# populated by mxnet_tpu/__init__ after all op modules import
