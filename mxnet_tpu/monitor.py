"""Monitor: tap intermediate outputs/weights during training (reference
python/mxnet/monitor.py, wired through MXExecutorSetMonitorCallback /
src/executor/graph_executor.cc:69-72,770-790).

``Monitor.install`` hooks an executor's per-node tap; with our executors the
tap runs the graph eagerly (unfused) while installed, so values match what a
fused run computes but each node is observable — the TPU analog of the
reference's engine-callback tap.
"""
from __future__ import annotations

import re

from .ndarray import NDArray

__all__ = ["Monitor"]


class Monitor(object):
    """Collect per-node statistics every ``interval`` batches (reference
    monitor.py:Monitor).

    Parameters
    ----------
    interval : int
        Sample every N calls of ``tic()``.
    stat_func : callable(NDArray) -> NDArray, optional
        Statistic; default mean(|x|) like the reference.
    pattern : str
        Regex on node names to include.
    sort : bool
        Sort stats by name in ``toc()``.
    monitor_all : bool
        Also tap arguments/aux states, not just op outputs.
    """

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False,
                 monitor_all=False):
        if stat_func is None:
            def asum_stat(x):
                return abs(x).asnumpy().mean()
            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort
        self.monitor_all = monitor_all
        self._guard_sources = []

        def stat_helper(name, array):
            if not self.activated or not self.re_prog.match(name):
                return
            self.queue.append((self.step, name, self.stat_func(array)))
        self.stat_helper = stat_helper

    def install(self, exe):
        """Attach to an executor (reference monitor.py:install)."""
        exe.set_monitor_callback(self.stat_helper, self.monitor_all)
        self.exes.append(exe)

    def install_step_guard(self, source):
        """Also report the NaN/Inf step guard's counters each ``toc()``.

        ``source`` is a Module (``skipped_update_count``) or SPMDTrainer
        (``skipped_steps``/``consecutive_bad_steps``); rows appear as
        ``step_guard_skipped`` / ``step_guard_consecutive_bad`` next to
        the per-node stats, so a skipping run is visible in the same
        place its activations are being debugged.

        Deferred-metric interaction: the counters live in-graph and the
        source properties FLUSH them on read, so every reported row is
        exact at its ``toc()`` — even when the trainer's routine
        host<->device sync is deferred to every MXTPU_METRIC_INTERVAL
        steps, reading here forces the fold (between tocs the host copy
        lags by at most that interval)."""
        self._guard_sources.append(source)

    def tic(self):
        """Start collecting for this batch if it is a sampled one
        (reference monitor.py:tic)."""
        if self.step % self.interval == 0:
            for exe in self.exes:
                for array in exe.arg_arrays:
                    array.wait_to_read()
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """Finish the batch; return list of (step, name, stat)
        (reference monitor.py:toc)."""
        if not self.activated:
            return []
        for src in self._guard_sources:
            skipped = getattr(src, "skipped_update_count",
                              getattr(src, "skipped_steps", 0))
            self.queue.append((self.step, "step_guard_skipped",
                               float(skipped)))
            self.queue.append((self.step, "step_guard_consecutive_bad",
                               float(getattr(src, "consecutive_bad_steps",
                                             0) or 0)))
        for exe in self.exes:
            for array in exe.arg_arrays:
                array.wait_to_read()
        # re-tap weights too when monitor_all is requested via queue —
        # the executor tap already reported args; nothing extra to do here
        self.activated = False
        res = []
        queue = self.queue
        if self.sort:
            queue = sorted(queue, key=lambda x: x[1])
        for n, k, v_list in queue:
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            res.append((n, k, str(v_list)))
        self.queue = []
        return res

    def toc_print(self):
        """Collect and print stats (reference monitor.py:toc_print)."""
        res = self.toc()
        for n, k, v in res:
            print("Batch: {:7d} {:30s} {}".format(n, k, v))
        return res
