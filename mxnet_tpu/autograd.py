"""Imperative autograd — record-and-replay differentiation of NDArray code.

Re-design of the reference's AutogradRuntime (src/ndarray/autograd.cc:27-215)
and its Python surface (python/mxnet/contrib/autograd.py:22-120).

The reference records imperative ops as NNVM nodes while ``train_section`` is
active, then ``ComputeGradient`` builds a symbol from the tape, binds a fresh
GraphExecutor and runs backward with ones head-grads (autograd.cc:123-200).

Here the tape records (opdef, attrs, inputs, outputs) per imperative op (hook
installed in ndarray._RECORD_HOOK); ``compute_gradient`` replays the tape as a
*pure JAX function* of the marked variables and differentiates it with
``jax.vjp`` — one traced+jit-compiled XLA program instead of a fresh
executor, which is the idiomatic TPU equivalent: the whole backward fuses.

Random ops (Dropout etc.) replay with the PRNG key captured at record time,
so the replayed forward is bit-identical to what the user observed.
"""
from __future__ import annotations

import contextlib
import functools

import jax
import numpy as np

from .base import MXNetError
from . import ndarray as _nd
from .ndarray import NDArray

__all__ = [
    "set_is_training", "train_section", "test_section", "mark_variables",
    "unmark_variables", "backward", "compute_gradient", "grad_and_loss",
    "grad", "is_recording", "is_training",
]


class _TapeEntry(object):
    __slots__ = ("opdef", "attrs", "inputs", "input_values", "outputs",
                 "is_train", "rng")

    def __init__(self, opdef, attrs, inputs, outputs, is_train, rng):
        self.opdef = opdef
        self.attrs = dict(attrs)
        self.inputs = tuple(inputs)       # strong refs — keep tape alive
        # values at record time: replay constants for unmarked, possibly
        # later-mutated arrays (handle swaps don't retro-change the tape)
        self.input_values = tuple(a._data for a in inputs)
        self.outputs = tuple(outputs)
        self.is_train = is_train
        self.rng = rng


class _AutogradState(object):
    def __init__(self):
        self.recording = False
        self.training = False
        self.depth = 0          # train_section nesting
        self.tape = []
        # id(NDArray) -> (variable, gradient holder, grad_req)
        self.marked = {}

    def record_hook(self, opdef, attrs, inputs, outputs, is_train, rng):
        self.tape.append(_TapeEntry(opdef, attrs, inputs, outputs,
                                    is_train, rng))


_STATE = _AutogradState()


def is_recording():
    return _STATE.recording


def is_training():
    return _STATE.training


def set_is_training(is_train):
    """Turn recording on/off; returns previous state (reference
    MXAutogradSetIsTraining, contrib/autograd.py:22-36)."""
    prev = _STATE.recording
    if prev == bool(is_train):
        return prev
    _STATE.recording = bool(is_train)
    _STATE.training = bool(is_train)
    if is_train:
        _nd._RECORD_HOOK[0] = _STATE.record_hook
        _nd._TRAIN_MODE[0] = True
    else:
        _nd._RECORD_HOOK[0] = None
        _nd._TRAIN_MODE[0] = None
        _STATE.tape = []
        # marked variables persist across sections (the reference's marks
        # live on the NDArray itself, autograd.cc:35-50)
    return prev


@contextlib.contextmanager
def train_section():
    """Scope in which imperative ops are recorded for gradient computation
    (reference contrib/autograd.py TrainingStateScope/train_section).
    Nested sections (even across a test_section) share one tape; only the
    outermost exit clears it."""
    _STATE.depth += 1
    prev = set_is_training(True)
    try:
        yield
    finally:
        _STATE.depth -= 1
        if _STATE.depth == 0 and not prev:
            set_is_training(False)


@contextlib.contextmanager
def test_section():
    """Scope that pauses recording inside a train_section."""
    prev = _STATE.recording
    prev_training = _STATE.training
    _STATE.recording = False
    _STATE.training = False
    hook = _nd._RECORD_HOOK[0]
    mode = _nd._TRAIN_MODE[0]
    _nd._RECORD_HOOK[0] = None
    _nd._TRAIN_MODE[0] = False
    try:
        yield
    finally:
        _STATE.recording = prev
        _STATE.training = prev_training
        _nd._RECORD_HOOK[0] = hook
        _nd._TRAIN_MODE[0] = mode


def mark_variables(variables, gradients, grad_reqs="write"):
    """Declare NDArrays as differentiation leaves with paired gradient
    holders (reference AutogradRuntime::MarkVariables, autograd.cc:35-50)."""
    if isinstance(variables, NDArray):
        variables = [variables]
        gradients = [gradients]
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    if not (len(variables) == len(gradients) == len(grad_reqs)):
        raise MXNetError("variables/gradients/grad_reqs length mismatch")
    for var, g, req in zip(variables, gradients, grad_reqs):
        if not isinstance(var, NDArray) or not isinstance(g, NDArray):
            raise MXNetError("mark_variables expects NDArrays")
        _STATE.marked[id(var)] = (var, g, req)


def unmark_variables(variables):
    """Remove marks set by mark_variables (frees the tape's strong refs)."""
    if isinstance(variables, NDArray):
        variables = [variables]
    for var in variables:
        _STATE.marked.pop(id(var), None)


def _replay(leaves, outputs):
    """Build the pure replay function f(leaf values) -> output values."""
    tape = list(_STATE.tape)
    leaf_ids = [id(v) for v in leaves]
    out_ids = [id(o) for o in outputs]

    def f(leaf_vals):
        env = dict(zip(leaf_ids, leaf_vals))
        for entry in tape:
            op = entry.opdef
            attrs = op.normalize_attrs(entry.attrs)
            kw = {}
            if op.needs_is_train:
                kw["is_train"] = entry.is_train
            if op.needs_rng:
                kw["rng"] = entry.rng
            vals = [env.get(id(a), rec)
                    for a, rec in zip(entry.inputs, entry.input_values)]
            res = op.fn(*vals, **attrs, **kw)
            if not isinstance(res, (tuple, list)):
                res = (res,)
            for out_nd, out_val in zip(entry.outputs, res):
                env[id(out_nd)] = out_val
        missing = [i for i in out_ids if i not in env]
        if missing:
            raise MXNetError(
                "compute_gradient: an output is not on the autograd tape "
                "(was it created outside a train_section?)")
        return [env[i] for i in out_ids]

    return f


def backward(outputs, out_grads=None, retain_graph=False):
    """Compute gradients of ``outputs`` w.r.t. the marked variables used by
    the current tape and accumulate them into the paired gradient holders
    (reference MXAutogradBackward / ComputeGradient, autograd.cc:65-215)."""
    if isinstance(outputs, NDArray):
        outputs = [outputs]
    if not _STATE.marked:
        raise MXNetError("no variables marked — call mark_variables first")
    if not _STATE.tape:
        raise MXNetError("autograd tape is empty — record inside "
                         "train_section()")
    # only vars this tape actually reads participate — stale marks from
    # earlier backwards must not have their holders zero-overwritten.
    # A leaf's linearization point is its value at FIRST tape read (the
    # reference differentiates the recorded computation, autograd.cc:172) —
    # in-place mutations after that must not shift it.
    first_val = {}
    for entry in _STATE.tape:
        for a, rec in zip(entry.inputs, entry.input_values):
            first_val.setdefault(id(a), rec)
    active = [(v, g, r) for (v, g, r) in _STATE.marked.values()
              if id(v) in first_val]
    if not active:
        raise MXNetError("no marked variable is used by the recorded tape")
    leaves = [v for (v, _g, _r) in active]
    grads_out = [g for (_v, g, _r) in active]
    reqs = [r for (_v, _g, r) in active]

    f = _replay(leaves, outputs)
    leaf_vals = [first_val[id(v)] for v in leaves]
    _outs, vjp_fn = jax.vjp(f, leaf_vals)
    if out_grads is None:
        cotangents = [jax.numpy.ones_like(o) for o in _outs]
    else:
        if isinstance(out_grads, NDArray):
            out_grads = [out_grads]
        if len(out_grads) != len(_outs):
            raise MXNetError(
                "backward: %d head gradients for %d outputs"
                % (len(out_grads), len(_outs)))
        # cast to each output's dtype: float16/bfloat16 outputs with
        # float32 head grads would make jax.vjp raise a dtype mismatch
        cotangents = [
            (g._data if isinstance(g, NDArray)
             else jax.numpy.asarray(g)).astype(o.dtype)
            for g, o in zip(out_grads, _outs)]
    (leaf_grads,) = vjp_fn(cotangents)
    for g_holder, g_val, req in zip(grads_out, leaf_grads, reqs):
        if req == "null":
            continue
        g_val = g_val.astype(g_holder._data.dtype)
        if req == "add":
            g_holder._data = g_holder._data + g_val
        else:
            g_holder._data = g_val
    if not retain_graph:
        _STATE.tape = []


def compute_gradient(outputs):
    """Reference contrib/autograd.py compute_gradient: ones head-grads."""
    backward(outputs)


def grad_and_loss(func, argnum=None):
    """Decorator returning (gradients, loss) of ``func`` w.r.t. its NDArray
    arguments (reference contrib/autograd.py:60-97)."""
    @functools.wraps(func)
    def wrapped(*args):
        variables = list(args)
        if argnum is not None:
            argnums = [argnum] if isinstance(argnum, int) else list(argnum)
            variables = [args[i] for i in argnums]
        for v in variables:
            if not isinstance(v, NDArray):
                raise MXNetError("grad_and_loss arguments must be NDArrays")
        grads = [_nd.zeros(v.shape, ctx=v.context,
                           dtype=np.dtype(v.dtype).name) for v in variables]
        try:
            with train_section():
                mark_variables(variables, grads)
                outputs = func(*args)
                compute_gradient(
                    [outputs] if isinstance(outputs, NDArray) else outputs)
        finally:
            unmark_variables(variables)
        return grads, outputs
    return wrapped


def grad(func, argnum=None):
    """Decorator returning only the gradients (reference
    contrib/autograd.py:100-120)."""
    grad_with_loss_func = grad_and_loss(func, argnum)

    @functools.wraps(grad_with_loss_func)
    def wrapped(*args):
        return grad_with_loss_func(*args)[0]
    return wrapped
