"""mxnet_tpu — a TPU-native deep-learning framework with the capabilities of
MXNet v0.9.5 (NNVM era), re-designed on JAX/XLA/pjit/Pallas.

Usage mirrors the reference's ``import mxnet as mx``::

    import mxnet_tpu as mx
    a = mx.nd.ones((2, 3), ctx=mx.tpu())
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=10)
    mod = mx.mod.Module(net, ...)
"""

__version__ = "0.1.0"

from .base import MXNetError
from . import resilience
from .resilience import CheckpointManager, PreemptionHandler, StepWatchdog

# Persistent XLA compilation cache: MXTPU_COMPILE_CACHE=<dir> makes every
# relaunch reuse compiled programs from disk instead of recompiling the
# fused step from scratch (bench.py reports cold vs warm bring-up).
# Configured BEFORE anything can trigger a compile; thresholds are zeroed
# so even small CPU-test programs land in the cache.
import os as _os
from .base import ENV_COMPILE_CACHE as _ENV_COMPILE_CACHE
from .base import get_env as _get_env
_compile_cache = _get_env(_ENV_COMPILE_CACHE)
if _compile_cache:
    import jax as _jax
    _jax.config.update("jax_compilation_cache_dir",
                       _os.path.expanduser(_compile_cache))
    for _k, _v in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                   ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            _jax.config.update(_k, _v)
        except Exception:  # noqa: BLE001 — older jax without the knob
            pass
    del _jax
del _os, _compile_cache, _get_env, _ENV_COMPILE_CACHE

# Join the process group BEFORE anything can touch a JAX backend: under
# tools/launch.py the MXTPU_* envs are set, and jax.distributed.initialize
# must precede backend creation (it also pins the worker platform).  This is
# the analog of the reference consulting DMLC_ROLE at import
# (python/mxnet/kvstore_server.py:58-68); a no-op when unlaunched.
from . import distributed
distributed.initialize()
from .context import Context, cpu, gpu, tpu, current_context, num_gpus, num_tpus
from . import ops
from . import operator
from . import ndarray
from . import ndarray as nd
from . import random
from . import random as rnd

ndarray._init_ndarray_module()

from .ndarray import NDArray
from . import name
from . import attribute
from .attribute import AttrScope
from . import symbol
from . import symbol as sym
from .symbol import Symbol

symbol._init_symbol_module()

from . import executor
from .executor import Executor
from . import engine
from . import recordio
from . import image
from . import io
# reference parity: the C++ record iterators register as mx.io.* iterators
# (src/io/iter_image_recordio.cc:319, iter_image_det_recordio.cc:563); ours
# live in image.py / image_det.py
from . import image_det
io.ImageRecordIter = image.ImageRecordIter
io.ImageRecordUInt8Iter = image.ImageRecordUInt8Iter
io.ImageDetRecordIter = image_det.ImageDetRecordIter
from . import dataflow
from .dataflow import DevicePrefetchIter
io.DevicePrefetchIter = DevicePrefetchIter
from . import initializer
from .initializer import init_registry
from . import optimizer
from .optimizer import Optimizer
from . import lr_scheduler
from . import metric
from . import callback
from . import kvstore
from . import kvstore as kv
from . import executor_manager
from . import parallel
from . import autograd
from . import contrib
from . import rtc
from . import torch_bridge
from .torch_bridge import th
# both addressing styles work: mx.contrib.symbol.X (the reference's v0.9.5
# layout) and mx.sym.contrib.X / mx.nd.contrib.X (later-API convenience)
symbol.contrib = contrib.symbol
ndarray.contrib = contrib.ndarray
from . import monitor
from . import monitor as mon
from .monitor import Monitor
from . import profiler
from .profiler import profiler_set_config, profiler_set_state, dump_profile
from . import visualization
from . import visualization as viz
from . import models
from . import rnn
from . import model
from . import libinfo
from .model import FeedForward
from . import module
from . import module as mod
from . import predict
from . import serving
# multi-replica serving fleet (jax-free package; imported for env
# registry completeness, like serving)
from . import fleet
from . import test_utils
from . import analysis
# fused Pallas/lax kernels (registers the _FusedLSTMCell op and the
# MXTPU_FLASH_BLOCK knob — imported at package init for registry
# completeness, like serving)
from . import kernels
