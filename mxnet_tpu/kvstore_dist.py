"""Distributed KVStore over XLA collectives.

Replaces the reference's ps-lite parameter-server column
(src/kvstore/kvstore_dist.h worker, kvstore_dist_server.h server; ZMQ/TCP)
with the TPU-native design from SURVEY §2.3: gradients are all-reduced
across workers with XLA collectives over ICI/DCN instead of being pushed to
sharded server processes, and the optimizer ("updater on server") runs
locally on the reduced gradient — numerically identical to the reference's
``dist_sync`` protocol (sync servers aggregate all NumWorkers pushes, apply
the updater once, broadcast).

Process model: one JAX process per host (``distributed.initialize``),
every process sees its local chips; collectives ride ICI within a host /
DCN across hosts.  Clusters are launched with ``tools/launch.py`` (the
reference launcher's analog: it spawns N worker processes with
coordinator/rank envs the way the reference's launcher forks
scheduler/server/worker roles with DMLC_* envs, tools/launch.py:46-70).
Closed-form multi-worker semantics are asserted by
``tests/dist/dist_sync_kvstore.py`` (port of the reference's
tests/nightly/dist_sync_kvstore.py).  ``dist_async`` has no ICI analog and
raises (documented decision, SURVEY §5.8).
"""
from __future__ import annotations

from .base import MXNetError
from .kvstore import KVStore
from .ndarray import NDArray
from . import distributed

__all__ = ["KVStoreTPU"]


class KVStoreTPU(KVStore):
    """kvstore for 'tpu' / 'dist_sync' / 'dist_device_sync'."""

    def __init__(self, kind="tpu"):
        if "async" in kind:
            raise MXNetError(
                "dist_async has no ICI analog on TPU (no parameter server); "
                "use 'tpu' / 'dist_sync'. (SURVEY §5.8 design decision)")
        super().__init__(kind)
        distributed.initialize()  # no-op unless launched via tools/launch.py
        distributed.start_heartbeat()  # liveness stamps for dead-node query
        import jax
        self._jax = jax
        self._coll = None  # built lazily, after the backend is up

    def get_num_dead_node(self, node_id=-1, timeout=60):
        """Count workers with stale liveness stamps (reference ps-lite
        heartbeat query, kvstore_dist.h:158-167; see
        distributed.num_dead_nodes — collectives stay all-or-nothing, this
        is the monitoring-side observation mechanism)."""
        return distributed.num_dead_nodes(node_id=node_id, timeout=timeout)

    @property
    def _collective(self):
        if self._coll is None:
            self._coll = distributed.Collective()
        return self._coll

    @property
    def rank(self):
        return self._jax.process_index()

    @property
    def num_workers(self):
        return self._jax.process_count()

    def _allreduce(self, arr):
        """Sum an NDArray across worker processes (device-side AllReduce)."""
        if self.num_workers == 1:
            return arr
        summed = self._collective.allreduce_sum(arr._data)
        return NDArray._from_jax(summed, arr._ctx)

    def init(self, key, value):
        """Init + broadcast rank 0's value so all workers start identical
        (the reference's init-push lands on servers once and every worker
        pulls the same bytes, kvstore_dist.h Init)."""
        super().init(key, value)
        if self.num_workers > 1:
            from .kvstore import _key_value
            from .ndarray import _to_device
            keys, _ = _key_value(key, value)
            for k in keys:
                stored = self._store[k]
                # keep the stored array committed to the store's context
                # device (the collective's result lives on its designated
                # per-process device, which may differ on multi-chip hosts)
                stored._data = _to_device(
                    self._collective.broadcast(stored._data), stored._ctx)

    def push(self, key, value, priority=0):
        from .kvstore import _key_value, _updater_key
        keys, vals = _key_value(key, value)
        for k, vlist in zip(keys, vals):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % (k,))
            store_ctx = self._store[k].context
            merged = vlist[0].as_in_context(store_ctx).copy()
            for v in vlist[1:]:
                merged += v.as_in_context(store_ctx)
            merged = self._allreduce(merged)
            if self._updater is not None:
                self._updater(_updater_key(k), merged, self._store[k])
            else:
                self._store[k]._data = merged._data

    def barrier(self):
        distributed.barrier("kvstore_barrier")

    _barrier = barrier
