"""Pure-Python modules (reference python/mxnet/module/python_module.py).

``PythonModule`` stubs the Module API for computation written directly
in Python (no Symbol, no executor); ``PythonLossModule`` is the loss-
as-module variant: it treats its input as scores and backpropagates a
user-supplied gradient function — composable behind a network module
via ``SequentialModule`` (example/module/python_loss.py).
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from ..initializer import Uniform
from ..ndarray import NDArray, array as nd_array
from .base_module import BaseModule

__all__ = ["PythonModule", "PythonLossModule"]


class PythonModule(BaseModule):
    """Module-API adapter for Python-defined computation: parameter and
    optimizer hooks default to no-ops; subclasses implement forward/
    backward and ``_compute_output_shapes``."""

    def __init__(self, data_names, label_names, output_names,
                 logger=logging):
        super(PythonModule, self).__init__(logger=logger)
        self._data_names = list(data_names)
        self._label_names = list(label_names) if label_names is not None \
            else None
        self._output_names = list(output_names)
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None

    # -- symbol information ----------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    # -- shapes ----------------------------------------------------------
    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    # -- parameters (none by default) ------------------------------------
    def get_params(self):
        return {}, {}

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False,
                    force_init=False):
        self.params_initialized = True

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True):
        self.params_initialized = True

    def update(self):
        pass

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self.optimizer_initialized = True

    def update_metric(self, eval_metric, labels):
        if self._label_shapes is None:
            return   # no labels: nothing to evaluate against
        eval_metric.update(labels, self.get_outputs())

    # -- setup -----------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        if grad_req != "write":
            raise MXNetError("PythonModule only supports grad_req='write'")
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        names = [d[0] if isinstance(d, tuple) else d.name
                 for d in data_shapes]
        if names != self._data_names:
            raise MXNetError("data_shapes %s do not match data_names %s"
                             % (names, self._data_names))
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        self._output_shapes = self._compute_output_shapes()
        self.binded = True

    def _compute_output_shapes(self):
        raise NotImplementedError()


class PythonLossModule(PythonModule):
    """Loss as a module: forward passes scores through; backward calls
    ``grad_func(scores, labels) -> d(loss)/d(scores)``."""

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 grad_func=None):
        super(PythonLossModule, self).__init__(
            data_names, label_names, [name + "_output"], logger=logger)
        self._name = name
        if len(self._data_names) != 1 or len(self._label_names) != 1:
            raise MXNetError("PythonLossModule expects exactly one data "
                             "and one label name")
        if grad_func is not None and not callable(grad_func):
            raise MXNetError("grad_func must be callable")
        self._grad_func = grad_func
        self._scores = None
        self._labels = None
        self._scores_grad = None

    def _compute_output_shapes(self):
        shape = self._data_shapes[0][1] \
            if isinstance(self._data_shapes[0], tuple) \
            else self._data_shapes[0].shape
        return [(self._name + "_output", shape)]

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if is_train is None:
            is_train = self.for_training
        if is_train:
            self._labels = data_batch.label[0]

    def get_outputs(self, merge_multi_context=True):
        return [self._scores]

    def backward(self, out_grads=None):
        if out_grads is not None:
            raise MXNetError("for a loss module, out_grads must be None")
        assert self.for_training
        self._backward_impl()

    def _backward_impl(self):
        if self._grad_func is None:
            raise NotImplementedError(
                "pass grad_func= or override _backward_impl")
        grad = self._grad_func(self._scores, self._labels)
        if not isinstance(grad, NDArray):
            grad = nd_array(grad)
        self._scores_grad = grad

    def get_input_grads(self, merge_multi_context=True):
        return [self._scores_grad]

    def install_monitor(self, mon):
        raise NotImplementedError()
