"""DataParallelExecutorGroup (reference python/mxnet/module/executor_group.py,
652 LoC).

Manages one executor per device context, slices each batch across devices
along the layout's batch axis (decide_slices, reference :207-231), fans out
forward/backward, merges outputs.  Parameter NDArrays may be shared across
groups (BucketingModule's shared_group) — sharing works by sharing the
NDArray cells themselves.

TPU note: for the single-device case (one TPU chip or one pjit mesh) this
degenerates to a single fused executor; multi-chip data parallelism via
kvstore='tpu' runs one *sharded* executor over a Mesh instead of N
executors (see parallel/), keeping this class for API/test parity with
cpu(0)/cpu(1)-style fake multi-device setups.
"""
from __future__ import annotations

import logging

import numpy as np

from ..base import MXNetError
from ..context import cpu
from ..executor_manager import _split_input_slice
from ..io import DataDesc
from ..ndarray import NDArray, zeros as nd_zeros, concatenate

__all__ = ["DataParallelExecutorGroup"]


def _as_data_desc(shapes):
    if shapes is None:
        return None
    out = []
    for s in shapes:
        if isinstance(s, DataDesc):
            out.append(s)
        else:
            out.append(DataDesc(s[0], s[1]))
    return out


class DataParallelExecutorGroup(object):
    def __init__(self, symbol, contexts, workload, data_shapes, label_shapes,
                 param_names, for_training, inputs_need_grad,
                 shared_group=None, logger=logging, fixed_param_names=None,
                 grad_req="write", state_names=None):
        self.symbol = symbol
        self.contexts = contexts
        self.workload = workload if workload else [1] * len(contexts)
        self.param_names = list(param_names)
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = set(fixed_param_names or [])
        self.logger = logger

        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.output_names = symbol.list_outputs()

        if shared_group is not None:
            self.shared_data_arrays = shared_group.shared_data_arrays
        else:
            self.shared_data_arrays = [{} for _ in contexts]

        self.grad_req = {}
        for name in self.arg_names:
            if name in self.param_names:
                self.grad_req[name] = ("null" if name in self.fixed_param_names
                                       or not for_training else grad_req)
            elif inputs_need_grad and any(
                    name == d[0] if not isinstance(d, DataDesc) else
                    name == d.name for d in data_shapes):
                self.grad_req[name] = grad_req
            else:
                self.grad_req[name] = "null"

        self.execs = []
        self.data_shapes = None
        self.label_shapes = None
        self.slices = None
        self.batch_size = None
        self._default_execs = None
        self.bind_exec(data_shapes, label_shapes, shared_group)

    # -- binding ----------------------------------------------------------
    def decide_slices(self, data_shapes):
        """Per-device batch slices along the layout batch axis (reference
        executor_group.py:207)."""
        assert len(data_shapes) > 0
        major_axis = [DataDesc.get_batch_axis(getattr(d, "layout", "NCHW"))
                      for d in data_shapes]
        for (desc, axis) in zip(data_shapes, major_axis):
            if axis == -1:
                continue
            batch_size = desc.shape[axis]
            if self.batch_size is not None:
                assert batch_size == self.batch_size, \
                    ("all data must have the same batch size: "
                     + ("batch_size = %d, but " % self.batch_size)
                     + ("%s has shape %s" % (desc.name, desc.shape)))
            else:
                self.batch_size = batch_size
                self.slices = _split_input_slice(self.batch_size,
                                                 self.workload)
        return major_axis

    def bind_exec(self, data_shapes, label_shapes, shared_group=None,
                  reshape=False):
        data_shapes = _as_data_desc(data_shapes)
        label_shapes = _as_data_desc(label_shapes)
        self.batch_size = None
        self.data_major_axis = self.decide_slices(data_shapes)
        if label_shapes is not None and len(label_shapes):
            self.label_major_axis = self.decide_slices(label_shapes)
        else:
            self.label_major_axis = []
        self.data_shapes = data_shapes
        self.label_shapes = label_shapes

        self.execs = []
        for i in range(len(self.contexts)):
            self.execs.append(
                self._bind_ith_exec(i, data_shapes, label_shapes,
                                    shared_group))
        self._wire_arrays()

    def _wire_arrays(self):
        """Rebuild the array-list views over self.execs (split out so
        reshape's executor-cache swap can re-wire without rebinding)."""
        data_shapes, label_shapes = self.data_shapes, self.label_shapes
        self.data_arrays = [
            [(self.slices[i], e.arg_dict[name]) for i, e in enumerate(self.execs)]
            for name, _ in [(d.name, d.shape) for d in data_shapes]]
        if label_shapes is not None:
            self.label_arrays = [
                [(self.slices[i], e.arg_dict[name])
                 for i, e in enumerate(self.execs)]
                for name in [l.name for l in label_shapes]
                if name in self.execs[0].arg_dict]
        else:
            self.label_arrays = None

        self.param_arrays = [[e.arg_dict[name] for e in self.execs]
                             for name in self.param_names
                             if name in self.arg_names]
        if self.for_training:
            self.grad_arrays = [
                [e.grad_dict.get(name) for e in self.execs]
                for name in self.param_names if name in self.arg_names]
        else:
            self.grad_arrays = [[None] * len(self.execs)
                                for _ in self.param_names]
        data_names = [d.name for d in data_shapes]
        if self.inputs_need_grad:
            self.input_grad_arrays = [
                [e.grad_dict.get(name) for e in self.execs]
                for name in data_names]
        else:
            self.input_grad_arrays = None
        self.aux_arrays = [[e.aux_dict[name] for e in self.execs]
                           for name in self.aux_names]

    def reshape(self, data_shapes, label_shapes=None):
        """Rebind executors for new input shapes, sharing the existing
        parameter/gradient/aux cells (reference executor_group.py
        DataParallelExecutorGroup.reshape) — weights and optimizer
        attachment survive; only input-shaped buffers are fresh.

        Executor sets are CACHED per shape signature: every cached set
        shares the same parameter NDArray cells, so updates made while
        one shape is active are visible to all (alternating between an
        act-batch and a train-batch shape, the RL pattern, costs one
        bind each — and XLA caches compiled programs per shape, so no
        recompiles either)."""
        import copy
        data_shapes = _as_data_desc(data_shapes)
        label_shapes = _as_data_desc(label_shapes)
        if not hasattr(self, "_reshape_cache"):
            # seed the cache with the currently-bound shape
            self._reshape_cache = {self._shape_sig(
                self.data_shapes, self.label_shapes): self.execs}
        sig = self._shape_sig(data_shapes, label_shapes)
        cached = self._reshape_cache.get(sig)
        if cached is not None and cached is not self.execs:
            self.batch_size = None
            self.data_major_axis = self.decide_slices(data_shapes)
            if label_shapes:
                self.label_major_axis = self.decide_slices(label_shapes)
            self.data_shapes = data_shapes
            self.label_shapes = label_shapes
            self.execs = cached
            self._wire_arrays()
            return
        if cached is None:
            prev = copy.copy(self)   # shallow: exposes .execs for sharing
            self.bind_exec(data_shapes, label_shapes, shared_group=prev,
                           reshape=True)
            self._reshape_cache[sig] = self.execs

    @staticmethod
    def _shape_sig(data_shapes, label_shapes):
        return (tuple((d.name, tuple(d.shape)) for d in data_shapes),
                tuple((l.name, tuple(l.shape))
                      for l in (label_shapes or [])))

    def _sliced_shape(self, shapes, i, major_axis):
        """Shape of the i-th device slice (reference executor_group.py
        _sliced_shape)."""
        sliced = []
        for desc, axis in zip(shapes, major_axis):
            shape = list(desc.shape)
            if axis >= 0:
                shape[axis] = self.slices[i].stop - self.slices[i].start
            sliced.append(DataDesc(desc.name, tuple(shape), desc.dtype,
                                   desc.layout))
        return sliced

    def _bind_ith_exec(self, i, data_shapes, label_shapes, shared_group):
        ctx = self.contexts[i]
        shared_data = self.shared_data_arrays[i]
        d_shapes = self._sliced_shape(data_shapes, i, self.data_major_axis)
        input_shapes = {d.name: d.shape for d in d_shapes}
        if label_shapes is not None:
            l_shapes = self._sliced_shape(label_shapes, i,
                                          self.label_major_axis)
            input_shapes.update({l.name: l.shape for l in l_shapes})
        arg_shapes, _, aux_shapes = self.symbol.infer_shape(**input_shapes)
        args = {}
        grads = {}
        shared_exec = shared_group.execs[i] if shared_group else None
        for name, shape in zip(self.arg_names, arg_shapes):
            if name in self.param_names:
                if shared_exec is not None and name in shared_exec.arg_dict:
                    # share parameter cells across buckets
                    args[name] = shared_exec.arg_dict[name]
                    if name in shared_exec.grad_dict and \
                            shared_exec.grad_dict[name] is not None:
                        grads[name] = shared_exec.grad_dict[name]
                else:
                    args[name] = nd_zeros(shape, ctx=ctx)
                    if self.grad_req.get(name, "null") != "null":
                        grads[name] = nd_zeros(shape, ctx=ctx)
            else:
                # the reference reuses one big data buffer across buckets
                # (executor_group.py shared_data_arrays); with immutable XLA
                # buffers there is nothing to save — share only exact-shape
                # arrays (the NDArray cell), else allocate fresh
                if name in shared_data and \
                        shared_data[name].shape == tuple(shape):
                    args[name] = shared_data[name]
                else:
                    args[name] = nd_zeros(shape, ctx=ctx)
                    shared_data[name] = args[name]
                if self.grad_req.get(name, "null") != "null":
                    grads[name] = nd_zeros(shape, ctx=ctx)
        aux = {}
        for name, shape in zip(self.aux_names, aux_shapes):
            if shared_exec is not None and name in shared_exec.aux_dict:
                aux[name] = shared_exec.aux_dict[name]
            else:
                aux[name] = nd_zeros(shape, ctx=ctx)
        return self.symbol.bind(ctx, args, args_grad=grads or None,
                                grad_req=self.grad_req, aux_states=aux)

    # -- parameter sync ----------------------------------------------------
    def set_params(self, arg_params, aux_params):
        for e in self.execs:
            e.copy_params_from(arg_params, aux_params,
                               allow_extra_params=True)

    def get_params(self, arg_params, aux_params):
        """Average weights over devices into the given dicts (reference
        executor_group.py:get_params)."""
        for name, block in zip([n for n in self.param_names
                                if n in self.arg_names], self.param_arrays):
            weight = sum(w.copyto(cpu()) for w in block) / len(block)
            weight.copyto(arg_params[name])
        for name, block in zip(self.aux_names, self.aux_arrays):
            weight = sum(w.copyto(cpu()) for w in block) / len(block)
            weight.copyto(aux_params[name])

    def release_device_buffers(self):
        """Free the device memory behind this group's executors (arg, grad,
        aux cells shrink to 0-size placeholders).  Used by Module when the
        fused SPMD path engages — the trainer holds the live parameters, so
        keeping a second full copy (plus gradient buffers) here would double
        HBM.  A later set_params() re-materializes the cells."""
        import jax.numpy as jnp
        for e in self.execs:
            for d in (e.arg_dict, e.grad_dict, e.aux_dict):
                for arr in d.values():
                    if arr is not None:
                        arr._data = jnp.zeros((0,), arr._data.dtype)

    # -- execution ---------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        from ..ndarray import _to_device
        if is_train is None:
            is_train = self.for_training
        for name_arrays, src in zip(self.data_arrays, data_batch.data):
            for slc, dst in name_arrays:
                dst._data = _to_device(
                    src[slc]._data.astype(dst._data.dtype), dst._ctx)
        if self.label_arrays is not None and data_batch.label:
            for name_arrays, src in zip(self.label_arrays, data_batch.label):
                for slc, dst in name_arrays:
                    dst._data = _to_device(
                        src[slc]._data.astype(dst._data.dtype), dst._ctx)
        for e in self.execs:
            e.forward(is_train=is_train)

    def backward(self, out_grads=None):
        assert self.for_training, "re-bind with for_training=True to run backward"
        for i, e in enumerate(self.execs):
            if out_grads is None:
                e.backward()
            else:
                og = [g[self.slices[i]] if g is not None else None
                      for g in out_grads]
                e.backward(og)

    def get_outputs(self, merge_multi_context=True):
        outputs = [[e.outputs[i] for e in self.execs]
                   for i in range(len(self.execs[0].outputs))]
        if merge_multi_context:
            return [x[0] if len(x) == 1 else concatenate(x, axis=0)
                    for x in outputs]
        return outputs

    def get_input_grads(self, merge_multi_context=True):
        assert self.inputs_need_grad
        if merge_multi_context:
            return [x[0] if len(x) == 1 else concatenate(x, axis=0)
                    for x in self.input_grad_arrays]
        return self.input_grad_arrays

    def update_metric(self, eval_metric, labels):
        for i, e in enumerate(self.execs):
            labels_slice = [label[self.slices[i]] for label in labels]
            eval_metric.update(labels_slice, e.outputs)

    def install_monitor(self, mon):
        for e in self.execs:
            mon.install(e)
