"""Module — the primary high-level interface (reference
python/mxnet/module/module.py, 705 LoC)."""
from __future__ import annotations

import logging

import numpy as np

from .. import optimizer as opt
from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..initializer import Uniform, InitDesc
from ..model import (_create_kvstore, _initialize_kvstore, _update_params,
                     _update_params_on_kvstore, load_checkpoint,
                     save_checkpoint)
from ..io import DataDesc
from ..ndarray import NDArray, zeros as nd_zeros
from .base_module import BaseModule, _check_input_names
from .executor_group import DataParallelExecutorGroup

__all__ = ["Module"]


class Module(BaseModule):
    """Module over a Symbol (reference module.py:Module)."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, shared_params=False):
        """``shared_params=True`` declares that this module's parameter
        cells will be shared with other executors (BucketingModule's
        contract); the fused SPMD path then never engages, since the
        trainer owns its parameters exclusively."""
        super().__init__(logger=logger)
        self._shared_across_buckets = bool(shared_params)
        if context is None:
            context = current_context()
        if isinstance(context, Context):
            context = [context]
        self._context = context
        if work_load_list is None:
            work_load_list = [1] * len(self._context)
        assert len(work_load_list) == len(self._context)
        self._work_load_list = work_load_list

        self._symbol = symbol
        data_names = list(data_names) if data_names is not None else []
        label_names = list(label_names) if label_names is not None else []
        arg_names = symbol.list_arguments()
        input_names = data_names + label_names
        self._param_names = [x for x in arg_names if x not in input_names]
        self._fixed_param_names = list(fixed_param_names or [])
        self._aux_names = symbol.list_auxiliary_states()
        self._data_names = data_names
        self._label_names = label_names
        self._state_names = list(state_names or [])
        self._output_names = symbol.list_outputs()
        _check_input_names(symbol, data_names, "data", True)
        _check_input_names(symbol, label_names, "label", False)
        _check_input_names(symbol, self._fixed_param_names, "fixed_param", True)

        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False

        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None

        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None

        # fused SPMD path (kvstore='tpu'): the whole per-batch pipeline —
        # forward, backward, gradient AllReduce, optimizer — runs as ONE
        # jit-compiled sharded XLA program instead of the executor fan-out +
        # kvstore push/pull protocol (SURVEY §2.3 TPU mapping note)
        self._fused = None
        self._fused_batch = None
        self._fused_outputs = None
        self._fused_outputs_from_update = False
        self._monitor_installed = False

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """Create Module from checkpoint (reference module.py:97)."""
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        blocking=None):
        """Save symbol+params(+optimizer states) (reference module.py:135).
        Every file lands atomically (temp + fsync + rename) so a crash
        mid-save leaves any prior checkpoint intact.

        ``blocking=False`` (default: the ``MXTPU_CKPT_ASYNC`` env)
        returns after snapshotting params (+ serialized optimizer state)
        to host copies; the background writer does the file IO — drain
        with ``resilience.wait_checkpoints()``."""
        from ..model import save_checkpoint as _model_save
        from ..resilience import (atomic_write, checkpoint_async,
                                  snapshot_params, submit_checkpoint)
        if blocking is None:
            blocking = not checkpoint_async()
        states = self.get_optimizer_states() if save_optimizer_states \
            else None
        arg_params, aux_params = self.get_params()
        sym_json = self._symbol.tojson()
        state_name = "%s-%04d.states" % (prefix, epoch)

        def _write_states():
            if states is not None:
                atomic_write(state_name, states)
                logging.info("Saved optimizer state to \"%s\"", state_name)

        if blocking:
            _model_save(prefix, epoch, sym_json, arg_params, aux_params,
                        blocking=True)
            _write_states()
        else:
            # ONE submitted job for params + states: the writer is
            # single-slot, so two submits would block this caller for
            # the first job's full serialize+write+fsync — the stall
            # async mode exists to remove.  Snapshot here (the only
            # synchronous cost); sym_json and the states bytes are
            # immutable already.
            arg_params = snapshot_params(arg_params)
            aux_params = snapshot_params(aux_params)

            def _write_all():
                _model_save(prefix, epoch, sym_json, arg_params,
                            aux_params, blocking=True)
                _write_states()

            submit_checkpoint(_write_all, "%s epoch %d" % (prefix, epoch))

    # -- properties --------------------------------------------------------
    @property
    def skipped_update_count(self):
        """Updates skipped by the fused step's NaN/Inf guard (0 on the
        executor path, which has no in-graph guard)."""
        return self._fused.skipped_steps if self._fused is not None else 0

    @property
    def consecutive_bad_steps(self):
        """Current run of guard-skipped updates (0 on the executor path)."""
        return self._fused.consecutive_bad_steps \
            if self._fused is not None else 0

    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        from ..io import DataDesc
        shapes = {}
        for d in (self._data_shapes or []) + (self._label_shapes or []):
            if isinstance(d, DataDesc):
                shapes[d.name] = d.shape
            else:
                shapes[d[0]] = tuple(d[1])
        _, out_shapes, _ = self._symbol.infer_shape(**shapes)
        return list(zip(self._output_names, out_shapes))

    # -- params ------------------------------------------------------------
    def get_params(self):
        assert self.binded and self.params_initialized
        if self._params_dirty:
            self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False):
        """reference module.py:init_params"""
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing the parameters"

        if self._arg_params is None:
            self._arg_params = {
                name: nd_zeros(arr[0].shape, dtype=arr[0].dtype)
                for name, arr in zip(
                    [n for n in self._param_names
                     if n in self._symbol.list_arguments()],
                    self._exec_group.param_arrays)}
        if self._aux_params is None:
            self._aux_params = {
                name: nd_zeros(arr[0].shape, dtype=arr[0].dtype)
                for name, arr in zip(self._aux_names,
                                     self._exec_group.aux_arrays)}

        attrs = self._symbol.attr_dict()

        def _impl(name, arr, cache):
            if cache is not None:
                if name in cache:
                    cache_arr = cache[name]
                    if cache_arr is not arr:
                        cache_arr.copyto(arr)
                else:
                    if not allow_missing:
                        raise RuntimeError(
                            "%s is not presented" % name)
                    if initializer is not None:
                        initializer(_desc(name), arr)
            else:
                if initializer is not None:
                    initializer(_desc(name), arr)

        def _desc(name):
            return InitDesc(name, attrs.get(name))

        for name, arr in sorted(self._arg_params.items()):
            _impl(name, arr, arg_params)
        for name, arr in sorted(self._aux_params.items()):
            _impl(name, arr, aux_params)

        self.params_initialized = True
        self._params_dirty = False
        if self._fused is not None:
            # trainer is the live copy; exec_group buffers stay released
            self._fused.set_params(self._arg_params, self._aux_params)
        else:
            self._exec_group.set_params(self._arg_params, self._aux_params)

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True):
        if not allow_missing:
            self.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params,
                             allow_missing=allow_missing,
                             force_init=force_init)
            return
        if self.params_initialized and not force_init:
            return
        if self._fused is not None:
            self._fused.set_params(arg_params, aux_params)
        else:
            self._exec_group.set_params(arg_params, aux_params)
        self._params_dirty = True
        self.params_initialized = True

    # -- binding -----------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """reference module.py:bind"""
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Already binded, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        if not for_training:
            assert not inputs_need_grad

        self._data_shapes = data_shapes
        self._label_shapes = label_shapes

        if shared_module is not None:
            assert isinstance(shared_module, Module) and \
                shared_module.binded and shared_module.params_initialized
            if shared_module._fused is not None:
                raise MXNetError(
                    "shared_module runs the fused SPMD path (its executor "
                    "buffers are released and its optimizer state lives in "
                    "the trainer); construct both modules with "
                    "shared_params=True before init_optimizer, or use a "
                    "non-tpu kvstore")
            # the parent's parameter cells are now shared: it must never
            # fuse later either (fusing would release the cells this
            # module's executors alias)
            shared_module._shared_across_buckets = True
            self._shared_across_buckets = True
            shared_group = shared_module._exec_group
        else:
            shared_group = None

        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, self._work_load_list, data_shapes,
            label_shapes, self._param_names, for_training, inputs_need_grad,
            shared_group, logger=self.logger,
            fixed_param_names=self._fixed_param_names, grad_req=grad_req)

        if shared_module is not None:
            self.params_initialized = True
            self._arg_params = shared_module._arg_params
            self._aux_params = shared_module._aux_params
        elif self.params_initialized:
            # bind() after load(): push params to devices
            self._exec_group.set_params(self._arg_params, self._aux_params)

        if shared_module is not None and shared_module.optimizer_initialized:
            self.borrow_optimizer(shared_module)

    def _reset_bind(self):
        self.binded = False
        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None
        self._fused = None
        self._fused_batch = None
        self._fused_outputs = None
        self._fused_outputs_from_update = False
        self._monitor_installed = False
        if getattr(self, "_deferred_metric", None) is not None:
            self._deferred_metric.detach_deferred_source()
        self._deferred_metric = None
        self._deferred_interval = 0
        self._deferred_calls = 0

    # -- optimizer ---------------------------------------------------------
    def reshape(self, data_shapes, label_shapes=None):
        """Re-bind for new input shapes keeping parameters and optimizer
        state (reference module.py:405 Module.reshape).  The executor
        group shares its parameter cells into the re-bound executors;
        the fused trainer (kvstore='tpu') just re-binds its step — XLA
        caches compiled programs per shape, so flipping between batch
        sizes costs one compile each, once."""
        assert self.binded
        self._data_shapes = [d if isinstance(d, DataDesc)
                             else DataDesc(d[0], d[1]) for d in data_shapes]
        self._label_shapes = [l if isinstance(l, DataDesc)
                              else DataDesc(l[0], l[1])
                              for l in (label_shapes or [])] or None
        self._exec_group.reshape(self._data_shapes, self._label_shapes)
        if self._fused is not None:
            self._fused.bind(self._data_shapes, self._label_shapes or [])

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """reference module.py:432-508"""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring...")
            return

        (kvstore, update_on_kvstore) = _create_kvstore(
            kvstore, len(self._context), self._arg_params)

        batch_size = self._exec_group.batch_size
        # sync-replicated stores ('dist_sync*' and the collective 'tpu'
        # store) sum gradients across workers, so rescale by the global
        # batch (reference module.py:461-462)
        if kvstore and ("tpu" in kvstore.type or
                        ("dist" in kvstore.type and "_sync" in kvstore.type)):
            batch_size *= kvstore.num_workers
        rescale_grad = 1.0 / batch_size

        if isinstance(optimizer, str):
            idx2name = {}
            if update_on_kvstore:
                idx2name.update(enumerate(self._exec_group.param_names))
            else:
                for k in range(len(self._context)):
                    idx2name.update(
                        {i * len(self._context) + k: n
                         for i, n in enumerate(self._exec_group.param_names)})
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt.create(optimizer, sym=self.symbol,
                                   param_idx2name=idx2name,
                                   **optimizer_params)
        else:
            assert isinstance(optimizer, opt.Optimizer)

        self._optimizer = optimizer
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._updater = None

        was_fused = self._fused is not None
        if self._params_dirty:
            # re-initializing mid-training: capture the trained weights
            # from whichever side currently owns them (trainer or
            # exec_group) before the ownership may change below
            self._sync_params_from_devices()
        self._fused = self._maybe_init_fused(kvstore, optimizer)
        if self._fused is not None:
            self.logger.info(
                "kvstore '%s': using the fused SPMD train step "
                "(fwd+bwd+allreduce+update in one XLA program)",
                kvstore.type)
            # the trainer holds the live params now; drop the executor
            # group's duplicate device buffers (re-materialized below if a
            # later init_optimizer falls back)
            self._exec_group.release_device_buffers()
        else:
            if was_fused:
                # buffers were released while the trainer owned the params
                self._exec_group.set_params(self._arg_params,
                                            self._aux_params)
            if kvstore:
                _initialize_kvstore(
                    kvstore=kvstore,
                    param_arrays=self._exec_group.param_arrays,
                    arg_params=self._arg_params,
                    param_names=self._param_names,
                    update_on_kvstore=update_on_kvstore)
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
            else:
                self._updater = opt.get_updater(optimizer)

        self.optimizer_initialized = True

        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    def _maybe_init_fused(self, kvstore, optimizer):
        """Build the fused SPMDTrainer for a 'tpu'/'dist' kvstore, or None
        when the configuration needs the generic executor path."""
        if kvstore is None or not ("tpu" in kvstore.type
                                   or "dist" in kvstore.type):
            return None
        if not self.for_training:
            return None
        reasons = []
        if self._shared_across_buckets:
            # BucketingModule shares parameter cells between bucket
            # executors; the fused trainer owns its params exclusively
            reasons.append("bucketed shape sharing")
        if self._state_names:
            reasons.append("state_names")
        if self.inputs_need_grad:
            reasons.append("inputs_need_grad")
        if self._fixed_param_names:
            reasons.append("fixed_param_names")
        if self._monitor_installed:
            reasons.append("an installed Monitor (needs per-op taps)")
        if any(self._exec_group.grad_req.get(n) not in (None, "null", "write")
               for n in self._param_names):
            reasons.append("grad_req != 'write'")
        from ..parallel.trainer import SUPPORTED_OPTIMIZERS
        kind = type(optimizer).__name__.lower()
        if kind not in SUPPORTED_OPTIMIZERS:
            reasons.append("optimizer %r (no in-graph rule)" % kind)
        if reasons:
            self.logger.info(
                "kvstore '%s': falling back to the kvstore push/pull path "
                "(fused step unavailable with %s)", kvstore.type,
                ", ".join(reasons))
            return None

        import jax
        import numpy as _np
        from ..parallel import SPMDTrainer
        from jax.sharding import Mesh

        num_workers = kvstore.num_workers
        if num_workers > 1:
            devs = sorted(jax.devices(),
                          key=lambda d: (d.process_index, d.id))
            local_batch = self._exec_group.batch_size
            if (local_batch * num_workers) % len(devs) != 0:
                self.logger.info(
                    "kvstore '%s': global batch %d not divisible by %d "
                    "devices; falling back to kvstore push/pull",
                    kvstore.type, local_batch * num_workers, len(devs))
                return None
            mesh = Mesh(_np.asarray(devs), ("dp",))
        elif len(self._context) > 1:
            # single-process multi-device: kvstore='tpu' + a context list
            # runs ONE fused step dp-sharded over exactly those devices
            # (the SPMD analog of the reference's executor-group fan-out
            # over context=[gpu(0..k)]); indivisible batches fall back to
            # the executor-group path
            if self._exec_group.batch_size % len(self._context) != 0:
                self.logger.info(
                    "kvstore '%s': batch %d not divisible by %d contexts; "
                    "falling back to the executor-group path",
                    kvstore.type, self._exec_group.batch_size,
                    len(self._context))
                return None
            try:
                devs = [c.jax_device for c in self._context]
            except Exception:
                self.logger.info(
                    "kvstore '%s': context list not mappable to devices; "
                    "falling back to the executor-group path", kvstore.type)
                return None
            if len(set(devs)) != len(devs):
                # duplicated contexts (the reference idiom for
                # oversubscribing one device) cannot form a Mesh
                self.logger.info(
                    "kvstore '%s': duplicate devices in context list; "
                    "falling back to the executor-group path", kvstore.type)
                return None
            mesh = Mesh(_np.asarray(devs), ("dp",))
        else:
            mesh = None

        trainer = SPMDTrainer(self._symbol, optimizer, mesh=mesh)
        trainer.bind(self._data_shapes, self._label_shapes)
        trainer.init_params(None, self._arg_params, self._aux_params)
        return trainer

    def _fused_feed(self, data_batch):
        """Assemble the trainer's input list (data then labels) from a
        DataBatch, synthesizing zero labels when absent (predict path —
        labels only matter for the backward).  A StagedBatch (inputs
        already placed on the mesh by DevicePrefetchIter/stage_batch)
        passes through whole — the trainer consumes it directly and skips
        the host->device transfer."""
        from ..io import StagedBatch
        if isinstance(data_batch, StagedBatch):
            return [data_batch]
        arrays = list(data_batch.data)
        labels = list(data_batch.label or [])
        if len(labels) < len(self._fused.label_names):
            labels = labels + [
                nd_zeros(self._fused.arg_shapes[name])
                for name in self._fused.label_names[len(labels):]]
        return arrays + labels

    def borrow_optimizer(self, shared_module):
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self.optimizer_initialized = True

    # -- execution ---------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if self._fused is not None:
            if is_train is None:
                is_train = self.for_training
            if is_train:
                # the train step is deferred to update() so the reference's
                # forward → backward → update contract (metric sees outputs
                # of pre-update weights) holds with one fused program
                self._fused_batch = self._fused_feed(data_batch)
                # this step's RNG key is drawn LAZILY (first of
                # get_outputs-preview or update) so a forward that is never
                # followed by either leaves the training key stream
                # untouched, while a preview still sees the exact masks the
                # deferred step will apply (advisor r2 finding)
                self._fused_key = None
                self._fused_outputs = None
                self._fused_outputs_from_update = False
            else:
                outs = self._fused.eval_step(*self._fused_feed(data_batch))
                self._fused_outputs = [NDArray._from_jax(o) for o in outs]
                self._fused_outputs_from_update = False
            return
        self._exec_group.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        if self._fused is not None:
            assert out_grads is None, \
                "custom head gradients need the executor path (use a " \
                "non-tpu kvstore)"
            return
        self._exec_group.backward(out_grads=out_grads)

    def update(self):
        """reference module.py:553 → model.py:88-123"""
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        self._params_dirty = True
        if self._fused is not None:
            assert self._fused_batch is not None, \
                "update() without a prior forward(is_train=True)"
            outs = self._fused.step(*self._fused_batch,
                                    key=self._draw_fused_key())
            self._fused_outputs = [NDArray._from_jax(o) for o in outs]
            self._fused_outputs_from_update = True
            self._fused_batch = None
            self._fused_key = None
            return
        if self._update_on_kvstore:
            _update_params_on_kvstore(self._exec_group.param_arrays,
                                      self._exec_group.grad_arrays,
                                      self._kvstore)
        else:
            _update_params(self._exec_group.param_arrays,
                           self._exec_group.grad_arrays,
                           updater=self._updater,
                           num_device=len(self._context),
                           kvstore=self._kvstore)

    def _draw_fused_key(self):
        """Draw the deferred step's key on first need; a repeated call
        (preview then update) returns the same key."""
        if getattr(self, "_fused_key", None) is None:
            from .. import random as _random
            self._fused_key = _random.next_key()
        return self._fused_key

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        if self._fused is not None:
            if self._fused_outputs is None and self._fused_batch is not None:
                # outputs requested between forward_backward() and update()
                # (e.g. a custom loop): train-mode forward with the SAME key
                # the deferred step will consume, so stochastic layers show
                # the outputs that correspond to the applied gradients
                outs = self._fused.forward_only(
                    *self._fused_batch, key=self._draw_fused_key())
                self._fused_outputs = [NDArray._from_jax(o) for o in outs]
            return list(self._fused_outputs or [])
        return self._exec_group.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and \
            self.inputs_need_grad
        return self._exec_group.get_input_grads(merge_multi_context)

    def _deferred_metric_trainer(self):
        return self._fused  # None on the executor path

    def update_metric(self, eval_metric, labels):
        if self._fused is not None:
            if self._fused_outputs_from_update and \
                    self._deferred_metric_update(eval_metric):
                # the step itself accumulated (sum, count) in-graph —
                # nothing to fetch per step
                return
            if self._fused_outputs_from_update and self._fused.step_guard:
                # a guard-skipped step's outputs are non-finite by
                # definition; one NaN into a summing metric would poison
                # the whole epoch's Train-* rows (the flush costs nothing
                # extra: reading the outputs below syncs the same program)
                self._fused.flush_step_guard()
                if self._fused.last_step_skipped:
                    return
            eval_metric.update(list(labels or []), self.get_outputs())
            return
        self._exec_group.update_metric(eval_metric, labels)

    def _sync_params_from_devices(self):
        if self._fused is not None:
            self._arg_params, self._aux_params = self._fused.get_params()
        else:
            self._exec_group.get_params(self._arg_params, self._aux_params)
        self._params_dirty = False

    def get_optimizer_states(self):
        """Serialized optimizer state as bytes, from whichever side owns
        it (fused trainer / kvstore updater / local updater).  Under
        sharded fused params the gather is COLLECTIVE — call on all
        ranks."""
        assert self.optimizer_initialized
        if self._fused is not None:
            return self._fused.get_states()
        if self._update_on_kvstore:
            return self._kvstore.get_optimizer_states()
        return self._updater.get_states()

    def set_optimizer_states(self, states):
        assert self.optimizer_initialized
        if self._fused is not None:
            self._fused.set_states(states)
        elif self._update_on_kvstore:
            self._kvstore.set_optimizer_states(states)
        else:
            self._updater.set_states(states)

    def save_optimizer_states(self, fname):
        from ..resilience import atomic_write
        atomic_write(fname, self.get_optimizer_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        with open(fname, "rb") as f:
            self.set_optimizer_states(f.read())

    def install_monitor(self, mon):
        assert self.binded
        if self._fused is not None:
            raise MXNetError(
                "Monitor taps need per-op execution; install the monitor "
                "before init_optimizer or use a non-tpu kvstore")
        self._monitor_installed = True
        self._exec_group.install_monitor(mon)
